#!/usr/bin/env python
"""Parameter-sweep benchmark driver (qa/workunits/erasure-code/bench.sh
analogue).

Sweeps plugins x techniques x k/m like the reference harness
(reference: qa/workunits/erasure-code/bench.sh:50-130: k in {2,3,4,6,10},
m per k-map, vandermonde+cauchy for isa/jerasure, TOTAL_SIZE/SIZE
iterations, cauchy packetsize heuristic) and emits one JSON line per cell:
{"plugin":…, "technique":…, "k":…, "m":…, "workload":…, "gibps":…}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ceph_tpu.plugins import registry as registry_mod  # noqa: E402

KS = [2, 3, 4, 6, 10]
M_MAP = {2: [1, 2], 3: [2], 4: [2, 3], 6: [3], 10: [4]}


def packetsize_heuristic(size: int, k: int, w: int = 8, wordsize: int = 4) -> int:
    """bench.sh:92-101 cauchy packetsize heuristic, capped at 3100."""
    ps = (size // k // w // wordsize) * wordsize
    return max(4, min(ps, 3100))


def bench_cell(plugin, technique, k, m, size, total, backend):
    profile = {"k": str(k), "m": str(m), "technique": technique}
    if backend:
        profile["backend"] = backend
    if technique in ("cauchy_good", "cauchy_orig"):
        profile["packetsize"] = str(packetsize_heuristic(size, k))
    ec = registry_mod.instance().factory(plugin, profile)
    payload = np.full(size, ord("X"), dtype=np.uint8)
    want = set(range(ec.get_chunk_count()))
    iterations = max(1, total // size)
    ec.encode(want, payload)  # warmup (jit etc.)
    t0 = time.perf_counter()
    for _ in range(iterations):
        ec.encode(want, payload)
    dt = time.perf_counter() - t0
    return iterations * size / dt / (1 << 30)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=1 << 20)
    p.add_argument("--total-size", type=int, default=16 << 20)
    p.add_argument("--plugins", default="jerasure,isa")
    p.add_argument("--backend", default="", help="cpu|native|tpu")
    args = p.parse_args(argv if argv is not None else sys.argv[1:])

    techniques = {
        "jerasure": ["reed_sol_van", "cauchy_good"],
        "isa": ["reed_sol_van", "cauchy"],
        "tpu": ["reed_sol_van", "cauchy_good"],
    }
    for plugin in args.plugins.split(","):
        for technique in techniques.get(plugin, ["reed_sol_van"]):
            for k in KS:
                for m in M_MAP[k]:
                    if plugin == "isa" and technique == "reed_sol_van" and m > 4:
                        continue
                    try:
                        gibps = bench_cell(
                            plugin, technique, k, m,
                            args.size, args.total_size, args.backend,
                        )
                        print(json.dumps({
                            "plugin": plugin, "technique": technique,
                            "k": k, "m": m, "workload": "encode",
                            "gibps": round(gibps, 3),
                        }))
                    except Exception as e:  # guard-railed combos
                        print(json.dumps({
                            "plugin": plugin, "technique": technique,
                            "k": k, "m": m, "error": str(e)[:80],
                        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
