#!/usr/bin/env python
"""Corpus non-regression tool (ceph_erasure_code_non_regression equivalent).

--create writes a deterministic payload + every encoded chunk into a
directory keyed by plugin/stripe-width/parameters; --check re-encodes the
archived payload and memcmps chunk-for-chunk, then decodes with 1 and 2
erasures verifying recovered bytes (reference: src/test/erasure-code/
ceph_erasure_code_non_regression.cc:119-139 directory layout, :154-197
create, :226-289 check).  This is the cross-version bit-exactness guarantee:
a corpus created by any version of this framework must check against every
later version.
"""

from __future__ import annotations

import argparse
import os
import random
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ceph_tpu.plugins import registry as registry_mod  # noqa: E402


def parse_args(argv):
    p = argparse.ArgumentParser(description="erasure code non-regression")
    p.add_argument("--stripe-width", type=int, default=4 * 1024,
                   help="stripe width in bytes")
    p.add_argument("--plugin", default="jerasure")
    p.add_argument("--base", default=".",
                   help="base directory for the corpus")
    p.add_argument("--parameter", action="append", default=[])
    p.add_argument("--create", action="store_true")
    p.add_argument("--check", action="store_true")
    return p.parse_args(argv)


class NonRegression:
    def __init__(self, args):
        self.args = args
        self.profile = {}
        directory = os.path.join(
            args.base,
            f"plugin={args.plugin} stripe-width={args.stripe_width}",
        )
        for param in args.parameter:
            if param.count("=") != 1:
                print(f"--parameter {param} ignored", file=sys.stderr)
                continue
            key, val = param.split("=")
            self.profile[key] = val
            directory += " " + param
        self.directory = directory

    def content_path(self):
        return os.path.join(self.directory, "content")

    def chunk_path(self, i):
        return os.path.join(self.directory, str(i))

    def codec(self):
        return registry_mod.instance().factory(
            self.args.plugin, dict(self.profile)
        )

    def run_create(self) -> int:
        ec = self.codec()
        os.makedirs(self.directory, exist_ok=False)
        payload_chunk = bytes(
            ord("a") + random.randrange(26) for _ in range(37)
        )
        data = (payload_chunk * (self.args.stripe_width // 37 + 1))[
            : self.args.stripe_width
        ]
        with open(self.content_path(), "wb") as f:
            f.write(data)
        want = set(range(ec.get_chunk_count()))
        encoded = ec.encode(want, data)
        for i, chunk in encoded.items():
            with open(self.chunk_path(i), "wb") as f:
                f.write(chunk.tobytes())
        return 0

    def decode_erasures(self, ec, erasures, encoded) -> int:
        available = {
            i: c for i, c in encoded.items() if i not in erasures
        }
        decoded = ec.decode(set(erasures), available)
        for e in erasures:
            if not np.array_equal(decoded[e], encoded[e]):
                print(f"chunk {e} incorrectly recovered", file=sys.stderr)
                return 1
        return 0

    def run_check(self) -> int:
        ec = self.codec()
        with open(self.content_path(), "rb") as f:
            data = f.read()
        want = set(range(ec.get_chunk_count()))
        encoded = ec.encode(want, data)
        for i, chunk in encoded.items():
            with open(self.chunk_path(i), "rb") as f:
                existing = f.read()
            if existing != chunk.tobytes():
                print(f"chunk {i} encodes differently", file=sys.stderr)
                return 1
        # single erasure: specific code path in every plugin
        if self.decode_erasures(ec, {0}, encoded):
            return 1
        if ec.get_chunk_count() - ec.get_data_chunk_count() > 1:
            # two erasures: the general case
            if self.decode_erasures(
                ec, {0, ec.get_chunk_count() - 1}, encoded
            ):
                return 1
        return 0


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if not args.create and not args.check:
        print("must specify either --check, or --create", file=sys.stderr)
        return 1
    nr = NonRegression(args)
    if args.create:
        ret = nr.run_create()
        if ret:
            return ret
    if args.check:
        ret = nr.run_check()
        if ret:
            return ret
    return 0


if __name__ == "__main__":
    sys.exit(main())
