#!/usr/bin/env python
"""ceph_erasure_code_benchmark-compatible CLI.

Same flags and output format as the reference tool (reference:
src/test/erasure-code/ceph_erasure_code_benchmark.cc:39-137 setup,
:150-188 encode, :253-327 decode): prints ``<elapsed_seconds>\\t<KiB>`` where
KiB = iterations * size / 1024, so throughput = KiB / seconds.

Examples:
    python tools/ec_benchmark.py --plugin tpu --workload encode \\
        --size 4194304 --iterations 10 --parameter k=8 --parameter m=4
    python tools/ec_benchmark.py --workload decode --erasures-generation \\
        exhaustive --erasures 2 --parameter k=4 --parameter m=2
"""

from __future__ import annotations

import argparse
import random
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ceph_tpu.plugins import registry as registry_mod  # noqa: E402


def parse_args(argv):
    p = argparse.ArgumentParser(description="erasure code benchmark")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-s", "--size", type=int, default=1024 * 1024,
                   help="size of the buffer to be encoded")
    p.add_argument("-i", "--iterations", type=int, default=1,
                   help="number of encode/decode runs")
    p.add_argument("-p", "--plugin", default="jerasure",
                   help="erasure code plugin name")
    p.add_argument("-w", "--workload", default="encode",
                   choices=["encode", "decode", "storage-path",
                            "cluster-path", "tier-path",
                            "recovery-path", "repair-path", "elastic-path",
                            "mesh-path",
                            "trace-path",
                            "qos-path", "telemetry-path", "wire-tax"])
    p.add_argument("--smoke", action="store_true",
                   help="qos-path/telemetry-path/repair-path/elastic-path: "
                        "the "
                        "fast CI shape (shrunk client counts, object "
                        "counts and durations, loose overhead limits) "
                        "instead of the full acceptance run")
    p.add_argument("--stages", default=None,
                   choices=["overload", "chaos", "scale"],
                   help="qos-path only: run a single sub-stage")
    p.add_argument("--mesh-sizes", default="1,2,4,8",
                   help="mesh-path only: comma-separated mesh device "
                        "counts to sweep")
    p.add_argument("-e", "--erasures", type=int, default=1,
                   help="number of erasures when decoding")
    p.add_argument("--erased", type=int, action="append", default=[],
                   help="erased chunk (repeatable)")
    p.add_argument("-E", "--erasures-generation", default="random",
                   choices=["random", "exhaustive"])
    p.add_argument("-P", "--parameter", action="append", default=[],
                   help="add a parameter to the erasure code profile")
    p.add_argument("--erasure-code-dir", default="",
                   help="plugin directory (out-of-tree plugins)")
    p.add_argument("-b", "--batch", type=int, default=0,
                   help="stripes per iteration through the batched/pipelined "
                        "plugin API (encode_batch/decode_batch); bytes "
                        "processed scale by the batch size. 0 = reference "
                        "per-call loop")
    p.add_argument("--writers", type=int, default=8,
                   help="concurrent writers for --workload storage-path")
    p.add_argument("--objects", type=int, default=64,
                   help="objects per storage-path pass")
    p.add_argument("--profile", action="store_true",
                   help="storage-path only: print the per-stage transfer "
                        "ledger (h2d/d2h ops+bytes, jit retraces, granules, "
                        "h2d-per-granule) as one JSON object instead of the "
                        "full result -- the CI transfer-regression probe "
                        "(tools/ci_lint.sh smoke mode).  Exits nonzero on "
                        "any steady-state retrace (the harness gate)")
    p.add_argument("--payload", default="X", choices=["X", "random"],
                   help="payload contents: 'X' matches the reference tool "
                        "(ceph_erasure_code_benchmark.cc:173); 'random' "
                        "defeats transport-level compression. NOTE: either "
                        "way each iteration re-encodes the same buffer, so "
                        "the tpu plugin's content-addressed upload cache "
                        "still elides repeat H2D (the analogue of the CPU "
                        "codec re-reading an LLC-resident buffer); set "
                        "CEPH_TPU_NO_H2D_CACHE=1 to force a fresh upload "
                        "every iteration")
    return p.parse_args(argv)


def display_chunks(chunks, chunk_count):
    out = "chunks "
    for c in range(chunk_count):
        out += f"({c})  " if c not in chunks else f" {c}  "
    print(out + "(X) is an erased chunk")


def decode_erasures(all_chunks, chunks, i, want_erasures, ec, verbose):
    """Recursive exhaustive erasure enumeration (reference :205-252)."""
    if want_erasures == 0:
        if verbose:
            display_chunks(chunks, ec.get_chunk_count())
        want_to_read = set(range(ec.get_chunk_count())) - set(chunks.keys())
        decoded = ec.decode(want_to_read, chunks)
        for chunk in want_to_read:
            if not np.array_equal(all_chunks[chunk], decoded[chunk]):
                print(
                    f"chunk {chunk} content and recovered content are different",
                    file=sys.stderr,
                )
                return -1
        return 0
    for j in range(i, ec.get_chunk_count()):
        one_less = dict(chunks)
        one_less.pop(j, None)
        code = decode_erasures(
            all_chunks, one_less, j + 1, want_erasures - 1, ec, verbose
        )
        if code:
            return code
    return 0


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    profile = {}
    for param in args.parameter:
        if param.count("=") != 1:
            print(f"--parameter {param} ignored (needs exactly one =)",
                  file=sys.stderr)
            continue
        key, val = param.split("=")
        profile[key] = val

    if args.workload == "mesh-path":
        # Mesh-scaling stage (round 15): the full TCP cluster path and
        # the PG-sliced SPMD encode dispatch swept over mesh device
        # counts (osd_mesh_data_plane on) vs the TCP-only baseline.
        # Correctness-gated inside the harness: bit-exact read-back,
        # byte-identical shards across configurations, monotone
        # wire-bytes-avoided, and ZERO steady-state retraces in the
        # timed pass -- any steady retrace raises, so the PR-8 ledger
        # contract is this command's exit code (tools/ci_lint.sh runs
        # it as the multichip smoke).  The pool profile is fixed
        # (k=2 m=2 tpu unless -P overrides); --objects/--size scale
        # the payload set.
        import json

        from ceph_tpu.msg.mesh_bench import run_mesh_path_bench

        sizes = tuple(int(t) for t in args.mesh_sizes.split(",") if t)
        result = run_mesh_path_bench(
            n_objects=args.objects, obj_bytes=args.size,
            writers=args.writers, iters=max(1, args.iterations),
            mesh_sizes=sizes or (1, 2, 4, 8),
            k=int(profile.get("k", "2")), m=int(profile.get("m", "2")),
        )
        if args.profile:
            print(json.dumps({
                "workload": "mesh-path",
                "k": result["k"], "m": result["m"],
                "mesh_sizes": result["mesh_sizes"],
                "bit_exact": result["bit_exact"],
                "steady_jit_retraces": result["steady_jit_retraces"],
                "wire_bytes_avoided": result["wire_bytes_avoided"],
                "wire_bytes_sent": result["wire_bytes_sent"],
            }))
            return 1 if result["steady_jit_retraces"] else 0
        print(json.dumps(result))
        print(
            f"mesh-path k={result['k']} m={result['m']} "
            f"{args.objects}x{args.size}B over TCP: speedup vs mesh_1 "
            f"{result['speedup_vs_mesh1']}, wire bytes avoided "
            f"{result['wire_bytes_avoided']}, encode GiB/s "
            f"{result['encode_GiBs']}", file=sys.stderr,
        )
        return 1 if result["steady_jit_retraces"] else 0

    if args.workload == "qos-path":
        # Unified-QoS scale stage (round 17): the loadgen harness over
        # real TCP -- reservation-floor overload proof, thrash/rebuild
        # chaos with the exactly-once audit, and the >=1000-client
        # saturation run (--smoke shrinks every sub-stage; the gates
        # stay armed and any violation raises -> nonzero exit, which is
        # how tools/ci_lint.sh consumes it).
        import json

        from ceph_tpu.osd.qos_bench import run_qos_path_bench

        result = run_qos_path_bench(smoke=args.smoke, stages=args.stages)
        print(json.dumps(result))
        print(
            f"qos-path{' (smoke)' if args.smoke else ''}: "
            f"{result.get('qos_path_clients', '?')} clients, saturation "
            f"p99 {result.get('qos_path_saturation_p99_ms', '?')}ms, "
            f"reservation ratio "
            f"{result.get('qos_path_reservation_ratio', '?')}, fairness "
            f"spread {result.get('qos_path_fairness_spread_max', '?')}, "
            f"cas exact {result.get('qos_path_cas_exact', '?')}",
            file=sys.stderr,
        )
        return 0

    if args.workload == "telemetry-path":
        # Wire-fed telemetry stage (round 18): MgrClient report-loop
        # overhead vs reports-off on the storage-path workload,
        # exposition scrape-parse roundtrip, and the chaos health gate
        # (mid-run OSD wipe -> PG_DEGRADED draining monotonically to
        # HEALTH_OK over real TCP).  Any gate violation exits nonzero.
        import json

        from ceph_tpu.mgr.telemetry_bench import run_telemetry_bench

        result = run_telemetry_bench(
            n_objects=args.objects, obj_bytes=args.size,
            writers=args.writers, iters=max(1, args.iterations),
            smoke=args.smoke,
        )
        print(json.dumps(result))
        print(
            f"telemetry-path: report-loop overhead "
            f"{result['telemetry_overhead_pct']}% "
            f"(limit {result['overhead_limit_pct']}%), "
            f"{result['reports_folded']} reports folded, chaos "
            f"degraded peak {result['chaos']['degraded_max']} -> "
            f"{result['chaos']['health_final']}",
            file=sys.stderr,
        )
        return 0

    if args.workload == "wire-tax":
        # Wire-tax attribution stage (round 19): the saturated cluster
        # path under the hot-path profiler (ceph_tpu/profiling/) --
        # decomposition coverage >=90%, enabled overhead <=3%, off-mode
        # allocations exactly zero, speedscope export contract.  Any
        # gate violation exits nonzero.
        import json

        from ceph_tpu.profiling.wire_tax_bench import run_wire_tax_bench

        if args.smoke:
            result = run_wire_tax_bench(
                n_objects=8, obj_bytes=4096, writers=4, iters=1,
                coverage_min_pct=50.0, overhead_limit_pct=50.0,
                codec_gain_min=0.5, codec_share_ratio_max=0.95)
        else:
            result = run_wire_tax_bench(
                n_objects=args.objects, obj_bytes=args.size,
                writers=args.writers, iters=max(1, args.iterations))
        print(json.dumps(result))
        top = ", ".join(
            f"{r['stage']} {r['pct']}%" for r in result["wire_tax_top"])
        print(
            f"wire-tax: {result['wire_tax_ops_per_sec']} ops/s "
            f"decomposed at {result['wire_tax_coverage_pct']}% "
            f"coverage (enabled overhead "
            f"{result['wire_tax_overhead_pct_enabled']}%, off allocs "
            f"{result['wire_tax_alloc_blocks_off']}, native-codec "
            f"gain {result.get('wire_codec_gain')}x at share ratio "
            f"{result.get('wire_codec_share_ratio')}); top: {top}",
            file=sys.stderr,
        )
        return 0

    if args.workload == "repair-path":
        # Regenerating-repair stage: rebuild a wiped OSD on a
        # product-matrix MSR pool (plugin regen, k=4 m=3, d=2k-2=6)
        # through the beta-fractional repair lane vs the classic
        # full-stripe gather on the SAME pool.  Chaos sequence
        # (wipe -> degraded peak -> monotone drain -> clean),
        # bit-exactness, cross-mode shard bytes, measured
        # gather-bytes ratio <= 0.75 and time-to-clean no worse are
        # all gated before any number is printed.  Prints one JSON
        # line (the shape bench.py records as repair_path_*);
        # --smoke runs the tiny CI shape.
        import json

        from ceph_tpu.osd.repair_bench import run_repair_path_bench

        if args.smoke:
            result = run_repair_path_bench(
                n_osds=8, n_objects=8, obj_bytes=6 << 10)
        else:
            result = run_repair_path_bench(
                n_objects=args.objects, obj_bytes=args.size)
        print(json.dumps(result))
        print(
            f"repair-path {result['n_objects']}x{result['obj_bytes']}B:"
            f" gather ratio {result['repair_bytes_ratio']} "
            f"(gate 0.75), time-to-clean ratio "
            f"{result['time_to_clean_ratio']}, "
            f"{result['bytes_saved']} repair bytes saved, "
            f"{result['fractional']['counters']['regen_helpers_served']}"
            " helper symbols served",
            file=sys.stderr,
        )
        return 0

    if args.workload == "elastic-path":
        # Elastic membership stage: +2-OSD online expansion under
        # client load (movement <= 1.25x the theoretical-minimum
        # bytes, misplaced peak -> monotone drain -> HEALTH_OK,
        # bounded client p99), then three chaos arms on the SAME
        # cluster: kill the backfill target mid-migration, rm a live
        # primary under load, add-then-immediately-rm flapping.
        # Bit-exact reads and an exactly-once write audit gate every
        # stage before any number is printed.  Prints one JSON line
        # (the shape bench.py records as elastic_path_*); --smoke
        # runs the tiny CI shape.
        import json

        from ceph_tpu.osd.elastic_bench import run_elastic_path_bench

        result = run_elastic_path_bench(smoke=args.smoke)
        print(json.dumps(result))
        print(
            f"elastic-path {result['n_osds']}osd "
            f"{result['n_objects']}x{result['obj_bytes']}B "
            f"{result['n_clients']}cl: moved ratio "
            f"{result['data_moved_ratio']} (gate 1.25), "
            f"time-to-clean {result['time_to_clean_s']}s, "
            f"client p99 {result['client_p99_during_expansion_ms']}ms, "
            f"misplaced peak {result['misplaced_peak']} "
            f"({result['misplaced_upticks']} upticks), chaos "
            f"kill/rm/flap rounds "
            f"{result['chaos']['target_kill']['rounds']}/"
            f"{result['chaos']['primary_rm']['rounds']}/"
            f"{result['chaos']['flap']['rounds']}",
            file=sys.stderr,
        )
        return 0

    k = int(profile.get("k", "0"))
    m = int(profile.get("m", "0"))
    if k <= 0:
        print(f"parameter k is {k}. But k needs to be > 0.")
        return -22
    if m < 0:
        print(f"parameter m is {m}. But m needs to be >= 0.")
        return -22

    registry = registry_mod.instance()
    registry.disable_dlclose = True
    ec = registry.factory(args.plugin, profile, args.erasure_code_dir)

    if (
        ec.get_data_chunk_count() != k
        or ec.get_chunk_count() - ec.get_data_chunk_count() != m
    ):
        print(
            f"parameter k is {k}/m is {m}. But data chunk count is "
            f"{ec.get_data_chunk_count()}/parity chunk count is "
            f"{ec.get_chunk_count() - ec.get_data_chunk_count()}"
        )
        return -22

    if args.payload == "random":
        payload = np.random.RandomState(42).randint(
            0, 256, size=args.size, dtype=np.uint8
        )
    else:
        payload = np.full(args.size, ord("X"), dtype=np.uint8)
    want = set(range(ec.get_chunk_count()))

    if args.workload == "storage-path":
        # Host OSD storage-path stage (round 6): assemble -> transpose ->
        # encode -> commit (+ signature-grouped degraded decode) with
        # concurrent writers, coalescing on vs off, bit-exactness gated
        # before timing.  Prints one JSON line with the per-stage
        # breakdown (the shape bench.py records in the round JSON).
        import json

        from ceph_tpu.osd.storage_bench import run_storage_path_bench

        result = run_storage_path_bench(
            ec, n_objects=args.objects, obj_bytes=args.size,
            writers=args.writers, iters=max(1, args.iterations),
        )
        if args.profile:
            # the transfer-ledger cut of the result: what CI diffs to
            # catch residency regressions (a steady-state retrace
            # already raised inside the harness -> nonzero exit)
            print(json.dumps({
                "workload": "storage-path",
                "k": result["k"], "m": result["m"],
                "n_objects": result["n_objects"],
                "obj_bytes": result["obj_bytes"],
                "bit_exact": result["bit_exact"],
                "steady_jit_retraces": result["steady_jit_retraces"],
                "ledger": {
                    mode: result[mode]["residency"]
                    for mode in ("per_op", "coalesced")
                },
                "write_h2d_per_granule": (
                    result["coalesced"]["residency"]["write"]
                    ["h2d_per_granule"]),
            }))
            return 0
        print(json.dumps(result))
        print(
            f"storage-path k={result['k']} m={result['m']} "
            f"{args.objects}x{args.size}B x{args.writers} writers: "
            f"coalesced write {result['coalesced']['write_GiBs']:.4f} "
            f"GiB/s ({result['write_speedup']}x per-op), read "
            f"{result['coalesced']['read_GiBs']:.4f} GiB/s "
            f"({result['read_speedup']}x)", file=sys.stderr,
        )
        return 0

    if args.workload == "cluster-path":
        # Distributed storage-path stage (round 8): client Objecter ->
        # primary OSD -> k+m sub-op fan-out over REAL localhost TCP,
        # per-message wire vs corked/zero-copy wire (piggybacked acks),
        # bit-exactness gated before timing, plus the messenger-level
        # wire stage and wire-shape counters.  Prints one JSON line
        # (the shape bench.py records as cluster_path_host_*).
        import json

        from ceph_tpu.msg.cluster_bench import run_cluster_path_bench

        result = run_cluster_path_bench(
            ec, n_objects=args.objects, obj_bytes=args.size,
            writers=args.writers, iters=max(1, args.iterations),
        )
        print(json.dumps(result))
        wc = result["wire_corked"]["counters"]
        print(
            f"cluster-path k={result['k']} m={result['m']} "
            f"{args.objects}x{args.size}B x{args.writers} writers over "
            f"TCP: corked write {result['corked']['write_MiBs']:.3f} "
            f"MiB/s ({result['write_speedup']}x per-message), wire "
            f"stage {result['wire_write_speedup']}x "
            f"({wc['frames_per_burst']} frames/burst, "
            f"{wc['ack_piggyback_ratio']} acks piggybacked)",
            file=sys.stderr,
        )
        return 0

    if args.workload == "recovery-path":
        # Background data-plane stage (round 14): rebuild two wiped
        # OSDs' shards through the batched recovery coalescer vs the
        # per-object windowed path, with a concurrent client workload
        # on the mClock queues; bit-exactness + cross-mode shard bytes
        # + client-p99 bound gated before any number is printed.
        # Prints one JSON line (the shape bench.py records as
        # recovery_path_host_*).  The cluster profile is fixed (k=4
        # m=2 tpu plugin, cpu-fallback safe); --objects/--size scale
        # the rebuilt set.
        import json

        from ceph_tpu.osd.recovery_bench import run_recovery_path_bench

        result = run_recovery_path_bench(
            n_objects=args.objects, obj_bytes=args.size,
        )
        print(json.dumps(result))
        print(
            f"recovery-path {args.objects}x{args.size}B: batched "
            f"time-to-clean {result['batched']['time_to_clean_s']:.3f}s "
            f"({result['rebuild_speedup']}x per-object), client p99 "
            f"{result['batched']['client_p99_ms']}ms during rebuild, "
            f"{result['batched']['counters']['recovery_ops_batched']} "
            f"objects through the batched lane",
            file=sys.stderr,
        )
        return 0

    if args.workload == "trace-path":
        # Observability stage (round 16): the same storage-path +
        # cluster-path workload under trace_mode off/sampled/full,
        # correctness-gated (stitched cross-daemon trace, timeline
        # segments summing to end-to-end, slow-op detection, zero
        # unfinished spans) and FAILING if sampled-mode overhead
        # exceeds the gate.  Prints one JSON line (the shape bench.py
        # records as trace_path_host_*).
        import json

        from ceph_tpu.osd.trace_bench import run_trace_overhead_bench

        result = run_trace_overhead_bench(
            ec, n_objects=args.objects, obj_bytes=args.size,
            writers=args.writers, iters=max(1, args.iterations),
        )
        print(json.dumps(result))
        print(
            f"trace-path {args.objects}x{args.size}B x{args.writers} "
            f"writers: sampled overhead "
            f"{result['trace_overhead_pct_sampled']}% / full "
            f"{result['trace_overhead_pct_full']}% vs off, "
            f"{result['stitched']['spans']} spans stitched, "
            f"{result['slow_ops_detected']} slow ops detected",
            file=sys.stderr,
        )
        return 0

    if args.workload == "tier-path":
        # Device cache-tier stage (round 9): hot tier-resident read (one
        # D2H + transpose from the shard-major device block) vs the cold
        # miss path (frombuffer ingest + degraded decode), bit-exactness
        # gated before timing.  Prints one JSON line (the shape bench.py
        # records as tier_path_host_*).
        import json

        from ceph_tpu.tier.tier_bench import run_tier_path_bench

        result = run_tier_path_bench(
            ec, n_objects=args.objects, obj_bytes=args.size,
            iters=max(1, args.iterations), erasures=args.erasures,
        )
        print(json.dumps(result))
        print(
            f"tier-path k={result['k']} m={result['m']} "
            f"{args.objects}x{args.size}B: hot read "
            f"{result['hot_read_GiBs']:.4f} GiB/s vs cold decode "
            f"{result['cold_read_GiBs']:.4f} GiB/s "
            f"({result['read_speedup']}x), "
            f"{result['resident_bytes']} bytes resident",
            file=sys.stderr,
        )
        return 0

    if args.batch and not hasattr(ec, "encode_batch"):
        print(f"plugin {args.plugin} has no batched API; ignoring --batch",
              file=sys.stderr)
        args.batch = 0

    if args.workload == "encode" and args.batch:
        stripes = [payload] * args.batch
        ec.encode_batch(stripes[:1])  # warm: compile + matrix upload
        begin = time.perf_counter()
        for _ in range(args.iterations):
            ec.encode_batch(stripes)
        elapsed = time.perf_counter() - begin
        print(f"{elapsed:.6f}\t{args.iterations * args.batch * (args.size // 1024)}")
        return 0
    if args.workload == "decode" and args.batch:
        encoded = ec.encode(want, payload)
        rng = random.Random(7)
        maps = []
        for _ in range(args.batch):
            chunks = dict(encoded)
            for _ in range(args.erasures):
                while True:
                    erasure = rng.randrange(ec.get_chunk_count())
                    if erasure in chunks:
                        break
                del chunks[erasure]
            maps.append(chunks)
        ec.decode_batch(maps[:1])  # warm
        begin = time.perf_counter()
        for _ in range(args.iterations):
            ec.decode_batch(maps)
        elapsed = time.perf_counter() - begin
        print(f"{elapsed:.6f}\t{args.iterations * args.batch * (args.size // 1024)}")
        return 0

    if args.workload == "encode":
        # One untimed call first: the reference codec builds its GF tables in
        # prepare() before the timer starts (ceph_erasure_code_benchmark.cc:
        # setup vs :179); our XLA compile is the same one-time setup but is
        # triggered lazily by the first call, so it must not pollute the
        # steady-state measurement. Applies to every plugin equally.
        ec.encode(want, payload)
        begin = time.perf_counter()
        for _ in range(args.iterations):
            ec.encode(want, payload)
        elapsed = time.perf_counter() - begin
    else:
        encoded = ec.encode(want, payload)
        if args.erased:
            for e in args.erased:
                encoded.pop(e, None)
            display_chunks(encoded, ec.get_chunk_count())
        begin = time.perf_counter()
        for _ in range(args.iterations):
            if args.erasures_generation == "exhaustive":
                code = decode_erasures(
                    encoded, encoded, 0, args.erasures, ec, args.verbose
                )
                if code:
                    return code
            elif args.erased:
                ec.decode(want, encoded)
            else:
                chunks = dict(encoded)
                for _ in range(args.erasures):
                    while True:
                        erasure = random.randrange(ec.get_chunk_count())
                        if erasure in chunks:
                            break
                    del chunks[erasure]
                ec.decode(want, chunks)
        elapsed = time.perf_counter() - begin

    print(f"{elapsed:.6f}\t{args.iterations * (args.size // 1024)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
