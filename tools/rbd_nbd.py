#!/usr/bin/env python3
"""rbd-nbd: serve a pool's RBD images over the NBD protocol.

Reference: src/tools/rbd_nbd/rbd-nbd.cc (`rbd-nbd map`).  This serves
the standard fixed-newstyle NBD protocol on a TCP port; attach with any
NBD client, e.g.:

    nbd-client 127.0.0.1 <port> /dev/nbd0 -name <image>
    qemu-nbd --connect=... / nbdfuse mnt 'nbd://127.0.0.1:<port>/<image>'

Usage:
  rbd_nbd.py --dir RUN [--port P]          serve a vstart cluster's pool
  (runs until SIGINT/SIGTERM; prints the bound port when up)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ceph_tpu.daemon.client import RemoteClient  # noqa: E402
from ceph_tpu.rbd.nbd import NBDServer  # noqa: E402
from ceph_tpu.utils import aio  # noqa: E402


async def serve(args) -> None:
    conf = await aio.read_json(os.path.join(args.dir, "cluster.json"))
    keyring = os.path.join(args.dir, "keyring")
    c = await RemoteClient.connect(
        os.path.join(args.dir, "addr_map.json"), dict(conf["profile"]),
        keyring=keyring if conf.get("auth") and os.path.exists(keyring)
        else None,
    )
    srv = NBDServer(c.backend, port=args.port)
    port = await srv.start()
    print(f"nbd server up on 127.0.0.1:{port}", flush=True)
    stop = asyncio.get_event_loop().create_future()
    for sig in (signal.SIGTERM, signal.SIGINT):
        asyncio.get_event_loop().add_signal_handler(
            sig, lambda: stop.done() or stop.set_result(True))
    await stop
    await srv.stop()
    await c.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", required=True,
                    help="vstart run directory (addr_map/cluster.json)")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    try:
        asyncio.run(serve(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
