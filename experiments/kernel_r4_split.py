"""Software-pipelining probe: split each tile into independent half-chains
so Mosaic's scheduler can overlap the VPU plane extraction of one half with
the MXU dots of the other. Also checks DEFAULT-precision correctness (the
2-field values 65536/65537 are not bf16-representable, so DEFAULT should
MISMATCH -- documenting why HIGHEST is required)."""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ceph_tpu.matrices import reed_sol
from ceph_tpu.matrices.bitmatrix import matrix_to_bitmatrix
from ceph_tpu.ops.pallas_gf import _matrix_encode_call, prep_matrix_w8

K, M, W = 8, 4, 8
ITERS = 512


def _cdiv(a, b):
    return -(-a // b)


def _half(b_ref, x, prec):
    mask = jnp.int32(0x00010001)
    lo = jnp.concatenate(
        [((x >> s) & mask).astype(jnp.float32) for s in range(8)], axis=0
    )
    hi = jnp.concatenate(
        [((x >> (8 + s)) & mask).astype(jnp.float32) for s in range(8)], axis=0
    )
    dn = (((1,), (0,)), ((), ()))
    accL = jax.lax.dot_general(
        b_ref[:], lo, dn, precision=prec, preferred_element_type=jnp.float32
    ).astype(jnp.int32)
    accH = jax.lax.dot_general(
        b_ref[:], hi, dn, precision=prec, preferred_element_type=jnp.float32
    ).astype(jnp.int32)
    return accL + (accH << 8)


def _kernel_split(b_ref, x_ref, o_ref, *, k: int, m: int, parts: int, prec):
    x = x_ref[:]
    t = x.shape[-1]
    h = t // parts
    zs = [_half(b_ref, x[:, i * h:(i + 1) * h], prec) for i in range(parts)]
    z = jnp.concatenate(zs, axis=-1)
    pb = z & jnp.int32(0x01010101)
    ob = pb.reshape(m, 8, t)
    packed = ob[:, 0, :]
    for l in range(1, 8):
        packed = packed | (ob[:, l, :] << l)
    o_ref[:] = packed


def run(name, call, d32, ref, nbytes):
    out = np.asarray(jax.device_get(call(d32)))
    ok = bool((out == ref).all())

    @jax.jit
    def many(d):
        def body(c, _):
            p = call(c)
            return c.at[0, :].set(p[0, :] ^ c[0, :]), ()

        d, _ = jax.lax.scan(body, d, None, length=ITERS)
        return d

    w = many(d32)
    jax.block_until_ready(w)
    t0 = time.perf_counter()
    w = many(w)
    jax.block_until_ready(w)
    dt = (time.perf_counter() - t0) / ITERS
    print(
        f"{name:28s} {'bit-exact' if ok else 'MISMATCH '} "
        f"{nbytes / dt / (1<<30):7.2f} GiB/s", flush=True,
    )


def main():
    Mmat = reed_sol.vandermonde_coding_matrix(K, M, W)
    bits = matrix_to_bitmatrix(Mmat, W)
    Bp = jnp.asarray(prep_matrix_w8(bits, K))
    rng = np.random.RandomState(0)
    chunk = 8 << 20
    data_np = rng.randint(0, 256, size=(K, chunk), dtype=np.uint8)
    d32 = jax.device_put(jnp.asarray(data_np.view(np.int32)))
    n4 = d32.shape[1]
    ref = np.asarray(jax.device_get(_matrix_encode_call(Bp, d32, K, M, 4096)))

    for parts, tile, prec_name, prec in (
        (2, 8192, "HIGHEST", jax.lax.Precision.HIGHEST),
        (4, 16384, "HIGHEST", jax.lax.Precision.HIGHEST),
        (8, 16384, "HIGHEST", jax.lax.Precision.HIGHEST),
        (1, 16384, "DEFAULT", jax.lax.Precision.DEFAULT),
    ):
        @jax.jit
        def call(d, parts=parts, tile=tile, prec=prec):
            return pl.pallas_call(
                functools.partial(
                    _kernel_split, k=K, m=M, parts=parts, prec=prec
                ),
                out_shape=jax.ShapeDtypeStruct((M, n4), jnp.int32),
                grid=(_cdiv(n4, tile),),
                in_specs=[
                    pl.BlockSpec((M * 8, K * 8), lambda i: (0, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((K, tile), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((M, tile), lambda i: (0, i),
                                       memory_space=pltpu.VMEM),
            )(Bp, d)

        run(f"split{parts} tile={tile} {prec_name}", call, d32, ref,
            data_np.nbytes)


if __name__ == "__main__":
    main()
