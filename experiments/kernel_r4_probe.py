"""Split-cost probes for the packed-lane kernel: where does the time go?

probe_extract   planes extraction only (16 shift/and/f32-convert per lane),
                cheap non-MXU reduction to force materialization
probe_mxu       dots+merge only, planes pre-extracted on device (input is
                the [2,8k,T] f32 plane tensor; no extraction in-kernel)
probe_full      production kernel (reference point)

Also sweeps tile width for the production kernel.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ceph_tpu.matrices import reed_sol
from ceph_tpu.matrices.bitmatrix import matrix_to_bitmatrix
from ceph_tpu.ops.pallas_gf import _matrix_encode_call, prep_matrix_w8

K, M, W = 8, 4, 8
ITERS = 512


def _cdiv(a, b):
    return -(-a // b)


def _extract_kernel(x_ref, o_ref, *, k: int, m: int):
    x = x_ref[:]
    mask = jnp.int32(0x00010001)
    lo = jnp.concatenate(
        [((x >> s) & mask).astype(jnp.float32) for s in range(8)], axis=0
    )
    hi = jnp.concatenate(
        [((x >> (8 + s)) & mask).astype(jnp.float32) for s in range(8)], axis=0
    )
    # cheap merge, no MXU: fold 8k rows into m rows by strided XOR of casts
    acc = lo[: m, :] + hi[: m, :]
    for r in range(m, 8 * k, m):
        acc = acc + lo[r:r + m, :] + hi[r:r + m, :]
    o_ref[:] = acc.astype(jnp.int32)


def _mxu_kernel(b_ref, p_ref, o_ref, *, k: int, m: int):
    dn = (((1,), (0,)), ((), ()))
    lo = p_ref[0]
    hi = p_ref[1]
    accL = jax.lax.dot_general(
        b_ref[:], lo, dn, precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    accH = jax.lax.dot_general(
        b_ref[:], hi, dn, precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    z = accL + (accH << 8)
    pb = z & jnp.int32(0x01010101)
    t = pb.shape[-1]
    ob = pb.reshape(m, 8, t)
    packed = ob[:, 0, :]
    for l in range(1, 8):
        packed = packed | (ob[:, l, :] << l)
    o_ref[:] = packed


def timeit(fn, init, iters=ITERS, feedback=True):
    @jax.jit
    def many(d):
        def body(c, _):
            p = fn(c)
            if feedback:
                return c.at[0, :].set(p[0, :] ^ c[0, :]), ()
            return c, ()

        d, _ = jax.lax.scan(body, d, None, length=iters)
        return d

    w = many(init)
    jax.block_until_ready(w)
    t0 = time.perf_counter()
    w = many(w)
    jax.block_until_ready(w)
    return (time.perf_counter() - t0) / iters


def main():
    Mmat = reed_sol.vandermonde_coding_matrix(K, M, W)
    bits = matrix_to_bitmatrix(Mmat, W)
    Bp = jnp.asarray(prep_matrix_w8(bits, K))
    rng = np.random.RandomState(0)
    chunk = 8 << 20
    data_np = rng.randint(0, 256, size=(K, chunk), dtype=np.uint8)
    d32 = jax.device_put(jnp.asarray(data_np.view(np.int32)))
    n4 = d32.shape[1]
    nbytes = data_np.nbytes

    # full kernel, tile sweep
    for tile in (2048, 4096, 8192, 16384):
        fn = lambda d, t=tile: _matrix_encode_call(Bp, d, K, M, t)
        dt = timeit(fn, d32)
        print(f"full  tile={tile:6d}  {nbytes / dt / (1<<30):7.2f} GiB/s", flush=True)

    # extraction-only
    tile = 4096

    @jax.jit
    def extract(d):
        return pl.pallas_call(
            functools.partial(_extract_kernel, k=K, m=M),
            out_shape=jax.ShapeDtypeStruct((M, n4), jnp.int32),
            grid=(_cdiv(n4, tile),),
            in_specs=[pl.BlockSpec((K, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((M, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
        )(d)

    dt = timeit(extract, d32)
    print(f"extract-only      {nbytes / dt / (1<<30):7.2f} GiB/s", flush=True)

    # mxu-only: input is the pre-extracted plane tensor [2, 8K, T] f32
    planes_np = np.zeros((2, 8 * K, n4), np.float32)
    x = data_np.view(np.int32).astype(np.int64)
    for s in range(8):
        planes_np[0, s * K:(s + 1) * K, :] = ((x >> s) & 0x00010001)
        planes_np[1, s * K:(s + 1) * K, :] = ((x >> (8 + s)) & 0x00010001)
    # NB plane-major rows must match prep order (s*k + j): rows above are
    # [s,K-block] == s*K + j. matches.
    planes = jax.device_put(jnp.asarray(planes_np))

    @jax.jit
    def mxu(p):
        return pl.pallas_call(
            functools.partial(_mxu_kernel, k=K, m=M),
            out_shape=jax.ShapeDtypeStruct((M, n4), jnp.int32),
            grid=(_cdiv(n4, tile),),
            in_specs=[
                pl.BlockSpec((M * 8, K * 8), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((2, 8 * K, tile), lambda i: (0, 0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((M, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
        )(Bp, p)

    @jax.jit
    def mxu_loop(p):
        def body(c, _):
            o = mxu(c)
            return c.at[0, 0, :].set(o[0, :].astype(jnp.float32) + c[0, 0, :]), ()

        p, _ = jax.lax.scan(body, p, None, length=ITERS)
        return p

    w = mxu_loop(planes)
    jax.block_until_ready(w)
    t0 = time.perf_counter()
    w = mxu_loop(w)
    jax.block_until_ready(w)
    dt = (time.perf_counter() - t0) / ITERS
    print(f"mxu-only (per data-byte equiv) {nbytes / dt / (1<<30):7.2f} GiB/s",
          flush=True)


if __name__ == "__main__":
    main()
