"""Round-4 device-kernel experiments: close the gap to the op-count model.

Hypothesis (PERF_NOTES round 4): the round-1 packed-lane kernel is
MXU-issue-bound, not bandwidth-bound. Its two dots are [32,64]x[64,T]
f32 with precision=HIGHEST: the 32x64 operand pads to the 128x128
systolic array (1/8 utilization) and HIGHEST on values {0,1,65536,65537}
forces the multi-pass f32 path (~6 passes on v5e). 197e12/2 MACs/s
/ 8 (padding) / 6 (passes) = 2.05e12 useful MACs/s; the kernel needs
128 MACs per data byte -> ~16 GiB/s predicted, ~18.4 measured. The fix
is to make the operand values {0,1} (exact in bf16, single pass) and/or
leave the MXU entirely (static XOR network on the VPU).

Variants (all bit-exact-checked against the production kernel):
  base          round-1 packed-lane kernel (2x f32-HIGHEST dots)
  bf16_4dot     4 single-bit-plane dots [32,64]x[64,T] bf16 (one per byte pos)
  bf16_blockdiag one [128,256]x[256,T] bf16 dot, block-diagonal B
  int8_4dot     as bf16_4dot with int8 operands (MXU s8 path if supported)
  xornet        no MXU: static XOR network over packed int32 planes

Run: python experiments/kernel_r4.py [--size-mib 8] [--iters 32]
"""

from __future__ import annotations

import argparse
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ceph_tpu.matrices import reed_sol
from ceph_tpu.matrices.bitmatrix import matrix_to_bitmatrix
from ceph_tpu.ops.pallas_gf import _matrix_encode_call, prep_matrix_w8

K, M, W = 8, 4, 8


def _cdiv(a, b):
    return -(-a // b)


# -- variant: single-bit planes, one dot per byte position ------------------


def _kernel_bf16_4dot(b_ref, x_ref, o_ref, *, k: int, m: int, dtype):
    x = x_ref[:]  # [k, T] int32
    one = jnp.int32(1)
    dn = (((1,), (0,)), ((), ()))
    out = jnp.zeros_like(x[:m, :])
    B = b_ref[:].astype(dtype)
    for b in range(4):
        planes = jnp.concatenate(
            [((x >> (8 * b + s)) & one).astype(dtype) for s in range(8)],
            axis=0,
        )  # [8k, T] values {0,1}
        acc = jax.lax.dot_general(
            B, planes, dn, preferred_element_type=jnp.float32
        ).astype(jnp.int32)  # sums <= 64: exact in bf16/f32
        pb = acc & one  # [m*8, T]
        t = pb.shape[-1]
        ob = pb.reshape(m, 8, t)
        byte = ob[:, 0, :]
        for l in range(1, 8):
            byte = byte | (ob[:, l, :] << l)
        out = out | (byte << (8 * b))
    o_ref[:] = out


def _kernel_int8_4dot(b_ref, x_ref, o_ref, *, k: int, m: int):
    x = x_ref[:]
    one = jnp.int32(1)
    dn = (((1,), (0,)), ((), ()))
    out = jnp.zeros_like(x[:m, :])
    B = b_ref[:].astype(jnp.int8)
    for b in range(4):
        planes = jnp.concatenate(
            [((x >> (8 * b + s)) & one).astype(jnp.int8) for s in range(8)],
            axis=0,
        )
        acc = jax.lax.dot_general(
            B, planes, dn, preferred_element_type=jnp.int32
        )
        pb = acc & one
        t = pb.shape[-1]
        ob = pb.reshape(m, 8, t)
        byte = ob[:, 0, :]
        for l in range(1, 8):
            byte = byte | (ob[:, l, :] << l)
        out = out | (byte << (8 * b))
    o_ref[:] = out


def _kernel_bf16_blockdiag(b_ref, x_ref, o_ref, *, k: int, m: int):
    # b_ref: [4*m*8, 4*8k] block-diagonal; one dot, full 128-row utilization
    x = x_ref[:]
    one = jnp.int32(1)
    dn = (((1,), (0,)), ((), ()))
    planes = jnp.concatenate(
        [
            ((x >> (8 * b + s)) & one).astype(jnp.bfloat16)
            for b in range(4)
            for s in range(8)
        ],
        axis=0,
    )  # [32k, T]
    acc = jax.lax.dot_general(
        b_ref[:].astype(jnp.bfloat16), planes, dn,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)  # [4*m*8, T]
    pb = acc & one
    t = pb.shape[-1]
    ob = pb.reshape(4, m, 8, t)
    out = jnp.zeros_like(x[:m, :])
    for b in range(4):
        byte = ob[b, :, 0, :]
        for l in range(1, 8):
            byte = byte | (ob[b, :, l, :] << l)
        out = out | (byte << (8 * b))
    o_ref[:] = out


def _make_xornet_kernel(bitmatrix: np.ndarray, k: int, m: int):
    """Static XOR network: B is a compile-time constant, no MXU.

    plane q[j][s] = (x[j] >> s) & 0x01010101 (bit s of all 4 byte
    positions); output row (mi, l) = XOR of planes in the bitmatrix row's
    support, then packed back over l.
    """
    B = bitmatrix.astype(bool)

    def kernel(x_ref, o_ref):
        x = x_ref[:]
        mask = jnp.int32(0x01010101)
        planes = {}
        for j in range(k):
            xr = x[j, :]
            for s in range(W):
                if B[:, j * W + s].any():
                    planes[(j, s)] = (xr >> s) & mask
        for mi in range(m):
            byte = None
            for l in range(W):
                row = B[mi * W + l]
                z = None
                for j in range(k):
                    for s in range(W):
                        if row[j * W + s]:
                            z = planes[(j, s)] if z is None else z ^ planes[(j, s)]
                zb = z << l if l else z
                byte = zb if byte is None else byte | zb
            o_ref[mi, :] = byte

    return kernel


def _call_variant(kernel, nin, nout, d32, tile, extra=None):
    n4 = d32.shape[1]
    in_specs = []
    args = []
    if extra is not None:
        in_specs.append(
            pl.BlockSpec(extra.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
        )
        args.append(extra)
    in_specs.append(
        pl.BlockSpec((nin, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    )
    args.append(d32)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((nout, n4), jnp.int32),
        grid=(_cdiv(n4, tile),),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((nout, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
    )(*args)


def build_variants(bits: np.ndarray, tile: int):
    """Return dict name -> jitted fn(d32)->parity32 [m, n4]."""
    Bp = jnp.asarray(prep_matrix_w8(bits, K))  # [m*8, 8k] shift-major
    Bblk = np.zeros((4 * M * W, 4 * W * K), np.float32)
    Bp_np = np.asarray(prep_matrix_w8(bits, K))
    for b in range(4):
        Bblk[b * M * W:(b + 1) * M * W, b * W * K:(b + 1) * W * K] = Bp_np
    Bblk = jnp.asarray(Bblk)

    variants = {}

    variants["base"] = jax.jit(
        lambda d: _matrix_encode_call(Bp, d, K, M, tile)
    )

    @jax.jit
    def bf16_4dot(d):
        return _call_variant(
            functools.partial(_kernel_bf16_4dot, k=K, m=M, dtype=jnp.bfloat16),
            K, M, d, tile, extra=Bp,
        )

    variants["bf16_4dot"] = bf16_4dot

    @jax.jit
    def f32_4dot(d):
        return _call_variant(
            functools.partial(_kernel_bf16_4dot, k=K, m=M, dtype=jnp.float32),
            K, M, d, tile, extra=Bp,
        )

    variants["f32_4dot"] = f32_4dot

    @jax.jit
    def bf16_blockdiag(d):
        return _call_variant(
            functools.partial(_kernel_bf16_blockdiag, k=K, m=M),
            K, M, d, tile, extra=Bblk,
        )

    variants["bf16_blockdiag"] = bf16_blockdiag

    @jax.jit
    def int8_4dot(d):
        return _call_variant(
            functools.partial(_kernel_int8_4dot, k=K, m=M),
            K, M, d, tile, extra=Bp,
        )

    variants["int8_4dot"] = int8_4dot

    xk = _make_xornet_kernel(np.asarray(bits), K, M)

    @jax.jit
    def xornet(d):
        return _call_variant(xk, K, M, d, tile)

    variants["xornet"] = xornet
    return variants


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mib", type=int, default=8)
    ap.add_argument("--iters", type=int, default=32)
    ap.add_argument("--tile", type=int, default=4096)
    ap.add_argument("--only", type=str, default="")
    args = ap.parse_args()

    Mmat = reed_sol.vandermonde_coding_matrix(K, M, W)
    bits = matrix_to_bitmatrix(Mmat, W)
    rng = np.random.RandomState(0)
    chunk = args.size_mib << 20
    data_np = rng.randint(0, 256, size=(K, chunk), dtype=np.uint8)
    d32_np = data_np.view(np.int32)
    d32 = jax.device_put(jnp.asarray(d32_np))

    variants = build_variants(bits, args.tile)
    if args.only:
        only = args.only.split(",")
        variants = {n: f for n, f in variants.items() if n in only}

    # oracle: production kernel output
    ref = None
    results = {}
    for name, fn in variants.items():
        try:
            t0 = time.perf_counter()
            out = np.asarray(jax.device_get(fn(d32)))
            compile_s = time.perf_counter() - t0
        except Exception as e:
            print(f"{name:16s} FAILED: {type(e).__name__}: {e}", flush=True)
            continue
        if ref is None and "base" in variants:
            ref = np.asarray(jax.device_get(variants["base"](d32)))
        ok = (ref is None) or bool((out == ref).all())
        # chained timing: carry depends on previous parity
        iters = args.iters

        @jax.jit
        def many(d, fn=fn):
            def body(c, _):
                p = fn(c)
                return c.at[0, :].set(p[0, :] ^ c[0, :]), ()

            d, _ = jax.lax.scan(body, d, None, length=iters)
            return d

        w = many(d32)
        jax.block_until_ready(w)
        t0 = time.perf_counter()
        w = many(w)
        jax.block_until_ready(w)
        dt = (time.perf_counter() - t0) / iters
        gibps = data_np.nbytes / dt / (1 << 30)
        results[name] = gibps
        print(
            f"{name:16s} {'bit-exact' if ok else 'MISMATCH '}"
            f"  {gibps:8.2f} GiB/s   (compile+first {compile_s:.1f}s)",
            flush=True,
        )
    return results


if __name__ == "__main__":
    main()


# -- precision variants of the production kernel (appended probe) -----------

def _kernel_prec(b_ref, x_ref, o_ref, *, k: int, m: int, prec):
    x = x_ref[:]
    mask = jnp.int32(0x00010001)
    lo = jnp.concatenate(
        [((x >> s) & mask).astype(jnp.float32) for s in range(8)], axis=0
    )
    hi = jnp.concatenate(
        [((x >> (8 + s)) & mask).astype(jnp.float32) for s in range(8)], axis=0
    )
    dn = (((1,), (0,)), ((), ()))
    accL = jax.lax.dot_general(
        b_ref[:], lo, dn, precision=prec, preferred_element_type=jnp.float32
    ).astype(jnp.int32)
    accH = jax.lax.dot_general(
        b_ref[:], hi, dn, precision=prec, preferred_element_type=jnp.float32
    ).astype(jnp.int32)
    z = accL + (accH << 8)
    pb = z & jnp.int32(0x01010101)
    t = pb.shape[-1]
    ob = pb.reshape(m, 8, t)
    packed = ob[:, 0, :]
    for l in range(1, 8):
        packed = packed | (ob[:, l, :] << l)
    o_ref[:] = packed


def main_prec():
    import ceph_tpu.ops.pallas_gf as pg

    Mmat = reed_sol.vandermonde_coding_matrix(K, M, W)
    bits = matrix_to_bitmatrix(Mmat, W)
    Bp = jnp.asarray(prep_matrix_w8(bits, K))
    rng = np.random.RandomState(0)
    chunk = 8 << 20
    data_np = rng.randint(0, 256, size=(K, chunk), dtype=np.uint8)
    d32 = jax.device_put(jnp.asarray(data_np.view(np.int32)))
    n4 = d32.shape[1]
    ref = np.asarray(jax.device_get(_matrix_encode_call(Bp, d32, K, M, 4096)))

    import time as _t

    for prec_name, prec in (
        ("HIGHEST", jax.lax.Precision.HIGHEST),
        ("HIGH", jax.lax.Precision.HIGH),
        ("DEFAULT", jax.lax.Precision.DEFAULT),
    ):
        for tile in (4096, 16384):
            @jax.jit
            def call(d, prec=prec, tile=tile):
                return pl.pallas_call(
                    functools.partial(_kernel_prec, k=K, m=M, prec=prec),
                    out_shape=jax.ShapeDtypeStruct((M, n4), jnp.int32),
                    grid=(_cdiv(n4, tile),),
                    in_specs=[
                        pl.BlockSpec((M * 8, K * 8), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM),
                        pl.BlockSpec((K, tile), lambda i: (0, i),
                                     memory_space=pltpu.VMEM),
                    ],
                    out_specs=pl.BlockSpec((M, tile), lambda i: (0, i),
                                           memory_space=pltpu.VMEM),
                )(Bp, d)

            out = np.asarray(jax.device_get(call(d32)))
            ok = bool((out == ref).all())

            iters = 512

            @jax.jit
            def many(d, call=call):
                def body(c, _):
                    p = call(c)
                    return c.at[0, :].set(p[0, :] ^ c[0, :]), ()

                d, _ = jax.lax.scan(body, d, None, length=iters)
                return d

            w = many(d32)
            jax.block_until_ready(w)
            t0 = _t.perf_counter()
            w = many(w)
            jax.block_until_ready(w)
            dt = (_t.perf_counter() - t0) / iters
            print(
                f"prec={prec_name:8s} tile={tile:6d} "
                f"{'bit-exact' if ok else 'MISMATCH '} "
                f"{data_np.nbytes / dt / (1<<30):7.2f} GiB/s",
                flush=True,
            )
