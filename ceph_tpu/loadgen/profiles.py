"""Workload profiles: weighted op/size tables per traffic family.

Each profile is a declarative schema (documented in docs/qos.md) the
LoadClient samples from:

* ``mix`` -- (op kind, weight) pairs.  Kinds map onto the Objecter
  surface: ``put``/``get`` whole objects (RGW S3/Swift object I/O),
  ``range_write``/``range_read`` sub-object extents (RBD small random
  I/O -- extent writes exercise the RMW read lane), ``meta_set``/
  ``meta_get`` omap metadata (CephFS dirfrag-style), ``cas`` atomic
  omap compare-and-swap and ``exec`` a cls method call (the
  transactional/non-idempotent family the PR-5 exactly-once machinery
  guards).
* ``sizes`` -- (bytes, weight) pairs for data-carrying ops.

The tables are data, not code: a scenario can pass a custom
WorkloadProfile without touching this module.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

#: op kinds that carry a data payload (size sampling applies)
DATA_KINDS = frozenset({"put", "get", "range_write", "range_read"})
#: op kinds that mutate state (the read/write split in reporting)
WRITE_KINDS = frozenset({"put", "range_write", "meta_set", "cas", "exec"})


@dataclasses.dataclass(frozen=True)
class WorkloadProfile:
    name: str
    mix: Tuple[Tuple[str, float], ...]
    sizes: Tuple[Tuple[int, float], ...]
    description: str = ""

    def sample(self, rng) -> Tuple[str, int]:
        """One (op kind, payload bytes) draw."""
        kind = _weighted(rng, self.mix)
        size = _weighted(rng, self.sizes) if kind in DATA_KINDS else 0
        return kind, size


def _weighted(rng, pairs):
    total = sum(w for _v, w in pairs)
    roll = rng.random() * total
    acc = 0.0
    for value, weight in pairs:
        acc += weight
        if roll < acc:
            return value
    return pairs[-1][0]


#: the shipped profile set (scenario groups reference these by name)
PROFILES: Dict[str, WorkloadProfile] = {
    p.name: p
    for p in [
        WorkloadProfile(
            "rgw",
            mix=(("put", 3.0), ("get", 6.0), ("meta_get", 1.0)),
            sizes=((4 << 10, 4.0), (16 << 10, 3.0), (64 << 10, 1.0)),
            description="S3/Swift-style object store traffic: GET-heavy "
                        "whole-object I/O with mixed sizes and a bucket-"
                        "listing-ish metadata read share",
        ),
        WorkloadProfile(
            "rbd",
            mix=(("range_write", 5.0), ("range_read", 5.0)),
            sizes=((4 << 10, 6.0), (8 << 10, 3.0), (16 << 10, 1.0)),
            description="block-device-style small random extent I/O "
                        "inside preallocated images (extent writes take "
                        "the RMW lane)",
        ),
        WorkloadProfile(
            "cephfs",
            mix=(("meta_set", 3.0), ("meta_get", 3.0), ("put", 2.0),
                 ("get", 2.0)),
            sizes=((4 << 10, 5.0), (32 << 10, 2.0)),
            description="filesystem-style metadata+data mix: omap "
                        "create/lookup traffic alongside small file "
                        "bodies",
        ),
        WorkloadProfile(
            "put8k",
            mix=(("put", 1.0),),
            sizes=((8 << 10, 1.0),),
            description="uniform 8 KiB PUTs: the fixed-cost probe the "
                        "QoS bench calibrates capacity and reservation "
                        "floors against",
        ),
        WorkloadProfile(
            "txn",
            mix=(("cas", 6.0), ("exec", 2.0), ("meta_get", 2.0)),
            sizes=(),
            description="transactional traffic: omap compare-and-swap "
                        "counters and cls exec calls -- the non-"
                        "idempotent family whose exactly-once accounting "
                        "gates every scenario",
        ),
    ]
}
