"""ScenarioRunner: scenario-diverse scale runs over the real-TCP path.

One scenario = a real localhost-TCP cluster (every byte through
``msg/tcp.py``), N client groups (each a profile + arrival process +
QoS class), and a chaos set running CONCURRENTLY with the load:

* ``thrash``  -- true TCP kills: a victim OSD's listener is closed and
  its sockets torn, so clients discover the death by failed probes and
  fail over, exactly-once gated by the PR-5 reqid dup machinery;
* ``rebuild`` -- one OSD's store is wiped mid-run (replacement-disk
  semantics) and the round-14 batched background plane rebuilds it
  under load, admitted through the unified QoS layer;
* ``promote`` -- pools run in writeback tier mode, so hot objects
  promote into the device tier during the run (tier ticks);
* ``churn``   -- elastic membership under load (docs/elasticity.md): a
  victim OSD is weighted OUT of CRUSH mid-run while its daemon keeps
  serving -- data drains off through the placement-epoch-skew backfill
  on the peering tick -- then weighted back IN, migrating everything
  home again.  Both remaps run concurrently with the client load and
  the exactly-once audit.

Scale machinery: thousands of Objecters multiplex over a handful of
client-hub messengers via the ``<name>@<hub>`` entity aliasing
(msg/tcp.py ``_node_of``), so a 1000-client run costs tens of sockets,
not thousands; per-client in-flight budgets bound harness memory.

Results: per-group throughput/latency percentiles, per-class fairness
spread (max/min achieved per-client ops within a group -- published to
the prometheus gauge via osd/qos.py), pooled saturation p99, and the
exactly-once audit: every transactional client's counters are read
back and must equal its acked successes (bounded only by explicitly
booked indeterminate outcomes).
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.loadgen.arrival import ClosedLoop, OpenLoop
from ceph_tpu.loadgen.clients import LoadClient
from ceph_tpu.loadgen.profiles import PROFILES
from ceph_tpu.utils.encoding import Decoder

#: clients per hub messenger (bounds sockets AND dispatch-loop tasks
#: per hub); hubs = ceil(clients / HUB_FANOUT), capped.  The cap
#: clears the 10^4-client stage (qos_bench scale10x): 10_000 / 256 =
#: 40 hubs, still just tens of sockets against the cluster
HUB_FANOUT = 256
MAX_HUBS = 40


@dataclasses.dataclass(frozen=True)
class ClientGroup:
    count: int
    profile: str = "rgw"
    qos_class: Optional[str] = None
    mode: str = "closed"          # "closed" | "open"
    rate_ops_s: float = 2.0       # per client, open-loop only
    think_s: float = 0.0          # closed-loop think time
    #: closed-loop vectorized submit: > 1 drives put/get through
    #: Objecter.submit_many in chunks of this many sampled ops (one
    #: submit stage crossing + one wire burst per chunk)
    batch_ops: int = 1


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    duration_s: float
    groups: Tuple[ClientGroup, ...]
    chaos: Tuple[str, ...] = ()
    seed: int = 1234


@dataclasses.dataclass
class ScenarioResult:
    scenario: str
    wall_s: float
    n_clients: int
    ops: int
    errors: int
    ops_per_s: float
    p50_ms: float
    p99_ms: float
    groups: List[dict]
    cas_clients: int
    cas_exact: bool
    cas_mismatches: int
    #: exec counters that overshot acked successes within the
    #: DOCUMENTED mid-method replay window (docs/resilience.md Limits:
    #: a primary dying between a cls method's internal mutations and
    #: its awaited dup_record fan-out re-executes the method) -- only
    #: accepted when the owning client demonstrably failed over
    exec_replays: int
    client_resends: int
    indeterminate: int
    arrivals_shed: int
    inflight_hwm: int
    dup_op_hits: int
    kills: int
    wipes: int
    qos_counters: Dict[str, int]
    #: wire-fed telemetry gate fields (telemetry=True runs an mgr
    #: endpoint fed by per-OSD ReportSenders over the same real TCP and
    #: samples cluster health during the run): the degraded-objects
    #: series around a chaos wipe, its peak, whether it drained
    #: monotonically (bounded transient upticks from concurrent load),
    #: and the final health status
    health_timeline: List[tuple] = dataclasses.field(default_factory=list)
    degraded_max: int = 0
    degraded_final: int = 0
    degraded_monotonic_violations: int = 0
    health_final: str = ""
    #: chaos=churn: CRUSH weight flips applied mid-run (out + back in)
    churn_events: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * len(s)))]


class ScenarioRunner:
    """Boots the TCP cluster, drives one Scenario, collects results."""

    #: config shrunk to the mini-cluster's chaos time scale for the
    #: run's duration (restored on shutdown)
    TUNING = {
        "client_probe_grace": 0.15,
        "client_probe_retries": 1,
        "client_backoff_base": 0.02,
        "client_backoff_max": 0.4,
        "osd_client_op_commit_timeout": 3.0,
        "osd_read_gather_timeout": 3.0,
        # a saturated scale run holds thousands of ops past the default
        # 5s complaint time; per-op WARNING logging at that volume is
        # its own load source (the forensics ring still records them)
        "osd_op_complaint_time": 60.0,
    }

    def __init__(self, scenario: Scenario, *, n_osds: int = 6,
                 k: int = 2, m: int = 1, op_queue: str = "mclock",
                 pool: str = "lgpool", op_timeout: float = 20.0,
                 tuning: Optional[Dict[str, object]] = None,
                 telemetry: bool = False):
        # at scale the probe grace must sit ABOVE the loaded op p50:
        # a probe tears down the hub's SHARED connection to re-test the
        # wire, so a grace below typical queueing latency makes every
        # queued op probe, killing the socket 250 other clients are
        # multiplexed over -- a self-inflicted livelock.  Scenarios
        # with heavy closed-loop overload pass a larger grace via
        # ``tuning``; chaos failover then costs ~grace to detect, which
        # is the honest price of not lying to the failure detector.
        self.tuning = dict(self.TUNING)
        self.telemetry = telemetry
        if telemetry:
            # wire-fed health must react on the chaos time scale
            self.tuning.update({
                "mgr_beacon_interval": 0.1,
                "mgr_report_interval": 0.2,
                "mgr_daemon_beacon_grace": 1.0,
                "mgr_pg_stale_grace": 2.0,
            })
        if tuning:
            self.tuning.update(tuning)
        self.scenario = scenario
        self.n_osds = n_osds
        self.k = k
        self.m = m
        self.op_queue = op_queue
        self.pool = pool
        self.op_timeout = op_timeout
        self.osds = []
        self.osd_messengers = []
        self.hubs = []
        self.clients: List[LoadClient] = []
        self._client_groups: List[Tuple[ClientGroup, List[LoadClient]]] = []
        self.kills = 0
        self.wipes = 0
        self._churn_events = 0
        self._prior_cfg: Dict[str, object] = {}
        self._rng = random.Random(scenario.seed)
        self.perf = None
        self.placement = None
        self.ec = None
        self.mgr = None
        self._mgr_messenger = None
        self._reporters: List[object] = []
        self._health_samples: List[tuple] = []

    # -- cluster lifecycle --------------------------------------------------

    async def start(self) -> None:
        from ceph_tpu.msg.cluster_bench import free_ports
        from ceph_tpu.msg.fault import FaultInjector
        from ceph_tpu.msg.tcp import TCPMessenger
        from ceph_tpu.osd.placement import CrushPlacement
        from ceph_tpu.osd.shard import OSDShard
        from ceph_tpu.plugins import registry as registry_mod
        from ceph_tpu.utils.config import get_config
        from ceph_tpu.utils.perf import PerfCounters

        cfg = get_config()
        for key, val in self.tuning.items():
            self._prior_cfg[key] = cfg.get_val(key)
        cfg.apply_changes(dict(self.tuning))

        self.perf = PerfCounters("loadgen")
        self.ec = registry_mod.instance().factory("jerasure", {
            "k": str(self.k), "m": str(self.m),
            "technique": "reed_sol_van",
        })
        km = self.ec.get_chunk_count()
        n_clients = sum(g.count for g in self.scenario.groups)
        n_hubs = min(MAX_HUBS, max(1, -(-n_clients // HUB_FANOUT)))
        n_mgrs = 1 if self.telemetry else 0
        ports = free_ports(self.n_osds + n_hubs + n_mgrs)
        addr = {f"osd.{i}": ("127.0.0.1", ports[i])
                for i in range(self.n_osds)}
        for h in range(n_hubs):
            addr[f"lg{h}"] = ("127.0.0.1", ports[self.n_osds + h])
        if n_mgrs:
            addr["mgr.0"] = ("127.0.0.1", ports[self.n_osds + n_hubs])
        self.placement = CrushPlacement(self.n_osds, km)
        for i in range(self.n_osds):
            mess = TCPMessenger(f"osd.{i}", addr, fault=FaultInjector())
            await mess.start()
            shard = OSDShard(i, mess, op_queue=self.op_queue)
            shard.host_pool(self.pool, self.ec, self.n_osds,
                            self.placement)
            if "promote" in self.scenario.chaos:
                shard.pools[self.pool].tier_mode = "writeback"
            # event-driven peering/scrub/tier ticks: chaos recovery and
            # tier promotion both ride these
            shard.start_tick(0.25)
            self.osd_messengers.append(mess)
            self.osds.append(shard)
        if self.telemetry:
            # the wire-fed telemetry plane rides the SAME real TCP: one
            # mgr endpoint, every OSD running its MgrClient report loop
            from ceph_tpu.mgr.pgmap import MgrServer
            from ceph_tpu.mgr.report import ReportSender

            self._mgr_messenger = TCPMessenger("mgr.0", addr)
            await self._mgr_messenger.start()
            self.mgr = MgrServer("mgr.0", self._mgr_messenger,
                                 addr_map=addr)
            for shard, mess in zip(self.osds, self.osd_messengers):
                sender = ReportSender(shard.name, mess,
                                      shard.mgr_report_stats, ["mgr.0"],
                                      perf=shard.perf)
                sender.start()
                self._reporters.append(sender)
        for h in range(n_hubs):
            hub = TCPMessenger(f"lg{h}", addr, fault=FaultInjector())
            await hub.start()
            self.hubs.append(hub)
        self._build_clients(km, n_hubs)

    def _build_clients(self, km: int, n_hubs: int) -> None:
        from ceph_tpu.osd.objecter import Objecter

        seq = 0
        for group in self.scenario.groups:
            members: List[LoadClient] = []
            for _ in range(group.count):
                hub_i = seq % n_hubs
                name = f"c{seq}@lg{hub_i}"
                seq += 1
                objecter = Objecter(
                    self.hubs[hub_i], km, self.n_osds,
                    placement=self.placement, name=name, pool=self.pool,
                    op_timeout=self.op_timeout,
                    qos_class=group.qos_class,
                )
                arrival = (OpenLoop(group.rate_ops_s)
                           if group.mode == "open"
                           else ClosedLoop(group.think_s))
                client = LoadClient(
                    objecter, PROFILES[group.profile],
                    random.Random(self.scenario.seed * 1000 + seq),
                    arrival=arrival, perf=self.perf,
                    batch_ops=group.batch_ops,
                )
                members.append(client)
                self.clients.append(client)
            self._client_groups.append((group, members))

    async def shutdown(self) -> None:
        from ceph_tpu.utils.config import get_config

        for sender in self._reporters:
            sender.stop()
        if self.mgr is not None:
            await self.mgr.stop()
        messengers = self.hubs + self.osd_messengers
        if self._mgr_messenger is not None:
            messengers.append(self._mgr_messenger)
        for mess in messengers:
            await mess.shutdown()
        if self._prior_cfg:
            get_config().apply_changes(self._prior_cfg)

    # -- chaos --------------------------------------------------------------

    async def _kill_osd(self, idx: int) -> None:
        """True TCP death: stop accepting, tear every socket, stop
        executing.  Clients discover it by failed probes (connection
        refused) and fail over; in-flight acks are simply lost."""
        osd = self.osds[idx]
        mess = self.osd_messengers[idx]
        osd.frozen = True
        if self._reporters:
            # a dead daemon must stop beaconing, or the wire-fed map
            # would keep reading it as alive (outbound sends still
            # work after the listener teardown below)
            self._reporters[idx].stop()
        if mess._server is not None:
            mess._server.close()
        for conn in list(mess._conns.values()):
            try:
                conn[1].close()
            except Exception:  # noqa: BLE001 -- already-dead socket
                pass
        mess._conns.clear()
        for task in list(mess._serve_tasks):
            task.cancel()
        self.kills += 1

    async def _revive_osd(self, idx: int) -> None:
        osd = self.osds[idx]
        mess = self.osd_messengers[idx]
        await mess.start()
        osd.frozen = False
        mess.mark_up(osd.name)
        if self._reporters:
            self._reporters[idx].start()
        for shard in self.osds:
            shard.request_peering()

    def _wipe_osd(self, idx: int) -> None:
        """Replacement-disk semantics mid-run (mirrors
        ECCluster.wipe_osd for the TCP harness)."""
        from ceph_tpu.osd.types import Transaction

        osd = self.osds[idx]
        # degraded accounting, event time: the lost holdings land on
        # their primaries' incremental pg_stats BEFORE the store
        # empties, so the wire-fed map shows PG_DEGRADED immediately
        # and drains as the batched rebuild completes objects
        for stored in osd.store.list_objects():
            base, _, _tag = stored.rpartition("@")
            if not base:
                continue
            for other in self.osds:
                b = other.pools.get(self.pool)
                if b is None:
                    continue
                acting = b.acting_set(base)
                for s in range(b.km):
                    if b._shard_up(acting, s):
                        self.osds[acting[s]].pools[
                            self.pool].pg_stats.note_down_victims(
                            f"wipe:{osd.name}", [base])
                        break
                break
        txn = Transaction()
        for stored in osd.store.list_objects():
            txn.remove(stored)
        osd.store.queue_transaction(txn)
        osd._applied_version.clear()
        osd.tier.clear()
        osd._store_nonempty = False
        osd._scrub_bases = None
        for other in self.osds:
            for backend in other.pools.values():
                backend._peer_seq.pop(osd.name, None)
                backend._peer_dup_seq.pop(osd.name, None)
        for shard in self.osds:
            shard.request_peering()
        self.wipes += 1

    async def _chaos_task(self, stop: asyncio.Event) -> None:
        duration = self.scenario.duration_s
        thrash = "thrash" in self.scenario.chaos
        rebuild = "rebuild" in self.scenario.chaos
        churn = "churn" in self.scenario.chaos
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        wiped = False
        down: Optional[int] = None
        churn_out: Optional[int] = None
        churn_done = False
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(),
                                       timeout=max(0.2, duration / 8))
                break
            except asyncio.TimeoutError:
                pass
            elapsed = loop.time() - t0
            if rebuild and not wiped and elapsed >= duration / 4:
                self._wipe_osd(self._rng.randrange(self.n_osds))
                wiped = True
                continue
            if churn and not churn_done:
                if churn_out is None and elapsed >= duration / 4:
                    # elastic membership drain (docs/elasticity.md):
                    # weight the victim OUT of CRUSH while its daemon
                    # keeps serving; every engine's next peering tick
                    # sees the epoch skew and backfills the remap
                    churn_out = self._rng.randrange(self.n_osds)
                    self.placement.mark_out(churn_out)
                    self._churn_events += 1
                    continue
                if churn_out is not None and elapsed >= duration * 0.6:
                    self.placement.mark_in(churn_out)
                    self._churn_events += 1
                    churn_out = None
                    churn_done = True
                    continue
            if not thrash:
                continue
            if down is not None:
                await self._revive_osd(down)
                down = None
            elif elapsed < duration * 0.75:
                # stay within the failure budget: one OSD down at a
                # time, and none in the final quarter so the run can
                # settle for the exactly-once audit
                down = self._rng.randrange(self.n_osds)
                await self._kill_osd(down)
        if down is not None:
            await self._revive_osd(down)
        if churn_out is not None:
            # never leave the victim weighted out past the run: the
            # settle window needs the full width for the audit
            self.placement.mark_in(churn_out)
            self._churn_events += 1

    # -- the run ------------------------------------------------------------

    async def _health_sampler(self, stop: asyncio.Event) -> None:
        """Sample the wire-fed map during the run (telemetry=True): the
        chaos gate's degraded-drain series comes from here."""
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        while not stop.is_set():
            await asyncio.sleep(0.2)
            health = self.mgr.pgmap.health()
            degraded = self.mgr.pgmap.totals()["degraded"]
            self._health_samples.append(
                (round(loop.time() - t0, 3), health["status"], degraded))

    async def run(self) -> ScenarioResult:
        stop = asyncio.Event()
        chaos = asyncio.get_event_loop().create_task(
            self._chaos_task(stop))
        sampler = None
        if self.mgr is not None:
            sampler = asyncio.get_event_loop().create_task(
                self._health_sampler(stop))
        t0 = time.perf_counter()
        drivers = [
            asyncio.get_event_loop().create_task(client.run(stop))
            for client in self.clients
        ]
        await asyncio.sleep(self.scenario.duration_s)
        stop.set()
        done, pending = await asyncio.wait(
            drivers, timeout=max(5.0, self.op_timeout))
        for task in pending:
            task.cancel()
        if pending:
            await asyncio.wait(pending, timeout=5.0)
        await chaos
        wall = time.perf_counter() - t0
        # settle: every OSD back up before the audit reads
        for i, osd in enumerate(self.osds):
            if osd.frozen:
                await self._revive_osd(i)
        if sampler is not None:
            # keep sampling the drain until the map reads clean (or a
            # bounded settle window expires): the health gate asserts
            # wipe -> degraded>0 -> monotone drain -> HEALTH_OK
            drain_stop = asyncio.Event()
            sampler2 = asyncio.get_event_loop().create_task(
                self._health_sampler(drain_stop))
            deadline = time.perf_counter() + max(20.0, self.op_timeout)
            while time.perf_counter() < deadline:
                await asyncio.sleep(0.25)
                if self.mgr.pgmap.totals()["degraded"] == 0 and \
                        self.mgr.pgmap.health()["status"] == "HEALTH_OK":
                    break
            drain_stop.set()
            await sampler2
            await sampler
        return await self._collect(wall)

    # -- results ------------------------------------------------------------

    async def _collect(self, wall: float) -> ScenarioResult:
        from ceph_tpu.osd import qos as qos_mod

        pooled: List[float] = []
        groups_out: List[dict] = []
        total_ops = total_errors = total_shed = total_indet = 0
        for group, members in self._client_groups:
            ops = [c.stats.ops for c in members]
            lat: List[float] = []
            for c in members:
                lat.extend(c.stats.latencies)
            pooled.extend(lat)
            total_ops += sum(ops)
            total_errors += sum(c.stats.errors for c in members)
            total_shed += sum(c.stats.arrivals_shed for c in members)
            total_indet += sum(c.stats.indeterminate for c in members)
            lo, hi = (min(ops), max(ops)) if ops else (0, 0)
            spread = (hi / lo) if lo > 0 else None
            label = group.qos_class or group.profile
            if spread is not None:
                qos_mod.set_fairness_spread(label, spread)
            groups_out.append({
                "profile": group.profile,
                "qos_class": group.qos_class,
                "mode": group.mode,
                "clients": group.count,
                "ops": sum(ops),
                "errors": sum(c.stats.errors for c in members),
                "ops_per_s": round(sum(ops) / wall, 3),
                "client_ops_min": lo,
                "client_ops_max": hi,
                "clients_at_zero": sum(1 for n in ops if n == 0),
                "fairness_spread": round(spread, 3) if spread else None,
                "p50_ms": round(_pct(lat, 0.50) * 1e3, 3),
                "p99_ms": round(_pct(lat, 0.99) * 1e3, 3),
            })
        cas_clients, mismatches, exec_replays = \
            await self._audit_exactly_once()
        dup_hits = sum(
            osd.perf.snapshot().get("dup_op_hit", 0) for osd in self.osds)
        resends = sum(
            c.objecter.perf.snapshot().get("op_resend", 0)
            for c in self.clients)
        qos_counters: Dict[str, int] = {}
        for osd in self.osds:
            for key, val in osd.perf.snapshot().items():
                if key.startswith("qos_") and isinstance(val, int):
                    qos_counters[key] = qos_counters.get(key, 0) + val
        samples = list(self._health_samples)
        degraded_series = [d for _, _, d in samples]
        degraded_max = max(degraded_series, default=0)
        violations = 0
        if degraded_max:
            # monotone-drain check from the peak: concurrent client
            # writes against a half-rebuilt object can re-dirty it, so
            # bounded transient upticks are tolerated by the caller --
            # the count is reported, the gate decides
            peak_at = degraded_series.index(degraded_max)
            prev = degraded_max
            for d in degraded_series[peak_at:]:
                if d > prev:
                    violations += 1
                prev = d
        return ScenarioResult(
            scenario=self.scenario.name,
            wall_s=round(wall, 3),
            n_clients=len(self.clients),
            ops=total_ops,
            errors=total_errors,
            ops_per_s=round(total_ops / wall, 3),
            p50_ms=round(_pct(pooled, 0.50) * 1e3, 3),
            p99_ms=round(_pct(pooled, 0.99) * 1e3, 3),
            groups=groups_out,
            cas_clients=cas_clients,
            cas_exact=mismatches == 0,
            cas_mismatches=mismatches,
            exec_replays=exec_replays,
            client_resends=resends,
            indeterminate=total_indet,
            arrivals_shed=total_shed,
            inflight_hwm=self.perf.snapshot().get(
                "client_inflight_hwm", 0),
            dup_op_hits=dup_hits,
            kills=self.kills,
            wipes=self.wipes,
            qos_counters=qos_counters,
            health_timeline=samples,
            degraded_max=degraded_max,
            degraded_final=degraded_series[-1] if degraded_series else 0,
            degraded_monotonic_violations=violations,
            health_final=(self.mgr.pgmap.health()["status"]
                          if self.mgr is not None else ""),
            churn_events=self._churn_events,
        )

    async def _audit_exactly_once(self) -> Tuple[int, int, int]:
        """Read every transactional client's counters back: each must
        equal its acked successes exactly, widened only by explicitly
        booked indeterminate outcomes (ops whose ack was lost to a
        chaos window).  A value past that bound is a double-apply; one
        below it is a lost acked op -- both count as mismatches.

        One DOCUMENTED exception (docs/resilience.md Limits): ``exec``
        composes engine ops without a transaction, so a primary dying
        mid-method -- after the internal mutations, before the awaited
        ``dup_record`` fan-out -- re-executes on replay.  An exec
        counter overshooting its acked successes is therefore accepted
        (and counted as an ``exec_replay``) iff the owning client
        demonstrably failed over (op_resend > 0) and the overshoot
        stays within that resend budget; omap_cas has a zero-width
        dup window and gets no such allowance."""
        from ceph_tpu.osd.objecter import Objecter

        verifier = Objecter(
            self.hubs[0], self.ec.get_chunk_count(), self.n_osds,
            placement=self.placement, name=f"auditor@{self.hubs[0].node}",
            pool=self.pool, op_timeout=self.op_timeout,
        )
        checked = 0
        mismatches = 0
        exec_replays = 0
        for client in self.clients:
            st = client.stats
            if st.cas_ok or st.cas_indet:
                checked += 1
                base = client.name.split("@")[0]
                try:
                    raw = (await verifier.omap_get(
                        f"{base}-cnt", ["n"])).get("n")
                    val = Decoder(raw).value() if raw else 0
                except Exception:  # noqa: BLE001 -- an unreadable
                    # counter IS an audit failure
                    mismatches += 1
                    continue
                if not (st.cas_ok <= val <= st.cas_ok + st.cas_indet):
                    mismatches += 1
            if st.exec_ok or st.exec_indet:
                checked += 1
                base = client.name.split("@")[0]
                try:
                    ret, out = await verifier.exec(
                        f"{base}-exn", "version", "get")
                    val = Decoder(out).value() if ret == 0 else -1
                except Exception:  # noqa: BLE001
                    mismatches += 1
                    continue
                hi = st.exec_ok + st.exec_indet
                resends = client.objecter.perf.snapshot().get(
                    "op_resend", 0)
                if st.exec_ok <= val <= hi:
                    pass
                elif hi < val <= hi + resends:
                    # the documented exec mid-method replay window
                    exec_replays += val - hi
                else:
                    mismatches += 1
        return checked, mismatches, exec_replays


async def run_scenario(scenario: Scenario, **kw) -> ScenarioResult:
    """Boot, run, audit, shutdown -- the one-call surface the bench and
    tests use."""
    runner = ScenarioRunner(scenario, **kw)
    await runner.start()
    try:
        return await runner.run()
    finally:
        await runner.shutdown()
