"""Arrival processes for the load generator.

Two families (the open/closed distinction matters for what a benchmark
can claim -- an open-loop process keeps arriving while the system
stalls, so it measures queueing honestly; a closed-loop process models
a bounded client population):

* :class:`ClosedLoop` -- each client issues its next op when the
  previous completes, optionally separated by exponentially-distributed
  think time (mean ``think_s``).
* :class:`OpenLoop` -- Poisson arrivals at ``rate_ops_s`` per client:
  inter-arrival gaps are exponential and arrivals do NOT wait for
  completions (in-flight ops bounded by the client's budget semaphore;
  an arrival that finds the budget exhausted parks and is counted as
  shed -- the bounded-memory contract).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ClosedLoop:
    think_s: float = 0.0

    def gap(self, rng) -> float:
        if self.think_s <= 0:
            return 0.0
        return rng.expovariate(1.0 / self.think_s)


@dataclasses.dataclass(frozen=True)
class OpenLoop:
    rate_ops_s: float

    def gap(self, rng) -> float:
        if self.rate_ops_s <= 0:
            return 0.0
        return rng.expovariate(self.rate_ops_s)
