"""LoadClient: one simulated application client over an Objecter.

Bounded by construction (the million-client contract): every in-flight
op holds a permit from a per-client budget semaphore
(``loadgen_client_inflight``), so an open-loop client whose arrivals
outrun the cluster parks -- counted as ``arrivals_shed`` -- instead of
accumulating unbounded tasks/futures; the observed in-flight high-water
mark is surfaced as the ``client_inflight_hwm`` perf counter on the
harness-wide PerfCounters.

Each client works an isolated object namespace (``<name>-o<i>``), so a
thousand concurrent clients never write-conflict by construction and
per-client achieved throughput is a clean fairness signal.  The
transactional kinds keep exactly-once books: ``cas_ok``/``exec_ok``
count acked successes, ``indeterminate`` counts ops whose outcome was
lost to a timeout (possible only under chaos), and the scenario runner
closes the loop by reading the final counters back -- the PR-5
zero-double-apply gate.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Dict, List, Optional

from ceph_tpu.loadgen.arrival import ClosedLoop, OpenLoop
from ceph_tpu.loadgen.profiles import WorkloadProfile
from ceph_tpu.utils.encoding import Decoder, Encoder

#: per-client latency reservoir bound (the scenario pools these; a
#: million clients x unbounded lists would BE the OOM this module
#: exists to prevent)
LATENCY_RESERVOIR = 128
#: preallocated image bytes for the extent (rbd-style) kinds
IMAGE_BYTES = 64 << 10


@dataclasses.dataclass
class ClientStats:
    ops: int = 0
    errors: int = 0
    bytes_moved: int = 0
    cas_ok: int = 0
    exec_ok: int = 0
    cas_indet: int = 0
    exec_indet: int = 0
    arrivals_shed: int = 0
    by_kind: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: bounded latency sample (reservoir past LATENCY_RESERVOIR)
    latencies: List[float] = dataclasses.field(default_factory=list)
    _seen: int = 0

    @property
    def indeterminate(self) -> int:
        return self.cas_indet + self.exec_indet

    def note_latency(self, rng, dt: float) -> None:
        self._seen += 1
        if len(self.latencies) < LATENCY_RESERVOIR:
            self.latencies.append(dt)
        else:
            slot = rng.randrange(self._seen)
            if slot < LATENCY_RESERVOIR:
                self.latencies[slot] = dt


class LoadClient:
    """One profile-driven client; ``objecter`` carries its identity,
    pool and qos_class."""

    def __init__(self, objecter, profile: WorkloadProfile, rng, *,
                 arrival=None, inflight: Optional[int] = None,
                 perf=None, batch_ops: int = 1):
        if inflight is None:
            from ceph_tpu.utils.config import get_config

            inflight = int(get_config().get_val("loadgen_client_inflight"))
        self.objecter = objecter
        self.profile = profile
        self.rng = rng
        #: closed-loop vectorized submit: > 1 gathers this many sampled
        #: ops per cycle and hands the put/get share to
        #: Objecter.submit_many -- one submit stage crossing and one
        #: wire burst per chunk (non-batchable kinds still run
        #: individually, keeping the transactional books exact)
        self.batch_ops = max(1, int(batch_ops))
        self.arrival = arrival if arrival is not None else ClosedLoop()
        self.stats = ClientStats()
        self.perf = perf
        self._budget = asyncio.Semaphore(max(1, inflight))
        self._inflight = 0
        self._inflight_hwm = 0
        self._written: List[str] = []
        self._meta_written = False
        self._image_ready = False
        self._oid_seq = 0
        self._tasks: set = set()

    # -- namespace ----------------------------------------------------------

    @property
    def name(self) -> str:
        return self.objecter.name

    def _data_oid(self, new: bool) -> str:
        if new or not self._written:
            self._oid_seq += 1
            oid = f"{self.name.split('@')[0]}-o{self._oid_seq}"
            return oid
        return self._written[self.rng.randrange(len(self._written))]

    @property
    def _meta_oid(self) -> str:
        return f"{self.name.split('@')[0]}-meta"

    @property
    def _cas_oid(self) -> str:
        return f"{self.name.split('@')[0]}-cnt"

    @property
    def _exec_oid(self) -> str:
        return f"{self.name.split('@')[0]}-exn"

    @property
    def _image_oid(self) -> str:
        return f"{self.name.split('@')[0]}-img"

    # -- one op -------------------------------------------------------------

    async def _do_op(self, kind: str, size: int) -> None:
        ob = self.objecter
        payload = b"L" * size if size else b""
        if kind == "get" and not self._written:
            kind = "put"  # first touch seeds the namespace
        if kind in ("range_write", "range_read") and not self._image_ready:
            await ob.write(self._image_oid, b"\0" * IMAGE_BYTES)
            # concurrent ops of one open-loop client can race the lazy
            # image preallocation; the duplicate write is idempotent and
            # the flag is re-checked yield-free before the store
            if not self._image_ready:
                self._image_ready = True
        if kind == "put":
            # grow the working set to 16 objects before re-writing:
            # CRUSH then spreads every client's demand over all the
            # primaries, which is what lets per-OSD QoS reservations
            # add up to the cluster-wide floor
            oid = self._data_oid(new=len(self._written) < 16)
            await ob.write(oid, payload)
            if oid not in self._written:
                self._written.append(oid)
                del self._written[:-16]  # bounded namespace memory
            self.stats.bytes_moved += size
        elif kind == "get":
            got = await ob.read(self._data_oid(new=False))
            self.stats.bytes_moved += len(got)
        elif kind == "range_write":
            off = self.rng.randrange(max(1, IMAGE_BYTES - size))
            await ob.write_range(self._image_oid, off, payload)
            self.stats.bytes_moved += size
        elif kind == "range_read":
            off = self.rng.randrange(max(1, IMAGE_BYTES - size))
            got = await ob.read_range(self._image_oid, off, size)
            self.stats.bytes_moved += len(got)
        elif kind == "meta_set":
            key = f"k{self.rng.randrange(16)}"
            await ob.omap_set(self._meta_oid, {key: b"v"})
            if not self._meta_written:  # yield-free re-check (racing
                self._meta_written = True  # ops both only ever set it)
        elif kind == "meta_get":
            if not self._meta_written:
                await ob.omap_set(self._meta_oid, {"k0": b"v"})
                if not self._meta_written:
                    self._meta_written = True
            else:
                await ob.omap_get(self._meta_oid)
        elif kind == "cas":
            cur = (await ob.omap_get(self._cas_oid, ["n"])).get("n")
            nxt = Encoder().value(
                (Decoder(cur).value() if cur else 0) + 1).bytes()
            try:
                ok, _seen = await ob.omap_cas(self._cas_oid, "n", cur, nxt)
            except IOError:
                # outcome lost (chaos window): the counter may or may
                # not have advanced -- booked as indeterminate so the
                # exactly-once gate can bound, not guess
                self.stats.cas_indet += 1
                raise
            if ok:
                self.stats.cas_ok += 1
        elif kind == "exec":
            try:
                ret, _out = await ob.exec(self._exec_oid, "version", "inc")
            except IOError:
                self.stats.exec_indet += 1
                raise
            if ret == 0:
                self.stats.exec_ok += 1
        else:
            raise ValueError(f"unknown op kind {kind!r}")

    async def _one_batched(self) -> None:
        """One closed-loop cycle through the vectorized submit: sample
        ``batch_ops`` ops, hand the put/get share to
        ``Objecter.submit_many`` as one batch (per-op outcomes booked
        from its return_exceptions slots), and run the remaining kinds
        -- omap/cas/exec carry their own exactly-once accounting --
        through the per-op path unchanged."""
        batched: List[tuple] = []   # submit_many (kind, oid, fields)
        booked: List[tuple] = []    # (kind, size, oid)
        rest: List[tuple] = []
        for _ in range(self.batch_ops):
            kind, size = self.profile.sample(self.rng)
            if kind == "get" and not self._written:
                kind = "put"  # first touch seeds the namespace
            if kind == "put":
                oid = self._data_oid(new=len(self._written) < 16)
                batched.append(("write", oid,
                                {"data": b"L" * size, "snapc": None}))
                booked.append((kind, size, oid))
            elif kind == "get":
                oid = self._data_oid(new=False)
                batched.append(("read", oid, {"snap": None}))
                booked.append((kind, size, oid))
            else:
                rest.append((kind, size))
        if batched:
            t0 = time.perf_counter()
            results = await self.objecter.submit_many(
                batched, return_exceptions=True)
            dt = time.perf_counter() - t0
            for (kind, size, oid), res in zip(booked, results):
                self.stats.by_kind[kind] = \
                    self.stats.by_kind.get(kind, 0) + 1
                if isinstance(res, asyncio.CancelledError):
                    raise res
                if isinstance(res, BaseException):
                    self.stats.errors += 1
                    continue
                if kind == "put":
                    if oid not in self._written:
                        self._written.append(oid)
                        del self._written[:-16]
                    self.stats.bytes_moved += size
                else:
                    self.stats.bytes_moved += len(res or b"")
                self.stats.ops += 1
                # ops of one batch resolve concurrently: the batch wall
                # IS each op's latency
                self.stats.note_latency(self.rng, dt)
        for kind, size in rest:
            self.stats.by_kind[kind] = self.stats.by_kind.get(kind, 0) + 1
            t0 = time.perf_counter()
            try:
                await self._do_op(kind, size)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 -- chaos makes individual
                # op failures expected; the scenario gates on the books
                self.stats.errors += 1
                continue
            self.stats.ops += 1
            self.stats.note_latency(self.rng, time.perf_counter() - t0)

    async def _one(self) -> None:
        kind, size = self.profile.sample(self.rng)
        self.stats.by_kind[kind] = self.stats.by_kind.get(kind, 0) + 1
        t0 = time.perf_counter()
        try:
            await self._do_op(kind, size)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 -- chaos makes individual op
            # failures expected; the scenario gates on the books
            self.stats.errors += 1
            return
        self.stats.ops += 1
        self.stats.note_latency(self.rng, time.perf_counter() - t0)

    # -- the drive loops ----------------------------------------------------

    def _note_inflight(self, delta: int) -> None:
        self._inflight += delta
        if self._inflight > self._inflight_hwm:
            self._inflight_hwm = self._inflight
            if self.perf is not None:
                self.perf.hwm("client_inflight_hwm", self._inflight_hwm)

    async def run(self, stop: asyncio.Event) -> None:
        """Drive ops until ``stop`` is set, then drain in-flight work."""
        if isinstance(self.arrival, OpenLoop):
            await self._run_open(stop)
        else:
            await self._run_closed(stop)

    async def _run_closed(self, stop: asyncio.Event) -> None:
        while not stop.is_set():
            async with self._budget:
                self._note_inflight(1)
                try:
                    if self.batch_ops > 1:
                        await self._one_batched()
                    else:
                        await self._one()
                finally:
                    self._note_inflight(-1)
            gap = self.arrival.gap(self.rng)
            if gap > 0:
                try:
                    await asyncio.wait_for(stop.wait(), timeout=gap)
                except asyncio.TimeoutError:
                    pass

    async def _run_open(self, stop: asyncio.Event) -> None:
        loop = asyncio.get_event_loop()
        while not stop.is_set():
            gap = self.arrival.gap(self.rng)
            if gap > 0:
                try:
                    await asyncio.wait_for(stop.wait(), timeout=gap)
                    break
                except asyncio.TimeoutError:
                    pass
            # bounded fan-out: each spawned op holds a budget permit;
            # an arrival past the budget parks here (and is counted)
            # instead of growing the task set without bound
            if self._budget.locked():
                self.stats.arrivals_shed += 1
            # the permit's ownership TRANSFERS to the spawned op task
            # (_one_open releases it in its finally), so it is held
            # across this loop's parks by design -- the same sanctioned
            # shape as the messenger's dispatch-throttle budget
            await self._budget.acquire()  # cephlint: disable=async-lock-across-await
            spawned = False
            try:
                self._note_inflight(1)
                task = loop.create_task(self._one_open())
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
                spawned = True
            finally:
                if not spawned:  # failed spawn must not leak the permit
                    self._note_inflight(-1)
                    self._budget.release()
        if self._tasks:
            await asyncio.wait(self._tasks, timeout=10.0)

    async def _one_open(self) -> None:
        try:
            await self._one()
        finally:
            self._note_inflight(-1)
            self._budget.release()
