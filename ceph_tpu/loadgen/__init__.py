"""Million-client scale harness: scenario-diverse load generation over
the real-TCP cluster path (ROADMAP item 3).

The package composes four layers (docs/qos.md):

* :mod:`profiles` -- named workload shapes (RGW-style object PUT/GET
  mixes, RBD-style small random extent I/O, CephFS-style metadata+data,
  transactional omap_cas/exec traffic) as weighted op/size tables;
* :mod:`arrival` -- open-loop (Poisson) and closed-loop (think-time)
  arrival processes;
* :mod:`clients` -- LoadClient: one Objecter driven by a profile under
  an arrival process, with a per-client in-flight budget semaphore
  (``loadgen_client_inflight``) so a million-client run can never OOM
  the harness, and exactly-once CAS accounting built in;
* :mod:`scenario` -- ScenarioRunner: a real-TCP cluster (client hubs
  multiplex thousands of Objecters over a handful of sockets -- the
  ``name@hub`` messenger aliasing), client groups with per-group QoS
  classes, concurrent chaos (thrash kills, failover, background
  rebuild, tier promotion), and fairness/percentile/exactly-once
  result collection.
"""

from ceph_tpu.loadgen.arrival import ClosedLoop, OpenLoop  # noqa: F401
from ceph_tpu.loadgen.clients import ClientStats, LoadClient  # noqa: F401
from ceph_tpu.loadgen.profiles import PROFILES, WorkloadProfile  # noqa: F401
from ceph_tpu.loadgen.scenario import (ClientGroup, Scenario,  # noqa: F401
                                       ScenarioResult, ScenarioRunner,
                                       run_scenario)
