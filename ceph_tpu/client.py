"""librados-style client API (Rados / IoCtx surface).

Reference: src/librados (Rados cluster handle, IoCtx per pool with
write_full/read/remove/stat, pool create with an EC profile validated by
instantiating the plugin -- the OSDMonitor::get_erasure_code role,
reference src/mon/OSDMonitor.cc:5353).  Synchronous wrappers drive the
async mini-cluster; aio_* variants return awaitables.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ceph_tpu.osd.cluster import ECCluster
from ceph_tpu.osd.ecbackend import SIZE_KEY, shard_oid
from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.utils.config import get_config


class Rados:
    """Cluster handle: owns the OSDs and the pools."""

    def __init__(self, n_osds: int = 8):
        self.n_osds = n_osds
        self._pools: Dict[str, ECCluster] = {}
        self._loop = asyncio.new_event_loop()

    # -- pool ops (mon-role: profile validation at create time) ------------

    def pool_create(self, name: str, profile: Optional[Dict[str, str]] = None,
                    pool_type: str = "erasure", size: int = 3):
        """Create a pool.  ``pool_type`` mirrors `ceph osd pool create
        <name> replicated|erasure` (reference src/mon/OSDMonitor.cc:5529):
        replicated pools take ``size`` full copies and no EC profile."""
        if name in self._pools:
            raise ValueError(f"pool {name} exists")
        if pool_type == "replicated":
            if size < 1 or size > self.n_osds:
                raise ValueError(f"bad replicated size {size}")
            self._pools[name] = self._run(
                self._make_pool({"size": str(size)}, pool_type)
            )
            return self.open_ioctx(name)
        if profile is None:
            text = get_config().get_val("osd_pool_default_erasure_code_profile")
            profile = dict(kv.split("=", 1) for kv in text.split())
        # validate the profile by instantiating the codec (monitor behavior)
        check = dict(profile)
        plugin = check.pop("plugin", "jerasure")
        registry_mod.instance().factory(plugin, check)
        self._pools[name] = self._run(self._make_pool(profile, pool_type))
        return self.open_ioctx(name)

    async def _make_pool(self, profile, pool_type="erasure"):
        return ECCluster(self.n_osds, dict(profile), pool_type=pool_type)

    def pool_delete(self, name: str) -> None:
        pool = self._pools.pop(name, None)
        if pool is not None:
            self._run(pool.shutdown())

    def list_pools(self) -> List[str]:
        return sorted(self._pools)

    def open_ioctx(self, name: str) -> "IoCtx":
        if name not in self._pools:
            raise KeyError(f"no pool {name}")
        return IoCtx(self, self._pools[name])

    def shutdown(self) -> None:
        for name in list(self._pools):
            self.pool_delete(name)
        self._loop.close()

    def _run(self, coro):
        return self._loop.run_until_complete(coro)


class IoCtx:
    """Per-pool I/O context (librados::IoCtx role)."""

    def __init__(self, rados: Rados, cluster: ECCluster):
        self._rados = rados
        self._cluster = cluster
        #: self-managed snapshot state (librados: the APPLICATION owns the
        #: snap context -- rados_ioctx_selfmanaged_snap_* -- exactly as
        #: librbd keeps snap ids in its own header object)
        self._snap_seq = 0
        self._snaps: List[int] = []  # live snap ids, newest first
        self.snap_read: Optional[int] = None  # set_snap_read target

    # -- self-managed snapshots (librados selfmanaged_snap_* surface) ------

    def _snapc(self) -> Optional[dict]:
        if not self._snaps:
            return None
        return {"seq": self._snap_seq, "snaps": list(self._snaps)}

    def selfmanaged_snap_create(self) -> int:
        """Allocate a snap id; subsequent writes preserve pre-snap state
        via COW clones (reference rados_ioctx_selfmanaged_snap_create)."""
        self._snap_seq += 1
        self._snaps.insert(0, self._snap_seq)
        return self._snap_seq

    def selfmanaged_snap_remove(self, snapid: int) -> None:
        """Drop a snap id and trim clones it alone kept alive (the
        SnapMapper/snap-trimmer role, run client-side: trims fan out
        concurrently, one round per object)."""
        import asyncio as _aio

        if snapid in self._snaps:
            self._snaps.remove(snapid)
        backend = self._cluster.backend
        live = list(self._snaps)
        heads = [o for o in self.list_objects() if "~" not in o]

        async def trim_all():
            await _aio.gather(
                *(backend.snap_trim(oid, live) for oid in heads)
            )

        self._rados._run(trim_all())

    def selfmanaged_snap_rollback(self, oid: str, snapid: int) -> None:
        self._rados._run(
            self._cluster.backend.snap_rollback(
                oid, snapid, snapc=self._snapc()
            )
        )

    def set_snap_read(self, snapid: Optional[int]) -> None:
        """Route subsequent reads to the object state at ``snapid``
        (None = head)."""
        self.snap_read = snapid

    def list_snaps(self, oid: str) -> dict:
        return self._rados._run(self._cluster.backend.list_snaps(oid))

    # -- sync surface ------------------------------------------------------

    def write_full(self, oid: str, data: bytes) -> None:
        self._rados._run(
            self._cluster.backend.write(oid, data, snapc=self._snapc())
        )

    def read(self, oid: str) -> bytes:
        return self._rados._run(
            self._cluster.backend.read(oid, snap=self.snap_read)
        )

    def remove(self, oid: str) -> None:
        self._rados._run(
            self._cluster.backend.remove_object(oid, snapc=self._snapc())
        )

    def stat(self, oid: str) -> int:
        """Logical object size from the HIGHEST-VERSIONED reachable
        shard's xattrs (a first-reachable answer could be a stale
        removal tombstone, or a stale copy, on a replica that was down
        through the newest writes).  A replicated-pool removal tombstone
        (whiteout "removed", ceph_tpu/osd/replicated.py) stats as
        absent, matching the EC pool's physical delete."""
        from ceph_tpu.osd.pg import VERSION_KEY, WHITEOUT_KEY, vt

        backend = self._cluster.backend
        acting = backend.acting_set(oid)
        best = None  # (version, size, whiteout)
        for s in range(backend.km):
            if acting[s] is None:
                continue
            store = self._cluster.osds[acting[s]].store
            soid = shard_oid(oid, s)
            try:
                size = store.getattr(soid, SIZE_KEY)
            except FileNotFoundError:
                continue
            if size is None:
                continue
            ver = vt(store.getattr(soid, VERSION_KEY))
            if best is None or ver > best[0]:
                best = (ver, size, store.getattr(soid, WHITEOUT_KEY))
        if best is None or best[2] == "removed":
            raise FileNotFoundError(oid)
        return best[1]

    def list_objects(self) -> List[str]:
        from ceph_tpu.osd.pg import POOL_KEY, VERSION_KEY, WHITEOUT_KEY, vt

        live: Dict[str, tuple] = {}     # base -> newest live version
        removed: Dict[str, tuple] = {}  # base -> newest tombstone version
        for osd in self._cluster.osds:
            for soid in osd.store.list_objects():
                if soid.endswith("@meta") and \
                        osd.store.getattr(soid, "_meta_removed"):
                    continue  # removal tombstone, not a live object
                ptag = osd.store.getattr(soid, POOL_KEY)
                if ptag is not None and ptag != self._cluster.pool:
                    continue  # a co-hosted pool's object
                base = soid.rsplit("@", 1)[0]
                ver = vt(osd.store.getattr(soid, VERSION_KEY))
                # replicated plain-removal tombstone (whiteout "removed",
                # ceph_tpu/osd/replicated.py): a dead name unless a NEWER
                # live copy exists (the object was re-created after)
                bucket = removed if osd.store.getattr(
                    soid, WHITEOUT_KEY) == "removed" else live
                prev = bucket.get(base)
                # None sentinel: version-less objects (omap-only meta
                # twins, pre-versioning writes) decode as (0, "") and
                # must still register as live
                if prev is None or ver > prev:
                    bucket[base] = ver
        return sorted(
            b for b, v in live.items()
            if b not in removed or v > removed[b]
        )

    def scrub(self, oid: str) -> dict:
        return self._rados._run(self._cluster.deep_scrub(oid))

    # -- omap / cls exec / watch-notify (librados metadata surface) --------

    def omap_set(self, oid: str, kvs: Dict[str, bytes]) -> None:
        self._rados._run(self._cluster.backend.omap_set(oid, kvs))

    def omap_get(self, oid: str, keys: Optional[List[str]] = None
                 ) -> Dict[str, bytes]:
        return self._rados._run(self._cluster.backend.omap_get(oid, keys))

    def omap_rm(self, oid: str, keys: List[str]) -> None:
        self._rados._run(self._cluster.backend.omap_rm(oid, keys))

    def exec(self, oid: str, cls: str, method: str, inp: bytes = b""):
        """Invoke a server-side object-class method (librados exec)."""
        return self._rados._run(
            self._cluster.backend.exec(oid, cls, method, inp)
        )

    def watch(self, oid: str, callback) -> None:
        self._rados._run(self._cluster.backend.watch(oid, callback))

    def unwatch(self, oid: str) -> None:
        self._rados._run(self._cluster.backend.unwatch(oid))

    def notify(self, oid: str, payload=None, timeout: float = 5.0) -> dict:
        return self._rados._run(
            self._cluster.backend.notify(oid, payload, timeout)
        )

    def lock_exclusive(self, oid: str, name: str, cookie: str) -> int:
        from ceph_tpu.utils.encoding import Encoder

        ret, _ = self.exec(oid, "lock", "lock", Encoder().value(
            {"name": name, "locker": cookie, "type": "exclusive"}
        ).bytes())
        return ret

    def unlock(self, oid: str, name: str, cookie: str) -> int:
        from ceph_tpu.utils.encoding import Encoder

        ret, _ = self.exec(oid, "lock", "unlock", Encoder().value(
            {"name": name, "locker": cookie}
        ).bytes())
        return ret

    # -- async surface -----------------------------------------------------

    def aio_write_full(self, oid: str, data: bytes):
        return self._cluster.write(oid, data)

    def aio_read(self, oid: str):
        return self._cluster.read(oid)
