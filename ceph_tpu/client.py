"""librados-style client API (Rados / IoCtx surface).

Reference: src/librados (Rados cluster handle, IoCtx per pool with
write_full/read/remove/stat, pool create with an EC profile validated by
instantiating the plugin -- the OSDMonitor::get_erasure_code role,
reference src/mon/OSDMonitor.cc:5353).  Synchronous wrappers drive the
async mini-cluster; aio_* variants return awaitables.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ceph_tpu.osd.cluster import ECCluster
from ceph_tpu.osd.ecbackend import SIZE_KEY, shard_oid
from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.utils.config import get_config


class Rados:
    """Cluster handle: owns the OSDs and the pools."""

    def __init__(self, n_osds: int = 8):
        self.n_osds = n_osds
        self._pools: Dict[str, ECCluster] = {}
        self._loop = asyncio.new_event_loop()

    # -- pool ops (mon-role: profile validation at create time) ------------

    def pool_create(self, name: str, profile: Optional[Dict[str, str]] = None):
        if name in self._pools:
            raise ValueError(f"pool {name} exists")
        if profile is None:
            text = get_config().get_val("osd_pool_default_erasure_code_profile")
            profile = dict(kv.split("=", 1) for kv in text.split())
        # validate the profile by instantiating the codec (monitor behavior)
        check = dict(profile)
        plugin = check.pop("plugin", "jerasure")
        registry_mod.instance().factory(plugin, check)
        self._pools[name] = self._run(self._make_pool(profile))
        return self.open_ioctx(name)

    async def _make_pool(self, profile):
        return ECCluster(self.n_osds, dict(profile))

    def pool_delete(self, name: str) -> None:
        pool = self._pools.pop(name, None)
        if pool is not None:
            self._run(pool.shutdown())

    def list_pools(self) -> List[str]:
        return sorted(self._pools)

    def open_ioctx(self, name: str) -> "IoCtx":
        if name not in self._pools:
            raise KeyError(f"no pool {name}")
        return IoCtx(self, self._pools[name])

    def shutdown(self) -> None:
        for name in list(self._pools):
            self.pool_delete(name)
        self._loop.close()

    def _run(self, coro):
        return self._loop.run_until_complete(coro)


class IoCtx:
    """Per-pool I/O context (librados::IoCtx role)."""

    def __init__(self, rados: Rados, cluster: ECCluster):
        self._rados = rados
        self._cluster = cluster

    # -- sync surface ------------------------------------------------------

    def write_full(self, oid: str, data: bytes) -> None:
        self._rados._run(self._cluster.write(oid, data))

    def read(self, oid: str) -> bytes:
        return self._rados._run(self._cluster.read(oid))

    def remove(self, oid: str) -> None:
        async def _rm():
            backend = self._cluster.backend
            acting = backend.acting_set(oid)
            from ceph_tpu.osd.types import ECSubWrite, Transaction

            # only shards with a mapped, live OSD can ack (CRUSH holes are
            # None; down OSDs never reply — waiting on either stalls)
            up = [s for s in range(backend.km) if backend._shard_up(acting, s)]
            backend._tid += 1
            tid = backend._tid
            done = asyncio.get_event_loop().create_future()
            backend._pending[tid] = {
                "committed": set(),
                "expected": {f"osd.{acting[s]}" for s in up},
                "done": done,
            }
            version = max(backend._versions.values(), default=0) + 1
            backend._versions[oid] = version
            for s in up:
                txn = Transaction().remove(shard_oid(oid, s))
                await backend.messenger.send_message(
                    backend.name,
                    f"osd.{acting[s]}",
                    ECSubWrite(
                        from_shard=s, tid=tid, oid=oid,
                        transaction=txn, at_version=version,
                    ),
                )
            await asyncio.wait_for(done, timeout=30)
            del backend._pending[tid]

        self._rados._run(_rm())

    def stat(self, oid: str) -> int:
        """Logical object size (from the first reachable shard's xattr)."""
        backend = self._cluster.backend
        acting = backend.acting_set(oid)
        for s in range(backend.km):
            if acting[s] is None:
                continue
            try:
                size = self._cluster.osds[acting[s]].store.getattr(
                    shard_oid(oid, s), SIZE_KEY
                )
            except FileNotFoundError:
                continue
            if size is not None:
                return size
        raise FileNotFoundError(oid)

    def list_objects(self) -> List[str]:
        names = set()
        for osd in self._cluster.osds:
            for soid in osd.store.list_objects():
                names.add(soid.rsplit("@", 1)[0])
        return sorted(names)

    def scrub(self, oid: str) -> dict:
        return self._rados._run(self._cluster.deep_scrub(oid))

    # -- async surface -----------------------------------------------------

    def aio_write_full(self, oid: str, data: bytes):
        return self._cluster.write(oid, data)

    def aio_read(self, oid: str):
        return self._cluster.read(oid)
