"""Compressor plugin registry (src/compressor equivalent).

Reference: src/compressor/Compressor.cc:83 Compressor::create with
zlib/snappy/zstd/lz4/brotli plugins loaded through the generic
PluginRegistry (the same dlopen pattern as EC plugins,
src/common/PluginRegistry.cc).  Here: the same factory surface with the
backends available in-image (zlib, bz2, lzma via stdlib; passthrough);
unavailable algorithms raise like a missing plugin would.
"""

from __future__ import annotations

import bz2
import lzma
import zlib
from typing import Dict, Optional


class Compressor:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes) -> bytes:
        raise NotImplementedError


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 5):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)


class Bz2Compressor(Compressor):
    name = "bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return bz2.decompress(data)


class LzmaCompressor(Compressor):
    name = "lzma"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return lzma.decompress(data)


class PassthroughCompressor(Compressor):
    name = "none"

    def compress(self, data: bytes) -> bytes:
        return bytes(data)

    def decompress(self, data: bytes) -> bytes:
        return bytes(data)


_REGISTRY: Dict[str, type] = {
    "zlib": ZlibCompressor,
    "bz2": Bz2Compressor,
    "lzma": LzmaCompressor,
    "none": PassthroughCompressor,
}

#: algorithms the reference ships that this image has no backend for
_KNOWN_UNAVAILABLE = {"snappy", "zstd", "lz4", "brotli"}


def create(alg: str) -> Compressor:
    """Compressor::create: factory by algorithm name."""
    cls = _REGISTRY.get(alg)
    if cls is None:
        if alg in _KNOWN_UNAVAILABLE:
            raise ModuleNotFoundError(
                f"compression algorithm {alg} has no backend in this build"
            )
        raise ValueError(f"unknown compression algorithm {alg}")
    return cls()


def get_supported() -> list:
    return sorted(_REGISTRY)
