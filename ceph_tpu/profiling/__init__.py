"""Wire-tax profiler: hot-path cost attribution for the Python wire loop.

Three arms, one ledger (docs/observability.md "Wire-tax profiling"):

* **stage cost ledger** (:mod:`ledger`): zero-alloc ``with
  prof.stage(name)`` markers on the real wire-loop seams (encoder
  assembly, crc fold, cork append, writelines, frame parse, body
  codecs, objecter/coalescer submit), exclusive-time nested, with
  per-connection per-burst sub-accounting;
* **event-loop + GC arm** (:mod:`loopmon`): every asyncio callback's
  duration + timer scheduling latency (subsuming ``LoopLagProbe`` --
  the probe's sleeper task is the sampled fallback when this arm is
  off) and ``gc.callbacks`` pause accounting, GC pauses credited OUT of
  the stage they interrupted so nothing double counts;
* **sampling profiler** (:mod:`sampler`): a thread sampler attributing
  stacks to the declared stages, exporting speedscope + collapsed
  flamegraph JSON.

Modes (``profile_mode``): ``off`` (default -- the instrumented seams
run one global-bool branch and allocate nothing), ``on`` (ledger +
loop/GC arms; the <=3%-overhead configuration the bench stage gates),
``full`` (``on`` plus the continuous stack sampler).

The artifact this subsystem exists to produce is the ranked wire-tax
bill of costs (``bench.py wire_tax_*`` / PERF_NOTES round 19) that
ROADMAP item 2's native transport executes against.
"""

from __future__ import annotations

from typing import Optional

from ceph_tpu.profiling import ledger as _ledger
from ceph_tpu.profiling import loopmon as _loopmon

# the hot-path surface, re-exported (instrumented modules import these)
stage = _ledger.stage
stage_enter = _ledger.stage_enter
stage_exit = _ledger.stage_exit
note_burst = _ledger.note_burst
enabled = _ledger.enabled

_MODES = ("off", "on", "full")
_mode = "off"
_monitor: Optional["_loopmon.LoopMonitor"] = None
_sampler = None


def mode() -> str:
    return _mode


def loop_monitor():
    """The active LoopMonitor (None when the loop arm is off) -- the
    LoopLagProbe fold reads this to decide whether to run its own
    sleeper task."""
    return _loopmon.active()


def configure(mode: Optional[str] = None) -> str:
    """Apply ``profile_mode`` (argument overrides + persists to the
    config, the trace.configure() discipline); installs/uninstalls the
    arms.  Returns the effective mode."""
    global _mode, _monitor, _sampler
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    if mode is not None:
        if mode not in _MODES:
            raise ValueError(f"bad profile mode {mode!r}")
        cfg.set_val("profile_mode", mode)
    eff = str(cfg.get_val("profile_mode"))
    if eff not in _MODES:
        eff = "off"
    if eff == _mode:
        return _mode
    # tear down what the old mode had up
    if _sampler is not None:
        _sampler.stop()
        _sampler = None
    if _monitor is not None and eff == "off":
        _monitor.uninstall()
    if eff == "off":
        _ledger.set_enabled(False)
        _mode = eff
        return _mode
    _ledger.set_enabled(True)
    if _monitor is None:
        _monitor = _loopmon.LoopMonitor()
    _monitor.install()
    if eff == "full":
        from ceph_tpu.profiling.sampler import StackSampler

        hz = float(cfg.get_val("profile_sample_hz"))
        _sampler = StackSampler(hz=hz)
        _sampler.start()
    _mode = eff
    return _mode


def current_sampler():
    return _sampler


def reset() -> None:
    _ledger.reset()
    if _monitor is not None:
        _monitor.reset()


# -- views -------------------------------------------------------------------

def snapshot() -> dict:
    out = {
        "mode": _mode,
        "stages": _ledger.stages_snapshot(),
        "bursts": _ledger.bursts_snapshot(),
    }
    mon = _loopmon.active()
    if mon is not None:
        out["loop"] = mon.snapshot()
    if _sampler is not None:
        out["sampler"] = {
            "samples": _sampler.samples,
            "stage_shares": _sampler.stage_shares(),
        }
    return out


def decomposition(wall_ns: int) -> dict:
    """The wire-tax bill of costs for a measured ``wall_ns`` window
    (callers reset() before and snapshot after).

    Rows sum to ``covered_ns`` with no double counting: stage time is
    exclusive (nesting banks the parent), GC pauses are credited OUT of
    the stage they interrupted (ledger.gc_credit), and
    ``event_loop_other`` is callback time not inside any declared stage
    or GC pause.  ``idle`` is the selector/off-loop remainder.
    ``coverage_pct`` = covered / wall -- the bench gates it >= 90 on
    the saturated cluster path."""
    stages = _ledger.stages_snapshot()
    stage_ns = sum(s["ns"] for s in stages.values())
    mon = _loopmon.active()
    gc_ns = mon.gc_ns if mon is not None else 0
    cb_ns = mon.callback_ns if mon is not None else 0
    other = max(0, cb_ns - stage_ns - gc_ns)
    covered = stage_ns + gc_ns + other
    idle = max(0, wall_ns - covered)
    rows = [
        {"stage": name, "ns": s["ns"], "calls": s["calls"],
         "bytes": s["bytes"],
         "pct": round(100 * s["ns"] / wall_ns, 2) if wall_ns else 0.0}
        for name, s in stages.items()
    ]
    rows.append({"stage": "gc.pause", "ns": gc_ns,
                 "calls": mon.gc_collections if mon is not None else 0,
                 "bytes": 0,
                 "pct": round(100 * gc_ns / wall_ns, 2) if wall_ns else 0.0})
    rows.append({"stage": "event_loop.other", "ns": other,
                 "calls": mon.callbacks if mon is not None else 0,
                 "bytes": 0,
                 "pct": round(100 * other / wall_ns, 2) if wall_ns else 0.0})
    rows.sort(key=lambda r: -r["ns"])
    return {
        "wall_ns": wall_ns,
        "covered_ns": covered,
        "idle_ns": idle,
        "coverage_pct": round(100 * covered / wall_ns, 2)
        if wall_ns else 0.0,
        "rows": rows,
    }


def report_slice() -> Optional[dict]:
    """The compact MgrReport payload slice (None when off): per-stage
    ns + the loop/GC scalars -- what the mgr renders as
    ``ceph_profile_stage_seconds_total{stage}``."""
    if _mode == "off":
        return None
    out = {"stages": {name: s["ns"]
                      for name, s in _ledger.stages_snapshot().items()}}
    mon = _loopmon.active()
    if mon is not None:
        out["gc_ns"] = mon.gc_ns
        out["callback_ns"] = mon.callback_ns
        out["lag_ms"] = round(mon.lag_ms, 3)
    return out


def prometheus_text() -> str:
    """In-process exposition: cumulative per-stage seconds (the
    wire-fed twin renders the same family from report frames in
    mgr/pgmap.py)."""
    if _mode == "off":
        return ""
    lines = [
        "# HELP ceph_profile_stage_seconds_total exclusive seconds "
        "per wire-tax profiler stage (ceph_tpu/profiling/)",
        "# TYPE ceph_profile_stage_seconds_total counter",
    ]
    for name, s in _ledger.stages_snapshot().items():
        lines.append(
            f'ceph_profile_stage_seconds_total{{stage="{name}"}} '
            f"{s['ns'] / 1e9:.6f}")
    mon = _loopmon.active()
    if mon is not None:
        lines += [
            "# HELP ceph_profile_gc_seconds_total GC pause seconds "
            "(gc.callbacks accounting)",
            "# TYPE ceph_profile_gc_seconds_total counter",
            f"ceph_profile_gc_seconds_total {mon.gc_ns / 1e9:.6f}",
            "# HELP ceph_profile_callback_seconds_total seconds inside "
            "asyncio callbacks (the event-loop arm)",
            "# TYPE ceph_profile_callback_seconds_total counter",
            f"ceph_profile_callback_seconds_total "
            f"{mon.callback_ns / 1e9:.6f}",
        ]
    return "\n".join(lines)


# -- admin-socket hooks (daemon/osd.py registers these) ----------------------

def asok_status(cmd=None) -> dict:
    out = {"mode": _mode}
    mon = _loopmon.active()
    if mon is not None:
        out.update({
            "callback_ns": mon.callback_ns,
            "callbacks": mon.callbacks,
            "lag_ms": round(mon.lag_ms, 3),
            "gc_ns": mon.gc_ns,
            "gc_collections": mon.gc_collections,
        })
    stages = _ledger.stages_snapshot()
    out["stages_active"] = len(stages)
    out["stage_ns_total"] = sum(s["ns"] for s in stages.values())
    return out


def asok_dump(cmd=None) -> dict:
    out = snapshot()
    fmt = (cmd or {}).get("format")
    if fmt == "speedscope" and _sampler is not None:
        out["speedscope"] = _sampler.speedscope()
    elif fmt == "collapsed" and _sampler is not None:
        out["collapsed"] = _sampler.collapsed()
    return out


def asok_reset(cmd=None) -> dict:
    reset()
    return {"reset": True, "mode": _mode}
