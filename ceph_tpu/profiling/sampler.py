"""Thread stack sampler: stage-attributed flamegraph evidence.

The ledger says WHAT each declared stage costs; the sampler says WHERE
inside a stage the time goes, without instrumenting anything -- a
background thread snapshots the event-loop thread's stack at
``profile_sample_hz`` via ``sys._current_frames()`` and attributes each
sample to the ledger's innermost active stage (``unattributed`` between
stages).  This is the signal/thread-sampler arm of the wire-tax
profiler: safe under asyncio (no signal delivery into the loop thread),
portable, and bounded (distinct stacks cap at ``_STACK_CAP``; overflow
is counted, never silently dropped).

Exports:

* :meth:`StackSampler.speedscope` -- a speedscope.app ``sampled``
  profile (shared frame table + per-sample frame-index stacks +
  weights), one profile per attributed stage so the viewer's profile
  picker IS the cost-center picker.
* :meth:`StackSampler.collapsed` -- Brendan-Gregg collapsed/folded
  lines (``stage;outer;...;leaf count``) for flamegraph.pl-style
  tooling and cheap diffing in tests.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

#: bound on distinct (stage, stack) keys retained
_STACK_CAP = 8192
#: frames deeper than this are truncated from the root side (the leaf
#: frames carry the attribution signal)
_MAX_DEPTH = 48


class StackSampler:
    """Samples ``target_thread`` (default: the thread that constructs
    the sampler) from a daemon thread until :meth:`stop`."""

    def __init__(self, hz: float = 87.0,
                 target_thread_id: Optional[int] = None):
        self.interval = 1.0 / max(1.0, float(hz))
        self.target_thread_id = (
            target_thread_id if target_thread_id is not None
            else threading.get_ident())
        #: (stage, (frame, frame, ...)) -> sample count; frames are
        #: "qualname (file:line)" strings leaf-last
        self.stacks: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        self.samples = 0
        self.dropped = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling -----------------------------------------------------------

    def _snap_once(self) -> None:
        from ceph_tpu.profiling import ledger

        frame = sys._current_frames().get(self.target_thread_id)
        if frame is None:
            return
        stack: List[str] = []
        depth = 0
        while frame is not None and depth < _MAX_DEPTH:
            code = frame.f_code
            stack.append(
                f"{code.co_qualname if hasattr(code, 'co_qualname') else code.co_name}"  # noqa: E501
                f" ({code.co_filename.rsplit('/', 1)[-1]}:"
                f"{frame.f_lineno})")
            frame = frame.f_back
            depth += 1
        stack.reverse()  # root first
        stage = ledger.current_stage_name() or "unattributed"
        key = (stage, tuple(stack))
        if key not in self.stacks and len(self.stacks) >= _STACK_CAP:
            self.dropped += 1
            return
        self.stacks[key] = self.stacks.get(key, 0) + 1
        self.samples += 1

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._snap_once()
            except Exception:  # noqa: BLE001 -- a torn frame walk (the
                # target mutated under us) just loses one sample
                pass

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ceph-tpu-profiler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)

    # -- attribution views --------------------------------------------------

    def stage_shares(self) -> Dict[str, float]:
        """Fraction of samples per attributed stage."""
        totals: Dict[str, int] = {}
        for (stage, _stack), n in self.stacks.items():
            totals[stage] = totals.get(stage, 0) + n
        total = sum(totals.values())
        if not total:
            return {}
        return {stage: round(n / total, 4)
                for stage, n in sorted(totals.items())}

    # -- exports ------------------------------------------------------------

    def collapsed(self) -> str:
        """Folded-stack lines ``stage;root;...;leaf count``."""
        lines = []
        for (stage, stack), n in sorted(self.stacks.items()):
            lines.append(";".join((stage,) + stack) + f" {n}")
        return "\n".join(lines)

    def speedscope(self, name: str = "ceph_tpu wire-tax") -> dict:
        """A speedscope file (schema
        https://www.speedscope.app/file-format-schema.json): one
        ``sampled`` profile per attributed stage, shared frame table.
        Sample weights are the sampler interval (seconds)."""
        frame_index: Dict[str, int] = {}
        frames: List[dict] = []

        def fidx(f: str) -> int:
            i = frame_index.get(f)
            if i is None:
                i = frame_index[f] = len(frames)
                frames.append({"name": f})
            return i

        by_stage: Dict[str, List[tuple]] = {}
        for (stage, stack), n in sorted(self.stacks.items()):
            by_stage.setdefault(stage, []).append((stack, n))
        profiles = []
        for stage, rows in sorted(by_stage.items()):
            samples: List[List[int]] = []
            weights: List[float] = []
            for stack, n in rows:
                idx = [fidx(f) for f in stack]
                for _ in range(n):
                    samples.append(idx)
                    weights.append(self.interval)
            profiles.append({
                "type": "sampled",
                "name": stage,
                "unit": "seconds",
                "startValue": 0,
                "endValue": round(sum(weights), 6),
                "samples": samples,
                "weights": weights,
            })
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "name": name,
            "exporter": "ceph_tpu.profiling",
            "activeProfileIndex": 0,
            "shared": {"frames": frames},
            "profiles": profiles,
            "exported_at": time.time(),
        }
