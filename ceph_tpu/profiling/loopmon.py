"""Event-loop + GC arm: scheduler and collector time as cost centers.

The stage ledger attributes the wire loop's DECLARED seams; this module
closes the residual: every asyncio callback's duration (the scheduler's
whole working set -- task steps, timer callbacks, reader wakeups), the
scheduling latency of timer callbacks (the sleep-drift signal
``LoopLagProbe`` used to sample with its own sleeper task), and GC
pauses via ``gc.callbacks``.

Instrumentation point: ``asyncio.events.Handle._run`` -- the one
choke point every callback of every pure-Python event loop passes
through.  The wrapper is installed class-wide while the monitor is
active and removed on uninstall, so the disabled state runs the stock
asyncio code with zero residue.  Per-callback cost while enabled: two
``perf_counter_ns`` reads, one isinstance check, slot arithmetic.

``LoopLagProbe`` (mgr/report.py) treats an active monitor as THE lag
source: its sampled-sleeper task is the fallback when profiling is off,
so a daemon never runs two lag estimators (the round-19 fold -- one lag
number feeds both the MgrReport ``lag_ms`` field and this ledger).

Callback top-K: resolving a callback's qualname per run would dominate
the callback itself, so names are resolved ONLY for callbacks slower
than ``TOPK_MIN_NS`` -- the slow tail is the actionable set anyway.
"""

from __future__ import annotations

import asyncio
import gc
import time
from typing import Dict, Optional

_now_ns = time.perf_counter_ns

#: callbacks faster than this never pay the name lookup (100us)
TOPK_MIN_NS = 100_000
#: hard bound on distinct top-K callback names retained
_TOPK_CAP = 256

_orig_handle_run = None
_installed: Optional["LoopMonitor"] = None


def active() -> Optional["LoopMonitor"]:
    """The installed monitor, or None (profiling off / loop arm off)."""
    return _installed


class LoopMonitor:
    """Process-wide asyncio + GC instrumentation (one per process;
    install()/uninstall() bracket the enabled window)."""

    def __init__(self, alpha: float = 0.25):
        self.alpha = alpha
        #: total ns spent INSIDE loop callbacks (the scheduler's whole
        #: execution share of wall time -- the coverage denominator's
        #: complement is selector idle)
        self.callback_ns = 0
        self.callbacks = 0
        #: EWMA of timer-callback scheduling latency (the LoopLagProbe
        #: semantics: how late a due callback actually ran), plus hwm
        self.lag_ms = 0.0
        self.lag_hwm_ms = 0.0
        self.timer_lags = 0
        #: scheduling-latency histogram (log2 usec buckets)
        from ceph_tpu.utils.perf import HistogramAxis

        self._lag_axis = HistogramAxis("sched_lag_usec", 0, 64, 32, "log2")
        self.lag_counts = [0] * self._lag_axis.buckets
        #: slow-callback top-K: qualname -> [ns, calls]
        self.topk: Dict[str, list] = {}
        self.topk_overflow = 0
        #: GC pause accounting (gc.callbacks start/stop pairs)
        self.gc_ns = 0
        self.gc_collections = 0
        self.gc_pause_hwm_ns = 0
        self._gc_t0 = 0

    # -- the Handle._run wrapper -------------------------------------------

    def _timed_run(self, handle) -> None:
        t0 = _now_ns()
        if isinstance(handle, asyncio.TimerHandle):
            # scheduling latency: how far past its due time this timer
            # actually ran -- the event-loop stall signal
            try:
                lag_s = handle._loop.time() - handle._when
            except AttributeError:
                lag_s = 0.0
            if lag_s > 0:
                lag_ms = lag_s * 1e3
                self.lag_ms += self.alpha * (lag_ms - self.lag_ms)
                if lag_ms > self.lag_hwm_ms:
                    self.lag_hwm_ms = lag_ms
                self.lag_counts[
                    self._lag_axis.bucket_for(lag_s * 1e6)] += 1
                self.timer_lags += 1
        try:
            _orig_handle_run(handle)
        finally:
            dt = _now_ns() - t0
            self.callback_ns += dt
            self.callbacks += 1
            if dt >= TOPK_MIN_NS:
                self._note_slow(handle, dt)

    def _note_slow(self, handle, dt: int) -> None:
        cb = handle._callback
        name = getattr(cb, "__qualname__", None)
        if name is None:
            func = getattr(cb, "func", None)  # functools.partial
            name = getattr(func, "__qualname__", type(cb).__name__)
        row = self.topk.get(name)
        if row is None:
            if len(self.topk) >= _TOPK_CAP:
                self.topk_overflow += 1
                return
            row = self.topk[name] = [0, 0]
        row[0] += dt
        row[1] += 1

    # -- GC callbacks -------------------------------------------------------

    def _gc_cb(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._gc_t0 = _now_ns()
        elif phase == "stop" and self._gc_t0:
            dt = _now_ns() - self._gc_t0
            self._gc_t0 = 0
            self.gc_ns += dt
            self.gc_collections += 1
            if dt > self.gc_pause_hwm_ns:
                self.gc_pause_hwm_ns = dt
            # the pause ran inside whatever stage was open: credit it
            # out so stage time and gc time stay disjoint
            from ceph_tpu.profiling import ledger

            ledger.gc_credit(dt)

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> None:
        global _orig_handle_run, _installed
        if _installed is self:
            return
        if _installed is not None:
            _installed.uninstall()
        _orig_handle_run = asyncio.events.Handle._run
        monitor = self

        def _run(handle_self):
            monitor._timed_run(handle_self)

        asyncio.events.Handle._run = _run
        gc.callbacks.append(self._gc_cb)
        _installed = self

    def uninstall(self) -> None:
        global _orig_handle_run, _installed
        if _installed is not self:
            return
        if _orig_handle_run is not None:
            asyncio.events.Handle._run = _orig_handle_run
            _orig_handle_run = None
        try:
            gc.callbacks.remove(self._gc_cb)
        except ValueError:
            pass
        _installed = None

    # -- views --------------------------------------------------------------

    def lag_histogram(self) -> dict:
        return {
            "bounds_usec": self._lag_axis.upper_bounds(),
            "counts": list(self.lag_counts),
            "samples": self.timer_lags,
        }

    def top_callbacks(self, limit: int = 20) -> list:
        rows = sorted(self.topk.items(), key=lambda kv: -kv[1][0])
        return [{"callback": name, "ns": ns, "calls": calls}
                for name, (ns, calls) in rows[:limit]]

    def snapshot(self) -> dict:
        return {
            "callback_ns": self.callback_ns,
            "callbacks": self.callbacks,
            "lag_ms": round(self.lag_ms, 3),
            "lag_hwm_ms": round(self.lag_hwm_ms, 3),
            "sched_lag_histogram": self.lag_histogram(),
            "top_callbacks": self.top_callbacks(),
            "topk_overflow": self.topk_overflow,
            "gc_ns": self.gc_ns,
            "gc_collections": self.gc_collections,
            "gc_pause_hwm_ns": self.gc_pause_hwm_ns,
        }

    def reset(self) -> None:
        self.callback_ns = 0
        self.callbacks = 0
        self.lag_ms = 0.0
        self.lag_hwm_ms = 0.0
        self.timer_lags = 0
        for i in range(len(self.lag_counts)):
            self.lag_counts[i] = 0
        self.topk.clear()
        self.topk_overflow = 0
        self.gc_ns = 0
        self.gc_collections = 0
        self.gc_pause_hwm_ns = 0
