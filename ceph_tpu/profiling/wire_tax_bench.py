"""Wire-tax benchmark stage: the ranked bill of costs for ROADMAP 2.

Round 17 measured the saturated cluster-path ceiling (~250 ops/s at
99% CPU in the Python wire loop) but could not say WHERE the 99% goes;
this stage runs the same saturated full-stack path (client Objecter ->
primary -> k+m fan-out over real localhost TCP, ``msg/cluster_bench.py``
harness) under the wire-tax profiler and emits the decomposition table
ROADMAP item 2's native transport will execute against.

Four gates, every one raising on violation:

* **coverage**: the decomposition (declared stages + GC + event-loop
  residual) must sum to >= ``coverage_min_pct`` (90%) of the measured
  saturated wall -- an attribution that misses a tenth of the wall is
  aimed blind;
* **enabled overhead**: profiling ``on`` (ledger + loop/GC arms) must
  cost <= ``overhead_limit_pct`` (3%) vs off, measured as per-block
  off/on ratios (modes back to back so machine drift cancels, min
  ratio across blocks, bounded retries -- the trace_bench discipline);
* **disabled overhead**: exactly zero ALLOCATIONS -- the deterministic
  form of "exactly zero": a ``sys.getallocatedblocks`` delta of 0
  across thousands of disabled marker cycles (a wall-clock zero is not
  measurable against noise; the off path is the same code minus one
  branch, and the alloc pin is what keeps it that way).  The off/off
  wall ratio is also reported, un-gated, as evidence;
* **export contract**: a short ``full``-mode segment must produce a
  speedscope document with the schema's required keys and at least one
  stage-attributed profile;
* **native-codec A/B** (round 20): the r14/r19 architecture (pure
  Python codec, per-op submit) vs the r20 one (native batched codec +
  vectorized Objecter submit) on the same payloads -- frame bytes
  byte-identical across codecs asserted in the gate itself, the
  serialization cost centers at <= half their python-mode share of the
  saturated wall, and ops/s at >= 1.5x the remeasured python-mode
  baseline.  Skipped (and recorded) when the native codec is
  unavailable -- the graceful-fallback contract.
* **OSD-exec A/B** (round 22): per-op client-op execution
  (``osd_op_batch_exec`` off) vs the array-batched fast path, same
  payloads and submit batching -- stored shard bytes byte-identical
  across modes, and the OSD execution cost centers (``osd.op_exec`` +
  ``osd.batch_exec``) at <= ``osd_share_ratio_max`` of their per-op
  share of the saturated wall.
* **ring-vs-TCP A/B** (round 22): localhost TCP vs shared-memory frame
  rings (``osd_msgr_shm_ring``) for the colocated daemons -- the rings
  must actually carry the traffic (``ring_conns`` counter), shard
  bytes identical, ops/s >= ``ring_gain_min`` x the TCP baseline, and
  per-frame send cost recorded per mode.

Used by bench.py (``wire_tax_host`` + the ``wire_tax_*`` headline
keys), ``tools/ec_benchmark.py --workload wire-tax [--smoke]``, and
``tools/ci_lint.sh --profile-smoke``.
"""

from __future__ import annotations

import asyncio
import gc
import sys
import time
from typing import Dict, List, Optional

from ceph_tpu import profiling


def _restore_mode(prior: str) -> None:
    profiling.configure(mode=prior if prior in ("off", "on", "full")
                        else "off")


async def _cycle(harness, payloads: Dict[str, bytes],
                 writers: int, batch: int = 0) -> float:
    write_s = await harness.run_writes(payloads, writers, batch=batch)
    read_s, got = await harness.run_reads(payloads, writers, batch=batch)
    for oid, data in payloads.items():
        if got.get(oid) != data:
            raise AssertionError(
                f"wire-tax: read-back of {oid} mismatched")
    return write_s + read_s


def _serialization_share(decomp: dict) -> float:
    """The serialization cost centers' summed share of the wall: the
    r19 bill's wire.encode + wire.decode_body + wire.envelope rows --
    exactly what the native codec exists to shrink."""
    return round(sum(
        row["pct"] for row in decomp["rows"]
        if row["stage"] in ("wire.encode", "wire.decode_body",
                            "wire.envelope")), 3)


def _osd_exec_share(decomp: dict) -> float:
    """The OSD execution cost centers' summed share of the wall:
    ``osd.op_exec`` (the per-op bookkeeping sections) plus
    ``osd.batch_exec`` (the batched fast path's array passes) -- what
    the round-22 batch-execution A/B compares across modes."""
    return round(sum(
        row["pct"] for row in decomp["rows"]
        if row["stage"] in ("osd.op_exec", "osd.batch_exec")), 3)


def _codec_frame_bytes_gate() -> None:
    """Native and Python codecs must emit byte-identical frame bodies
    for representative typed messages -- asserted INSIDE the A/B gate,
    so a codec drift can never hide behind a throughput win."""
    from ceph_tpu.msg import wire
    from ceph_tpu.native import wire_codec
    from ceph_tpu.osd.types import (ECSubRead, ECSubReadReply,
                                    ECSubWrite, ECSubWriteReply,
                                    LogEntry, Transaction)

    nat = wire_codec.native()
    if nat is None:
        raise AssertionError("wire-tax codec A/B: native codec gone "
                             "mid-run")
    txn = Transaction().write("o@1", 0, b"\xa5" * 16384)
    txn.setattr("o@1", "hinfo", {"crc": [1, 2, 3, 4], "sz": 16384})
    sample = [
        ECSubWrite(1, 7, "o@1", txn, (3, "osd.1"),
                   [LogEntry(3, "o@1", "append", 16)],
                   reqid=("c", 12, 34), trace=[5, 1, 0],
                   qos_class="gold"),
        ECSubWriteReply(2, 7, committed=True, applied=True,
                        current_version=(5, "osd.0")),
        ECSubRead(0, 9, to_read={"a": [(0, 4096)]},
                  attrs_to_read=["hinfo"]),
        ECSubReadReply(3, 9,
                       buffers_read={"a": [(0, b"\x5a" * 4096)]},
                       attrs_read={"a": {}}, errors={}),
        {"op": "client_op", "tid": 5, "kind": "write", "oid": "o",
         "pool": "p", "data": b"d" * 16384, "reqid": ["c", 1, 2],
         "snapc": None},
        {"op": "client_reply", "tid": 5, "ok": True, "result": None},
    ]
    for msg in sample:
        py = wire.encode_message(msg)
        na = nat.encode_body(msg)
        if py != na:
            raise AssertionError(
                "wire-tax codec A/B: native and Python codecs emitted "
                f"different bytes for {type(msg).__name__}")
        if wire.decode_message(na) != nat.decode_body(py):
            raise AssertionError(
                "wire-tax codec A/B: cross-decode mismatch for "
                f"{type(msg).__name__}")


def _alloc_pin(cycles: int = 20000) -> int:
    """The off-mode zero-allocation pin: disabled marker enter/exit
    must allocate NOTHING beyond the bare loop scaffolding.  The
    measurement is control-subtracted -- the identical loop without the
    markers is measured alongside, so interpreter bookkeeping (range
    iterators, freelist growth) cancels and the returned delta is the
    markers' own contribution, deterministically."""
    if profiling.enabled():
        raise AssertionError("wire-tax: alloc pin must run with "
                             "profiling off")
    m1 = profiling.stage("wire.encode")
    m2 = profiling.stage("wire.crc32c")

    def marked():
        for _ in range(cycles):
            with m1:
                with m2:
                    pass

    def control():
        for _ in range(cycles):
            pass

    def measure(fn):
        base = sys.getallocatedblocks()
        fn()
        return sys.getallocatedblocks() - base

    marked()  # warm: bytecode/freelist steady state
    control()
    gc.disable()
    try:
        deltas = [measure(marked) - measure(control)
                  for _trial in range(3)]
    finally:
        gc.enable()
    return min(deltas)


def run_wire_tax_bench(ec=None, *, n_objects: int = 48,
                       obj_bytes: int = 16 << 10, writers: int = 12,
                       iters: int = 2, seed: int = 191,
                       coverage_min_pct: float = 90.0,
                       overhead_limit_pct: float = 3.0,
                       retries: int = 3,
                       n_osds: Optional[int] = None,
                       top_n: int = 5,
                       codec_gain_min: float = 1.5,
                       codec_share_ratio_max: float = 0.5,
                       codec_batch: int = 8,
                       osd_share_ratio_max: float = 0.6,
                       ring_gain_min: float = 0.85) -> dict:
    """The full stage; raises on any gate violation.  Returns the
    JSON-ready dict bench.py records as ``wire_tax_host``."""
    from ceph_tpu.msg.cluster_bench import ClusterHarness, make_payloads

    if ec is None:
        from ceph_tpu.plugins import registry as registry_mod

        ec = registry_mod.instance().factory(
            "jerasure", {"k": "4", "m": "2",
                         "technique": "reed_sol_van"})
    if n_osds is None:
        n_osds = ec.get_chunk_count()
    payloads = make_payloads(n_objects, obj_bytes, seed)
    from ceph_tpu.utils.config import get_config

    prior_mode = str(get_config().get_val("profile_mode"))
    profiling.configure(mode="off")
    # gate 3 first: it requires profiling off and is deterministic
    alloc_delta = _alloc_pin()
    if alloc_delta != 0:
        raise AssertionError(
            f"wire-tax: disabled markers allocated {alloc_delta} "
            "blocks over the pin loop -- the off path must be "
            "allocation-free")
    loop = asyncio.new_event_loop()
    harness = ClusterHarness(ec, n_osds, cork=True, pool="wiretaxpool")
    out: dict = {
        "n_objects": n_objects, "obj_bytes": obj_bytes,
        "writers": writers, "n_osds": n_osds,
        "coverage_min_pct": coverage_min_pct,
        "overhead_limit_pct": overhead_limit_pct,
        "wire_tax_alloc_blocks_off": alloc_delta,
    }
    try:
        loop.run_until_complete(harness.start())
        for oid in payloads:
            harness.objecter.acting_set(oid)
        # warm: TCP sessions, codec tables, placement -- off-profile
        loop.run_until_complete(_cycle(harness, payloads, writers))

        # -- overhead: per-block off/on (+ off/off evidence) ratios ---
        # Each measurement block runs TWO cycles: the native codec
        # halved the cycle wall, and a single ~150ms cycle is inside
        # this harness's machine-noise band -- the min-of-ratios
        # defense needs blocks long enough that a ratio means anything.
        async def _block():
            return (await _cycle(harness, payloads, writers)
                    + await _cycle(harness, payloads, writers))

        ratios: List[float] = []
        off_off: List[float] = []
        attempts = 0
        while True:
            attempts += 1
            for _ in range(max(1, iters)):
                profiling.configure(mode="off")
                off_a = loop.run_until_complete(_block())
                off_b = loop.run_until_complete(_block())
                profiling.configure(mode="on")
                profiling.reset()
                on_s = loop.run_until_complete(_block())
                ratios.append(on_s / min(off_a, off_b))
                off_off.append(off_b / off_a)
            overhead = (min(ratios) - 1) * 100
            if overhead <= overhead_limit_pct or \
                    attempts >= max(1, retries):
                break
        if overhead > overhead_limit_pct:
            raise AssertionError(
                f"wire-tax: enabled-profiler overhead {overhead:.2f}% "
                f"exceeds the {overhead_limit_pct}% gate after "
                f"{attempts} attempts")
        out["wire_tax_overhead_pct_enabled"] = round(overhead, 3)
        out["wire_tax_overhead_pct_off"] = round(
            (min(off_off) - 1) * 100, 3)
        out["overhead_attempts"] = attempts

        # -- the decomposition segment (the artifact) -----------------
        profiling.configure(mode="on")
        profiling.reset()
        t0 = time.perf_counter_ns()
        seg_cycles = max(2, iters)
        for _ in range(seg_cycles):
            loop.run_until_complete(_cycle(harness, payloads, writers))
        wall_ns = time.perf_counter_ns() - t0
        decomp = profiling.decomposition(wall_ns)
        snap = profiling.snapshot()
        if decomp["coverage_pct"] < coverage_min_pct:
            raise AssertionError(
                f"wire-tax: decomposition covers "
                f"{decomp['coverage_pct']}% of the saturated wall, "
                f"below the {coverage_min_pct}% gate -- the "
                "attribution is missing a cost center")
        ops = seg_cycles * 2 * n_objects  # writes + reads
        out["wire_tax_ops_per_sec"] = round(ops / (wall_ns / 1e9), 1)
        out["wire_tax_coverage_pct"] = decomp["coverage_pct"]
        out["decomposition"] = decomp
        out["wire_tax_top"] = [
            {"stage": r["stage"], "pct": r["pct"], "ns": r["ns"],
             "calls": r["calls"]}
            for r in decomp["rows"][:top_n]
        ]
        out["bursts"] = snap["bursts"]
        out["loop"] = {
            k: snap["loop"][k]
            for k in ("lag_ms", "lag_hwm_ms", "gc_ns",
                      "gc_collections", "callbacks", "callback_ns")
        } if "loop" in snap else None

        # -- native-codec A/B (the round-20 architecture gate) --------
        # The r14/r19 wire architecture (pure-Python codec, per-op
        # submit) against the r20 one (native batched codec +
        # vectorized Objecter submit), same payloads, each read-back
        # gated inside its cycles.  Three gates when the native codec
        # is available: frame bytes byte-identical across codecs
        # (asserted directly, IN this gate), the serialization cost
        # centers (wire.encode + wire.decode_body + wire.envelope) at
        # <= codec_share_ratio_max of their python-mode share of the
        # saturated wall, and ops/s >= codec_gain_min x the python-mode
        # baseline (the ~250 ops/s r14 ceiling remeasured in-run).
        # Native unavailable (no toolchain / CEPH_TPU_NATIVE=0) skips
        # the gates and records the degraded state -- the graceful-
        # fallback contract keeps this stage green everywhere.
        from ceph_tpu.native import wire_codec as _wire_codec
        from ceph_tpu.utils.config import get_config as _get_config

        out["wire_codec_native_enabled"] = _wire_codec.enabled()
        if out["wire_codec_native_enabled"]:
            _codec_frame_bytes_gate()
            out["wire_codec_frame_bytes_identical"] = True
            cfg2 = _get_config()
            prior_codec = bool(cfg2.get_val("osd_wire_codec_native"))
            ab: Dict[str, dict] = {}
            seg_cycles2 = max(2, iters)
            try:
                for mode, native_on, batch in (
                        ("python", False, 0),
                        ("native", True, codec_batch)):
                    cfg2.apply_changes({"osd_wire_codec_native":
                                        native_on})
                    h2 = ClusterHarness(ec, n_osds, cork=True,
                                        pool=f"wcab{mode}")
                    loop.run_until_complete(h2.start())
                    try:
                        for oid in payloads:
                            h2.objecter.acting_set(oid)
                        loop.run_until_complete(
                            _cycle(h2, payloads, writers, batch=batch))
                        profiling.configure(mode="on")
                        profiling.reset()
                        t0 = time.perf_counter_ns()
                        for _ in range(seg_cycles2):
                            loop.run_until_complete(_cycle(
                                h2, payloads, writers, batch=batch))
                        wall2 = time.perf_counter_ns() - t0
                        ab[mode] = {
                            "ops_per_sec": round(
                                seg_cycles2 * 2 * n_objects
                                / (wall2 / 1e9), 1),
                            "serialization_share_pct":
                                _serialization_share(
                                    profiling.decomposition(wall2)),
                        }
                        profiling.configure(mode="off")
                    finally:
                        loop.run_until_complete(h2.shutdown())
            finally:
                cfg2.apply_changes(
                    {"osd_wire_codec_native": prior_codec})
            gain = ab["native"]["ops_per_sec"] / \
                max(1e-9, ab["python"]["ops_per_sec"])
            ratio = ab["native"]["serialization_share_pct"] / \
                max(1e-9, ab["python"]["serialization_share_pct"])
            out["wire_codec_python_ops_per_sec"] = \
                ab["python"]["ops_per_sec"]
            out["wire_codec_native_ops_per_sec"] = \
                ab["native"]["ops_per_sec"]
            out["wire_codec_gain"] = round(gain, 3)
            out["wire_codec_serialization_share_python_pct"] = \
                ab["python"]["serialization_share_pct"]
            out["wire_codec_serialization_share_native_pct"] = \
                ab["native"]["serialization_share_pct"]
            out["wire_codec_share_ratio"] = round(ratio, 3)
            if ratio > codec_share_ratio_max:
                raise AssertionError(
                    f"wire-tax codec A/B: serialization share with the "
                    f"native codec is {ratio:.2f}x the python-mode "
                    f"share, above the {codec_share_ratio_max} gate")
            if gain < codec_gain_min:
                raise AssertionError(
                    f"wire-tax codec A/B: {gain:.2f}x ops/s over the "
                    f"python-codec baseline, below the "
                    f"{codec_gain_min}x gate")

        # -- OSD-exec A/B (the round-22 batch-execution gate) ---------
        # The per-op execution loop (osd_op_batch_exec off, the pre-r22
        # baseline) against the array-batched fast path, same payloads,
        # vectorized submit in BOTH modes so the only delta is the OSD
        # execution architecture.  Submit shape leans batch-heavy
        # (fewer writers, deeper bursts) so the dispatch loop hands the
        # shards real runs -- both modes get the identical shape.  Two
        # gates: the OSD execution cost centers (osd.op_exec +
        # osd.batch_exec) at <= osd_share_ratio_max of their per-op
        # share of the saturated wall (min ratio across bounded
        # attempts -- the overhead gate's machine-drift defense; the
        # shares are ~2% of wall, inside single-run noise), and the
        # stored shard bytes byte-identical across modes (asserted
        # directly on the stores every attempt, so a batching shortcut
        # can never hide behind a throughput win).
        cfg3 = _get_config()
        prior_batch_exec = bool(cfg3.get_val("osd_op_batch_exec"))
        seg_cycles3 = max(2, iters)
        ab_writers = max(2, writers // 3)
        ab_batch = max(codec_batch,
                       -(-n_objects // ab_writers))  # ceil division
        abx_best: Optional[dict] = None
        attempts3 = 0
        try:
            while True:
                attempts3 += 1
                abx: Dict[str, dict] = {}
                mode_shards: Dict[str, dict] = {}
                for mode, batch_on in (("perop", False),
                                       ("batched", True)):
                    cfg3.apply_changes({"osd_op_batch_exec": batch_on})
                    h3 = ClusterHarness(
                        ec, n_osds, cork=True,
                        pool=f"oxab{attempts3}{mode}")
                    loop.run_until_complete(h3.start())
                    try:
                        for oid in payloads:
                            h3.objecter.acting_set(oid)
                        loop.run_until_complete(_cycle(
                            h3, payloads, ab_writers, batch=ab_batch))
                        profiling.configure(mode="on")
                        profiling.reset()
                        t0 = time.perf_counter_ns()
                        for _ in range(seg_cycles3):
                            loop.run_until_complete(_cycle(
                                h3, payloads, ab_writers,
                                batch=ab_batch))
                        wall3 = time.perf_counter_ns() - t0
                        abx[mode] = {
                            "ops_per_sec": round(
                                seg_cycles3 * 2 * n_objects
                                / (wall3 / 1e9), 1),
                            "osd_exec_share_pct": _osd_exec_share(
                                profiling.decomposition(wall3)),
                        }
                        profiling.configure(mode="off")
                        mode_shards[mode] = h3.shard_bytes()
                    finally:
                        loop.run_until_complete(h3.shutdown())
                if mode_shards["perop"] != mode_shards["batched"]:
                    raise AssertionError(
                        "wire-tax osd-exec A/B: batched and per-op "
                        "execution left different shard bytes in the "
                        "stores")
                abx["ratio"] = abx["batched"]["osd_exec_share_pct"] / \
                    max(1e-9, abx["perop"]["osd_exec_share_pct"])
                if abx_best is None or abx["ratio"] < abx_best["ratio"]:
                    abx_best = abx
                if abx_best["ratio"] <= osd_share_ratio_max or \
                        attempts3 >= max(1, retries):
                    break
        finally:
            cfg3.apply_changes({"osd_op_batch_exec": prior_batch_exec})
        out["osd_exec_shard_bytes_identical"] = True
        out["osd_exec_ab_attempts"] = attempts3
        out["osd_exec_perop_ops_per_sec"] = \
            abx_best["perop"]["ops_per_sec"]
        out["osd_exec_batched_ops_per_sec"] = \
            abx_best["batched"]["ops_per_sec"]
        out["osd_batch_gain"] = round(
            abx_best["batched"]["ops_per_sec"]
            / max(1e-9, abx_best["perop"]["ops_per_sec"]), 3)
        out["osd_exec_share_perop_pct"] = \
            abx_best["perop"]["osd_exec_share_pct"]
        out["osd_exec_share_batched_pct"] = \
            abx_best["batched"]["osd_exec_share_pct"]
        out["osd_exec_share_ratio"] = round(abx_best["ratio"], 3)
        if abx_best["ratio"] > osd_share_ratio_max:
            raise AssertionError(
                f"wire-tax osd-exec A/B: OSD-execution share with "
                f"batching is {abx_best['ratio']:.2f}x the per-op "
                f"share after {attempts3} attempts, above the "
                f"{osd_share_ratio_max} gate")

        # -- ring-vs-TCP A/B (the round-22 shm frame-ring gate) -------
        # The same saturated path over localhost TCP against the
        # shared-memory frame rings (osd_msgr_shm_ring on; every daemon
        # pair colocated here, so every connection is ring-eligible).
        # Gates: the rings actually carried the traffic (ring_conns >
        # 0 in ring mode, 0 in tcp mode), stored shard bytes identical
        # across transports, and ring-mode ops/s >= ring_gain_min x the
        # tcp-mode baseline.  Per-frame send cost (wire.writelines ns /
        # frames sent in the measured segment) is recorded per mode as
        # the frame-latency evidence.
        cfg4 = _get_config()
        prior_ring = bool(cfg4.get_val("osd_msgr_shm_ring"))
        abr_best: Optional[dict] = None
        attempts4 = 0
        try:
            while True:
                attempts4 += 1
                abr: Dict[str, dict] = {}
                ring_shards: Dict[str, dict] = {}
                for mode, ring_on in (("tcp", False), ("ring", True)):
                    cfg4.apply_changes({"osd_msgr_shm_ring": ring_on})
                    h4 = ClusterHarness(
                        ec, n_osds, cork=True,
                        pool=f"rgab{attempts4}{mode}")
                    loop.run_until_complete(h4.start())
                    try:
                        for oid in payloads:
                            h4.objecter.acting_set(oid)
                        loop.run_until_complete(_cycle(
                            h4, payloads, writers, batch=codec_batch))
                        frames_warm = h4.wire_counters().get(
                            "frames_sent", 0)
                        profiling.configure(mode="on")
                        profiling.reset()
                        t0 = time.perf_counter_ns()
                        for _ in range(seg_cycles3):
                            loop.run_until_complete(_cycle(
                                h4, payloads, writers,
                                batch=codec_batch))
                        wall4 = time.perf_counter_ns() - t0
                        decomp4 = profiling.decomposition(wall4)
                        wc4 = h4.wire_counters()
                        frames_seg = max(
                            1, wc4.get("frames_sent", 0) - frames_warm)
                        send_ns = sum(
                            r["ns"] for r in decomp4["rows"]
                            if r["stage"] in ("wire.writelines",
                                              "ring.push"))
                        abr[mode] = {
                            "ops_per_sec": round(
                                seg_cycles3 * 2 * n_objects
                                / (wall4 / 1e9), 1),
                            "frame_send_ns": round(
                                send_ns / frames_seg),
                            "ring_conns": wc4.get("ring_conns", 0),
                            "tcp_conns": wc4.get("tcp_conns", 0),
                        }
                        profiling.configure(mode="off")
                        ring_shards[mode] = h4.shard_bytes()
                    finally:
                        loop.run_until_complete(h4.shutdown())
                if abr["ring"]["ring_conns"] <= 0:
                    raise AssertionError(
                        "wire-tax ring A/B: ring mode opened no "
                        "shm-ring connections -- the A/B measured TCP "
                        "twice")
                if abr["tcp"]["ring_conns"] != 0:
                    raise AssertionError(
                        "wire-tax ring A/B: tcp baseline mode carried "
                        "traffic over shm rings")
                if ring_shards["tcp"] != ring_shards["ring"]:
                    raise AssertionError(
                        "wire-tax ring A/B: ring and TCP transports "
                        "left different shard bytes in the stores")
                abr["gain"] = abr["ring"]["ops_per_sec"] / \
                    max(1e-9, abr["tcp"]["ops_per_sec"])
                if abr_best is None or abr["gain"] > abr_best["gain"]:
                    abr_best = abr
                if abr_best["gain"] >= ring_gain_min or \
                        attempts4 >= max(1, retries):
                    break
        finally:
            cfg4.apply_changes({"osd_msgr_shm_ring": prior_ring})
        out["ring_shard_bytes_identical"] = True
        out["ring_ab_attempts"] = attempts4
        out["ring_conns"] = abr_best["ring"]["ring_conns"]
        out["tcp_ops_per_sec"] = abr_best["tcp"]["ops_per_sec"]
        out["ring_ops_per_sec"] = abr_best["ring"]["ops_per_sec"]
        out["ring_gain"] = round(abr_best["gain"], 3)
        out["tcp_frame_send_ns"] = abr_best["tcp"]["frame_send_ns"]
        out["ring_frame_send_ns"] = abr_best["ring"]["frame_send_ns"]
        if out["ring_gain"] < ring_gain_min:
            raise AssertionError(
                f"wire-tax ring A/B: {out['ring_gain']:.2f}x ops/s "
                f"over the TCP baseline after {attempts4} attempts, "
                f"below the {ring_gain_min}x gate")

        # -- export contract: a short full-mode sampled segment -------
        profiling.configure(mode="full")
        loop.run_until_complete(_cycle(harness, payloads, writers))
        sampler = profiling.current_sampler()
        time.sleep(0.05)  # let the sampler thread land its last snap
        speedscope = sampler.speedscope()
        for key in ("$schema", "shared", "profiles"):
            if key not in speedscope:
                raise AssertionError(
                    f"wire-tax: speedscope export missing {key!r}")
        if not speedscope["profiles"] or \
                not speedscope["shared"]["frames"]:
            raise AssertionError(
                "wire-tax: speedscope export carries no samples")
        out["sampler"] = {
            "samples": sampler.samples,
            "stage_shares": sampler.stage_shares(),
            "speedscope_profiles": len(speedscope["profiles"]),
            "collapsed_lines": len(sampler.collapsed().splitlines()),
        }
    finally:
        try:
            loop.run_until_complete(harness.shutdown())
        finally:
            loop.close()
            _restore_mode(prior_mode)
    return out


def main(argv=None) -> int:
    """``python -m ceph_tpu.profiling.wire_tax_bench [--smoke]``: the
    ci_lint --profile-smoke arm -- tiny shapes, loose gates, every gate
    still armed."""
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes + loose coverage/overhead gates "
                         "(CI; bench.py runs the real gates)")
    args = ap.parse_args(argv)
    if args.smoke:
        result = run_wire_tax_bench(
            n_objects=8, obj_bytes=4096, writers=4, iters=1,
            coverage_min_pct=50.0, overhead_limit_pct=50.0,
            codec_gain_min=0.5, codec_share_ratio_max=0.95,
            osd_share_ratio_max=5.0, ring_gain_min=0.3)
    else:
        result = run_wire_tax_bench()
    print(json.dumps(result, indent=2), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
