"""The stage cost ledger: exclusive-time cost centers for the wire loop.

ROADMAP item 2 (the native/zero-copy transport) needs the 99%-CPU
Python wire loop decomposed into NAMED costs before the FFI rewrite can
be aimed; this ledger is the attribution substrate.  The discipline is
the one PAPERS "Accelerating XOR-based Erasure Coding using Program
Optimization Techniques" applied to the coding loop: measure the loop's
schedule first, then re-arrange it.

Design constraints (all load-bearing):

* **Markers are cached and reusable.**  ``stage(name)`` returns ONE
  marker per name for the process lifetime; instrumented modules fetch
  their markers at import time, so the per-frame cost is the ``with``
  protocol on a preallocated object -- no dict lookup, no allocation
  on the hot path.  Round 20: the marker is ``_wire_native.Stage``
  (C: two ``clock_gettime`` reads + struct-field math) when the native
  extension loads, and the pure-Python :class:`StageMarker` twin
  otherwise -- identical semantics, selected once at import.
* **Off is (allocation-)free.**  Disabled markers take one global-bool
  branch in ``__enter__``/``__exit__`` and allocate NOTHING -- the
  off-mode pin in tests/test_profiling.py asserts a zero
  ``sys.getallocatedblocks`` delta across thousands of enter/exit
  cycles, and the bench stage re-asserts it per run.
* **Exclusive time.**  Stages nest (``wire.crc32c`` runs inside
  ``wire.crc_seal``); on child entry the parent's elapsed-so-far is
  banked and its clock pauses, so every nanosecond lands in exactly one
  stage and the decomposition sums without double counting.  Markers
  are NOT re-entrant (a stage nested inside itself would clobber the
  start stamp) and must never span an ``await`` -- a suspended stage
  would bill other tasks' work to itself.  The cephlint rule
  ``profile-stage-unpaired`` guards the paired-call form; the seams use
  yield-free blocks by construction.
* **Single event-loop thread.**  The wire loop is asyncio-single-
  threaded; the ledger inherits that and takes no locks on the hot
  path.  ``snapshot()`` reads are torn-tolerant (counters only grow).

Per-connection per-burst sub-accounting rides the same ledger:
``note_burst(node, frames, nbytes, ns)`` feeds a per-peer table and an
ns/frame histogram (the existing :class:`HistogramAxis` bucketing), so
the decomposition can say not just "writelines cost X" but "at N
frames/burst and P50/P99 ns/frame".
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ceph_tpu.utils.perf import HistogramAxis

_now_ns = time.perf_counter_ns

#: master switch, flipped only by profiling.configure(); module-global
#: so marker enter/exit pay one LOAD_GLOBAL + branch when off
_enabled = False

#: innermost active stage marker of the event-loop thread (exclusive
#: accounting + the sampler's attribution read; None between stages)
_current: Optional["StageMarker"] = None

#: name -> StageMarker (process-wide; markers live forever)
_markers: Dict[str, "StageMarker"] = {}

#: per-peer-node burst table: node -> [bursts, frames, bytes, ns]
_bursts: Dict[str, List[int]] = {}

#: ns-per-frame histogram axis: log2 buckets from 256ns up (~2^40ns
#: overflow bucket) -- the burst sub-accounting percentile source
_NSF_AXIS = HistogramAxis("ns_per_frame", 0, 256, 40, "log2")
_nsf_counts = [0] * _NSF_AXIS.buckets
_nsf_sum = 0
_nsf_n = 0


class StageMarker:
    """One named cost center; use as ``with stage("wire.encode"):``.

    ``ns``/``calls``/``nbytes`` accumulate for the process lifetime
    (reset() zeroes them).  ``add_bytes`` attributes payload bytes to
    the stage (callers pass what they already know -- no len() walks).
    """

    __slots__ = ("name", "ns", "calls", "nbytes", "_t0", "_parent")

    def __init__(self, name: str):
        self.name = name
        self.ns = 0
        self.calls = 0
        self.nbytes = 0
        self._t0 = 0
        self._parent: Optional["StageMarker"] = None

    def __enter__(self):
        if not _enabled:
            return self
        global _current
        now = _now_ns()
        parent = _current
        if parent is not None:
            # bank the parent's elapsed and pause its clock: exclusive
            # time, every nanosecond in exactly one stage
            parent.ns += now - parent._t0
        self._parent = parent
        self._t0 = now
        _current = self
        return self

    def __exit__(self, *exc):
        if not _enabled:
            return False
        global _current
        now = _now_ns()
        self.ns += now - self._t0
        self.calls += 1
        parent = self._parent
        _current = parent
        if parent is not None:
            parent._t0 = now  # restart the parent's exclusive clock
        return False

    def add_bytes(self, n: int) -> None:
        if _enabled:
            self.nbytes += n


#: round 20: the marker hot path moves to C with the native wire
#: extension (_wire_native.Stage -- identical exclusive-time semantics
#: at clock_gettime cost).  Selected ONCE at profiler import: against
#: the native codec's halved wire wall the Python markers' ~0.6us/pair
#: became a >3% enabled overhead, failing the wire-tax stage's own
#: gate; the C twin restores the r19 contract.  Python markers remain
#: the degraded-build fallback (CEPH_TPU_NATIVE=0 / no toolchain), and
#: reset()/snapshot()/gc_credit speak to both through the same
#: attribute surface.
_native_stages = None
try:
    from ceph_tpu.native import wire_codec as _wire_codec

    _native_stages = _wire_codec.native()
except Exception:  # noqa: BLE001 -- any loader surprise means the
    _native_stages = None  # Python markers carry the ledger


def stage(name: str) -> StageMarker:
    """The process-wide marker for ``name`` (created on first use;
    instrumented modules call this once at import)."""
    m = _markers.get(name)
    if m is None:
        impl = StageMarker if _native_stages is None \
            else _native_stages.Stage
        m = _markers[name] = impl(name)
    return m


# -- the paired-call form ----------------------------------------------------
#
# For seams where a `with` block cannot bracket the work (a dispatch
# whose result may be a coroutine that must be awaited OUTSIDE the
# stage), `stage_enter(marker)`/`stage_exit(marker)` are the explicit
# pair.  Every enter MUST reach an exit on every control-flow path --
# the cephlint rule `profile-stage-unpaired` walks the CFG for exactly
# this contract.

def stage_enter(marker: StageMarker) -> StageMarker:
    return marker.__enter__()


def stage_exit(marker: StageMarker) -> None:
    marker.__exit__(None, None, None)


def gc_credit(ns: int) -> None:
    """Credit a GC pause OUT of the stage it interrupted: the stage's
    clock ran through the collector, so pushing its start stamp forward
    by the pause keeps stage time and gc time disjoint (the
    decomposition sums without double counting)."""
    if _native_stages is not None:
        _native_stages.stage_gc_credit(ns)
        return
    cur = _current
    if cur is not None:
        cur._t0 += ns


def current_stage_name() -> Optional[str]:
    """The innermost active stage (the sampler's attribution read;
    racy by design -- a sample is a sample)."""
    if _native_stages is not None:
        return _native_stages.stage_current_name()
    cur = _current
    return cur.name if cur is not None else None


# -- burst sub-accounting ----------------------------------------------------

def note_burst(node: str, frames: int, nbytes: int, ns: int) -> None:
    """One corked flush burst to ``node``: frames/bytes/ns roll into the
    per-connection table and the ns/frame histogram."""
    if not _enabled or not frames:
        return
    row = _bursts.get(node)
    if row is None:
        row = _bursts[node] = [0, 0, 0, 0]
    row[0] += 1
    row[1] += frames
    row[2] += nbytes
    row[3] += ns
    global _nsf_sum, _nsf_n
    per = ns // frames
    _nsf_counts[_NSF_AXIS.bucket_for(per)] += 1
    _nsf_sum += per
    _nsf_n += 1


def _nsf_percentile(p: float) -> Optional[int]:
    """Inclusive upper bound of the bucket holding the p-quantile
    ns/frame observation (None with no data)."""
    total = _nsf_n
    if not total:
        return None
    want = p * total
    bounds = _NSF_AXIS.upper_bounds()
    cum = 0
    for b, count in enumerate(_nsf_counts):
        cum += count
        if cum >= want:
            return bounds[b] if b < len(bounds) else bounds[-1] * 2
    return bounds[-1] * 2


# -- views -------------------------------------------------------------------

def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip the master switch (profiling.configure() is the public
    surface).  Turning off mid-stage abandons the open stage's tail --
    acceptable: enable/disable are test/bench boundaries, not hot ops."""
    global _enabled, _current
    _enabled = bool(on)
    if not on:
        _current = None
    if _native_stages is not None:
        _native_stages.stage_set_enabled(_enabled)


def stages_snapshot() -> Dict[str, dict]:
    """Per-stage accumulators (ns exclusive, calls, bytes)."""
    return {
        name: {"ns": m.ns, "calls": m.calls, "bytes": m.nbytes}
        for name, m in sorted(_markers.items())
        if m.calls or m.ns
    }


def bursts_snapshot() -> dict:
    """Per-connection burst table + ns/frame percentiles."""
    by_conn = {}
    for node, (bursts, frames, nbytes, ns) in sorted(_bursts.items()):
        by_conn[node] = {
            "bursts": bursts,
            "frames": frames,
            "bytes": nbytes,
            "ns": ns,
            "frames_per_burst": round(frames / bursts, 2) if bursts else 0,
            "bytes_per_burst": round(nbytes / bursts, 1) if bursts else 0,
        }
    return {
        "by_connection": by_conn,
        "ns_per_frame_p50": _nsf_percentile(0.50),
        "ns_per_frame_p99": _nsf_percentile(0.99),
        "frames_observed": _nsf_n,
        "ns_per_frame_mean": round(_nsf_sum / _nsf_n) if _nsf_n else None,
    }


def reset() -> None:
    global _nsf_sum, _nsf_n, _current
    for m in _markers.values():
        m.ns = 0
        m.calls = 0
        m.nbytes = 0
    _bursts.clear()
    for i in range(len(_nsf_counts)):
        _nsf_counts[i] = 0
    _nsf_sum = 0
    _nsf_n = 0
    _current = None
