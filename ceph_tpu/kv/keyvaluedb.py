"""KeyValueDB interface + MemDB backend.

Reference: src/kv/KeyValueDB.h -- prefixed keyspaces, batched atomic
transactions (set/rmkey/rmkeys_by_prefix), whole-prefix iteration; MemDB
(src/kv/MemDB.cc) is the RAM backend.  Keys are (prefix, key) string
pairs exactly as in the reference; values are bytes.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple


class KVTransaction:
    """A batch of mutations applied atomically by submit_transaction."""

    def __init__(self) -> None:
        #: ordered ops: ("set", prefix, key, value) | ("rm", prefix, key)
        #: | ("rm_prefix", prefix)
        self.ops: List[tuple] = []

    def set(self, prefix: str, key: str, value: bytes) -> "KVTransaction":
        self.ops.append(("set", prefix, key, bytes(value)))
        return self

    def rmkey(self, prefix: str, key: str) -> "KVTransaction":
        self.ops.append(("rm", prefix, key))
        return self

    def rmkeys_by_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append(("rm_prefix", prefix))
        return self


class KeyValueDB:
    """Abstract store: open/close, point get, sorted iteration, atomic
    batched writes."""

    def open(self) -> None:  # mount/replay
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def submit_transaction(self, txn: KVTransaction, sync: bool = False) -> None:
        raise NotImplementedError

    def get(self, prefix: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_iterator(self, prefix: str) -> Iterator[Tuple[str, bytes]]:
        """Sorted (key, value) pairs under ``prefix``."""
        raise NotImplementedError


class MemDB(KeyValueDB):
    def __init__(self) -> None:
        self._data: Dict[Tuple[str, str], bytes] = {}

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def submit_transaction(self, txn: KVTransaction, sync: bool = False) -> None:
        for op in txn.ops:
            if op[0] == "set":
                self._data[(op[1], op[2])] = op[3]
            elif op[0] == "rm":
                self._data.pop((op[1], op[2]), None)
            elif op[0] == "rm_prefix":
                for pk in [pk for pk in self._data if pk[0] == op[1]]:
                    del self._data[pk]

    def get(self, prefix: str, key: str) -> Optional[bytes]:
        return self._data.get((prefix, key))

    def get_iterator(self, prefix: str) -> Iterator[Tuple[str, bytes]]:
        keys = sorted(k for p, k in self._data if p == prefix)
        for k in keys:
            yield k, self._data[(prefix, k)]
