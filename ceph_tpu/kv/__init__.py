"""KV sub-layer (reference: src/kv -- KeyValueDB with RocksDB/LevelDB/
MemDB backends behind one interface, src/kv/KeyValueDB.h)."""

from ceph_tpu.kv.keyvaluedb import KeyValueDB, KVTransaction, MemDB
from ceph_tpu.kv.lsm import LSMStore


def create(kind: str, path: str = "") -> KeyValueDB:
    """KeyValueDB::create analogue (src/kv/KeyValueDB.cc): pick a backend
    by name.  ``memdb`` is RAM-only; ``lsm`` is the persistent
    WAL+SSTable store (our rocksdb-equivalent)."""
    if kind == "memdb":
        return MemDB()
    if kind == "lsm":
        if not path:
            raise ValueError("lsm KeyValueDB needs a path")
        return LSMStore(path)
    raise ValueError(f"unknown KeyValueDB backend {kind!r}")


__all__ = ["KeyValueDB", "KVTransaction", "MemDB", "LSMStore", "create"]
