"""Persistent KeyValueDB: write-ahead log + sorted-table LSM.

The reference embeds RocksDB (src/kv/RocksDBStore.cc) for BlueStore
metadata and the monitor store.  Vendoring RocksDB is neither possible nor
idiomatic here; this is a small LSM with the same durability contract:

* every ``submit_transaction`` appends one crc-framed record to the WAL
  (fsync when ``sync=True`` -- the `submit_transaction_sync` path);
* the memtable absorbs writes; at ``memtable_limit`` bytes it is flushed
  to an immutable sorted table file (SSTable) and the WAL is truncated;
* ``open`` loads SSTables then replays the WAL, discarding a torn tail
  record (crash recovery);
* reads consult memtable, then SSTables newest-first; tombstones shadow
  older values; ``compact`` folds all tables into one and drops
  tombstones.

File layout under ``path/``:  ``wal.log``, ``sst.<n>`` (n increasing),
``CURRENT`` (framed manifest listing live tables -- written atomically via
rename, the manifest role of RocksDB's MANIFEST).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Tuple

from ceph_tpu.kv.keyvaluedb import KeyValueDB, KVTransaction
from ceph_tpu.utils.encoding import Decoder, Encoder, frame, unframe

#: memtable tombstone marker (values are bytes; None marks deletion)
_TOMBSTONE = None


def _encode_txn(txn: KVTransaction) -> bytes:
    enc = Encoder()
    enc.varint(len(txn.ops))
    for op in txn.ops:
        enc.string(op[0])
        if op[0] == "set":
            enc.string(op[1]).string(op[2]).blob(op[3])
        elif op[0] == "rm":
            enc.string(op[1]).string(op[2])
        else:  # rm_prefix
            enc.string(op[1])
    return enc.bytes()


def _decode_txn(payload: bytes) -> KVTransaction:
    dec = Decoder(payload)
    txn = KVTransaction()
    for _ in range(dec.varint()):
        kind = dec.string()
        if kind == "set":
            txn.set(dec.string(), dec.string(), dec.blob())
        elif kind == "rm":
            txn.rmkey(dec.string(), dec.string())
        else:
            txn.rmkeys_by_prefix(dec.string())
    return txn


class _SSTable:
    """Immutable sorted (prefix, key) -> value-or-tombstone file."""

    def __init__(self, path: str):
        self.path = path
        self._index: Dict[Tuple[str, str], Tuple[int, int, bool]] = {}
        self._load()

    def _load(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        payload, _ = unframe(data, 0)
        if payload is None:
            raise IOError(f"corrupt sstable {self.path}")
        dec = Decoder(payload)
        for _ in range(dec.varint()):
            prefix = dec.string()
            key = dec.string()
            is_tomb = dec.u8() == 1
            blob = dec.blob()
            # values stored inline in the single frame; remember directly
            self._index[(prefix, key)] = blob if not is_tomb else _TOMBSTONE  # type: ignore[assignment]

    @staticmethod
    def write(path: str, items: List[Tuple[Tuple[str, str], Optional[bytes]]]) -> None:
        enc = Encoder()
        enc.varint(len(items))
        for (prefix, key), value in sorted(items):
            enc.string(prefix).string(key)
            if value is _TOMBSTONE:
                enc.u8(1).blob(b"")
            else:
                enc.u8(0).blob(value)  # type: ignore[arg-type]
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame(enc.bytes()))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, prefix: str, key: str, default=KeyError):
        try:
            return self._index[(prefix, key)]
        except KeyError:
            return default

    def items(self) -> Iterator[Tuple[Tuple[str, str], Optional[bytes]]]:
        return iter(sorted(self._index.items()))


class LSMStore(KeyValueDB):
    def __init__(self, path: str, memtable_limit: int = 4 << 20):
        self.path = path
        self.memtable_limit = memtable_limit
        self._mem: Dict[Tuple[str, str], Optional[bytes]] = {}
        self._mem_bytes = 0
        self._tables: List[_SSTable] = []  # oldest .. newest
        self._wal = None
        self._next_sst = 0
        self._opened = False

    # -- lifecycle ---------------------------------------------------------

    def open(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        current = os.path.join(self.path, "CURRENT")
        names: List[str] = []
        if os.path.exists(current):
            with open(current, "rb") as f:
                payload, _ = unframe(f.read(), 0)
            if payload is not None:
                names = Decoder(payload).value()  # type: ignore[assignment]
        for name in names:
            self._tables.append(_SSTable(os.path.join(self.path, name)))
            self._next_sst = max(self._next_sst, int(name.split(".")[1]) + 1)
        # replay WAL (torn tail ends replay -- crash semantics)
        wal_path = os.path.join(self.path, "wal.log")
        if os.path.exists(wal_path):
            with open(wal_path, "rb") as f:
                data = f.read()
            pos = 0
            while True:
                payload, pos = unframe(data, pos)
                if payload is None:
                    break
                self._apply_mem(_decode_txn(payload))
        self._wal = open(wal_path, "ab")
        self._opened = True

    def close(self) -> None:
        if self._wal is not None:
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self._wal.close()
            self._wal = None
        self._opened = False

    # -- writes ------------------------------------------------------------

    def _apply_mem(self, txn: KVTransaction) -> None:
        for op in txn.ops:
            if op[0] == "set":
                self._mem[(op[1], op[2])] = op[3]
                self._mem_bytes += len(op[2]) + len(op[3])
            elif op[0] == "rm":
                self._mem[(op[1], op[2])] = _TOMBSTONE
            else:  # rm_prefix: tombstone every visible key under the prefix
                for pfx, key in list(self._visible_keys(op[1])):
                    self._mem[(pfx, key)] = _TOMBSTONE

    def submit_transaction(self, txn: KVTransaction, sync: bool = False) -> None:
        assert self._opened, "LSMStore used before open()"
        self._wal.write(frame(_encode_txn(txn)))
        if sync:
            self._wal.flush()
            os.fsync(self._wal.fileno())
        self._apply_mem(txn)
        if self._mem_bytes >= self.memtable_limit:
            self.flush()

    def flush(self) -> None:
        """Freeze the memtable into a new SSTable and truncate the WAL."""
        if not self._mem:
            return
        name = f"sst.{self._next_sst}"
        self._next_sst += 1
        _SSTable.write(
            os.path.join(self.path, name), list(self._mem.items())
        )
        self._tables.append(_SSTable(os.path.join(self.path, name)))
        self._write_manifest()
        self._mem.clear()
        self._mem_bytes = 0
        self._wal.close()
        self._wal = open(os.path.join(self.path, "wal.log"), "wb")

    def _write_manifest(self) -> None:
        names = [os.path.basename(t.path) for t in self._tables]
        tmp = os.path.join(self.path, "CURRENT.tmp")
        with open(tmp, "wb") as f:
            f.write(frame(Encoder().value(names).bytes()))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, "CURRENT"))

    def compact(self) -> None:
        """Fold everything into one table, dropping tombstones."""
        merged: Dict[Tuple[str, str], Optional[bytes]] = {}
        for table in self._tables:  # oldest first: newer wins
            for k, v in table.items():
                merged[k] = v
        merged.update(self._mem)
        live = [(k, v) for k, v in sorted(merged.items()) if v is not _TOMBSTONE]
        old = list(self._tables)
        name = f"sst.{self._next_sst}"
        self._next_sst += 1
        _SSTable.write(os.path.join(self.path, name), live)
        self._tables = [_SSTable(os.path.join(self.path, name))]
        self._write_manifest()
        self._mem.clear()
        self._mem_bytes = 0
        self._wal.close()
        self._wal = open(os.path.join(self.path, "wal.log"), "wb")
        for t in old:
            try:
                os.remove(t.path)
            except OSError:
                pass

    # -- reads -------------------------------------------------------------

    def get(self, prefix: str, key: str) -> Optional[bytes]:
        pk = (prefix, key)
        if pk in self._mem:
            v = self._mem[pk]
            return None if v is _TOMBSTONE else v
        for table in reversed(self._tables):
            v = table.get(prefix, key)
            if v is not KeyError:
                return None if v is _TOMBSTONE else v
        return None

    def _visible_keys(self, prefix: str) -> Iterator[Tuple[str, str]]:
        seen: Dict[str, bool] = {}
        for pk, v in self._mem.items():
            if pk[0] == prefix:
                seen[pk[1]] = v is not _TOMBSTONE
        for table in reversed(self._tables):
            for pk, v in table.items():
                if pk[0] == prefix and pk[1] not in seen:
                    seen[pk[1]] = v is not _TOMBSTONE
        for key in sorted(k for k, live in seen.items() if live):
            yield prefix, key

    def get_iterator(self, prefix: str) -> Iterator[Tuple[str, bytes]]:
        for _, key in self._visible_keys(prefix):
            v = self.get(prefix, key)
            if v is not None:
                yield key, v
