"""shec-equivalent plugin: Shingled Erasure Code.

Mirrors the reference shec plugin (reference: src/erasure-code/shec/
ErasureCodeShec.{h,cc}, ErasureCodePluginShec.cc):

* profile (k, m, c) with guards k<=12, k+m<=20, c<=m<=k
  (ErasureCodeShec.cc:271-342); w in {8,16,32} (bad w falls back to 8);
* technique ``single`` / ``multiple`` (default multiple,
  ErasureCodePluginShec.cc:45-58);
* coding matrix = reed_sol vandermonde matrix with shingle windows zeroed;
  ``multiple`` splits (m, c) into (m1, c1)+(m2, c2) minimizing the
  recovery-efficiency functional shec_calc_recovery_efficiency1
  (ErasureCodeShec.cc:415-524);
* ``minimum_to_decode`` searches parity subsets for the smallest recovery
  set (shec_make_decoding_matrix, :526-718) -- SHEC is not MDS; locality is
  the point: single-chunk recovery touches ~k*c/m chunks, not k.
"""

from __future__ import annotations

import errno as _errno
from typing import Dict, Iterable, List, Mapping, Tuple

import numpy as np

from ceph_tpu.matrices import reed_sol
from ceph_tpu.ops import cpu_engine
from ceph_tpu.ops.gf import gf
from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.plugins.interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
)


def calc_recovery_efficiency1(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """Faithful port of shec_calc_recovery_efficiency1."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [100000000] * k
    r_e1 = 0.0
    for mm, cc_ in ((m1, c1), (m2, c2)):
        for rr in range(mm):
            start = ((rr * k) // mm) % k
            end = (((rr + cc_) * k) // mm) % k
            cc = start
            first = True
            while first or cc != end:
                first = False
                r_eff_k[cc] = min(
                    r_eff_k[cc], ((rr + cc_) * k) // mm - (rr * k) // mm
                )
                cc = (cc + 1) % k
            r_e1 += ((rr + cc_) * k) // mm - (rr * k) // mm
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


def shec_matrix(k: int, m: int, c: int, w: int, is_single: bool) -> np.ndarray:
    """shec_reedsolomon_coding_matrix (ErasureCodeShec.cc:456-524)."""
    if is_single:
        m1, c1, m2, c2 = 0, 0, m, c
    else:
        c1_best, m1_best, min_r = -1, -1, 100.0
        for c1 in range(c // 2 + 1):
            for m1 in range(m + 1):
                c2, m2 = c - c1, m - m1
                if m1 < c1 or m2 < c2:
                    continue
                if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
                    continue
                if (m1 != 0 and c1 == 0) or (m2 != 0 and c2 == 0):
                    continue
                r = calc_recovery_efficiency1(k, m1, m2, c1, c2)
                if min_r - r > np.finfo(float).eps and r < min_r:
                    min_r, c1_best, m1_best = r, c1, m1
        m1, c1 = m1_best, c1_best
        m2, c2 = m - m1, c - c1

    M = reed_sol.vandermonde_coding_matrix(k, m, w).astype(np.uint32)
    for rr in range(m1):
        end = ((rr * k) // m1) % k
        start = (((rr + c1) * k) // m1) % k
        cc = start
        while cc != end:
            M[rr, cc] = 0
            cc = (cc + 1) % k
    for rr in range(m2):
        end = ((rr * k) // m2) % k
        start = (((rr + c2) * k) // m2) % k
        cc = start
        while cc != end:
            M[m1 + rr, cc] = 0
            cc = (cc + 1) % k
    return M


class ErasureCodeShec(ErasureCode):
    DEFAULT_K, DEFAULT_M, DEFAULT_C, DEFAULT_W = 4, 3, 2, 8

    def __init__(self, technique: str = "multiple"):
        super().__init__()
        self.technique = technique
        self.k = 0
        self.m = 0
        self.c = 0
        self.w = 8
        self._backend = "cpu"
        self.matrix: np.ndarray | None = None

    # -- contract ----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        return self.k * self.w * 4  # ErasureCodeShec.cc:266-269

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        self.prepare()
        ErasureCode.init(self, profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        ErasureCode.parse(self, profile)
        has = [n for n in ("k", "m", "c") if profile.get(n)]
        if not has:
            self.k, self.m, self.c = self.DEFAULT_K, self.DEFAULT_M, self.DEFAULT_C
            profile["k"], profile["m"], profile["c"] = "4", "3", "2"
        elif len(has) != 3:
            raise ErasureCodeError(_errno.EINVAL, "(k, m, c) must be chosen")
        else:
            try:
                self.k = int(profile["k"])
                self.m = int(profile["m"])
                self.c = int(profile["c"])
            except ValueError:
                raise ErasureCodeError(_errno.EINVAL, "k/m/c must be integers")
        if self.k <= 0 or self.m <= 0 or self.c <= 0:
            raise ErasureCodeError(_errno.EINVAL, "k, m, c must be positive")
        if self.m < self.c:
            raise ErasureCodeError(_errno.EINVAL, f"c={self.c} must be <= m={self.m}")
        if self.k > 12:
            raise ErasureCodeError(_errno.EINVAL, f"k={self.k} must be <= 12")
        if self.k + self.m > 20:
            raise ErasureCodeError(_errno.EINVAL, "k+m must be <= 20")
        if self.k < self.m:
            raise ErasureCodeError(_errno.EINVAL, f"m={self.m} must be <= k={self.k}")
        w = profile.get("w")
        self.w = self.DEFAULT_W
        if w:
            try:
                wv = int(w)
                if wv in (8, 16, 32):
                    self.w = wv
            except ValueError:
                pass
        profile["w"] = str(self.w)
        self._backend = self.to_string("backend", profile, "cpu")

    def prepare(self) -> None:
        self.matrix = shec_matrix(
            self.k, self.m, self.c, self.w, self.technique == "single"
        )

    # -- compute -----------------------------------------------------------

    def _engine(self):
        if self._backend == "tpu":
            from ceph_tpu.ops import xla_gf

            return xla_gf
        if self._backend == "native":
            from ceph_tpu.ops import native_engine

            return native_engine
        return cpu_engine

    def encode_chunks(
        self, want_to_encode: Iterable[int], encoded: Dict[int, np.ndarray]
    ) -> None:
        data = np.stack([encoded[i] for i in range(self.k)])
        coding = self._engine().matrix_encode(self.matrix, data, self.w)
        for i in range(self.m):
            encoded[self.k + i][:] = coding[i]

    # -- minimum-recovery search (shec_make_decoding_matrix) ---------------

    def _search(self, want: List[int], avail: List[int]):
        """Returns (minimum ids, dm_row ids) or raises EIO."""
        k, m = self.k, self.m
        F = gf(self.w)
        want = list(want)
        for i in range(m):
            if want[k + i] and not avail[k + i]:
                for j in range(k):
                    if self.matrix[i, j] > 0:
                        want[j] = 1

        mindup, minp = k + 1, k + 1
        best_rows: List[int] | None = None
        best_cols: List[int] | None = None
        for pp in range(1 << m):
            p = [i for i in range(m) if pp & (1 << i)]
            ek = len(p)
            if ek > minp:
                continue
            if not all(avail[k + pi] for pi in p):
                continue
            tmprow = [0] * (k + m)
            tmpcol = [0] * k
            for i in range(k):
                if want[i] and not avail[i]:
                    tmpcol[i] = 1
            for pi in p:
                tmprow[k + pi] = 1
                for j in range(k):
                    e = int(self.matrix[pi, j])
                    if e != 0:
                        tmpcol[j] = 1
                        if avail[j]:
                            tmprow[j] = 1
            dup_row, dup_col = sum(tmprow), sum(tmpcol)
            if dup_row != dup_col:
                continue
            dup = dup_row
            if dup == 0:
                mindup = 0
                best_rows, best_cols = [], []
                break
            if dup < mindup:
                rows = [i for i in range(k + m) if tmprow[i]]
                cols = [j for j in range(k) if tmpcol[j]]
                A = np.zeros((dup, dup), dtype=np.uint32)
                for r, rid in enumerate(rows):
                    for cidx, cid in enumerate(cols):
                        if rid < k:
                            A[r, cidx] = 1 if rid == cid else 0
                        else:
                            A[r, cidx] = self.matrix[rid - k, cid]
                try:
                    F.mat_invert(A)
                except np.linalg.LinAlgError:
                    continue
                mindup = dup
                best_rows, best_cols = rows, cols
                minp = ek

        if mindup == k + 1:
            raise ErasureCodeError(_errno.EIO, "can't find recover matrix")

        minimum = set(best_rows or [])
        for i in range(k):
            if want[i] and avail[i]:
                minimum.add(i)
        for i in range(m):
            if want[k + i] and avail[k + i] and (k + i) not in minimum:
                for j in range(k):
                    if self.matrix[i, j] > 0 and not want[j]:
                        minimum.add(k + i)
                        break
        return sorted(minimum), best_rows or [], best_cols or []

    def _minimum_to_decode(
        self, want_to_read: Iterable[int], available_chunks: Iterable[int]
    ) -> List[int]:
        km = self.k + self.m
        for ids in (want_to_read, available_chunks):
            for i in ids:
                if i < 0 or i >= km:
                    raise ErasureCodeError(_errno.EINVAL, "chunk id out of range")
        want = [1 if i in set(want_to_read) else 0 for i in range(km)]
        avail = [1 if i in set(available_chunks) else 0 for i in range(km)]
        minimum, _, _ = self._search(want, avail)
        return minimum

    # -- decode ------------------------------------------------------------

    def decode_chunks(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        F = gf(self.w)
        km = k + m
        avail = [1 if i in chunks else 0 for i in range(km)]
        want = [1 if i in set(want_to_read) or i not in chunks else 0 for i in range(km)]
        _, rows, cols = self._search(want, avail)
        blocksize = len(next(iter(chunks.values())))

        if cols:
            # solve A x = b where x are the unknown data chunks `cols`
            dup = len(rows)
            A = np.zeros((dup, dup), dtype=np.uint32)
            for r, rid in enumerate(rows):
                for cidx, cid in enumerate(cols):
                    A[r, cidx] = (
                        (1 if rid == cid else 0)
                        if rid < k
                        else int(self.matrix[rid - k, cid])
                    )
            inv = F.mat_invert(A)
            # rhs: available chunk minus known-data contributions
            rhs = np.zeros((dup, blocksize), dtype=np.uint8)
            known = [j for j in range(k) if avail[j] and j not in cols]
            for r, rid in enumerate(rows):
                b = np.array(decoded[rid], dtype=np.uint8)
                if rid >= k and known:
                    words = b.view(F.word_dtype).copy()
                    for j in known:
                        cco = int(self.matrix[rid - k, j])
                        if cco:
                            words ^= F.mul_region(
                                cco, decoded[j].view(F.word_dtype)
                            )
                    b = words.view(np.uint8)
                rhs[r] = b
            # x = inv @ rhs over GF(2^w)
            for cidx, cid in enumerate(cols):
                if avail[cid]:
                    continue
                acc = np.zeros(blocksize // (self.w // 8), dtype=F.word_dtype)
                for r in range(dup):
                    cco = int(inv[cidx, r])
                    if cco:
                        acc ^= F.mul_region(cco, rhs[r].view(F.word_dtype))
                decoded[cid][:] = acc.view(np.uint8)

        # re-encode erased coding chunks
        data = np.stack([decoded[j] for j in range(k)])
        for i in range(m):
            if (k + i) not in chunks:
                row = np.ascontiguousarray(self.matrix[i : i + 1, :])
                decoded[k + i][:] = self._engine().matrix_encode(
                    row, data, self.w
                )[0]


class ErasureCodePluginShec(registry_mod.ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        technique = profile.get("technique") or "multiple"
        profile["technique"] = technique
        if technique not in ("single", "multiple"):
            raise ErasureCodeError(
                _errno.ENOENT,
                f"technique={technique} is not a valid coding technique",
            )
        ec = ErasureCodeShec(technique)
        ec.init(profile)
        return ec


def __erasure_code_version__() -> str:
    from ceph_tpu import __version__

    return __version__


def __erasure_code_init__(name: str, directory: str) -> int:
    registry_mod.instance().add(name, ErasureCodePluginShec())
    return 0
