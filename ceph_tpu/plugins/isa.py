"""isa-equivalent plugin: Intel ISA-L semantics on the shared engines.

Mirrors the reference isa plugin (reference: src/erasure-code/isa/
ErasureCodeIsa.{h,cc}, ErasureCodePluginIsa.cc):

* techniques ``reed_sol_van`` (Vandermonde-by-generator, gf_gen_rs_matrix)
  and ``cauchy`` (gf_gen_cauchy1_matrix), both over GF(2^8)/0x11D
  (ceph_tpu/matrices/isa.py);
* parameter guard rails: Vandermonde requires k<=32, m<=4 and m==4 -> k<=21
  (ErasureCodeIsa.cc:322-363);
* per-chunk alignment EC_ISA_ADDRESS_ALIGNMENT=32 (:59-78, :314-318);
* m==1 encodes/decodes via pure XOR (region_xor, :124-126);
* Vandermonde single-erasure with id < k+1 decodes via XOR (:205-215) --
  same bytes as the general path since coding row 0 is all ones;
* decode tables are LRU-cached per erasure signature
  (ErasureCodeIsaTableCache.h:48); here the cached object is the inverted
  row block keyed the same way.
"""

from __future__ import annotations

import errno as _errno
from collections import OrderedDict
from typing import Dict, Iterable, Mapping

import numpy as np

from ceph_tpu.matrices import isa as isa_matrices
from ceph_tpu.ops import cpu_engine
from ceph_tpu.ops.gf import gf
from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.plugins.interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
)

EC_ISA_ADDRESS_ALIGNMENT = 32


class ErasureCodeIsaTableCache:
    """LRU of decode row-blocks keyed by (matrixtype, k, m, signature)."""

    MAX_ENTRIES = 2516  # ErasureCodeIsaTableCache.h:48

    def __init__(self):
        self._lru: "OrderedDict[tuple, np.ndarray]" = OrderedDict()

    def get(self, key):
        rows = self._lru.get(key)
        if rows is not None:
            self._lru.move_to_end(key)
        return rows

    def put(self, key, rows):
        self._lru[key] = rows
        self._lru.move_to_end(key)
        while len(self._lru) > self.MAX_ENTRIES:
            self._lru.popitem(last=False)


_TABLE_CACHE = ErasureCodeIsaTableCache()


class ErasureCodeIsaDefault(ErasureCode):
    DEFAULT_K = "7"
    DEFAULT_M = "3"

    def __init__(self, matrixtype: str = "reed_sol_van"):
        super().__init__()
        self.technique = matrixtype
        self.k = 0
        self.m = 0
        self.w = 8
        self._backend = "cpu"
        self.matrix: np.ndarray | None = None  # coding rows only [m, k]
        self.tcache = _TABLE_CACHE

    # -- contract ----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        return EC_ISA_ADDRESS_ALIGNMENT

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % alignment
        if modulo:
            chunk_size += alignment - modulo
        return chunk_size

    def init(self, profile: ErasureCodeProfile) -> None:
        profile["technique"] = self.technique
        self.parse(profile)
        self.prepare()
        ErasureCode.init(self, profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        ErasureCode.parse(self, profile)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self._backend = self.to_string("backend", profile, "cpu")
        self.sanity_check_k(self.k)
        if self.technique == "reed_sol_van":
            if self.k > 32:
                raise ErasureCodeError(
                    _errno.EINVAL, "Vandermonde: k=%d must be <= 32" % self.k
                )
            if self.m > 4:
                raise ErasureCodeError(
                    _errno.EINVAL, "Vandermonde: m=%d must be <= 4" % self.m
                )
            if self.m == 4 and self.k > 21:
                raise ErasureCodeError(
                    _errno.EINVAL, "Vandermonde: m=4 -> k must be <= 21"
                )

    def prepare(self) -> None:
        if self.technique == "cauchy":
            A = isa_matrices.gen_cauchy1_matrix(self.k, self.m)
        else:
            A = isa_matrices.gen_rs_matrix(self.k, self.m)
        self.matrix = np.ascontiguousarray(A[self.k :, :])

    # -- compute -----------------------------------------------------------

    def _engine(self):
        if self._backend == "tpu":
            from ceph_tpu.ops import xla_gf

            return xla_gf
        if self._backend == "native":
            from ceph_tpu.ops import native_engine

            return native_engine
        return cpu_engine

    def encode_chunks(
        self, want_to_encode: Iterable[int], encoded: Dict[int, np.ndarray]
    ) -> None:
        data = np.stack([encoded[i] for i in range(self.k)])
        if self.m == 1:
            # region_xor fast path (ErasureCodeIsa.cc:124-126)
            coding = np.bitwise_xor.reduce(data, axis=0)[None, :]
        else:
            coding = self._engine().matrix_encode(self.matrix, data, self.w)
        for i in range(self.m):
            encoded[self.k + i][:] = coding[i]

    def decode_chunks(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        have = {i: decoded[i] for i in range(self.k + self.m) if i in chunks}
        erased = [i for i in range(self.k + self.m) if i not in chunks]
        if len(have) < self.k:
            raise ErasureCodeError(_errno.EIO, "not enough chunks to decode")
        blocksize = len(next(iter(have.values())))

        # XOR fast paths: m==1, or Vandermonde single erasure of a chunk
        # that the all-ones first coding row covers (id < k+1)
        if len(erased) == 1 and (
            self.m == 1
            or (self.technique == "reed_sol_van" and erased[0] < self.k + 1)
        ):
            e = erased[0]
            srcs = [i for i in range(self.k + 1) if i != e][: self.k]
            acc = np.zeros(blocksize, dtype=np.uint8)
            for s in srcs:
                acc ^= decoded[s]
            decoded[e][:] = acc
            return

        rec = self._decode_general(have, blocksize)
        for i in erased:
            decoded[i][:] = rec[i]

    def _decode_general(self, have, blocksize):
        """General path with signature-keyed decode-row cache."""
        erased = tuple(
            i for i in range(self.k + self.m) if i not in have
        )
        key = (self.technique, self.k, self.m, erased)
        rows = self.tcache.get(key)
        available = sorted(have.keys())
        sel = available[: self.k]
        if rows is None:
            F = gf(8)
            A = np.zeros((self.k, self.k), dtype=np.uint32)
            for r, cid in enumerate(sel):
                if cid < self.k:
                    A[r, cid] = 1
                else:
                    A[r, :] = self.matrix[cid - self.k, :]
            rows = F.mat_invert(A)
            self.tcache.put(key, rows)
        out = {i: np.asarray(have[i], dtype=np.uint8) for i in available}
        erased_data = [e for e in erased if e < self.k]
        if erased_data:
            survivors = np.stack([out[cid] for cid in sel])
            rec = self._engine().matrix_encode(
                np.ascontiguousarray(rows[erased_data, :]), survivors, 8
            )
            for idx, e in enumerate(erased_data):
                out[e] = rec[idx]
        erased_coding = [e for e in erased if e >= self.k]
        if erased_coding:
            data = np.stack([out[j] for j in range(self.k)])
            sub = np.ascontiguousarray(
                self.matrix[[e - self.k for e in erased_coding], :]
            )
            rec = self._engine().matrix_encode(sub, data, 8)
            for idx, e in enumerate(erased_coding):
                out[e] = rec[idx]
        return out


class ErasureCodePluginIsa(registry_mod.ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        technique = profile.get("technique") or "reed_sol_van"
        profile["technique"] = technique
        if technique not in ("reed_sol_van", "cauchy"):
            raise ErasureCodeError(
                _errno.ENOENT,
                f"technique={technique} is not a valid coding technique",
            )
        ec = ErasureCodeIsaDefault(technique)
        ec.init(profile)
        return ec


def __erasure_code_version__() -> str:
    from ceph_tpu import __version__

    return __version__


def __erasure_code_init__(name: str, directory: str) -> int:
    registry_mod.instance().add(name, ErasureCodePluginIsa())
    return 0
