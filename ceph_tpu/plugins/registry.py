"""Erasure-code plugin registry.

Mirrors the reference's dlopen-based singleton registry semantics
(reference: src/erasure-code/ErasureCodePlugin.{h,cc}):

* ``factory()`` loads a plugin once, instantiates a codec through it, and
  verifies the instance's profile equals the requested profile
  (ErasureCodePlugin.cc:92-120);
* ``load()`` resolves ``ec_<name>`` from a plugin directory (the analogue of
  dlopen("<dir>/libec_<name>.so")), checks the plugin's version string
  against ours (mismatch -> -EXDEV), then calls its entry point which must
  register itself (missing entry point -> -ENOENT, registers nothing ->
  -EBADF, init failure propagates);
* ``preload()`` loads a configured list at startup
  (ErasureCodePlugin.cc:186).

Built-in plugins ship as modules in this package; out-of-tree plugins are
python files ``ec_<name>.py`` in ``directory`` (and the native C++ registry in
ceph_tpu/native loads real ``libec_<name>.so`` with the same handshake).
"""

from __future__ import annotations

import errno as _errno
import importlib
import importlib.util
import os
import threading
from typing import Dict, Optional

from ceph_tpu import __version__
from ceph_tpu.plugins.interface import (
    ErasureCodeError,
    ErasureCodeInterface,
    ErasureCodeProfile,
)

#: entry-point names an out-of-tree plugin module must define
ENTRY_POINT = "__erasure_code_init__"
VERSION_POINT = "__erasure_code_version__"

#: built-in plugin name -> module path
_BUILTIN = {
    "jerasure": "ceph_tpu.plugins.jerasure",
    "isa": "ceph_tpu.plugins.isa",
    "shec": "ceph_tpu.plugins.shec",
    "lrc": "ceph_tpu.plugins.lrc",
    "tpu": "ceph_tpu.plugins.tpu",
    "regen": "ceph_tpu.plugins.regen",
    "example": "ceph_tpu.plugins.example",
}

DEFAULT_PLUGINS = "jerasure lrc isa tpu"  # osd_erasure_code_plugins analogue


class ErasureCodePlugin:
    """Base class every plugin registers an instance of."""

    def factory(
        self, directory: str, profile: ErasureCodeProfile
    ) -> ErasureCodeInterface:
        raise NotImplementedError


class ErasureCodePluginRegistry:
    """Process-wide singleton (reference ErasureCodePlugin.h:45)."""

    _instance: Optional["ErasureCodePluginRegistry"] = None
    _instance_lock = threading.Lock()
    #: registry currently executing a plugin entry point; lets plugin modules
    #: resolve `instance()` to the loader even in tests that use a private
    #: registry (the reference's C entry points hit the process singleton)
    _current_loading: Optional["ErasureCodePluginRegistry"] = None

    def __init__(self):
        self._lock = threading.RLock()
        self._plugins: Dict[str, ErasureCodePlugin] = {}
        self.loading = False
        self.disable_dlclose = False  # kept for API parity with the bench tool

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        if cls._current_loading is not None:
            return cls._current_loading
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # -- registration ------------------------------------------------------

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise ErasureCodeError(_errno.EEXIST, f"plugin {name} already registered")
            self._plugins[name] = plugin

    def remove(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    def get(self, name: str) -> Optional[ErasureCodePlugin]:
        with self._lock:
            return self._plugins.get(name)

    # -- loading -----------------------------------------------------------

    def load(self, plugin_name: str, directory: str = "") -> ErasureCodePlugin:
        """Resolve and initialize plugin code (analogue of dlopen+handshake)."""
        with self._lock:
            self.loading = True
            ErasureCodePluginRegistry._current_loading = self
            try:
                module = self._resolve(plugin_name, directory)
                version_fn = getattr(module, VERSION_POINT, None)
                if version_fn is None:
                    raise ErasureCodeError(
                        _errno.EXDEV,
                        f"{plugin_name} plugin has no version (loaded from an older version?)",
                    )
                version = version_fn()
                if version != __version__:
                    raise ErasureCodeError(
                        _errno.EXDEV,
                        f"{plugin_name} version {version} != expected {__version__}",
                    )
                init_fn = getattr(module, ENTRY_POINT, None)
                if init_fn is None:
                    raise ErasureCodeError(
                        _errno.ENOENT,
                        f"{plugin_name} plugin is missing the {ENTRY_POINT} entry point",
                    )
                rc = init_fn(plugin_name, directory)
                if isinstance(rc, int) and rc < 0:
                    raise ErasureCodeError(rc, f"{plugin_name} init returned {rc}")
                plugin = self._plugins.get(plugin_name)
                if plugin is None:
                    raise ErasureCodeError(
                        _errno.EBADF,
                        f"{plugin_name} initialized but did not register itself",
                    )
                return plugin
            finally:
                self.loading = False
                ErasureCodePluginRegistry._current_loading = None

    def _resolve(self, plugin_name: str, directory: str):
        if directory:
            path = os.path.join(directory, f"ec_{plugin_name}.py")
            if os.path.exists(path):
                spec = importlib.util.spec_from_file_location(
                    f"ec_{plugin_name}", path
                )
                module = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(module)
                return module
        modpath = _BUILTIN.get(plugin_name)
        if modpath is None:
            raise ErasureCodeError(
                _errno.ENOENT, f"no plugin {plugin_name} in directory {directory!r}"
            )
        return importlib.import_module(modpath)

    def preload(self, plugins: str = DEFAULT_PLUGINS, directory: str = "") -> None:
        """Load a space/comma-separated plugin list at daemon start."""
        for name in plugins.replace(",", " ").split():
            if not self.get(name):
                self.load(name, directory)

    # -- the main entry point ---------------------------------------------

    def factory(
        self,
        plugin_name: str,
        profile: ErasureCodeProfile,
        directory: str = "",
    ) -> ErasureCodeInterface:
        plugin = self.get(plugin_name)
        if plugin is None:
            plugin = self.load(plugin_name, directory)
        ec = plugin.factory(directory, profile)
        if profile != ec.get_profile():
            raise ErasureCodeError(
                _errno.EINVAL,
                f"profile {profile} != get_profile() {ec.get_profile()}",
            )
        return ec


def instance() -> ErasureCodePluginRegistry:
    return ErasureCodePluginRegistry.instance()
