"""Minimal XOR example plugin (k=2, m=1) -- test fixture.

Mirrors the reference's example plugin used by registry/unit tests
(reference: src/test/erasure-code/ErasureCodeExample.h,
ErasureCodePluginExample.cc): parity chunk = XOR of the two data chunks.
"""

from __future__ import annotations

import errno as _errno
from typing import Dict, Iterable, Mapping

import numpy as np

from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.plugins.interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
)


class ErasureCodeExample(ErasureCode):
    k = 2
    m = 1

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        ErasureCode.init(self, profile)

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_size(self, object_size: int) -> int:
        return (object_size + self.k - 1) // self.k

    def minimum_to_decode_with_cost(self, want_to_read, available):
        # prefer the cheapest k chunks (reference ErasureCodeExample.h)
        if set(want_to_read) <= set(available.keys()):
            ranked = sorted(available.items(), key=lambda kv: kv[1])
            return [c for c, _ in ranked[: self.k]]
        return self._minimum_to_decode(want_to_read, available.keys())

    def encode_chunks(
        self, want_to_encode: Iterable[int], encoded: Dict[int, np.ndarray]
    ) -> None:
        encoded[2][:] = encoded[0] ^ encoded[1]

    def decode_chunks(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        have = sorted(chunks.keys())
        if len(have) < 2:
            raise ErasureCodeError(_errno.EIO, "need 2 of 3 chunks")
        missing = [i for i in range(3) if i not in chunks]
        for i in missing:
            others = [j for j in range(3) if j != i]
            decoded[i][:] = decoded[others[0]] ^ decoded[others[1]]


class ErasureCodePluginExample(registry_mod.ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        ec = ErasureCodeExample()
        ec.init(profile)
        return ec


def __erasure_code_version__() -> str:
    from ceph_tpu import __version__

    return __version__


def __erasure_code_init__(name: str, directory: str) -> int:
    registry_mod.instance().add(name, ErasureCodePluginExample())
    return 0
