"""lrc-equivalent plugin: Locally Repairable composite Code.

Mirrors the reference lrc plugin (reference: src/erasure-code/lrc/
ErasureCodeLrc.{h,cc}):

* a profile is either a JSON ``layers`` description (each layer = a
  chunks-map string like "DDc_D" plus an inner-plugin profile) with a
  ``mapping`` string, or the (k, m, l) shortcut that *generates* mapping +
  layers (one global layer + (k+m)/l local layers; parse_kml,
  ErasureCodeLrc.cc:293-420);
* each layer instantiates an inner codec through the registry
  (layers_init, :215-253; defaults plugin=jerasure technique=reed_sol_van);
* encode walks layers top-down over each layer's chunk subset (:739-776);
* decode walks layers in reverse, recovering what each layer can and
  feeding recovered chunks upward (:643-…); ``_minimum_to_decode`` prefers
  local repair (fewest reads) and falls back to global layers (:568-737,
  cases 1-3).
"""

from __future__ import annotations

import errno as _errno
import json
from typing import Dict, Iterable, List, Mapping, Set

import numpy as np

from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.plugins.interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
)

DEFAULT_KML = -1


class Layer:
    def __init__(self, chunks_map: str, profile: ErasureCodeProfile):
        self.chunks_map = chunks_map
        self.profile = profile
        self.data = [i for i, ch in enumerate(chunks_map) if ch == "D"]
        self.coding = [i for i, ch in enumerate(chunks_map) if ch == "c"]
        self.chunks = self.data + self.coding
        self.chunks_as_set: Set[int] = set(self.chunks)
        self.erasure_code = None  # filled by layers_init


def _parse_layer_profile(text: str) -> ErasureCodeProfile:
    """Layer profile may be a space-separated k=v string or a JSON object."""
    prof: ErasureCodeProfile = {}
    text = text.strip()
    if not text:
        return prof
    if text.startswith("{"):
        for key, val in json.loads(text).items():
            prof[str(key)] = str(val)
        return prof
    for tok in text.split():
        if "=" in tok:
            key, val = tok.split("=", 1)
            prof[key] = val
    return prof


class ErasureCodeLrc(ErasureCode):
    def __init__(self, directory: str = ""):
        super().__init__()
        self.layers: List[Layer] = []
        self.chunk_count_ = 0
        self.data_chunk_count_ = 0
        self.directory = directory
        self.rule_steps = [("chooseleaf", "host", 0)]

    # -- contract ----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.chunk_count_

    def get_data_chunk_count(self) -> int:
        return self.data_chunk_count_

    def get_chunk_size(self, object_size: int) -> int:
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    # -- profile parsing ---------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse_kml(profile)
        if "mapping" not in profile:
            raise ErasureCodeError(
                _errno.EINVAL, "the 'mapping' profile is missing"
            )
        mapping = profile["mapping"]
        self.to_mapping(profile)
        self.data_chunk_count_ = mapping.count("D")
        self.chunk_count_ = len(mapping)

        if "layers" not in profile:
            raise ErasureCodeError(
                _errno.EINVAL, "the 'layers' profile is missing"
            )
        self.layers_parse(profile["layers"])
        self.layers_sanity_checks(mapping)
        self.layers_init()
        ErasureCode.init(self, profile)

    def parse_kml(self, profile: ErasureCodeProfile) -> None:
        """(k, m, l) shortcut -> generated mapping + layers (parse_kml)."""
        k = int(profile.get("k", DEFAULT_KML) or DEFAULT_KML)
        m = int(profile.get("m", DEFAULT_KML) or DEFAULT_KML)
        l = int(profile.get("l", DEFAULT_KML) or DEFAULT_KML)
        if k == DEFAULT_KML and m == DEFAULT_KML and l == DEFAULT_KML:
            return
        if DEFAULT_KML in (k, m, l):
            raise ErasureCodeError(
                _errno.EINVAL, "all of k, m, l must be set or none of them"
            )
        for generated in ("mapping", "layers", "crush-steps"):
            if generated in profile:
                raise ErasureCodeError(
                    _errno.EINVAL,
                    f"the {generated} parameter cannot be set when k, m, l are set",
                )
        if (k + m) % l:
            raise ErasureCodeError(_errno.EINVAL, "k + m must be a multiple of l")
        groups = (k + m) // l
        if k % groups:
            raise ErasureCodeError(_errno.EINVAL, "k must be a multiple of (k+m)/l")
        if m % groups:
            raise ErasureCodeError(_errno.EINVAL, "m must be a multiple of (k+m)/l")

        mapping = ""
        for _ in range(groups):
            mapping += "D" * (k // groups) + "_" * (m // groups) + "_"
        profile["mapping"] = mapping

        layers = "[ "
        layers += ' [ "'
        for _ in range(groups):
            layers += "D" * (k // groups) + "c" * (m // groups) + "_"
        layers += '", "" ],'
        for i in range(groups):
            layers += ' [ "'
            for j in range(groups):
                layers += ("D" * l + "c") if i == j else "_" * (l + 1)
            layers += '", "" ],'
        profile["layers"] = layers + "]"

        locality = profile.get("crush-locality", "")
        failure_domain = profile.get("crush-failure-domain", "host")
        if locality:
            self.rule_steps = [
                ("choose", locality, groups),
                ("chooseleaf", failure_domain, l + 1),
            ]
        elif failure_domain:
            self.rule_steps = [("chooseleaf", failure_domain, 0)]

    def layers_parse(self, description: str) -> None:
        import re

        # json_spirit tolerates trailing commas (and parse_kml emits one)
        description = re.sub(r",\s*([\]}])", r" \1", description)
        try:
            desc = json.loads(description)
        except json.JSONDecodeError as e:
            raise ErasureCodeError(
                _errno.EINVAL, f"layers parse failure: {e}"
            )
        if not isinstance(desc, list):
            raise ErasureCodeError(
                _errno.EINVAL, "layers must be a JSON array"
            )
        for item in desc:
            if not isinstance(item, list) or not item:
                raise ErasureCodeError(
                    _errno.EINVAL, f"each layer must be a JSON array: {item!r}"
                )
            chunks_map = item[0]
            if not isinstance(chunks_map, str):
                raise ErasureCodeError(
                    _errno.EINVAL, "layer chunks map must be a string"
                )
            prof: ErasureCodeProfile = {}
            if len(item) > 1:
                if isinstance(item[1], str):
                    prof = _parse_layer_profile(item[1])
                elif isinstance(item[1], dict):
                    prof = {str(a): str(b) for a, b in item[1].items()}
            self.layers.append(Layer(chunks_map, prof))

    def layers_sanity_checks(self, mapping: str) -> None:
        if not self.layers:
            raise ErasureCodeError(
                _errno.EINVAL, "at least one layer is required"
            )
        for layer in self.layers:
            if len(layer.chunks_map) != len(mapping):
                raise ErasureCodeError(
                    _errno.EINVAL,
                    f"the size of layer {layer.chunks_map} does not match "
                    f"the mapping {mapping}",
                )

    def layers_init(self) -> None:
        registry = registry_mod.instance()
        for layer in self.layers:
            prof = layer.profile
            prof.setdefault("k", str(len(layer.data)))
            prof.setdefault("m", str(len(layer.coding)))
            prof.setdefault("plugin", "jerasure")
            prof.setdefault("technique", "reed_sol_van")
            plugin = prof["plugin"]
            inner = dict(prof)
            inner.pop("plugin", None)
            layer.erasure_code = registry.factory(
                plugin, inner, self.directory
            )

    # -- encode ------------------------------------------------------------

    def encode_chunks(
        self, want_to_encode: Iterable[int], encoded: Dict[int, np.ndarray]
    ) -> None:
        want = set(want_to_encode)
        top = len(self.layers)
        for layer in reversed(self.layers):
            top -= 1
            if want <= layer.chunks_as_set:
                break
        for layer in self.layers[top:]:
            layer_encoded = {
                j: encoded[c] for j, c in enumerate(layer.chunks)
            }
            layer_want = {
                j for j, c in enumerate(layer.chunks) if c in want
            }
            layer.erasure_code.encode_chunks(layer_want, layer_encoded)

    # -- minimum_to_decode (cases 1-3) --------------------------------------

    def _minimum_to_decode(
        self, want_to_read: Iterable[int], available_chunks: Iterable[int]
    ) -> List[int]:
        want = set(want_to_read)
        avail = set(available_chunks)
        km = self.get_chunk_count()
        erasures_total = {i for i in range(km) if i not in avail}
        erasures_not_recovered = set(erasures_total)
        erasures_want = erasures_total & want

        if not erasures_want:
            return sorted(want)

        minimum: Set[int] = set()
        for layer in reversed(self.layers):
            layer_want = want & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                layer_minimum = layer_want
            else:
                erasures = layer.chunks_as_set & erasures_not_recovered
                if len(erasures) > layer.erasure_code.get_coding_chunk_count():
                    continue
                layer_minimum = layer.chunks_as_set - erasures_not_recovered
                erasures_not_recovered -= erasures
                erasures_want -= erasures
            minimum |= layer_minimum
        if not erasures_want:
            minimum |= want
            minimum -= erasures_total
            return sorted(minimum)

        # case 3: recover helper chunks from any layer
        erasures_total = {i for i in range(km) if i not in avail}
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures_total
            if not layer_erasures:
                continue
            if len(layer_erasures) <= layer.erasure_code.get_coding_chunk_count():
                erasures_total -= layer_erasures
        if not erasures_total:
            return sorted(avail)
        raise ErasureCodeError(
            _errno.EIO, f"not enough chunks in {sorted(avail)} to read {sorted(want)}"
        )

    # -- decode ------------------------------------------------------------

    def decode_chunks(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        km = self.get_chunk_count()
        want = set(want_to_read)
        erasures = {i for i in range(km) if i not in chunks}
        want_erasures = erasures & want
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures
            if (
                not layer_erasures
                or len(layer_erasures)
                > layer.erasure_code.get_coding_chunk_count()
            ):
                continue
            layer_chunks = {
                j: decoded[c]
                for j, c in enumerate(layer.chunks)
                if c not in erasures
            }
            layer_decoded = {
                j: decoded[c] for j, c in enumerate(layer.chunks)
            }
            layer_want = {
                j for j, c in enumerate(layer.chunks) if c in want
            }
            layer.erasure_code.decode_chunks(
                layer_want, layer_chunks, layer_decoded
            )
            for j, c in enumerate(layer.chunks):
                decoded[c][:] = layer_decoded[j]
                erasures.discard(c)
            want_erasures = erasures & want
            if not want_erasures:
                break
        if want_erasures:
            raise ErasureCodeError(
                _errno.EIO, f"unable to read {sorted(want_erasures)}"
            )

    def create_rule(self, name: str, crush) -> int:
        return crush.add_rule(name, self.rule_steps, self.rule_root)


class ErasureCodePluginLrc(registry_mod.ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        ec = ErasureCodeLrc(directory)
        ec.init(profile)
        return ec


def __erasure_code_version__() -> str:
    from ceph_tpu import __version__

    return __version__


def __erasure_code_init__(name: str, directory: str) -> int:
    registry_mod.instance().add(name, ErasureCodePluginLrc())
    return 0
