"""Erasure-code codec contract and shared base class.

Python rendering of the reference's pure-virtual codec contract
(reference: src/erasure-code/ErasureCodeInterface.h:170-464) and the shared
base class logic (src/erasure-code/ErasureCode.{h,cc}): profile parsing,
chunk-mapping permutation, padding/preparation (`encode_prepare`), generic
encode/decode driving `encode_chunks`/`decode_chunks`, and the default
`minimum_to_decode` (want-if-available else first k available, with
(offset, count) sub-chunk ranges).

Chunks are numpy uint8 arrays; `ErasureCodeError` carries the reference's
-errno convention in `.errno`.
"""

from __future__ import annotations

import errno as _errno
from typing import Dict, Iterable, List, Mapping, Sequence, Tuple

import numpy as np

ErasureCodeProfile = Dict[str, str]

#: alignment every prepared chunk honors (reference ErasureCode.cc:29;
#: 32 also happens to be a TPU-friendly byte multiple for int8 lanes)
SIMD_ALIGN = 32


class ErasureCodeError(Exception):
    """Codec error carrying a negative errno like the reference's int codes."""

    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(f"{msg} (errno {self.errno})")


class ErasureCodeInterface:
    """Abstract codec contract (ErasureCodeInterface.h:170).

    Systematic codes only: an object is padded and split into k equal data
    chunks; m coding chunks are computed from them.  Chunk i of the encode
    output lands at position ``chunk_mapping[i]`` when a mapping is set.
    """

    def init(self, profile: ErasureCodeProfile) -> None:
        raise NotImplementedError

    def get_profile(self) -> ErasureCodeProfile:
        raise NotImplementedError

    def get_chunk_count(self) -> int:
        raise NotImplementedError

    def get_data_chunk_count(self) -> int:
        raise NotImplementedError

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        return 1

    def get_chunk_size(self, object_size: int) -> int:
        raise NotImplementedError

    def minimum_to_decode(
        self, want_to_read: Iterable[int], available: Iterable[int]
    ) -> Dict[int, List[Tuple[int, int]]]:
        raise NotImplementedError

    def minimum_to_decode_with_cost(
        self, want_to_read: Iterable[int], available: Mapping[int, int]
    ) -> List[int]:
        raise NotImplementedError

    def encode(
        self, want_to_encode: Iterable[int], data: bytes | np.ndarray
    ) -> Dict[int, np.ndarray]:
        raise NotImplementedError

    def encode_chunks(
        self, want_to_encode: Iterable[int], encoded: Dict[int, np.ndarray]
    ) -> None:
        raise NotImplementedError

    def decode(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        chunk_size: int = 0,
    ) -> Dict[int, np.ndarray]:
        raise NotImplementedError

    def decode_chunks(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        raise NotImplementedError

    def get_chunk_mapping(self) -> List[int]:
        raise NotImplementedError

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        raise NotImplementedError


def _as_u8(buf: bytes | bytearray | memoryview | np.ndarray) -> np.ndarray:
    if isinstance(buf, np.ndarray):
        return np.ascontiguousarray(buf, dtype=np.uint8).ravel()
    return np.frombuffer(bytes(buf), dtype=np.uint8)


class ErasureCode(ErasureCodeInterface):
    """Shared logic (reference src/erasure-code/ErasureCode.cc)."""

    def __init__(self):
        self.chunk_mapping: List[int] = []
        self._profile: ErasureCodeProfile = {}
        self.rule_root = "default"
        self.rule_failure_domain = "host"
        self.rule_device_class = ""

    # -- profile plumbing --------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = self.to_string("crush-root", profile, "default")
        self.rule_failure_domain = self.to_string(
            "crush-failure-domain", profile, "host"
        )
        self.rule_device_class = self.to_string("crush-device-class", profile, "")
        self._profile = profile

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    def parse(self, profile: ErasureCodeProfile) -> None:
        self.to_mapping(profile)

    def to_mapping(self, profile: ErasureCodeProfile) -> None:
        """Parse a 'DD_D...' mapping string: D positions take data chunks in
        order, the rest take coding chunks in order (ErasureCode.cc:258-277)."""
        if "mapping" in profile:
            mapping = profile["mapping"]
            data_pos = [i for i, ch in enumerate(mapping) if ch == "D"]
            coding_pos = [i for i, ch in enumerate(mapping) if ch != "D"]
            self.chunk_mapping = data_pos + coding_pos

    @staticmethod
    def to_int(
        name: str, profile: ErasureCodeProfile, default: str
    ) -> int:
        if not profile.get(name):
            profile[name] = default
        try:
            return int(profile[name])
        except ValueError:
            raise ErasureCodeError(
                _errno.EINVAL, f"could not convert {name}={profile[name]} to int"
            )

    @staticmethod
    def to_bool(
        name: str, profile: ErasureCodeProfile, default: str
    ) -> bool:
        if not profile.get(name):
            profile[name] = default
        return profile[name] in ("yes", "true")

    @staticmethod
    def to_string(
        name: str, profile: ErasureCodeProfile, default: str
    ) -> str:
        if not profile.get(name):
            profile[name] = default
        return profile[name]

    @staticmethod
    def sanity_check_k(k: int) -> None:
        if k < 2:
            raise ErasureCodeError(_errno.EINVAL, f"k={k} must be >= 2")

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if len(self.chunk_mapping) > i else i

    def get_chunk_mapping(self) -> List[int]:
        return self.chunk_mapping

    # -- minimum_to_decode -------------------------------------------------

    def _minimum_to_decode(
        self, want_to_read: Iterable[int], available_chunks: Iterable[int]
    ) -> List[int]:
        want = sorted(set(want_to_read))
        avail = sorted(set(available_chunks))
        if set(want) <= set(avail):
            return want
        k = self.get_data_chunk_count()
        if len(avail) < k:
            raise ErasureCodeError(_errno.EIO, "not enough chunks to decode")
        return avail[:k]

    def minimum_to_decode(
        self, want_to_read: Iterable[int], available: Iterable[int]
    ) -> Dict[int, List[Tuple[int, int]]]:
        ids = self._minimum_to_decode(want_to_read, available)
        sub = [(0, self.get_sub_chunk_count())]
        return {i: list(sub) for i in ids}

    def minimum_to_decode_with_cost(
        self, want_to_read: Iterable[int], available: Mapping[int, int]
    ) -> List[int]:
        return self._minimum_to_decode(want_to_read, available.keys())

    # -- encode ------------------------------------------------------------

    def encode_prepare(self, raw: np.ndarray) -> Dict[int, np.ndarray]:
        """Split+pad input into k zero-padded chunks and allocate m coding
        chunks, honoring the chunk mapping (ErasureCode.cc:138-173)."""
        k = self.get_data_chunk_count()
        m = self.get_chunk_count() - k
        blocksize = self.get_chunk_size(len(raw))
        padded_chunks = k - len(raw) // blocksize
        encoded: Dict[int, np.ndarray] = {}
        for i in range(k - padded_chunks):
            encoded[self.chunk_index(i)] = np.array(
                raw[i * blocksize : (i + 1) * blocksize]
            )
        if padded_chunks:
            remainder = len(raw) - (k - padded_chunks) * blocksize
            buf = np.zeros(blocksize, dtype=np.uint8)
            buf[:remainder] = raw[(k - padded_chunks) * blocksize :]
            encoded[self.chunk_index(k - padded_chunks)] = buf
            for i in range(k - padded_chunks + 1, k):
                encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        for i in range(k, k + m):
            encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        return encoded

    def encode(
        self, want_to_encode: Iterable[int], data: bytes | np.ndarray
    ) -> Dict[int, np.ndarray]:
        raw = _as_u8(data)
        encoded = self.encode_prepare(raw)
        self.encode_chunks(set(want_to_encode), encoded)
        for i in list(encoded):
            if i not in want_to_encode:
                del encoded[i]
        return encoded

    # -- decode ------------------------------------------------------------

    def _decode(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
    ) -> Dict[int, np.ndarray]:
        want = set(want_to_read)
        if want <= set(chunks.keys()):
            return {i: np.asarray(chunks[i], dtype=np.uint8) for i in want}
        k = self.get_data_chunk_count()
        m = self.get_chunk_count() - k
        if not chunks:
            raise ErasureCodeError(_errno.EIO, "no chunks to decode from")
        blocksize = len(next(iter(chunks.values())))
        decoded: Dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i in chunks:
                decoded[i] = np.array(chunks[i], dtype=np.uint8)
            else:
                decoded[i] = np.zeros(blocksize, dtype=np.uint8)
        self.decode_chunks(want, chunks, decoded)
        return {i: decoded[i] for i in want} if want else decoded

    def decode(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        chunk_size: int = 0,
    ) -> Dict[int, np.ndarray]:
        return self._decode(want_to_read, chunks)

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        k = self.get_data_chunk_count()
        want = [self.chunk_index(i) for i in range(k)]
        decoded = self._decode(want, chunks)
        return b"".join(decoded[i].tobytes() for i in want)

    # -- placement hook (CRUSH analogue wired up by the osd layer) ---------

    def create_rule(self, name: str, crush) -> int:
        """Register an 'indep'-mode placement rule with a crush-like object
        (reference ErasureCode.cc:54-73). The osd layer supplies `crush`."""
        return crush.add_simple_rule(
            name,
            self.rule_root,
            self.rule_failure_domain,
            self.rule_device_class,
            "indep",
            num_chunks=self.get_chunk_count(),
        )
