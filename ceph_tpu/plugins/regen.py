"""ErasureCodePluginRegen: repair-bandwidth-optimal regenerating codes.

Product-matrix MSR (d = 2k-2) behind the standard plugin registry
(profile ``plugin=regen k=.. m=..``), built on the construction in
``matrices/product_matrix.py``.  Because B = k*alpha exactly, the whole
code linearizes to ONE systematic GF(2^8) generator over *virtual rows*
(node i's sub-chunk j = virtual row ``i*alpha + j``), so encode, decode
AND repair are all plain GF matmuls riding the same rung-bucketed
device pipeline (``ops/pipeline.py``) as the tpu plugin.

What the plugin adds over the classic MDS family:

* ``get_sub_chunk_count() == alpha`` and a :meth:`minimum_to_decode`
  that, for a SINGLE lost shard with >= d survivors, returns a
  d-helper plan of ONE sub-chunk each (beta = chunk/alpha bytes) --
  the recovery coalescer turns that into beta-extent ``ECSubRead``
  bursts instead of whole-shard reads (d*beta = 2*chunk bytes moved,
  ratio 2/k of the full-stripe gather);
* :func:`compute_helpers` -- the survivor-side dot of its alpha stored
  sub-chunks with the wire-carried ``phi_f`` coefficients, batched
  over every object of a sub-read message as one pipelined dispatch
  (and dispatched on the daemon's own mesh slot when the process mesh
  data plane covers it);
* :meth:`regenerate_batch` -- the primary-side fused regenerating
  matmul: d stacked helper symbols -> the lost shard, one device
  dispatch per (lost, helper-set) signature for the whole batch.

Multi-loss falls back to the classic full-stripe decode (the virtual-
row generator is MDS over whole nodes), and fewer than d helpers are
REFUSED rather than mis-combined -- the repair matrix is only defined
for exactly d of them.
"""

from __future__ import annotations

import errno as _errno
import threading
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu.matrices.product_matrix import ProductMatrixMSR
from ceph_tpu.ops import cpu_engine
from ceph_tpu.ops.pipeline import (DeviceCodec, EncodePipeline,
                                   _backend_is_tpu,
                                   matrix_reconstruct_rows)
from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.plugins.interface import (SIMD_ALIGN, ErasureCode,
                                        ErasureCodeError, ErasureCodeProfile)


class ErasureCodeRegen(ErasureCode):
    DEFAULT_K = "4"
    DEFAULT_M = "3"
    DEFAULT_W = "8"

    #: the recovery coalescer's capability probe: minimum_to_decode may
    #: return plans covering FEWER than get_sub_chunk_count() sub-chunks,
    #: served by computed helper symbols (repair_coeffs + regenerate_batch)
    fractional_repair = True
    #: shard-major helpers may pad blocks up the shared rung ladder
    shape_bucketing = True

    def __init__(self, technique: str = "product_matrix"):
        super().__init__()
        self.technique = technique
        self.k = 0
        self.m = 0
        self.w = 8
        self.pm: ProductMatrixMSR | None = None
        #: systematic generator over virtual rows, (m*alpha, k*alpha)
        self.matrix: np.ndarray | None = None
        self._device_codec: DeviceCodec | None = None
        self._shared_pipe: EncodePipeline | None = None
        #: (lost, helper-sig) -> DeviceCodec(matrix=R_f, k=d, m=alpha)
        self._regen_codecs: Dict[tuple, DeviceCodec] = {}
        self._lock = threading.Lock()

    # -- profile -----------------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        profile["technique"] = self.technique
        self.parse(profile)
        try:
            self.pm = ProductMatrixMSR(self.k, self.m, self.w)
        except ValueError as e:
            raise ErasureCodeError(_errno.EINVAL, str(e))
        self.matrix = self.pm.generator
        profile["d"] = str(self.d)
        ErasureCode.init(self, profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        ErasureCode.parse(self, profile)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, self.DEFAULT_W)
        self.sanity_check_k(self.k)
        if self.w != 8:
            raise ErasureCodeError(
                _errno.EINVAL,
                f"w={self.w}: the product-matrix construction runs the "
                f"GF(2^8) byte lanes; only w=8 is supported",
            )
        if self.m < self.k - 1:
            raise ErasureCodeError(
                _errno.EINVAL,
                f"m={self.m} must be >= k-1={self.k - 1}: d=2k-2 repair "
                f"helpers must exist among the n-1 survivors",
            )
        if "d" in profile and str(profile["d"]) != "":
            d = self.to_int("d", profile, str(2 * self.k - 2))
            if d != 2 * self.k - 2:
                raise ErasureCodeError(
                    _errno.EINVAL,
                    f"d={d} is out of range: the product-matrix MSR "
                    f"construction requires d=2k-2={2 * self.k - 2}",
                )
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            self.chunk_mapping = []
            raise ErasureCodeError(
                _errno.EINVAL,
                f"mapping maps {len(self.chunk_mapping)} chunks != k+m",
            )

    # -- geometry ----------------------------------------------------------

    @property
    def alpha(self) -> int:
        return self.k - 1

    @property
    def d(self) -> int:
        return 2 * self.k - 2

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_sub_chunk_count(self) -> int:
        return self.alpha

    def get_chunk_size(self, object_size: int) -> int:
        """Chunks stay divisible into alpha SIMD-aligned sub-chunks, so
        beta extents keep the int32-lane pipeline kernels happy."""
        alignment = self.k * self.alpha * SIMD_ALIGN
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        return padded // self.k

    # -- virtual-row plumbing ---------------------------------------------

    @property
    def kv(self) -> int:
        return self.k * self.alpha

    @property
    def mv(self) -> int:
        return self.m * self.alpha

    def _virtual_rows(self, nodes: Iterable[int]) -> List[int]:
        a = self.alpha
        return [n * a + j for n in sorted(nodes) for j in range(a)]

    def _stack_virtual(
        self, chunks: Mapping[int, np.ndarray], nodes: Sequence[int]
    ) -> np.ndarray:
        """[len(nodes)*alpha, sub_len] virtual-row stack of whole chunks."""
        a = self.alpha
        return np.vstack([
            np.asarray(chunks[n], dtype=np.uint8).reshape(a, -1)
            for n in nodes
        ])

    def _dc(self) -> DeviceCodec:
        if self._device_codec is None:
            self._device_codec = DeviceCodec(
                matrix=self.matrix, k=self.kv, m=self.mv, w=self.w)
        return self._device_codec

    def _pipe(self) -> EncodePipeline:
        if self._shared_pipe is None:
            self._shared_pipe = EncodePipeline(self._dc().encode_stream())
        return self._shared_pipe

    def bucket_align(self) -> int:
        # whole sub-chunks of int32 lanes: padding must not shear the
        # virtual-row reshape
        return 4 * self.alpha

    def _pipeline_ok(self, chunk_len: int) -> bool:
        return chunk_len % (4 * self.alpha) == 0 and chunk_len > 0

    # -- sync contract -----------------------------------------------------

    def encode_chunks(
        self, want_to_encode: Iterable[int], encoded: Dict[int, np.ndarray]
    ) -> None:
        data = self._stack_virtual(encoded, range(self.k))
        if self._pipeline_ok(len(next(iter(encoded.values())))):
            parity = self._dc().encode(np.ascontiguousarray(data))
        else:
            parity = cpu_engine.matrix_encode(self.matrix, data, self.w)
        a = self.alpha
        for i in range(self.m):
            encoded[self.k + i][:] = np.ascontiguousarray(
                parity[i * a:(i + 1) * a]).reshape(-1)

    def decode_chunks(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        km = self.k + self.m
        have = sorted(c for c in range(km) if c in chunks)
        erased = [c for c in range(km) if c not in chunks]
        if not erased:
            return
        if len(have) < self.k:
            raise ErasureCodeError(_errno.EIO, "not enough chunks to decode")
        # whole-node virtual erasure: the first kv of the sorted
        # available virtual rows are exactly k whole survivor nodes, so
        # the composed reconstruction matrix is invertible (MDS)
        sel, rows = matrix_reconstruct_rows(
            self.matrix, self.kv, self.mv, self.w,
            self._virtual_rows(have), self._virtual_rows(erased))
        src_nodes = sorted({v // self.alpha for v in sel})
        vin = self._stack_virtual(decoded, src_nodes)
        rec = cpu_engine.matrix_encode(rows, vin, self.w)
        a = self.alpha
        for j, node in enumerate(erased):
            decoded[node][:] = np.ascontiguousarray(
                rec[j * a:(j + 1) * a]).reshape(-1)

    # -- minimum_to_decode: the beta/d repair plan -------------------------

    def minimum_to_decode(
        self, want_to_read: Iterable[int], available: Iterable[int]
    ) -> Dict[int, List[Tuple[int, int]]]:
        """Single-loss plans name d helpers at ONE sub-chunk (beta)
        each; everything else is the classic first-k full-chunk plan.
        Plan schema: {chunk: [(sub_chunk_offset, sub_chunk_count)]} --
        a count below get_sub_chunk_count() marks a fractional plan
        served by computed helper symbols, not raw extents."""
        want = sorted(set(want_to_read))
        avail = sorted(set(available))
        missing = [c for c in want if c not in avail]
        helpers_avail = [c for c in avail if c not in missing]
        if (len(missing) == 1 and self.alpha > 1
                and len(helpers_avail) >= self.d):
            return {h: [(0, 1)] for h in helpers_avail[: self.d]}
        return super().minimum_to_decode(want_to_read, available)

    # -- repair lane -------------------------------------------------------

    def repair_coeffs(self, lost: int) -> List[int]:
        """phi_f for the wire: every helper dots its own alpha
        sub-chunks with these (beta-symbol compute, not a raw read)."""
        assert self.pm is not None
        return self.pm.repair_coeffs(lost)

    def _regen_codec(self, lost: int, helpers: Tuple[int, ...]) -> DeviceCodec:
        key = (lost, helpers)
        with self._lock:
            codec = self._regen_codecs.get(key)
            if codec is None:
                assert self.pm is not None
                rf = self.pm.repair_matrix(lost, helpers)
                codec = DeviceCodec(
                    matrix=rf, k=self.d, m=self.alpha, w=self.w)
                if len(self._regen_codecs) >= 32:
                    self._regen_codecs.clear()  # bounded program cache
                self._regen_codecs[key] = codec
            return codec

    def regenerate_batch(
        self,
        lost: int,
        helpers: Sequence[int],
        helper_stacks: Sequence[np.ndarray],
    ) -> List[np.ndarray]:
        """Regenerate the lost chunk for MANY objects sharing one
        (lost, helper-set) signature: each stack is [d, beta] uint8
        (helper symbols in ``helpers`` order); returns the [chunk_len]
        regenerated shard per object -- ONE fused device dispatch for
        the whole batch (per rung bucket), the mesh plane's slot when
        the process plane is up.

        Fewer (or duplicate) helpers REFUSE via the repair-matrix
        validation: combining < d helper symbols has no consistent
        solution and must never fabricate shard bytes.
        """
        helpers = tuple(int(h) for h in helpers)
        assert self.pm is not None
        rf = self.pm.repair_matrix(lost, helpers)  # validates the set
        if not helper_stacks:
            return []
        beta = int(helper_stacks[0].shape[1])
        plane = _mesh_plane()
        if plane is not None and beta > 0:
            outs = _mesh_run_tab(
                plane, rf, self.d, self.alpha,
                [np.asarray(s, dtype=np.uint8) for s in helper_stacks])
            if outs is not None:
                return [np.ascontiguousarray(o).reshape(-1)
                        for o in outs]
        if beta % 4 == 0 and beta > 0 and _backend_is_tpu():
            codec = self._regen_codec(lost, helpers)
            pipe = EncodePipeline(codec.encode_stream())
            tickets = [pipe.submit(np.asarray(s, dtype=np.uint8))
                       for s in helper_stacks]
            pipe.flush()
            outs = [pipe.result(t) for t in tickets]
            pipe.drain()
            return [np.ascontiguousarray(o).reshape(-1) for o in outs]
        stacks = [np.asarray(s, dtype=np.uint8) for s in helper_stacks]
        if beta > 0 and all(s.shape[1] == beta for s in stacks):
            # cpu fallback: one fused LUT pass across the whole batch
            outs = cpu_engine.matrix_encode(
                rf, np.ascontiguousarray(np.hstack(stacks)), self.w)
            return [
                np.ascontiguousarray(
                    outs[:, i * beta:(i + 1) * beta]).reshape(-1)
                for i in range(len(stacks))
            ]
        return [
            np.ascontiguousarray(cpu_engine.matrix_encode(
                rf, s, self.w)).reshape(-1)
            for s in stacks
        ]

    # -- batched API (the coalescer/ecutil fast lanes) ---------------------

    def encode_batch(
        self, stripes: Sequence[bytes | np.ndarray]
    ) -> List[Dict[int, np.ndarray]]:
        if not stripes:
            return []
        prepared = [
            self.encode_prepare(np.frombuffer(s, dtype=np.uint8)
                                if isinstance(s, (bytes, bytearray))
                                else np.asarray(s, dtype=np.uint8))
            for s in stripes
        ]
        pipe_idx = [i for i, p in enumerate(prepared)
                    if self._pipeline_ok(len(p[0]))]
        results: List[Optional[Dict[int, np.ndarray]]] = \
            [None] * len(prepared)
        if pipe_idx:
            pipe = self._pipe()
            tickets = [
                pipe.submit(self._stack_virtual(
                    prepared[i], range(self.k)))
                for i in pipe_idx
            ]
            pipe.flush()
            a = self.alpha
            for i, t in zip(pipe_idx, tickets):
                parity = pipe.result(t)
                enc = dict(prepared[i])
                for j in range(self.m):
                    enc[self.k + j] = np.ascontiguousarray(
                        parity[j * a:(j + 1) * a]).reshape(-1)
                results[i] = enc
        for i, p in enumerate(prepared):
            if results[i] is None:
                enc = dict(p)
                self.encode_chunks(set(range(self.k + self.m)), enc)
                results[i] = enc
        return results  # type: ignore[return-value]

    def decode_batch(
        self, chunk_maps: Sequence[Dict[int, np.ndarray]],
    ) -> List[Dict[int, np.ndarray]]:
        """Signature-grouped fused decode: maps sharing an available
        set share one composed virtual-row stream (decode-stream LRU)
        and ride the same pipelined granules."""
        if not chunk_maps:
            return []
        km = self.k + self.m
        groups: Dict[tuple, List[int]] = {}
        for idx, cm in enumerate(chunk_maps):
            groups.setdefault(tuple(sorted(cm.keys())), []).append(idx)
        results: List[Dict[int, np.ndarray]] = \
            [None] * len(chunk_maps)  # type: ignore[list-item]
        for sig, idxs in groups.items():
            erased = [c for c in range(km) if c not in sig]
            if not erased:
                for i in idxs:
                    results[i] = {c: np.asarray(v, dtype=np.uint8)
                                  for c, v in chunk_maps[i].items()}
                continue
            if len(sig) < self.k:
                raise ErasureCodeError(
                    _errno.EIO, "not enough chunks to decode")
            chunk_len = len(next(iter(chunk_maps[idxs[0]].values())))
            if not self._pipeline_ok(chunk_len):
                for i in idxs:
                    results[i] = self._decode(
                        set(range(km)), dict(chunk_maps[i]))
                continue
            sel, stream = self._dc().decode_stream(
                self._virtual_rows(sig), self._virtual_rows(erased))
            src_nodes = sorted({v // self.alpha for v in sel})
            pipe = EncodePipeline(stream)
            tickets = [
                pipe.submit(self._stack_virtual(chunk_maps[i], src_nodes))
                for i in idxs
            ]
            pipe.flush()
            a = self.alpha
            for i, t in zip(idxs, tickets):
                rec = pipe.result(t)
                full = {c: np.asarray(v, dtype=np.uint8)
                        for c, v in chunk_maps[i].items()}
                for j, node in enumerate(erased):
                    full[node] = np.ascontiguousarray(
                        rec[j * a:(j + 1) * a]).reshape(-1)
                results[i] = full
            pipe.drain()
        return results


# -- survivor-side helper compute (the beta-symbol lane) ------------------

_HELPER_CODECS: Dict[Tuple[int, ...], DeviceCodec] = {}
_HELPER_LOCK = threading.Lock()


def _mesh_plane():
    try:
        from ceph_tpu.parallel import mesh_plane as mesh_mod

        return mesh_mod.current_plane()
    except Exception:  # noqa: BLE001 -- plane gated off / no backend
        return None


def _mesh_run_tab(plane, matrix: np.ndarray, k_in: int, rows_out: int,
                  blocks: List[np.ndarray],
                  slot_name: Optional[str] = None):
    """Dispatch ``matrix`` over [k_in, bs] blocks on the process mesh
    plane (the in-collective lane: survivors/primaries that are mesh
    members run their repair matmuls on their OWN mesh slot, and
    distinct daemons' async launches overlap across slots)."""

    class _Shim:
        """mesh_plane._codec keys programs by (matrix bytes, w)."""
        mesh_plane_capable = True

        def __init__(self):
            self.matrix = np.asarray(matrix, dtype=np.uint32)
            self.w = 8

        def get_data_chunk_count(self):
            return k_in

        def get_chunk_count(self):
            return k_in + rows_out

    try:
        slot = plane.slot_of(slot_name) if slot_name else None
        if slot is None:
            slot = 0
        bs = int(blocks[0].shape[1])
        bs_pad = plane._bucket_bs(bs)
        codec = plane._codec(_Shim())
        outs = codec.run_tab(
            codec._enc_tab, blocks, [0] * len(blocks), bs_pad, slot=slot)
        return [o[:, :bs] for o in outs]
    except Exception:  # noqa: BLE001 -- plane reshaped mid-call: fall back
        return None


def compute_helpers(
    coeffs: Sequence[int],
    shards: Sequence[np.ndarray],
    slot_name: Optional[str] = None,
) -> List[np.ndarray]:
    """Survivor-side helper symbols: dot each full shard's alpha
    sub-chunks with ``phi_f`` (the wire-carried ``regen`` coefficients)
    -- [shard_len] -> [shard_len/alpha] per object, every object of a
    sub-read message fused into one pipelined GF matmul dispatch (the
    mesh plane's slot for mesh-member daemons).

    The per-call shape is exactly the loop `jax-loop-invariant-transfer`
    exists for: the 1 x alpha coefficient matrix is uploaded ONCE per
    coefficient signature (content-keyed DeviceCodec cache), never per
    shard.
    """
    coeffs = tuple(int(c) for c in coeffs)
    alpha = len(coeffs)
    if alpha == 0 or not shards:
        return []
    blocks = []
    for s in shards:
        arr = np.asarray(s, dtype=np.uint8).reshape(-1)
        if arr.size % alpha:
            raise ValueError(
                f"shard of {arr.size} bytes is not divisible into "
                f"alpha={alpha} sub-chunks")
        blocks.append(arr.reshape(alpha, -1))
    beta = blocks[0].shape[1]
    matrix = np.array([coeffs], dtype=np.uint32)
    plane = _mesh_plane()
    if plane is not None and beta > 0:
        outs = _mesh_run_tab(plane, matrix, alpha, 1, blocks, slot_name)
        if outs is not None:
            return [np.ascontiguousarray(o[0]) for o in outs]
    if beta % 4 or beta == 0 or not _backend_is_tpu():
        # cpu fallback (or off-lane widths): ONE fused LUT pass over
        # the concatenated blocks -- per-object dispatches through the
        # cpu jax backend cost more than the GF math itself
        if all(b.shape[1] == beta for b in blocks):
            fused = np.ascontiguousarray(np.hstack(blocks))
            out = cpu_engine.matrix_encode(matrix, fused, 8)[0]
            return [
                np.ascontiguousarray(out[i * beta:(i + 1) * beta])
                for i in range(len(blocks))
            ]
        return [
            np.ascontiguousarray(cpu_engine.matrix_encode(
                matrix, b, 8)[0]) for b in blocks
        ]
    with _HELPER_LOCK:
        codec = _HELPER_CODECS.get(coeffs)
        if codec is None:
            if len(_HELPER_CODECS) >= 64:
                _HELPER_CODECS.clear()  # bounded program cache
            codec = _HELPER_CODECS[coeffs] = DeviceCodec(
                matrix=matrix, k=alpha, m=1, w=8)
    pipe = EncodePipeline(codec.encode_stream())
    tickets = [pipe.submit(b) for b in blocks]
    pipe.flush()
    outs = [np.ascontiguousarray(pipe.result(t)[0]) for t in tickets]
    pipe.drain()
    return outs


# -- plugin registration ---------------------------------------------------

class ErasureCodePluginRegen(registry_mod.ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        technique = profile.get("technique", "product_matrix")
        if technique != "product_matrix":
            raise ErasureCodeError(
                _errno.EINVAL,
                f"technique={technique} is not a valid regenerating "
                f"technique (product_matrix)",
            )
        ec = ErasureCodeRegen(technique)
        ec.init(profile)
        return ec


def __erasure_code_version__() -> str:
    from ceph_tpu import __version__

    return __version__


def __erasure_code_init__(name: str, directory: str) -> int:
    registry_mod.instance().add(name, ErasureCodePluginRegen())
    return 0
