"""jerasure-equivalent plugin: the canonical GF(2^w) technique family.

Mirrors the reference plugin's seven techniques and their parameter/alignment
semantics (reference: src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc},
ErasureCodePluginJerasure.cc:34-72 technique dispatch):

    reed_sol_van, reed_sol_r6_op          -- GF(2^w) matrix codes
    cauchy_orig, cauchy_good              -- bitmatrix + packetsize codes
    liberation, blaum_roth, liber8tion    -- RAID-6 bitmatrix codes

Compute runs on the numpy CPU engine by default; profile key
``backend=tpu`` routes encode/decode through the XLA GF(2) engine
(ceph_tpu/ops/xla_gf.py) -- same bytes either way.
"""

from __future__ import annotations

import errno as _errno
from typing import Dict, Iterable, Mapping

import numpy as np

from ceph_tpu.matrices import cauchy, liberation, reed_sol
from ceph_tpu.matrices.bitmatrix import matrix_to_bitmatrix
from ceph_tpu.ops import cpu_engine
from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.plugins.interface import (
    ErasureCode,
    ErasureCodeError,
    ErasureCodeProfile,
)

LARGEST_VECTOR_WORDSIZE = 16  # ErasureCodeJerasure.cc:30

_PRIMES = {
    2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53, 59, 61, 67,
    71, 73, 79, 83, 89, 97, 101, 103, 107, 109, 113, 127, 131, 137, 139, 149,
    151, 157, 163, 167, 173, 179, 181, 191, 193, 197, 199, 211, 223, 227,
    229, 233, 239, 241, 251, 257,
}


class ErasureCodeJerasure(ErasureCode):
    DEFAULT_K = "2"
    DEFAULT_M = "1"
    DEFAULT_W = "8"

    def __init__(self, technique: str):
        super().__init__()
        self.technique = technique
        self.k = 0
        self.m = 0
        self.w = 0
        self.per_chunk_alignment = False
        self._backend = "cpu"

    # -- contract ----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_size(self, object_size: int) -> int:
        """ErasureCodeJerasure.cc:73-96."""
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = object_size // self.k
            if object_size % self.k:
                chunk_size += 1
            if chunk_size < alignment:
                chunk_size = alignment
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    def init(self, profile: ErasureCodeProfile) -> None:
        profile["technique"] = self.technique
        self.parse(profile)
        self.prepare()
        ErasureCode.init(self, profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        ErasureCode.parse(self, profile)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, self.DEFAULT_W)
        self._backend = self.to_string("backend", profile, "cpu")
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            self.chunk_mapping = []
            raise ErasureCodeError(
                _errno.EINVAL,
                f"mapping maps {len(self.chunk_mapping)} chunks != k+m",
            )
        self.sanity_check_k(self.k)

    def encode_chunks(
        self, want_to_encode: Iterable[int], encoded: Dict[int, np.ndarray]
    ) -> None:
        data = np.stack([encoded[i] for i in range(self.k)])
        coding = self.jerasure_encode(data)
        for i in range(self.m):
            encoded[self.k + i][:] = coding[i]

    def decode_chunks(
        self,
        want_to_read: Iterable[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        have = {
            i: decoded[i] for i in range(self.k + self.m) if i in chunks
        }
        if len(have) < self.k:
            raise ErasureCodeError(_errno.EIO, "not enough chunks to decode")
        recovered = self.jerasure_decode(have, len(next(iter(have.values()))))
        for i in range(self.k + self.m):
            if i not in chunks:
                decoded[i][:] = recovered[i]

    # -- technique hooks ---------------------------------------------------

    def prepare(self) -> None:
        raise NotImplementedError

    def get_alignment(self) -> int:
        raise NotImplementedError

    def jerasure_encode(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def jerasure_decode(
        self, have: Dict[int, np.ndarray], blocksize: int
    ) -> Dict[int, np.ndarray]:
        raise NotImplementedError

    @staticmethod
    def is_prime(v: int) -> bool:
        return v in _PRIMES

    # -- backend dispatch --------------------------------------------------

    def _engine(self):
        if self._backend == "tpu":
            from ceph_tpu.ops import xla_gf

            return xla_gf
        if self._backend == "native":
            from ceph_tpu.ops import native_engine

            return native_engine
        return None  # numpy/CPU path


class _MatrixCode(ErasureCodeJerasure):
    """Shared implementation for the plain-matrix techniques."""

    def __init__(self, technique: str):
        super().__init__(technique)
        self.matrix: np.ndarray | None = None

    def get_alignment(self) -> int:
        if self.per_chunk_alignment:
            return self.w * LARGEST_VECTOR_WORDSIZE
        alignment = self.k * self.w * 4
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def jerasure_encode(self, data: np.ndarray) -> np.ndarray:
        eng = self._engine()
        if eng is not None:
            return eng.matrix_encode(self.matrix, data, self.w)
        return cpu_engine.matrix_encode(self.matrix, data, self.w)

    def jerasure_decode(self, have, blocksize):
        eng = self._engine()
        if eng is not None:
            return eng.matrix_decode(
                self.matrix, have, self.k, self.m, self.w, blocksize
            )
        return cpu_engine.matrix_decode(
            self.matrix, have, self.k, self.m, self.w, blocksize
        )


class ErasureCodeJerasureReedSolomonVandermonde(_MatrixCode):
    DEFAULT_K = "7"
    DEFAULT_M = "3"
    DEFAULT_W = "8"

    def __init__(self):
        super().__init__("reed_sol_van")

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        if self.w not in (8, 16, 32):
            profile["w"] = "8"
            self.w = 8
            raise ErasureCodeError(
                _errno.EINVAL, "w must be one of {8, 16, 32}"
            )
        self.per_chunk_alignment = self.to_bool(
            "jerasure-per-chunk-alignment", profile, "false"
        )

    def prepare(self) -> None:
        self.matrix = reed_sol.vandermonde_coding_matrix(self.k, self.m, self.w)


class ErasureCodeJerasureReedSolomonRAID6(_MatrixCode):
    DEFAULT_K = "7"
    DEFAULT_W = "8"

    def __init__(self):
        super().__init__("reed_sol_r6_op")

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        profile.pop("m", None)
        profile["m"] = "2"
        self.m = 2
        if self.w not in (8, 16, 32):
            profile["w"] = "8"
            self.w = 8
            raise ErasureCodeError(
                _errno.EINVAL, "w must be one of {8, 16, 32}"
            )

    def prepare(self) -> None:
        self.matrix = reed_sol.r6_coding_matrix(self.k, self.w)


class _BitmatrixCode(ErasureCodeJerasure):
    """Shared implementation for packetized bitmatrix techniques."""

    DEFAULT_PACKETSIZE = "2048"

    def __init__(self, technique: str):
        super().__init__(technique)
        self.packetsize = 0
        self.bitmatrix: np.ndarray | None = None

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.packetsize = self.to_int(
            "packetsize", profile, self.DEFAULT_PACKETSIZE
        )

    def jerasure_encode(self, data: np.ndarray) -> np.ndarray:
        eng = self._engine()
        if eng is not None:
            return eng.bitmatrix_encode(
                self.bitmatrix, data, self.w, self.packetsize
            )
        return cpu_engine.bitmatrix_encode(
            self.bitmatrix, data, self.w, self.packetsize
        )

    def jerasure_decode(self, have, blocksize):
        eng = self._engine()
        if eng is not None:
            return eng.bitmatrix_decode(
                self.bitmatrix, have, self.k, self.m, self.w, blocksize,
                self.packetsize,
            )
        return cpu_engine.bitmatrix_decode(
            self.bitmatrix, have, self.k, self.m, self.w, blocksize,
            self.packetsize,
        )


class ErasureCodeJerasureCauchy(_BitmatrixCode):
    DEFAULT_K = "7"
    DEFAULT_M = "3"
    DEFAULT_W = "8"

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.per_chunk_alignment = self.to_bool(
            "jerasure-per-chunk-alignment", profile, "false"
        )

    def get_alignment(self) -> int:
        """ErasureCodeJerasure.cc:272-286."""
        if self.per_chunk_alignment:
            alignment = self.w * self.packetsize
            modulo = alignment % LARGEST_VECTOR_WORDSIZE
            if modulo:
                alignment += LARGEST_VECTOR_WORDSIZE - modulo
            return alignment
        alignment = self.k * self.w * self.packetsize * 4
        if (self.w * self.packetsize * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * self.packetsize * LARGEST_VECTOR_WORDSIZE
        return alignment

    def prepare_schedule(self, matrix: np.ndarray) -> None:
        self.bitmatrix = matrix_to_bitmatrix(matrix, self.w)


class ErasureCodeJerasureCauchyOrig(ErasureCodeJerasureCauchy):
    def __init__(self):
        super().__init__("cauchy_orig")

    def prepare(self) -> None:
        self.prepare_schedule(
            cauchy.original_coding_matrix(self.k, self.m, self.w)
        )


class ErasureCodeJerasureCauchyGood(ErasureCodeJerasureCauchy):
    def __init__(self):
        super().__init__("cauchy_good")

    def prepare(self) -> None:
        self.prepare_schedule(
            cauchy.good_general_coding_matrix(self.k, self.m, self.w)
        )


class ErasureCodeJerasureLiberation(_BitmatrixCode):
    DEFAULT_K = "2"
    DEFAULT_M = "2"
    DEFAULT_W = "7"

    def __init__(self, technique: str = "liberation"):
        super().__init__(technique)

    def get_alignment(self) -> int:
        alignment = self.k * self.w * self.packetsize * 4
        if (self.w * self.packetsize * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * self.packetsize * LARGEST_VECTOR_WORDSIZE
        return alignment

    def check_k(self) -> bool:
        return self.k <= self.w

    def check_w(self) -> bool:
        return self.w > 2 and self.is_prime(self.w)

    def check_packetsize(self) -> bool:
        return self.packetsize > 0 and self.packetsize % 4 == 0

    def revert_to_default(self, profile: ErasureCodeProfile) -> None:
        profile["k"] = self.DEFAULT_K
        profile["w"] = self.DEFAULT_W
        profile["packetsize"] = self.DEFAULT_PACKETSIZE
        self.k = int(self.DEFAULT_K)
        self.w = int(self.DEFAULT_W)
        self.packetsize = int(self.DEFAULT_PACKETSIZE)

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.m = 2
        profile["m"] = "2"
        if not (self.check_k() and self.check_w() and self.check_packetsize()):
            self.revert_to_default(profile)
            raise ErasureCodeError(
                _errno.EINVAL,
                "invalid liberation parameters; reverted to defaults",
            )

    def prepare(self) -> None:
        self.bitmatrix = liberation.liberation_coding_bitmatrix(self.k, self.w)


class ErasureCodeJerasureBlaumRoth(ErasureCodeJerasureLiberation):
    def __init__(self):
        super().__init__("blaum_roth")

    def check_w(self) -> bool:
        # w=7 tolerated for backward compat (ErasureCodeJerasure.cc:453-466)
        if self.w == 7:
            return True
        return self.w > 2 and self.is_prime(self.w + 1)

    def prepare(self) -> None:
        self.bitmatrix = liberation.blaum_roth_coding_bitmatrix(self.k, self.w)


class ErasureCodeJerasureLiber8tion(ErasureCodeJerasureLiberation):
    DEFAULT_K = "2"
    DEFAULT_M = "2"
    DEFAULT_W = "8"

    def __init__(self):
        super().__init__("liber8tion")

    def parse(self, profile: ErasureCodeProfile) -> None:
        _BitmatrixCode.parse(self, profile)
        profile["m"] = "2"
        self.m = 2
        profile["w"] = "8"
        self.w = 8
        if not (self.check_k() and self.packetsize > 0):
            self.revert_to_default(profile)
            raise ErasureCodeError(
                _errno.EINVAL,
                "invalid liber8tion parameters; reverted to defaults",
            )

    def prepare(self) -> None:
        self.bitmatrix = liberation.liber8tion_coding_bitmatrix(self.k)


TECHNIQUES = {
    "reed_sol_van": ErasureCodeJerasureReedSolomonVandermonde,
    "reed_sol_r6_op": ErasureCodeJerasureReedSolomonRAID6,
    "cauchy_orig": ErasureCodeJerasureCauchyOrig,
    "cauchy_good": ErasureCodeJerasureCauchyGood,
    "liberation": ErasureCodeJerasureLiberation,
    "blaum_roth": ErasureCodeJerasureBlaumRoth,
    "liber8tion": ErasureCodeJerasureLiber8tion,
}


class ErasureCodePluginJerasure(registry_mod.ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        technique = profile.get("technique", "reed_sol_van")
        profile["technique"] = technique
        cls = TECHNIQUES.get(technique)
        if cls is None:
            raise ErasureCodeError(
                _errno.ENOENT, f"technique={technique} is not a valid technique"
            )
        ec = cls()
        ec.init(profile)
        return ec


def __erasure_code_version__() -> str:
    from ceph_tpu import __version__

    return __version__


def __erasure_code_init__(name: str, directory: str) -> int:
    registry_mod.instance().add(name, ErasureCodePluginJerasure())
    return 0
