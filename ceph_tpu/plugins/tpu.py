"""ErasureCodePluginTpu: the TPU-native codec plugin (the north star).

A drop-in peer to the jerasure/isa/shec plugins behind the same registry
(BASELINE.json north_star; reference plugin shape:
src/erasure-code/jerasure/ErasureCodePluginJerasure.cc): profile
``plugin=tpu technique=<any jerasure technique> k=.. m=..`` yields a codec
whose encode/decode run as bit-sliced GF(2) matmuls on the MXU, bit-exact
with the CPU oracle for every technique.

All device work routes through the persistent async pipeline
(ceph_tpu/ops/pipeline.py): the coding matrix is uploaded once per codec
instance, every sync encode()/decode() is one fused dispatch, and the
batched entry points (``encode_batch``/``decode_batch``/``encode_async``)
stream granules through the device with bounded in-flight depth --
overlapping host prep, H2D, MXU compute and D2H.  This is the seam the
reference's synchronous API cannot express (SURVEY.md section 7 step 5) and
the reason the plugin is benchmarked with ``tools/ec_benchmark.py --batch``
as well as the reference's per-call loop.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ceph_tpu.ops import bucketing, xla_gf
from ceph_tpu.ops.pipeline import DeviceCodec, EncodePipeline
from ceph_tpu.plugins import jerasure as jer
from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.plugins.interface import ErasureCodeProfile


class _TpuMixin:
    """Routes codec math through the persistent device pipeline.

    Profile keys ``mesh_shard`` / ``mesh_sub`` / ``mesh_data`` (all default
    1) additionally shard the device work over a jax.sharding.Mesh: the
    GF(2) contraction runs SPMD over the ``shard`` axis with psum over ICI
    (the fan-out/gather role of the reference's ECBackend,
    src/osd/ECBackend.cc:1976-2030) and chunk columns ride the ``sub`` axis
    (sub-chunk parallelism, ErasureCodeInterface.h:251-300).  A pool
    profile like ``plugin=tpu mesh_shard=4`` therefore exercises XLA
    collectives inside the storage write/read path.  Mesh mode requires a
    matrix technique with w=8 and k divisible by mesh_shard.
    """

    _device_codec: DeviceCodec | None = None
    _mesh_codec = None
    _mesh_spec = (1, 1, 1)
    #: the shard-major helpers may pad this codec's blocks up the shared
    #: rung ladder (ops/bucketing.py): its engine kernels compile per
    #: shape, so bucketing is what keeps steady state at zero retraces
    shape_bucketing = True

    def _engine(self):
        return xla_gf  # fallback path for shapes the pipeline can't take

    def init(self, profile: ErasureCodeProfile) -> None:
        self._mesh_spec = (
            int(profile.get("mesh_data", 1) or 1),
            int(profile.get("mesh_shard", 1) or 1),
            int(profile.get("mesh_sub", 1) or 1),
        )
        super().init(profile)
        if self._mesh_active():
            import errno

            from ceph_tpu.plugins.interface import ErasureCodeError

            if getattr(self, "matrix", None) is None:
                raise ErasureCodeError(
                    errno.EINVAL,
                    "mesh_shard/mesh_sub need a matrix technique "
                    "(reed_sol_van / reed_sol_r6_op / cauchy as matrix)",
                )
            if self.w != 8:
                raise ErasureCodeError(
                    errno.EINVAL, f"mesh mode supports w=8, not w={self.w}"
                )
            if self.k % self._mesh_spec[1]:
                raise ErasureCodeError(
                    errno.EINVAL,
                    f"k={self.k} must be divisible by "
                    f"mesh_shard={self._mesh_spec[1]}",
                )

    def _mesh_active(self) -> bool:
        return any(n > 1 for n in self._mesh_spec)

    @property
    def mesh_plane_capable(self) -> bool:
        """Can the OSD mesh data plane (``osd_mesh_data_plane``,
        ceph_tpu/parallel/mesh_plane.py) take this codec's coalesced
        encode/decode batches?  Matrix techniques at w=8 qualify (the
        plane's GF(2^8) row-table and psum_scatter lanes are bit-exact
        for them); a profile that ALREADY shards over its own mesh
        (``mesh_shard``/``mesh_sub``) keeps that path -- the plane must
        not re-shard a sharded codec."""
        return (getattr(self, "matrix", None) is not None
                and self.w == 8 and not self._mesh_active())

    def _mesh(self):
        if self._mesh_codec is None:
            from ceph_tpu.parallel.distributed import (
                DistributedCodec,
                make_mesh,
            )

            nd, ns, nb = self._mesh_spec
            # mesh_shard profile wiring: when the OSD mesh data plane is
            # up, the profile's mesh rides the SAME device set (one
            # process, one mesh ownership map) instead of grabbing raw
            # jax.devices() -- falling back to the raw set when the
            # plane spans fewer devices than the profile asks for
            devices = None
            from ceph_tpu.parallel import mesh_plane as mesh_mod

            plane = mesh_mod.current_plane()
            if plane is not None and len(plane.devices) >= nd * ns * nb:
                devices = plane.devices
            mesh = make_mesh(n_data=nd, n_shard=ns, n_sub=nb,
                             devices=devices)
            self._mesh_codec = DistributedCodec(self.matrix, self.w, mesh)
        return self._mesh_codec

    # -- mesh (SPMD) data path --------------------------------------------

    def _mesh_encode_many(self, stacks: List[np.ndarray]) -> List[np.ndarray]:
        """Encode a list of [k, bs] stripes in one sharded dispatch; pads
        the column axis to the sub-axis size and the batch axis to the
        data-axis size (GF parity is column-independent, so zero padding is
        exact and trimmed on the way out)."""
        nd, ns, nb = self._mesh_spec
        bs = stacks[0].shape[1]
        arr = np.stack(stacks)  # [B, k, bs]
        padn = (-bs) % nb
        if padn:
            arr = np.pad(arr, ((0, 0), (0, 0), (0, padn)))
        padb = (-arr.shape[0]) % nd
        if padb:
            arr = np.pad(arr, ((0, padb), (0, 0), (0, 0)))
        parity = np.asarray(self._mesh().encode(arr))
        return [parity[i, :, :bs] for i in range(len(stacks))]

    def _mesh_decode_many(
        self, sig: Sequence[int], erased: Sequence[int],
        survivor_stacks: List[np.ndarray],
    ) -> List[np.ndarray]:
        """Reconstruct erased chunks for stripes sharing one erasure
        signature: host-side row inversion (the ISA decode-table role),
        device-side sharded GF(2) contraction."""
        from ceph_tpu.ops.pipeline import matrix_reconstruct_rows

        nd, ns, nb = self._mesh_spec
        _, rows = matrix_reconstruct_rows(
            self.matrix, self.k, self.m, self.w, list(sig), list(erased)
        )
        bs = survivor_stacks[0].shape[1]
        arr = np.stack(survivor_stacks)  # [B, k, bs]
        padn = (-bs) % nb
        if padn:
            arr = np.pad(arr, ((0, 0), (0, 0), (0, padn)))
        padb = (-arr.shape[0]) % nd
        if padb:
            arr = np.pad(arr, ((0, padb), (0, 0), (0, 0)))
        rec = np.asarray(self._mesh().reconstruct(rows, arr))
        return [rec[i, :, :bs] for i in range(len(survivor_stacks))]

    def _dc(self) -> DeviceCodec:
        if self._device_codec is None:
            matrix = getattr(self, "matrix", None)
            bitmatrix = getattr(self, "bitmatrix", None)
            self._device_codec = DeviceCodec(
                matrix=matrix,
                bitmatrix=bitmatrix if matrix is None else None,
                k=self.k, m=self.m, w=self.w,
                packetsize=getattr(self, "packetsize", 0),
            )
        return self._device_codec

    _shared_pipe: EncodePipeline | None = None

    def _pipe(self) -> EncodePipeline:
        """The PERSISTENT encode pipeline of this codec instance: one
        jitted program per rung shared by every batched entry point
        (encode_batch / encode_async / the shard-major lane), so steady
        state never constructs pipeline state per call and the overlap
        slots span calls.  Tickets are claimed within each call, so
        reuse is state-free."""
        if self._shared_pipe is None:
            self._shared_pipe = EncodePipeline(self._dc().encode_stream())
        return self._shared_pipe

    def bucket_align(self) -> int:
        """Zero-padding granularity that keeps a padded blocksize both
        bit-exact (whole words / whole packet groups) and acceptable to
        the pipeline's lane kernels."""
        import math

        if getattr(self, "matrix", None) is not None:
            return math.lcm(4, self.w // 8)
        return math.lcm(self.w * max(1, getattr(self, "packetsize", 0)),
                        4 * self.w)

    def _pipeline_ok(self, blocksize: int) -> bool:
        """The packed-lane kernels want int32 lanes (matrix codes) or whole
        packet groups (bitmatrix codes); odd sizes fall back to the plain
        engine path, same bytes either way."""
        if getattr(self, "matrix", None) is not None:
            return blocksize % 4 == 0
        pw = self.w * getattr(self, "packetsize", 0)
        return pw > 0 and blocksize % pw == 0 and (blocksize // self.w) % 4 == 0

    # -- sync contract (one fused dispatch per call) -----------------------

    def jerasure_encode(self, data: np.ndarray) -> np.ndarray:
        if self._mesh_active():
            return self._mesh_encode_many([np.ascontiguousarray(data)])[0]
        bs = data.shape[1]
        if self._pipeline_ok(bs):
            return self._dc().encode(np.ascontiguousarray(data))
        # odd blocksize: zero-pad the column axis up the shared rung
        # ladder (whole words / packet groups, so parity of the padded
        # block is the original parity plus zero columns) and ride the
        # bucketed pipeline instead of retracing a raw-shape kernel
        target = bucketing.bucket_bytes(bs, self.bucket_align())
        if self._pipeline_ok(target):
            padded = np.zeros((data.shape[0], target), dtype=np.uint8)
            padded[:, :bs] = data
            return self._dc().encode(padded)[:, :bs]
        return super().jerasure_encode(data)

    def jerasure_decode(self, have, blocksize):
        if self._mesh_active():
            km = self.k + self.m
            available = sorted(have.keys())
            erased = [i for i in range(km) if i not in have]
            out = {c: np.asarray(a, dtype=np.uint8) for c, a in have.items()}
            if not erased:
                return out
            if len(available) < self.k:
                raise ValueError("not enough chunks to decode")
            sel = available[:self.k]
            rec = self._mesh_decode_many(
                available, erased, [np.stack([out[c] for c in sel])]
            )[0]
            for j, e in enumerate(erased):
                out[e] = rec[j]
            return out
        if self._pipeline_ok(blocksize):
            return self._dc().decode(have, blocksize)
        target = bucketing.bucket_bytes(blocksize, self.bucket_align())
        if self._pipeline_ok(target):
            # reconstruction is columnwise too: decode the zero-padded
            # survivors, trim every chunk back to the true blocksize
            padded_have = {}
            for c, arr in have.items():
                buf = np.zeros(target, dtype=np.uint8)
                buf[:blocksize] = np.asarray(arr, dtype=np.uint8)
                padded_have[c] = buf
            out = self._dc().decode(padded_have, target)
            return {c: arr[:blocksize] for c, arr in out.items()}
        return super().jerasure_decode(have, blocksize)

    # -- batched / async API (TPU extension) -------------------------------

    def encode_batch(self, stripes: Sequence[bytes | np.ndarray]) -> List[Dict[int, np.ndarray]]:
        """Encode many stripes, granule-fused and pipelined: stripes ride
        the matmul N axis; up to `depth` granules stream through the device
        concurrently."""
        if not stripes:
            return []
        prepared = [self.encode_prepare(_to_u8(s)) for s in stripes]
        k, m = self.k, self.m
        blocksize = len(prepared[0][0])
        if self._mesh_active():
            # sub-group by blocksize: one stacked sharded dispatch per size
            by_size: Dict[int, List[int]] = {}
            for idx, p in enumerate(prepared):
                by_size.setdefault(len(p[0]), []).append(idx)
            out: List[Dict[int, np.ndarray]] = [None] * len(prepared)  # type: ignore
            for idxs in by_size.values():
                codings = self._mesh_encode_many(
                    [np.stack([prepared[i][j] for j in range(k)])
                     for i in idxs]
                )
                for i, coding in zip(idxs, codings):
                    enc = dict(prepared[i])
                    for j in range(m):
                        enc[k + j] = coding[j]
                    out[i] = enc
            return out
        if not self._pipeline_ok(blocksize):
            out = []
            for p in prepared:
                data = np.stack([p[j] for j in range(k)])
                # self.jerasure_encode buckets odd blocksizes up the
                # rung ladder into the pipeline (zero steady retraces)
                coding = self.jerasure_encode(data)
                enc = dict(p)
                for i in range(m):
                    enc[k + i][:] = coding[i]
                out.append(enc)
            return out
        pipe = self._pipe()
        tickets = [
            pipe.submit(np.stack([p[j] for j in range(k)])) for p in prepared
        ]
        pipe.flush()
        out = []
        for p, t in zip(prepared, tickets):
            coding = pipe.result(t)
            enc = dict(p)
            for i in range(m):
                enc[k + i] = coding[i]
            out.append(enc)
        return out

    def encode_shard_major_batch(
        self,
        blocks: Sequence[np.ndarray],
        keep_device: Sequence[bool] | None = None,
    ):
        """Shard-major fast lane for the ecutil write-path helpers:
        ``[k, bs]`` uint8 blocks in, ``(chunk_maps, device_blocks)``
        out.  The blocks ARE the prepared chunk rows, so this skips the
        flatten -> encode_prepare -> restack round-trip of
        :meth:`encode_batch` (one full-granule copy plus k+m chunk
        allocations per stripe).  ``keep_device[i]`` asks for stripe
        i's still-resident ``[k+m, bs]`` device block
        (promote-from-encode); entries are None when the layout cannot
        compose one."""
        k, m = self.k, self.m
        keep = list(keep_device) if keep_device is not None \
            else [False] * len(blocks)
        out: List = [None] * len(blocks)
        devs: List = [None] * len(blocks)
        pipe_idx = [
            i for i, b in enumerate(blocks)
            if not self._mesh_active() and self._pipeline_ok(b.shape[1])
        ]
        if pipe_idx:
            pipe = self._pipe()
            tickets = [
                pipe.submit(np.asarray(blocks[i], dtype=np.uint8),
                            keep_device=keep[i])
                for i in pipe_idx
            ]
            pipe.flush()
            for i, t in zip(pipe_idx, tickets):
                coding = pipe.result(t)
                enc = {self.chunk_index(j): blocks[i][j] for j in range(k)}
                for j in range(m):
                    enc[self.chunk_index(k + j)] = coding[j]
                out[i] = enc
                if keep[i]:
                    devs[i] = pipe.device_result(t)
        rest = [i for i in range(len(blocks)) if out[i] is None]
        if rest:
            # mesh / odd shapes: the generic batched path (mesh shards
            # the dispatch; odd shapes bucket inside jerasure_encode)
            encs = self.encode_batch([blocks[i].reshape(-1) for i in rest])
            for i, enc in zip(rest, encs):
                out[i] = enc
        return out, devs

    def encode_async(self, data: bytes | np.ndarray):
        """Submit one stripe for encoding; returns a zero-arg callable that
        blocks until the parity lands and returns the full chunk map.  The
        async-completion face of the reference's sync encode()."""
        prepared = self.encode_prepare(_to_u8(data))
        k, m = self.k, self.m
        blocksize = len(prepared[0])
        if self._mesh_active():
            coding = self._mesh_encode_many(
                [np.stack([prepared[j] for j in range(k)])]
            )[0]
            enc = dict(prepared)
            for i in range(m):
                enc[k + i] = coding[i]
            return lambda: enc
        if not self._pipeline_ok(blocksize):
            result = self.encode(set(range(k + m)), data)
            return lambda: result
        pipe = self._pipe()
        ticket = pipe.submit(np.stack([prepared[j] for j in range(k)]))

        def wait() -> Dict[int, np.ndarray]:
            coding = pipe.result(ticket)
            enc = dict(prepared)
            for i in range(m):
                enc[k + i] = coding[i]
            return enc

        return wait

    def flush_async(self) -> None:
        pipe = getattr(self, "_shared_pipe", None)
        if pipe is not None:
            pipe.flush()

    def decode_batch(
        self,
        chunk_maps: Sequence[Dict[int, np.ndarray]],
    ) -> List[Dict[int, np.ndarray]]:
        """Reconstruct every stripe; stripes sharing an erasure signature
        share one reconstruction matrix (decode-stream LRU) and ride the
        same pipelined granule stream."""
        if not chunk_maps:
            return []
        km = self.k + self.m
        groups: Dict[tuple, List[int]] = {}
        for idx, cm in enumerate(chunk_maps):
            groups.setdefault(tuple(sorted(cm.keys())), []).append(idx)
        results: List[Dict[int, np.ndarray]] = [None] * len(chunk_maps)  # type: ignore
        for sig, idxs in groups.items():
            blocksize = len(next(iter(chunk_maps[idxs[0]].values())))
            erased = [i for i in range(km) if i not in sig]
            if not erased:
                for i in idxs:
                    results[i] = {
                        c: np.asarray(a, dtype=np.uint8)
                        for c, a in chunk_maps[i].items()
                    }
                continue
            if self._mesh_active():
                sel = sorted(sig)[:self.k]
                by_size: Dict[int, List[int]] = {}
                for i in idxs:
                    by_size.setdefault(
                        len(next(iter(chunk_maps[i].values()))), []
                    ).append(i)
                for sized_idxs in by_size.values():
                    recs = self._mesh_decode_many(
                        list(sig), erased,
                        [
                            np.stack([
                                np.asarray(chunk_maps[i][c], dtype=np.uint8)
                                for c in sel
                            ])
                            for i in sized_idxs
                        ],
                    )
                    for pos, i in enumerate(sized_idxs):
                        full = {
                            c: np.asarray(a, dtype=np.uint8)
                            for c, a in chunk_maps[i].items()
                        }
                        for j, e in enumerate(erased):
                            full[e] = recs[pos][j]
                        results[i] = full
                continue
            if not self._pipeline_ok(blocksize):
                for i in idxs:
                    results[i] = super().jerasure_decode(
                        dict(chunk_maps[i]), blocksize
                    )
                continue
            sel, stream = self._dc().decode_stream(list(sig), erased)
            pipe = EncodePipeline(stream)
            tickets = [
                pipe.submit(np.stack([chunk_maps[i][c] for c in sel]))
                for i in idxs
            ]
            pipe.flush()
            for pos, i in enumerate(idxs):
                rec = pipe.result(tickets[pos])
                full = {
                    c: np.asarray(a, dtype=np.uint8)
                    for c, a in chunk_maps[i].items()
                }
                for j, e in enumerate(erased):
                    full[e] = rec[j]
                results[i] = full
        return results


def _to_u8(buf) -> np.ndarray:
    if isinstance(buf, np.ndarray):
        return np.ascontiguousarray(buf, dtype=np.uint8).ravel()
    return np.frombuffer(bytes(buf), dtype=np.uint8)


def _make_tpu_class(base):
    name = "Tpu" + base.__name__
    return type(name, (_TpuMixin, base), {})


TECHNIQUES = {
    tech: _make_tpu_class(cls) for tech, cls in jer.TECHNIQUES.items()
}


class ErasureCodePluginTpu(registry_mod.ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        technique = profile.get("technique") or "reed_sol_van"
        profile["technique"] = technique
        cls = TECHNIQUES.get(technique)
        if cls is None:
            from ceph_tpu.plugins.interface import ErasureCodeError
            import errno

            raise ErasureCodeError(
                errno.ENOENT, f"technique={technique} is not a valid technique"
            )
        ec = cls()
        profile["backend"] = "tpu"
        ec.init(profile)
        return ec


def __erasure_code_version__() -> str:
    from ceph_tpu import __version__

    return __version__


def __erasure_code_init__(name: str, directory: str) -> int:
    registry_mod.instance().add(name, ErasureCodePluginTpu())
    return 0
