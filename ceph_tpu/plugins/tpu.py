"""ErasureCodePluginTpu: the TPU-native codec plugin (the north star).

A drop-in peer to the jerasure/isa/shec plugins behind the same registry
(BASELINE.json north_star; reference plugin shape:
src/erasure-code/jerasure/ErasureCodePluginJerasure.cc): profile
``plugin=tpu technique=<any jerasure technique> k=.. m=..`` yields a codec
whose encode/decode run as bit-sliced GF(2) matmuls on the MXU
(ceph_tpu/ops/xla_gf.py), bit-exact with the CPU oracle for every technique.

Beyond the synchronous per-stripe contract, the plugin exposes the batched
entry points the reference API cannot express (SURVEY.md section 5 "Hard
parts": sync-API <-> async-device impedance): ``encode_batch`` fuses a whole
stripe batch into one device dispatch -- stripes are the batch dimension,
concatenated along the matmul N axis, exactly how the MXU wants them.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ceph_tpu.ops import xla_gf
from ceph_tpu.plugins import jerasure as jer
from ceph_tpu.plugins import registry as registry_mod
from ceph_tpu.plugins.interface import ErasureCodeProfile


class _TpuMixin:
    """Forces the XLA engine and adds batched entry points."""

    def _engine(self):
        return xla_gf

    # -- batched API (TPU extension) --------------------------------------

    def encode_batch(self, stripes: Sequence[bytes | np.ndarray]) -> List[Dict[int, np.ndarray]]:
        """Encode many equal-length stripes in one device dispatch.

        Each stripe is padded/split exactly like encode(); all stripes must
        share a length so they share a chunk size.
        """
        if not stripes:
            return []
        prepared = [self.encode_prepare(_to_u8(s)) for s in stripes]
        k, m = self.k, self.m
        blocksize = len(prepared[0][0])
        nb = len(prepared)
        # stack: [k, nb * blocksize] -- stripes ride the matmul N axis
        data = np.stack(
            [np.concatenate([p[j] for p in prepared]) for j in range(k)]
        )
        coding = self.jerasure_encode(data)  # [m, nb*blocksize]
        out: List[Dict[int, np.ndarray]] = []
        for s in range(nb):
            enc = dict(prepared[s])
            for i in range(m):
                enc[k + i] = coding[i, s * blocksize : (s + 1) * blocksize]
            out.append(enc)
        return out

    def decode_batch(
        self,
        chunk_maps: Sequence[Dict[int, np.ndarray]],
    ) -> List[Dict[int, np.ndarray]]:
        """Reconstruct every stripe; stripes sharing an erasure signature are
        fused into one device dispatch (the ISA-L decode-table-LRU analogue:
        one host inversion covers the whole signature group)."""
        if not chunk_maps:
            return []
        groups: Dict[tuple, List[int]] = {}
        for idx, cm in enumerate(chunk_maps):
            groups.setdefault(tuple(sorted(cm.keys())), []).append(idx)
        results: List[Dict[int, np.ndarray]] = [None] * len(chunk_maps)  # type: ignore
        for sig, idxs in groups.items():
            blocksize = len(next(iter(chunk_maps[idxs[0]].values())))
            fused = {
                cid: np.concatenate([chunk_maps[i][cid] for i in idxs])
                for cid in sig
            }
            rec = self.jerasure_decode(fused, blocksize * len(idxs))
            for pos, i in enumerate(idxs):
                results[i] = {
                    cid: arr[pos * blocksize : (pos + 1) * blocksize]
                    for cid, arr in rec.items()
                }
        return results


def _to_u8(buf) -> np.ndarray:
    if isinstance(buf, np.ndarray):
        return np.ascontiguousarray(buf, dtype=np.uint8).ravel()
    return np.frombuffer(bytes(buf), dtype=np.uint8)


def _make_tpu_class(base):
    name = "Tpu" + base.__name__
    return type(name, (_TpuMixin, base), {})


TECHNIQUES = {
    tech: _make_tpu_class(cls) for tech, cls in jer.TECHNIQUES.items()
}


class ErasureCodePluginTpu(registry_mod.ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        technique = profile.get("technique") or "reed_sol_van"
        profile["technique"] = technique
        cls = TECHNIQUES.get(technique)
        if cls is None:
            from ceph_tpu.plugins.interface import ErasureCodeError
            import errno

            raise ErasureCodeError(
                errno.ENOENT, f"technique={technique} is not a valid technique"
            )
        ec = cls()
        profile["backend"] = "tpu"
        ec.init(profile)
        return ec


def __erasure_code_version__() -> str:
    from ceph_tpu import __version__

    return __version__


def __erasure_code_init__(name: str, directory: str) -> int:
    registry_mod.instance().add(name, ErasureCodePluginTpu())
    return 0
