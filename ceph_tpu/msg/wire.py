"""Typed message wire codecs.

Reference: src/messages/* -- each message is a typed, versioned struct
serialized through the encoding framework and carried in a crc-guarded
envelope (src/msg/Message.cc header/footer crcs).  Here every message
body is encoded with ``ceph_tpu.utils.encoding`` and the transport frames
it with ``frame()`` (magic + length + crc32c), so corruption and torn
writes are detected at the same layer they are in the reference.

Supported messages: the EC sub-op types (ECSubWrite/Read + replies) and
arbitrary control values (str/dict/tuple/... -- heartbeats, mon traffic).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ceph_tpu.mgr.report import MgrBeacon, MgrReport
from ceph_tpu.native import wire_codec
from ceph_tpu.osd.types import (
    ECSubRead,
    ECSubReadReply,
    ECSubWrite,
    ECSubWriteReply,
    LogEntry,
    Transaction,
    TxnOp,
)
from ceph_tpu.utils.encoding import Decoder, Encoder

# hand the native batched codec (ceph_tpu/native/wire_native.c) the
# message dataclasses it constructs: the C decode calls the SAME
# constructors this module does, and the C encode is property-tested
# byte-identical to the functions below (tests/test_wire_native.py).
# The functions in this module stay pure Python on purpose -- they are
# the fallback the transport runs bit-exactly when the extension is
# gated off (CEPH_TPU_NATIVE=0 / osd_wire_codec_native=false) or the
# host has no toolchain.
wire_codec.initialize(
    ec_sub_write=ECSubWrite, ec_sub_write_reply=ECSubWriteReply,
    ec_sub_read=ECSubRead, ec_sub_read_reply=ECSubReadReply,
    transaction=Transaction, txn_op=TxnOp, log_entry=LogEntry,
    mgr_beacon=MgrBeacon, mgr_report=MgrReport, np_integer=np.integer,
)

# message type codes (the reference's CEPH_MSG_* / MSG_OSD_EC_* ids)
_MSG_VALUE = 0
_MSG_EC_SUB_WRITE = 1
_MSG_EC_SUB_WRITE_REPLY = 2
_MSG_EC_SUB_READ = 3
_MSG_EC_SUB_READ_REPLY = 4
# mgr telemetry frames (MMgrBeacon / MMgrReport+MPGStats roles); peers
# that predate them drop unknown kinds at the transport (msg/tcp.py
# counts unknown_msg_dropped) instead of tearing the connection down
_MSG_MGR_BEACON = 5
_MSG_MGR_REPORT = 6


def encode_transaction(enc: Encoder, txn: Transaction) -> None:
    enc.varint(len(txn.ops))
    for op in txn.ops:
        enc.string(op.op).string(op.oid).varint(op.offset)
        enc.blob(op.data)
        enc.string(op.attr_name)
        enc.value(op.attr_value)


def decode_transaction(dec: Decoder) -> Transaction:
    txn = Transaction()
    for _ in range(dec.varint()):
        txn.ops.append(
            TxnOp(
                dec.string(), oid=dec.string(), offset=dec.varint(),
                data=dec.blob(), attr_name=dec.string(),
                attr_value=dec.value(),
            )
        )
    return txn


def _encode_log_entry(enc: Encoder, e: LogEntry) -> None:
    enc.varint(e.version).string(e.oid).string(e.op).varint(e.prior_size)


def _decode_log_entry(dec: Decoder) -> LogEntry:
    return LogEntry(
        version=dec.varint(), oid=dec.string(), op=dec.string(),
        prior_size=dec.varint(),
    )


def message_encoder(msg: object) -> Encoder:
    """Encode ``msg`` into an :class:`Encoder` WITHOUT joining it: the
    transport nests ``enc.parts()`` straight into its frame part list
    (``Encoder.blob_parts``), so large payload blobs -- EC shard bytes
    inside a sub-write transaction -- cross the messenger by reference
    instead of being copied at every layer."""
    enc = Encoder()
    if isinstance(msg, ECSubWrite):
        enc.u8(_MSG_EC_SUB_WRITE)
        enc.varint(msg.from_shard).varint(msg.tid).string(msg.oid)
        encode_transaction(enc, msg.transaction)
        enc.value(tuple(msg.at_version) if isinstance(
            msg.at_version, (tuple, list)) else msg.at_version)
        enc.varint(len(msg.log_entries))
        for e in msg.log_entries:
            _encode_log_entry(enc, e)
        enc.string(msg.op_class)
        enc.value(msg.rollback)
        enc.value(msg.prev_version)
        enc.value(tuple(msg.reqid) if isinstance(
            msg.reqid, (tuple, list)) else msg.reqid)
        enc.value(list(msg.trace) if isinstance(
            msg.trace, (tuple, list)) else msg.trace)
        enc.value(msg.qos_class)
    elif isinstance(msg, ECSubWriteReply):
        enc.u8(_MSG_EC_SUB_WRITE_REPLY)
        enc.varint(msg.from_shard).varint(msg.tid)
        enc.value(msg.committed).value(msg.applied)
        enc.value(tuple(msg.current_version) if isinstance(
            msg.current_version, (tuple, list)) else msg.current_version)
        enc.value(msg.missed)
    elif isinstance(msg, ECSubRead):
        enc.u8(_MSG_EC_SUB_READ)
        enc.varint(msg.from_shard).varint(msg.tid)
        enc.value({k: [tuple(x) for x in v] for k, v in msg.to_read.items()})
        enc.value(list(msg.attrs_to_read))
        enc.value({k: [tuple(x) for x in v] for k, v in msg.subchunks.items()})
        enc.string(msg.op_class)
        enc.value(list(msg.trace) if isinstance(
            msg.trace, (tuple, list)) else msg.trace)
        enc.value(msg.qos_class)
        enc.value({k: [int(c) for c in v] for k, v in msg.regen.items()}
                  if isinstance(msg.regen, dict) else msg.regen)
    elif isinstance(msg, ECSubReadReply):
        enc.u8(_MSG_EC_SUB_READ_REPLY)
        enc.varint(msg.from_shard).varint(msg.tid)
        enc.value(
            {k: [(off, bytes(b)) for off, b in v]
             for k, v in msg.buffers_read.items()}
        )
        enc.value(msg.attrs_read)
        enc.value(msg.errors)
    elif isinstance(msg, MgrBeacon):
        enc.u8(_MSG_MGR_BEACON)
        enc.string(msg.name).varint(msg.seq)
        enc.value(msg.lag_ms)
    elif isinstance(msg, MgrReport):
        enc.u8(_MSG_MGR_REPORT)
        enc.string(msg.name).varint(msg.seq)
        enc.value(msg.interval)
        enc.value(msg.stats)
        enc.value(msg.lag_ms)
    else:
        enc.u8(_MSG_VALUE)
        enc.value(msg)
    return enc


def encode_message(msg: object) -> bytes:
    return message_encoder(msg).bytes()


def decode_message(data: bytes) -> object:
    dec = Decoder(data)
    kind = dec.u8()
    if kind == _MSG_VALUE:
        return dec.value()
    if kind == _MSG_EC_SUB_WRITE:
        from_shard = dec.varint()
        tid = dec.varint()
        oid = dec.string()
        txn = decode_transaction(dec)
        at_version = dec.value()
        entries = [_decode_log_entry(dec) for _ in range(dec.varint())]
        return ECSubWrite(
            from_shard=from_shard, tid=tid, oid=oid, transaction=txn,
            at_version=at_version, log_entries=entries,
            op_class=dec.string(), rollback=dec.value(),
            prev_version=dec.value(),
            # cephlint: wire-optional -- pre-reqid senders end here
            reqid=dec.value() if dec.remaining() else None,
            # cephlint: wire-optional -- pre-trace senders end at the
            # reqid (and pre-trace DECODERS stop there, cleanly
            # ignoring this trailing context from newer senders)
            trace=dec.value() if dec.remaining() else None,
            # cephlint: wire-optional -- pre-qos senders end at the
            # trace context
            qos_class=dec.value() if dec.remaining() else None,
        )
    if kind == _MSG_EC_SUB_WRITE_REPLY:
        return ECSubWriteReply(
            from_shard=dec.varint(), tid=dec.varint(),
            committed=dec.value(), applied=dec.value(),
            current_version=dec.value(), missed=dec.value(),
        )
    if kind == _MSG_EC_SUB_READ:
        return ECSubRead(
            from_shard=dec.varint(), tid=dec.varint(),
            to_read={k: [tuple(x) for x in v]
                     for k, v in dec.value().items()},
            attrs_to_read=dec.value(),
            subchunks={k: [tuple(x) for x in v]
                       for k, v in dec.value().items()},
            op_class=dec.string(),
            # cephlint: wire-optional -- pre-trace senders end here
            trace=dec.value() if dec.remaining() else None,
            # cephlint: wire-optional -- pre-qos senders end at the
            # trace context
            qos_class=dec.value() if dec.remaining() else None,
            # cephlint: wire-optional -- pre-regen senders end at the
            # qos class
            regen=dec.value() if dec.remaining() else None,
        )
    if kind == _MSG_EC_SUB_READ_REPLY:
        return ECSubReadReply(
            from_shard=dec.varint(), tid=dec.varint(),
            buffers_read=dec.value(), attrs_read=dec.value(),
            errors=dec.value(),
        )
    if kind == _MSG_MGR_BEACON:
        return MgrBeacon(
            name=dec.string(), seq=dec.varint(),
            # cephlint: wire-optional -- pre-lag senders end at the seq
            lag_ms=dec.value() if dec.remaining() else None,
        )
    if kind == _MSG_MGR_REPORT:
        return MgrReport(
            name=dec.string(), seq=dec.varint(),
            interval=dec.value(), stats=dec.value(),
            # cephlint: wire-optional -- pre-lag senders end at the
            # stats payload
            lag_ms=dec.value() if dec.remaining() else None,
        )
    raise ValueError(f"unknown message type {kind}")
