"""Shared-memory frame rings: the byte transport between mesh-colocated
daemons.

Reference: the reference messenger's unix-domain / loopback fast paths
(msg/async/PosixStack.cc keeps the full protocol and swaps the byte
transport) and crimson's SPSC ring queues (crimson/common shared queues).
Round 15's DeliveryBoard proved the colocated-handoff idea for chunk
payloads; this module generalizes it to WHOLE FRAME BURSTS: everything
the TCP messenger ships -- client ops, sub-writes, acks, peering,
MgrReports -- can ride a seqlock'd shared-memory byte ring instead of the
localhost TCP hop, while the protocol layer above (banner, cephx auth,
session watermarks, cumulative acks, frame crcs, replay) runs UNCHANGED.

Design: the ring is a TRANSPORT SUBSTRATE, not a second protocol.
:class:`RingReader` / :class:`RingWriter` implement the exact asyncio
stream subset ``tcp.TCPMessenger`` uses (``read``/``readexactly``;
``write``/``writelines``/``drain``/``close``/``is_closing``/
``transport.abort``), so the messenger's connect path branches onto a
ring pair and every byte of the existing framing -- including
FaultInjector's mid-burst ``conn_kill_split`` tears and the
session-handshake replay that heals them -- flows through untouched.

Layout (models a real shm segment; header and data live in ONE
``bytearray`` so torn-producer injection is honest):

  [u64 head][u64 tail][u64 wseq] [data: capacity bytes, modular]

``head``/``tail`` are MONOTONIC byte offsets (consumer / producer);
``wseq`` is the seqlock generation -- odd while a producer is
mid-publish, bumped to even when the record is out.  Records are
``[u32 len][u32 crc32c(payload)][payload]`` laid out byte-modular in the
data region.  A reader that observes an odd ``wseq`` (producer
mid-write) backs off; a crc mismatch or impossible length means the
producer died mid-record -- a TORN RING -- and surfaces as
``RingTear`` (a ``ConnectionResetError``), which the messenger's
reconnect + session-replay machinery handles exactly like a TCP RST.

In-process scope: daemons here are asyncio tasks in one process, so the
"shared memory" is a shared ``bytearray`` and cross-daemon wakeups are
``asyncio.Event``s.  The byte layout, seqlock protocol and tear
semantics are the ones a real MAP_SHARED segment would use; only the
wakeup primitive would change (futex/eventfd).
"""

from __future__ import annotations

import argparse
import asyncio
import struct
from typing import Callable, Dict, List, Optional, Tuple

from ceph_tpu.native.gf_native import crc32c
from ceph_tpu.profiling import ledger as _profiler

#: ring cost centers (fetched once at import; native Stage twins when the
#: extension is loaded).  ``ring.push`` nests inside ``wire.writelines``
#: and ``ring.pop`` inside the frame-read loop -- exclusive accounting
#: splits the shm copy from the framing above it.
_PS_PUSH = _profiler.stage("ring.push")
_PS_POP = _profiler.stage("ring.pop")

_HDR = struct.Struct("<QQQ")  # head, tail, wseq
_REC = struct.Struct("<II")  # payload len, payload crc32c
_HDR_BYTES = _HDR.size
_REC_BYTES = _REC.size

#: default ring capacity when no config is consulted (tests); the
#: messenger passes ``osd_shm_ring_bytes``
DEFAULT_RING_BYTES = 4 << 20


class RingTear(ConnectionResetError):
    """The producer died mid-record (crc mismatch / impossible length /
    stuck-odd seqlock).  A ``ConnectionResetError`` subclass so the
    messenger's existing drop-reconnect-replay path fires unchanged."""


class ShmRing:
    """Seqlock'd SPSC byte ring over one contiguous buffer.

    Single producer, single consumer (one ring per direction per
    conduit).  ``try_push`` is synchronous and non-blocking (returns
    False when the record does not fit -- the writer adapter queues and
    retries on consumer progress); ``pop`` is synchronous and returns
    ``None`` on empty."""

    __slots__ = ("capacity", "_buf", "_view", "pushes", "pops",
                 "bytes_pushed", "tears", "hwm_used")

    def __init__(self, capacity: int = DEFAULT_RING_BYTES) -> None:
        if capacity < _REC_BYTES + 1:
            raise ValueError(f"ring capacity {capacity} too small")
        self.capacity = int(capacity)
        self._buf = bytearray(_HDR_BYTES + self.capacity)
        self._view = memoryview(self._buf)
        _HDR.pack_into(self._buf, 0, 0, 0, 0)
        self.pushes = 0
        self.pops = 0
        self.bytes_pushed = 0
        self.tears = 0
        self.hwm_used = 0

    # -- header accessors (the shm fields) --------------------------------

    def _load(self) -> Tuple[int, int, int]:
        return _HDR.unpack_from(self._buf, 0)

    def _store(self, head: int, tail: int, wseq: int) -> None:
        _HDR.pack_into(self._buf, 0, head, tail, wseq)

    def used(self) -> int:
        head, tail, _ = self._load()
        return tail - head

    def free(self) -> int:
        return self.capacity - self.used()

    # -- modular byte copies ----------------------------------------------

    def _copy_in(self, off: int, data) -> None:
        pos = off % self.capacity
        n = len(data)
        first = min(n, self.capacity - pos)
        base = _HDR_BYTES
        self._view[base + pos:base + pos + first] = data[:first]
        if first < n:
            self._view[base:base + (n - first)] = data[first:]

    def _copy_out(self, off: int, n: int) -> bytes:
        pos = off % self.capacity
        first = min(n, self.capacity - pos)
        base = _HDR_BYTES
        out = bytes(self._view[base + pos:base + pos + first])
        if first < n:
            out += bytes(self._view[base:base + (n - first)])
        return out

    # -- producer ----------------------------------------------------------

    def try_push(self, payload, *, torn: bool = False) -> bool:
        """Publish one record.  Returns False when it does not fit.

        ``torn=True`` models a producer crash mid-publish (FaultInjector
        ring-tear): the record header goes out and the tail advances,
        but only half the payload body lands and the seqlock is left
        where a dead producer would leave it -- the consumer's crc check
        turns this into :class:`RingTear`."""
        with _PS_PUSH:
            n = len(payload)
            need = _REC_BYTES + n
            if need > self.capacity:
                raise ValueError(
                    f"record {need}B exceeds ring capacity {self.capacity}B")
            head, tail, wseq = self._load()
            if need > self.capacity - (tail - head):
                return False
            # seqlock publish: odd while the body is in flight
            self._store(head, tail, wseq + 1)
            self._copy_in(tail, _REC.pack(n, crc32c(payload)))
            if torn:
                # producer "dies" here: half a body, tail published so
                # the consumer attempts the record, generation left even
                # (the crash happened after the bump in this interleaving)
                self._copy_in(tail + _REC_BYTES, payload[: n // 2])
                self._store(head, tail + need, wseq + 2)
                return True
            self._copy_in(tail + _REC_BYTES, payload)
            self._store(head, tail + need, wseq + 2)
            self.pushes += 1
            self.bytes_pushed += n
            used = (tail + need) - head
            if used > self.hwm_used:
                self.hwm_used = used
            return True

    # -- consumer ----------------------------------------------------------

    def pop(self) -> Optional[bytes]:
        """Consume one record.  ``None`` on empty; :class:`RingTear` on a
        torn record (crc mismatch / impossible length / stuck-odd
        seqlock -- the producer is gone and the ring is garbage)."""
        with _PS_POP:
            for _ in range(8):  # seqlock read retries (spurious in-process)
                head, tail, wseq = self._load()
                if tail == head:
                    return None
                if wseq & 1:
                    continue  # producer mid-publish; next iteration reloads
                avail = tail - head
                if avail < _REC_BYTES:
                    self.tears += 1
                    raise RingTear("torn ring: truncated record header")
                n, crc = _REC.unpack(self._copy_out(head, _REC_BYTES))
                if _REC_BYTES + n > avail or _REC_BYTES + n > self.capacity:
                    self.tears += 1
                    raise RingTear(
                        f"torn ring: record length {n} exceeds published "
                        f"bytes")
                payload = self._copy_out(head + _REC_BYTES, n)
                h2, _, w2 = self._load()
                if h2 != head or w2 != wseq:
                    continue  # raced a concurrent publish; re-read
                if crc32c(payload) != crc:
                    self.tears += 1
                    raise RingTear("torn ring: record crc mismatch")
                self._store(head + _REC_BYTES + n, tail, wseq)
                self.pops += 1
                return payload
            self.tears += 1
            raise RingTear("torn ring: seqlock stuck odd (producer died)")


class _RingTransport:
    """The ``writer.transport`` surface the messenger touches:
    ``abort()`` (conn_kill_split's hard kill)."""

    __slots__ = ("_conduit",)

    def __init__(self, conduit: "RingConduit") -> None:
        self._conduit = conduit

    def abort(self) -> None:
        self._conduit.kill()


class RingConduit:
    """One bidirectional colocated connection: two SPSC rings plus the
    wakeup events a shm segment would carry as futexes."""

    def __init__(self, capacity: int = DEFAULT_RING_BYTES) -> None:
        self.rings = (ShmRing(capacity), ShmRing(capacity))  # a->b, b->a
        self.data_evt = (asyncio.Event(), asyncio.Event())
        self.space_evt = (asyncio.Event(), asyncio.Event())
        self.closed = [False, False]  # writer side a / b closed cleanly
        self.killed = False

    def kill(self) -> None:
        """Hard abort (transport.abort / peer death): both directions
        fail immediately -- readers raise, writers raise."""
        self.killed = True
        for e in self.data_evt:
            e.set()
        for e in self.space_evt:
            e.set()

    def close_dir(self, d: int) -> None:
        self.closed[d] = True
        self.data_evt[d].set()

    def pair(self, *, fault=None) -> Tuple[Tuple["RingReader", "RingWriter"],
                                           Tuple["RingReader", "RingWriter"]]:
        """(reader, writer) endpoint tuples for side A and side B.
        ``fault`` (a FaultInjector) arms ring-tear injection on side A's
        writer -- the CONNECTING messenger's outbound direction."""
        a = (RingReader(self, 1), RingWriter(self, 0, fault=fault))
        b = (RingReader(self, 0), RingWriter(self, 1))
        return a, b


class RingReader:
    """The ``asyncio.StreamReader`` subset the messenger's frame loop
    uses.  Pops ring records and serves them as a byte stream."""

    def __init__(self, conduit: RingConduit, direction: int) -> None:
        self._c = conduit
        self._d = direction
        self._buf = bytearray()

    def _fill_from_ring(self) -> bool:
        """Drain every ready record into the local buffer (sync).
        Returns True if any bytes arrived."""
        ring = self._c.rings[self._d]
        got = False
        while True:
            try:
                rec = ring.pop()
            except RingTear:
                self._c.kill()
                raise
            if rec is None:
                return got
            self._buf += rec
            got = True
            self._c.space_evt[self._d].set()

    async def _wait_bytes(self) -> bool:
        """Block until bytes are buffered; False means clean EOF."""
        while not self._buf:
            if self._fill_from_ring():
                break
            if self._c.killed:
                raise ConnectionResetError("ring conduit aborted")
            if self._c.closed[self._d] and self._c.rings[self._d].used() == 0:
                return False
            self._c.data_evt[self._d].clear()
            # re-check after clear: a push between fill and clear would
            # otherwise be missed (the classic lost-wakeup window)
            if self._c.rings[self._d].used() or self._c.killed \
                    or self._c.closed[self._d]:
                continue
            await self._c.data_evt[self._d].wait()
        return True

    async def read(self, n: int) -> bytes:
        if n <= 0:
            return b""
        if not await self._wait_bytes():
            return b""
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    async def readexactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._fill_from_ring()
            if len(self._buf) >= n:
                break
            if self._c.killed:
                raise ConnectionResetError("ring conduit aborted")
            if self._c.closed[self._d] and self._c.rings[self._d].used() == 0:
                raise asyncio.IncompleteReadError(bytes(self._buf), n)
            self._c.data_evt[self._d].clear()
            # re-check after clear (lost-wakeup window)
            if self._c.rings[self._d].used() or self._c.killed \
                    or self._c.closed[self._d]:
                continue
            await self._c.data_evt[self._d].wait()
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


class RingWriter:
    """The ``asyncio.StreamWriter`` subset the messenger's flush paths
    use.  One ``writelines`` burst becomes ONE ring record (the shm
    analogue of one scatter-gather syscall); oversized bursts split at
    ring capacity."""

    def __init__(self, conduit: RingConduit, direction: int,
                 *, fault=None) -> None:
        self._c = conduit
        self._d = direction
        self._pending: List[bytes] = []  # records awaiting ring space
        self._fault = fault
        self._broken = False  # producer "crashed" after a torn record
        self.transport = _RingTransport(conduit)

    # -- internals ---------------------------------------------------------

    def _check_open(self) -> None:
        if self._c.killed or self._broken:
            raise ConnectionResetError("ring conduit aborted")
        if self._c.closed[self._d]:
            raise ConnectionResetError("ring writer closed")

    def _records_of(self, data: bytes) -> List[bytes]:
        ring = self._c.rings[self._d]
        limit = ring.capacity - _REC_BYTES
        if len(data) <= limit:
            return [data]
        return [data[i:i + limit] for i in range(0, len(data), limit)]

    def _push_now(self) -> None:
        """Sync best-effort flush of pending records into the ring."""
        ring = self._c.rings[self._d]
        while self._pending:
            rec = self._pending[0]
            torn = False
            if self._fault is not None and self._fault.ring_tear_fire():
                torn = True
            if not ring.try_push(rec, torn=torn):
                if torn:
                    # re-arm style: a tear that found no space still
                    # counts as the producer dying -- kill outright
                    self._broken = True
                    self._c.kill()
                    return
                return  # backpressure: wait for consumer progress
            self._pending.pop(0)
            self._c.data_evt[self._d].set()
            if torn:
                # the producer died mid-record: nothing further is ever
                # written on this conduit
                self._broken = True
                self._c.kill()
                return

    # -- StreamWriter subset ----------------------------------------------

    def write(self, data) -> None:
        self._check_open()
        self._pending.extend(self._records_of(bytes(data)))
        self._push_now()
        if self._broken:
            raise ConnectionResetError("ring torn mid-record")

    def writelines(self, bufs) -> None:
        self._check_open()
        self._pending.extend(self._records_of(b"".join(
            bytes(b) if not isinstance(b, bytes) else b for b in bufs)))
        self._push_now()
        if self._broken:
            raise ConnectionResetError("ring torn mid-record")

    async def drain(self) -> None:
        while self._pending:
            if self._c.killed or self._broken:
                raise ConnectionResetError("ring conduit aborted")
            self._push_now()
            if not self._pending:
                break
            self._c.space_evt[self._d].clear()
            if self._c.rings[self._d].free() > _REC_BYTES \
                    or self._c.killed or self._broken:
                continue
            await self._c.space_evt[self._d].wait()

    def close(self) -> None:
        if not self._c.closed[self._d]:
            self._push_now()
            self._c.close_dir(self._d)

    def is_closing(self) -> bool:
        return self._c.closed[self._d] or self._c.killed or self._broken

    async def wait_closed(self) -> None:
        return None


# -- colocated endpoint registry ------------------------------------------
#
# Keyed by the node's BOUND (host, port) -- unique per harness (ports come
# from free_ports) where node NAMES ("osd.0") repeat across sequentially
# created harnesses in one process.

class RingEndpoint:
    def __init__(self, addr: Tuple[str, int],
                 accept_cb: Callable[["RingReader", "RingWriter"], None],
                 ring_bytes: int) -> None:
        self.addr = addr
        self.accept_cb = accept_cb
        self.ring_bytes = ring_bytes
        self.conduits: List[RingConduit] = []

    def close(self) -> None:
        for c in self.conduits:
            c.kill()
        self.conduits.clear()


_ENDPOINTS: Dict[Tuple[str, int], RingEndpoint] = {}


def register(addr: Tuple[str, int],
             accept_cb: Callable[["RingReader", "RingWriter"], None],
             *, ring_bytes: int = DEFAULT_RING_BYTES) -> None:
    """Announce a messenger's accept endpoint as ring-reachable.
    ``accept_cb(reader, writer)`` is invoked (sync; it should spawn the
    serve task) when a colocated peer connects."""
    _ENDPOINTS[tuple(addr)] = RingEndpoint(tuple(addr), accept_cb,
                                           ring_bytes)


def unregister(addr: Tuple[str, int]) -> None:
    ep = _ENDPOINTS.pop(tuple(addr), None)
    if ep is not None:
        ep.close()


def lookup(addr: Tuple[str, int]) -> Optional[RingEndpoint]:
    return _ENDPOINTS.get(tuple(addr))


def connect(addr: Tuple[str, int], *, fault=None
            ) -> Optional[Tuple["RingReader", "RingWriter"]]:
    """Open a ring conduit to a registered colocated endpoint.  Returns
    the CLIENT side (reader, writer), or ``None`` when the address is
    not ring-reachable (caller falls back to TCP).  ``fault`` arms
    ring-tear injection on the client's outbound direction."""
    ep = _ENDPOINTS.get(tuple(addr))
    if ep is None:
        return None
    conduit = RingConduit(ep.ring_bytes)
    ep.conduits.append(conduit)
    client, server = conduit.pair(fault=fault)
    ep.accept_cb(server[0], server[1])
    return client


# -- smoke (tools/ci_lint.sh --ring-smoke) --------------------------------

async def _smoke() -> int:
    ring = ShmRing(1 << 16)
    msgs = [bytes([i & 0xFF]) * (997 * (i % 7 + 1)) for i in range(64)]
    out = []
    i = 0
    # interleaved push/pop forces wraparound several times over
    for m in msgs:
        while not ring.try_push(m):
            out.append(ring.pop())
        while len(out) < i - 2 and (r := ring.pop()) is not None:
            out.append(r)
        i += 1
    while (r := ring.pop()) is not None:
        out.append(r)
    assert out == msgs, "ring byte fidelity"
    assert ring.hwm_used <= ring.capacity

    # torn record -> RingTear
    ring2 = ShmRing(1 << 12)
    ring2.try_push(b"ok-record")
    ring2.try_push(b"x" * 512, torn=True)
    assert ring2.pop() == b"ok-record"
    try:
        ring2.pop()
    except RingTear:
        pass
    else:
        raise AssertionError("torn record not detected")

    # conduit echo through the stream adapters
    server_side = []
    register(("smoke", 1), lambda r, w: server_side.append((r, w)),
             ring_bytes=1 << 16)
    try:
        client = connect(("smoke", 1))
        assert client is not None
        cr, cw = client
        sr, sw = server_side[0]
        cw.write(b"ping" * 100)
        await cw.drain()
        got = await sr.readexactly(400)
        assert got == b"ping" * 100
        sw.writelines([b"po", b"ng"])
        await sw.drain()
        assert await cr.readexactly(4) == b"pong"
        cw.close()
        assert await sr.read(1) == b""  # clean EOF
        # abort surfaces as ConnectionResetError on the peer reader
        sw.transport.abort()
        try:
            await cr.read(1)
        except ConnectionResetError:
            pass
        else:
            raise AssertionError("abort not surfaced")
    finally:
        unregister(("smoke", 1))
    print("shm_ring smoke: OK "
          f"(pushes={ring.pushes} wraps_hwm={ring.hwm_used})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="shm ring smoke")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)
    if args.smoke:
        return asyncio.run(_smoke())
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
