"""TCP messenger: the in-process bus semantics over real sockets.

Reference: src/msg/async/AsyncMessenger.{h,cc} with the posix NetworkStack
(src/msg/async/Stack.h:287, PosixStack.h) -- a listening socket per
daemon, cached outgoing connections, a banner handshake naming the peer
node, framed messages.

Two policies, as in the reference (src/msg/Policy.h):

* **lossy** (client connections): a send to an unreachable peer is
  dropped and the peer marked unreachable; later sends retry the
  connect, so a restarted daemon becomes reachable again.
* **lossless peer** (OSD<->OSD, round 5): every message to a lossless
  peer carries a per-direction sequence number and stays on the sender's
  unacked queue until the receiver acks it; a connection drop triggers
  reconnect + REPLAY of everything past the peer's delivered watermark,
  and the receiver dedups by sequence -- the Pipe.cc connect/replay
  protocol (src/msg/simple/Pipe.cc:1040-1260 connect(), got_ack,
  in_seq/out_seq exchange).  Receive state is keyed by the sender's
  per-process INSTANCE id (the reference's connect nonce), so a
  restarted sender starts a fresh stream instead of colliding with its
  predecessor's watermark.  Like the reference, delivery across a
  RECEIVER restart degrades to at-least-once (unacked messages are
  retransmitted to the fresh process; the version-gated OSD apply paths
  make redelivery idempotent, the reqid-dedup role).

One ``TCPMessenger`` per process ("node").  A node hosts one or more
named entities (e.g. ``osd.3``); the address book maps every entity name
in the cluster to its node's (host, port).  Entity names co-hosted on
this node short-circuit delivery in process (the reference's local
fast-dispatch for self-sends, ECBackend.cc:2025-2032).

Frames on the socket are ``encoding.frame`` records (magic+len+crc32c);
payloads start with a kind byte: MSG (src|dst|seq|body), ACK (seq), or
SESSION (the reconnect watermark exchange).  The first frame on every
outgoing connection is a banner naming the sender node, protocol
version, and instance id (Pipe.cc banner exchange).
"""

from __future__ import annotations

import asyncio
import os
import struct
from collections import deque
from typing import Awaitable, Callable, Dict, Optional, Tuple

from ceph_tpu.msg.wire import decode_message, encode_message
from ceph_tpu.osd.messenger import FaultInjector
from ceph_tpu.utils.encoding import Decoder, Encoder, frame, unframe

_PROTOCOL_VERSION = 3
_BANNER = "ceph-tpu-msgr"
_SIG_LEN = 16

# frame kinds (payload byte 0)
_K_MSG = 0
_K_ACK = 1
_K_SESSION = 2


class _SendSession:
    """Per-lossless-peer send state (the Pipe out_seq/sent-queue role)."""

    __slots__ = ("out_seq", "acked", "sent", "sent_bytes", "reconnecting")

    def __init__(self):
        self.out_seq = 0
        self.acked = 0
        #: unacked (seq, payload-bytes) oldest first; payloads are kept
        #: UNSEALED -- signing is per-connection (fresh session key on
        #: every reconnect), so frames seal at (re)transmit time
        self.sent: deque = deque()
        self.sent_bytes = 0
        self.reconnecting = False

    def prune(self, acked_seq: int) -> None:
        self.acked = max(self.acked, acked_seq)
        while self.sent and self.sent[0][0] <= self.acked:
            _seq, payload = self.sent.popleft()
            self.sent_bytes -= len(payload)


async def _read_frame(reader: asyncio.StreamReader) -> Optional[bytes]:
    """Read one framed record off the stream; None on EOF/corruption."""
    try:
        header = await reader.readexactly(12)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    magic, length, crc = struct.unpack("<III", header)
    try:
        payload = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    rec, pos = unframe(header + payload, 0)
    return rec  # None if magic/crc check failed


class TCPMessenger:
    """API-compatible with ``osd.messenger.Messenger`` so OSDShard /
    ECBackend run unchanged over real sockets."""

    def __init__(
        self,
        node: str,
        addr_map: Dict[str, Tuple[str, int]],
        fault: Optional[FaultInjector] = None,
        keyring=None,
    ):
        #: this process's node name; must appear in addr_map for serving
        self.node = node
        self.addr_map = dict(addr_map)
        self.fault = fault if fault is not None else \
            FaultInjector.from_config()
        #: cephx-style auth: when a KeyRing is given, every connection
        #: must pass the mutual challenge-response handshake and every
        #: frame is signed with the derived session key (ms_sign_messages)
        self.keyring = keyring
        self._local_queues: Dict[str, asyncio.Queue] = {}
        self._dispatchers: Dict[str, Callable] = {}
        self._tasks: Dict[str, asyncio.Task] = {}
        #: cached outgoing connections per peer node: (reader, writer, lock)
        self._conns: Dict[str, Tuple] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        #: administratively dead entities (mark_down -- the thrasher hook)
        self._marked_down: set = set()
        #: peers whose last connect/send failed, with WHEN it failed:
        #: unreachability is a cached observation, not a verdict, and it
        #: expires -- a revived daemon whose boot races one failed
        #: connect must not be treated as down forever (its primary
        #: would otherwise refuse reads with "only N shards" while every
        #: peer is in fact alive)
        self._unreachable: dict = {}
        self._unreachable_ttl = 3.0
        self._reprobing: set = set()
        #: live incoming-connection handler tasks (cancelled on shutdown;
        #: Server.wait_closed would otherwise block on them forever)
        self._serve_tasks: set = set()
        #: inbound dispatch byte budget (DispatchThrottler /
        #: osd_client_message_size_cap, default 500 MiB): budget is held
        #: from socket read until the dispatcher finishes, so a flood of
        #: large messages back-pressures the senders' sockets instead of
        #: ballooning memory
        from ceph_tpu.utils.config import get_config
        from ceph_tpu.utils.throttle import Throttle

        try:
            cap = int(get_config().get_val("osd_client_message_size_cap"))
        except (KeyError, ValueError, TypeError):
            cap = 500 * 1024 * 1024
        self.dispatch_throttle = Throttle(f"{node}.msgr-dispatch", cap)
        #: per-process instance id (the Pipe connect nonce): receive
        #: state is keyed by it, so a restarted peer's fresh stream
        #: never collides with its predecessor's sequence watermark
        self.instance_id = os.urandom(8)
        #: lossless-peer send sessions: peer node -> _SendSession
        self._sessions: Dict[str, _SendSession] = {}
        self._connect_locks: Dict[str, asyncio.Lock] = {}
        #: last instance id seen from each peer node (inbound banners):
        #: an accept pops our cached outgoing conn only on a CHANGE
        #: (peer restart), never on ordinary bidirectional traffic
        self._peer_instances: Dict[str, bytes] = {}
        self._closing = False
        #: receive watermarks: (peer node, instance id) -> delivered seq
        self._in_seqs: Dict[tuple, int] = {}
        #: backlog cap per lossless peer (beyond it, NEW messages drop
        #: like lossy sends -- an honest bound; the reference relies on
        #: its throttles for the same purpose)
        self.lossless_max_backlog = 64 << 20

    def _lossless(self, node: str) -> bool:
        """Lossless-peer policy: OSD<->OSD connections (the reference's
        cluster-messenger policy, src/msg/Policy.h lossless_peer);
        everything else is a lossy client."""
        return self.node.startswith("osd.") and node.startswith("osd.")

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        host, port = self.addr_map[self.node]
        self._server = await asyncio.start_server(
            self._serve_connection, host, port
        )

    async def shutdown(self) -> None:
        self._closing = True  # stops lossless reconnect loops
        if self._server is not None:
            self._server.close()
        for conn in self._conns.values():
            conn[1].close()
        self._conns.clear()
        # cancel in ROUNDS (mirrors the in-process Messenger.shutdown):
        # under py<3.11 asyncio.wait_for can swallow a cancellation that
        # races its future's completion (bpo-42130); a tick loop that
        # lost its one cancel that way keeps running and a single
        # unbounded `await task` here then wedges the daemon inside its
        # SIGTERM handler -- the process never exits and the caller's
        # waitpid hangs.  Re-cancelling lands the next CancelledError at
        # the task's next await point; bounded rounds keep shutdown
        # finite no matter what.
        pending = [
            t for t in list(self._tasks.values()) + list(self._serve_tasks)
            if not t.done()
        ]
        for _ in range(50):
            if not pending:
                break
            for task in pending:
                task.cancel()
            _done, still = await asyncio.wait(pending, timeout=0.5)
            pending = list(still)
        if self._server is not None:
            await self._server.wait_closed()

    # -- entity registration (same surface as the in-process bus) ----------

    def register(
        self, name: str, dispatcher: Callable[[str, object], Awaitable[None]]
    ) -> None:
        self._local_queues[name] = asyncio.Queue()
        self._dispatchers[name] = dispatcher
        self._tasks[name] = asyncio.get_event_loop().create_task(
            self._dispatch_loop(name)
        )

    def adopt_task(self, name: str, task: "asyncio.Task") -> None:
        # completed tasks prune themselves (per-op tasks would otherwise
        # accumulate without bound on a long-lived daemon) and log any
        # unhandled exception on the way out
        from ceph_tpu.utils.aio import log_task_exception

        self._tasks[name] = task

        def _done(t, name=name):
            log_task_exception(t, name)
            if self._tasks.get(name) is t:
                self._tasks.pop(name, None)

        task.add_done_callback(_done)

    async def _dispatch_loop(self, name: str) -> None:
        queue = self._local_queues[name]
        while True:
            item = await queue.get()
            src, msg = item[0], item[1]
            cost = item[2] if len(item) > 2 else 0
            released = [False]

            def release(released=released, cost=cost):
                if not released[0]:
                    released[0] = True
                    self.dispatch_throttle.put(cost)

            claimed = [False]
            if cost and isinstance(msg, dict) and "op" in msg:
                # budget hand-off: a dispatcher that only ENQUEUES the
                # op (OSDShard's QoS queue) may claim the budget and
                # release it when the op actually executes -- that is
                # what makes the byte cap a real memory bound for
                # daemons instead of a transit-only throttle.  Blocking
                # here instead would deadlock: sub-op replies for
                # in-flight ops arrive through this same loop.
                msg["_budget_release"] = release
                msg["_budget_claim"] = (
                    lambda claimed=claimed: claimed.__setitem__(0, True))
            try:
                if name in self._marked_down:
                    continue
                try:
                    await self._dispatchers[name](src, msg)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 -- a dispatcher crash
                    # must not kill the loop (reference logs and drops)
                    import sys
                    import traceback

                    traceback.print_exc(file=sys.stderr)
            finally:
                if isinstance(msg, dict):
                    msg.pop("_budget_claim", None)
                if cost and not claimed[0]:
                    if isinstance(msg, dict):
                        msg.pop("_budget_release", None)
                    release()

    # -- server side -------------------------------------------------------

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        self._serve_tasks.add(task)
        try:
            await self._serve_connection_inner(reader, writer)
        finally:
            self._serve_tasks.discard(task)
            writer.close()

    async def _serve_connection_inner(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        banner = await _read_frame(reader)
        if banner is None:
            writer.close()
            return
        dec = Decoder(banner)
        if dec.string() != _BANNER or dec.varint() != _PROTOCOL_VERSION:
            writer.close()  # protocol mismatch: refuse (reference -EXDEV)
            return
        peer_node = dec.string()
        client_nonce = dec.blob()
        peer_instance = dec.blob()
        session_key = None
        if self.keyring is not None:
            session_key = await self._auth_accept(
                reader, writer, peer_node, client_nonce
            )
            if session_key is None:
                writer.close()  # failed handshake: refuse (-EACCES)
                return
        self._unreachable.pop(peer_node, None)
        # the peer RESTARTED (new instance id): any cached outgoing
        # connection targets its dead predecessor (writes into it are
        # silently buffered by TCP, losing replies) -- drop it so the
        # next send dials the live process (Pipe.cc replaces the old
        # session on accept).  Same instance = ordinary bidirectional
        # traffic: the healthy outgoing conn must survive the accept,
        # or two chatty OSDs would tear each other's sessions down
        # forever (review r5 finding).
        prev_instance = self._peer_instances.get(peer_node)
        self._peer_instances[peer_node] = peer_instance
        if prev_instance != peer_instance:
            # first contact or a restart: drop the (possibly stale)
            # cached conn once; repeat accepts from the SAME instance
            # leave it alone.  _drop_conn re-arms the reconnect loop if
            # unacked lossless traffic is pending (a popped conn's ack
            # reader cannot, its currency check fails by then), and
            # dead-instance receive watermarks are pruned with their
            # incarnation.
            self._drop_conn(peer_node)
            for key in [k for k in self._in_seqs
                        if k[0] == peer_node and k[1] != peer_instance]:
                del self._in_seqs[key]
        in_key = (peer_node, peer_instance)
        while True:
            rec = await _read_frame(reader)
            if rec is None:
                break
            try:
                rec = self._unseal(rec, session_key)
            except OSError:
                break  # short/forged/tampered frame: drop the connection
            dec = Decoder(rec)
            kind = dec.u8()
            if kind == _K_SESSION:
                # reconnect watermark exchange (Pipe.cc connect reply):
                # tell the peer what we have DELIVERED from this
                # instance, so it replays everything after
                reply = Encoder().u8(_K_SESSION).varint(
                    self._in_seqs.get(in_key, 0)).bytes()
                writer.write(frame(self._seal(reply, session_key)))
                await writer.drain()
                continue
            if kind != _K_MSG:
                continue  # ACK frames never arrive on an inbound socket
            src = dec.string()
            dst = dec.string()
            seq = dec.varint()
            body = dec.blob()
            if seq:
                # lossless stream (in order per TCP connection).  A dst
                # we do not host YET (the boot window between
                # messenger.start and entity registration) must NOT be
                # acked -- break the connection instead, so the sender
                # replays once the entity exists.  A marked-down dst is
                # an intentional kill: ack-and-drop.
                if dst not in self._local_queues and \
                        dst not in self._marked_down:
                    break
                ack = Encoder().u8(_K_ACK).varint(seq).bytes()
                writer.write(frame(self._seal(ack, session_key)))
                await writer.drain()
                if seq <= self._in_seqs.get(in_key, 0):
                    continue  # duplicate from a replay: already delivered
                self._in_seqs[in_key] = seq
            msg = decode_message(body)
            queue = self._local_queues.get(dst)
            if queue is not None and dst not in self._marked_down:
                if isinstance(msg, dict) and msg.get("op") == "client_op":
                    # throttle CLIENT ops only (the reference's
                    # DispatchThrottler guards the client messenger):
                    # sub-op replies must NEVER block here, or claimed
                    # client budget could wait on replies that are
                    # themselves stuck behind the throttle -- a
                    # distributed deadlock
                    cost = len(rec)
                    await self.dispatch_throttle.get(cost)
                    await queue.put((src, msg, cost))
                else:
                    await queue.put((src, msg))
        writer.close()

    async def _auth_accept(self, reader, writer, peer_node: str,
                           client_nonce: bytes):
        """Acceptor half of the cephx-style handshake; returns the
        session key, or None to refuse."""
        from ceph_tpu.auth.cephx import AuthHandshake

        secret = self.keyring.get(peer_node)
        if secret is None or not client_nonce:
            return None  # unknown entity / peer not speaking auth
        hs = AuthHandshake(secret, client_nonce, AuthHandshake.new_nonce())
        writer.write(frame(
            Encoder().blob(hs.server_nonce).blob(hs.server_proof()).bytes()
        ))
        await writer.drain()
        reply = await _read_frame(reader)
        if reply is None:
            return None
        if not hs.verify_client(Decoder(reply).blob()):
            return None
        return hs.session_key()

    # -- client side -------------------------------------------------------

    def _node_of(self, entity: str) -> Optional[str]:
        """The node hosting an entity: itself if it has an address, else
        its 'osd.N'-style name IS the node name in the default layout."""
        return entity if entity in self.addr_map else None

    async def _connect(self, node: str):
        from ceph_tpu.auth.cephx import AuthHandshake

        host, port = self.addr_map[node]
        reader, writer = await asyncio.open_connection(host, port)
        nonce = AuthHandshake.new_nonce() if self.keyring is not None else b""
        banner = (
            Encoder().string(_BANNER).varint(_PROTOCOL_VERSION)
            .string(self.node).blob(nonce).blob(self.instance_id).bytes()
        )
        writer.write(frame(banner))
        await writer.drain()
        session_key = None
        if self.keyring is not None:
            secret = self.keyring.get(self.node)
            if secret is None:
                writer.close()
                raise OSError(f"no key for {self.node} in keyring")
            try:
                # a no-auth peer never answers the handshake: time out
                # with a clear error instead of hanging every send
                reply = await asyncio.wait_for(_read_frame(reader), 3.0)
            except asyncio.TimeoutError:
                writer.close()
                raise OSError(
                    f"{node} did not answer the auth handshake "
                    "(auth-mode mismatch?)"
                )
            if reply is None:
                writer.close()
                raise OSError(f"auth refused by {node}")
            dec = Decoder(reply)
            server_nonce = dec.blob()
            hs = AuthHandshake(secret, nonce, server_nonce)
            if not hs.verify_server(dec.blob()):
                writer.close()
                raise OSError(f"{node} failed to prove keyring knowledge")
            writer.write(frame(Encoder().blob(hs.client_proof()).bytes()))
            await writer.drain()
            session_key = hs.session_key()
        return reader, writer, asyncio.Lock(), session_key

    def _drop_conn(self, node: str) -> None:
        """Pop + close the cached conn to ``node``; if unacked lossless
        traffic is queued, re-arm the reconnect loop (the popped conn's
        own ack reader can no longer do it -- its currency check fails
        once the conn left the cache)."""
        conn = self._conns.pop(node, None)
        if conn is not None:
            conn[1].close()
        sess = self._sessions.get(node)
        if sess is not None and sess.sent and not self._closing \
                and node not in self._marked_down:
            self._spawn_reconnect(node)

    def _conn_lock(self, node: str) -> asyncio.Lock:
        lock = self._connect_locks.get(node)
        if lock is None:
            lock = self._connect_locks[node] = asyncio.Lock()
        return lock

    async def _try_establish(self, node: str):
        """Connect to ``node`` and cache the connection; for a lossless
        peer, run the session watermark exchange + replay first (the
        Pipe.cc connect() path).  Returns the conn or None (peer down,
        unreachable mark refreshed).  Serialized per node so concurrent
        senders share one connection."""
        async with self._conn_lock(node):
            conn = self._conns.get(node)
            if conn is not None:
                return conn
            try:
                conn = await self._connect(node)
            except OSError:
                self._unreachable[node] = asyncio.get_event_loop().time()
                return None
            if self._lossless(node):
                try:
                    await self._session_handshake(node, conn)
                except (OSError, ConnectionError, asyncio.TimeoutError):
                    conn[1].close()
                    self._unreachable[node] = \
                        asyncio.get_event_loop().time()
                    return None
                except BaseException:
                    # cancellation (e.g. a probe's outer wait_for): the
                    # already-open socket must not leak
                    conn[1].close()
                    raise
                self._spawn_ack_reader(node, conn)
            self._conns[node] = conn
            self._unreachable.pop(node, None)
            return conn

    async def _session_handshake(self, node: str, conn) -> None:
        """Exchange delivered-watermarks with the peer and retransmit
        everything it has not delivered (Pipe.cc connect/replay)."""
        reader, writer, lock, skey = conn
        sess = self._sessions.setdefault(node, _SendSession())
        writer.write(frame(self._seal(
            Encoder().u8(_K_SESSION).bytes(), skey)))
        await writer.drain()
        rec = await asyncio.wait_for(_read_frame(reader), 3.0)
        if rec is None:
            raise OSError(f"{node}: session handshake EOF")
        dec = Decoder(self._unseal(rec, skey))
        if dec.u8() != _K_SESSION:
            raise OSError(f"{node}: bad session reply")
        sess.prune(dec.varint())  # peer already delivered these
        async with lock:
            # re-snapshot until stable: a send that lands while the
            # drain below is awaiting appends to sess.sent and is
            # caught by the next iteration (review r5 finding)
            sent_upto = 0
            while True:
                pending = [(s, p) for s, p in sess.sent if s > sent_upto]
                if not pending:
                    break
                for s, payload in pending:
                    writer.write(frame(self._seal(payload, skey)))
                    sent_upto = s
                await writer.drain()

    def _spawn_ack_reader(self, node: str, conn) -> None:
        """Consume ACK frames off a lossless outgoing connection,
        pruning the unacked queue; on EOF drop the cached conn and, if
        traffic is pending, start the reconnect loop."""

        async def ack_loop():
            reader, skey = conn[0], conn[3]
            while True:
                rec = await _read_frame(reader)
                if rec is None:
                    break
                try:
                    dec = Decoder(self._unseal(rec, skey))
                except OSError:
                    break
                if dec.u8() == _K_ACK:
                    sess = self._sessions.get(node)
                    if sess is not None:
                        sess.prune(dec.varint())
            if self._conns.get(node) is conn:
                self._drop_conn(node)
            else:
                conn[1].close()  # superseded conn: just release it

        self.adopt_task(
            f"ack.{node}.{id(conn)}",
            asyncio.get_event_loop().create_task(ack_loop()),
        )

    def _spawn_reconnect(self, node: str) -> None:
        """Lossless-peer reconnect loop: keep dialing (bounded backoff)
        until the peer answers and the queued messages replay, or the
        peer is administratively down / the queue drains."""
        sess = self._sessions.setdefault(node, _SendSession())
        if sess.reconnecting:
            return
        sess.reconnecting = True

        async def reconnect_loop():
            try:
                delay = 0.2
                while True:
                    if (self._closing or node in self._marked_down
                            or not sess.sent):
                        return
                    if self._conns.get(node) is not None:
                        return  # re-established elsewhere (replay done)
                    if await self._try_establish(node) is not None:
                        return
                    await asyncio.sleep(delay)
                    delay = min(delay * 1.7, 2.0)
            finally:
                sess.reconnecting = False

        self.adopt_task(
            f"reconnect.{node}",
            asyncio.get_event_loop().create_task(reconnect_loop()),
        )

    async def send_message(self, src: str, dst: str, msg: object) -> None:
        if src in self._marked_down or dst in self._marked_down:
            return
        # local short-circuit
        queue = self._local_queues.get(dst)
        if queue is not None:
            if self.fault.maybe_drop():
                return
            await self.fault.maybe_delay()
            await queue.put((src, msg))
            return
        node = self._node_of(dst)
        if node is None:
            return  # unknown peer: lossy
        if self.fault.maybe_drop():
            return
        await self.fault.maybe_delay()
        body = encode_message(msg)
        if self._lossless(node):
            await self._send_lossless(src, dst, node, body)
            return
        payload = (
            Encoder().u8(_K_MSG).string(src).string(dst).varint(0)
            .blob(body).bytes()
        )
        await self._send_lossy(node, payload)

    async def _send_lossy(self, node: str, payload: bytes) -> None:
        conn = self._conns.get(node)
        if conn is None:
            conn = await self._try_establish(node)
            if conn is None:
                return
        _, writer, lock, skey = conn
        rec = frame(self._seal(payload, skey))
        async with lock:
            try:
                writer.write(rec)
                await writer.drain()
                self._unreachable.pop(node, None)
            except (ConnectionError, OSError):
                self._conns.pop(node, None)
                writer.close()
                # one reconnect attempt (peer may have restarted)
                conn = await self._try_establish(node)
                if conn is None:
                    return
                try:
                    rec = frame(self._seal(payload, conn[3]))
                    conn[1].write(rec)
                    await conn[1].drain()
                except (ConnectionError, OSError):
                    self._conns.pop(node, None)
                    conn[1].close()
                    self._unreachable[node] = \
                        asyncio.get_event_loop().time()

    async def _send_lossless(self, src: str, dst: str, node: str,
                             body: bytes) -> None:
        """Queue-then-send with replay-on-reconnect (lossless peer)."""
        sess = self._sessions.setdefault(node, _SendSession())
        if sess.sent_bytes >= self.lossless_max_backlog:
            return  # honest bound: beyond the backlog, drop like lossy
        sess.out_seq += 1
        payload = (
            Encoder().u8(_K_MSG).string(src).string(dst)
            .varint(sess.out_seq).blob(body).bytes()
        )
        sess.sent.append((sess.out_seq, payload))
        sess.sent_bytes += len(payload)
        conn = self._conns.get(node)
        if conn is None:
            conn = await self._try_establish(node)
            if conn is None:
                # queued; keep dialing in the background
                self._spawn_reconnect(node)
                return
            # fall through and send: the establishing handshake may
            # already have replayed this payload (it was queued first),
            # in which case the receiver's watermark swallows the
            # duplicate -- double-send is safe, silent loss is not
        _, writer, lock, skey = conn
        async with lock:
            try:
                writer.write(frame(self._seal(payload, skey)))
                await writer.drain()
                self._unreachable.pop(node, None)
            except (ConnectionError, OSError):
                self._conns.pop(node, None)
                writer.close()
                self._unreachable[node] = asyncio.get_event_loop().time()
                self._spawn_reconnect(node)

    @staticmethod
    def _seal(payload: bytes, session_key) -> bytes:
        if session_key is None:
            return payload
        from ceph_tpu.auth.cephx import sign

        return payload + sign(session_key, payload)

    @staticmethod
    def _unseal(rec: bytes, session_key) -> bytes:
        if session_key is None:
            return rec
        from ceph_tpu.auth.cephx import verify as _verify

        if len(rec) < _SIG_LEN:
            raise OSError("short signed frame")
        body, sig = rec[:-_SIG_LEN], rec[-_SIG_LEN:]
        if not _verify(session_key, body, sig):
            raise OSError("bad frame signature")
        return body

    async def probe(self, entity: str, timeout: float = 1.0) -> bool:
        """Liveness probe: can we (re)connect to the entity's node?
        Updates the unreachable set -- the heartbeat role."""
        node = self._node_of(entity)
        if node is None or entity in self._marked_down:
            return False
        # drop any cached connection: it may be a dead socket whose peer
        # was SIGKILLed -- a probe must test the wire, not the cache
        self._drop_conn(node)
        try:
            conn = await asyncio.wait_for(
                self._try_establish(node), timeout)
        except asyncio.TimeoutError:
            self._unreachable[node] = asyncio.get_event_loop().time()
            return False
        return conn is not None

    # -- liveness view (thrasher + _shard_up hooks) ------------------------

    def mark_down(self, name: str) -> None:
        self._marked_down.add(name)

    def mark_up(self, name: str) -> None:
        self._marked_down.discard(name)
        self._unreachable.pop(self._node_of(name) or name, None)

    def is_down(self, name: str) -> bool:
        if name in self._marked_down:
            return True
        node = self._node_of(name)
        if node is None:
            return False
        t = self._unreachable.get(node)
        if t is None:
            return False
        if asyncio.get_event_loop().time() - t > self._unreachable_ttl:
            # stale observation: still report down (a genuinely dead
            # peer must not flap back up on a timer) but re-probe in the
            # background -- a live peer clears itself, a dead one
            # refreshes the timestamp
            self._schedule_reprobe(node)
        return True

    def _schedule_reprobe(self, node: str) -> None:
        if node in self._reprobing:
            return
        self._reprobing.add(node)

        async def reprobe():
            try:
                await self.probe(node)
            finally:
                self._reprobing.discard(node)

        task = asyncio.get_event_loop().create_task(reprobe())
        self.adopt_task(f"reprobe.{node}", task)
