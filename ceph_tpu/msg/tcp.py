"""TCP messenger: the in-process bus semantics over real sockets.

Reference: src/msg/async/AsyncMessenger.{h,cc} with the posix NetworkStack
(src/msg/async/Stack.h:287, PosixStack.h) -- a listening socket per
daemon, cached outgoing connections, a banner handshake naming the peer
node, framed messages.

Two policies, as in the reference (src/msg/Policy.h):

* **lossy** (client connections): a send to an unreachable peer is
  dropped and the peer marked unreachable; later sends retry the
  connect, so a restarted daemon becomes reachable again.
* **lossless peer** (OSD<->OSD, round 5): every message to a lossless
  peer carries a per-direction sequence number and stays on the sender's
  unacked queue until the receiver acks it; a connection drop triggers
  reconnect + REPLAY of everything past the peer's delivered watermark,
  and the receiver dedups by sequence -- the Pipe.cc connect/replay
  protocol (src/msg/simple/Pipe.cc:1040-1260 connect(), got_ack,
  in_seq/out_seq exchange).  Receive state is keyed by the sender's
  per-process INSTANCE id (the reference's connect nonce), so a
  restarted sender starts a fresh stream instead of colliding with its
  predecessor's watermark.  Like the reference, delivery across a
  RECEIVER restart degrades to at-least-once (unacked messages are
  retransmitted to the fresh process; the version-gated OSD apply paths
  make redelivery idempotent, the reqid-dedup role).

One ``TCPMessenger`` per process ("node").  A node hosts one or more
named entities (e.g. ``osd.3``); the address book maps every entity name
in the cluster to its node's (host, port).  Entity names co-hosted on
this node short-circuit delivery in process (the reference's local
fast-dispatch for self-sends, ECBackend.cc:2025-2032).

Frames on the socket are ``encoding.frame`` records (magic+len+crc32c);
payloads start with a kind byte: MSG (src|dst|seq|body[|ack]), ACK
(cumulative seq), or SESSION (the reconnect watermark exchange).  The
first frame on every outgoing connection is a banner naming the sender
node, protocol version, and instance id (Pipe.cc banner exchange).

Corked zero-copy send path (round 8, protocol v4; full protocol notes
in docs/messenger.md): outgoing frames queue per peer and flush at
end-of-tick (queue-drain, the ``osd/coalescer.py`` discipline) or past
a byte threshold, as ONE ``writer.writelines`` scatter-gather burst --
synchronously, straight into the transport buffer: no per-message task,
no per-message ``drain()``.  ``drain()`` becomes what it actually is,
flow control, awaited only once ``osd_msgr_cork_bytes`` have been
written since the last drain.  Message payloads are part lists
(``Encoder.parts``); large bodies are referenced, never joined, and
each payload's crc32c is computed once and only EXTENDED over the
per-transmission tail (piggyback ack + signature) on (re)transmit --
crc32c chains, see ``encoding.crc32c_parts``.  Delivery acks piggyback
as a trailing cumulative varint on outgoing MSG frames (v3 receivers
ignore trailing bytes); with no reverse traffic a receiver writes one
cumulative ACK frame per burst window instead of one frame + drain per
message.  The receive side parses every frame already buffered in one
wakeup (``_FrameReader``) instead of two ``readexactly`` awaits per
frame.  A flush failure falls back to the lossless reconnect/replay
machinery unchanged -- coalescing never weakens the delivery guarantee,
it only changes the syscall shape.
"""

from __future__ import annotations

import asyncio
import os
import struct
import time as _time
from collections import deque
from typing import Awaitable, Callable, Dict, Iterable, List, Optional, \
    Tuple

from ceph_tpu.msg import shm_ring as _shm
from ceph_tpu.msg.fault import FaultInjector
from ceph_tpu.msg.wire import decode_message, message_encoder
from ceph_tpu.native import wire_codec
from ceph_tpu.native.gf_native import crc32c
from ceph_tpu.profiling import ledger as _profiler
from ceph_tpu.utils.encoding import Decoder, Encoder, crc32c_parts, \
    frame, frame_parts, unframe

#: wire-tax profiler cost centers (ceph_tpu/profiling/): markers are
#: fetched ONCE here so the per-frame cost is the `with` protocol on a
#: preallocated object -- one global-bool branch when profiling is off.
#: Stage blocks are yield-free by construction (a stage spanning an
#: await would bill other tasks' work to itself).
_PS_ENCODE = _profiler.stage("wire.encode")        # envelope + part list
_PS_SEAL = _profiler.stage("wire.crc_seal")        # crc fold + sign + frame
_PS_CORK = _profiler.stage("wire.cork_append")     # cork-queue append
_PS_WRITE = _profiler.stage("wire.writelines")     # the send syscall
_PS_PARSE = _profiler.stage("wire.parse")          # _FrameReader frame scan
_PS_ENVELOPE = _profiler.stage("wire.envelope")    # inbound head/seq/ack
_PS_FANIN = _profiler.stage("wire.dispatch_fanin")  # per-msg dispatch prep
_PS_DECODE = _profiler.stage("wire.decode_body")   # typed body decode

#: v4 adds the trailing piggyback-ack varint on MSG frames and corked
#: multi-frame bursts; acceptors take any version in
#: [_MIN_PROTOCOL_VERSION, _PROTOCOL_VERSION] (banner negotiation --
#: v3 peers interop, see docs/messenger.md)
_PROTOCOL_VERSION = 4
_MIN_PROTOCOL_VERSION = 3
_BANNER = "ceph-tpu-msgr"
_SIG_LEN = 16

# frame kinds (payload byte 0)
_K_MSG = 0
_K_ACK = 1
_K_SESSION = 2

#: seconds a receiver waits before writing a standalone cumulative ACK
#: frame: long enough for same-op REPLY traffic to piggyback the
#: watermark on its own data frames (acks gate nothing but unacked-queue
#: pruning, so the latency is free), short enough to bound sender memory
_ACK_DELAY = 0.025

#: message payloads smaller than this are joined into one buffer at
#: enqueue (a short memcpy beats per-part crc/digest bookkeeping);
#: larger payloads stay scatter-gather so big blobs cross by reference
_JOIN_BELOW = 4096

#: serve-loop sentinel for an inbound body this build cannot decode
#: (a newer peer's frame kind): distinct from None, which is a
#: perfectly legal MSG_VALUE payload
_UNDECODABLE = object()


def _varint_bytes(v: int) -> bytes:
    """LEB128 unsigned varint as standalone bytes (the piggyback-ack
    tail appended to queued MSG payloads at transmit time)."""
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _QueuedMsg:
    """One unsealed MSG payload: a scatter-gather part list plus its
    payload crc32c, computed once on first transmit and cached.  Signing
    and the piggyback-ack tail are per-transmission (fresh session key
    per connection), so frames seal at (re)transmit time by EXTENDING
    the cached crc over the tail instead of re-digesting the payload."""

    __slots__ = ("seq", "parts", "crc", "nbytes", "t_enq")

    def __init__(self, seq: int, parts: List, crc: Optional[int] = None,
                 nbytes: Optional[int] = None):
        self.seq = seq
        self.parts = parts
        #: payload crc32c: the native encoder folds it in the same pass
        #: that composes the parts; the Python path computes it lazily
        #: on first transmit (_entry_frames)
        self.crc = crc
        self.nbytes = sum(len(p) for p in parts) if nbytes is None \
            else nbytes
        #: enqueue stamp: ack-lag (enqueue -> delivery-ack prune) feeds
        #: the per-node ack_lag latency histogram (observability)
        self.t_enq = _time.monotonic()


class _SendSession:
    """Per-lossless-peer send state (the Pipe out_seq/sent-queue role)."""

    __slots__ = ("out_seq", "acked", "sent", "sent_bytes", "reconnecting")

    def __init__(self):
        self.out_seq = 0
        self.acked = 0
        #: unacked _QueuedMsg oldest first; payloads are kept UNSEALED --
        #: signing is per-connection (fresh session key on every
        #: reconnect), so frames seal at (re)transmit time
        self.sent: deque = deque()
        self.sent_bytes = 0
        self.reconnecting = False

    def prune(self, acked_seq: int) -> None:
        self.acked = max(self.acked, acked_seq)
        while self.sent and self.sent[0].seq <= self.acked:
            entry = self.sent.popleft()
            self.sent_bytes -= entry.nbytes


class _CorkQueue:
    """Per-peer-node outgoing frame queue (cork/flush state)."""

    __slots__ = ("entries", "nbytes", "flushing", "scheduled",
                 "since_drain", "draining")

    def __init__(self):
        self.entries: List[_QueuedMsg] = []
        self.nbytes = 0
        self.flushing = False   # an async (slow-path) flusher owns the queue
        self.scheduled = False  # an end-of-tick flush callback is pending
        self.since_drain = 0    # bytes written since the last flow-control drain
        self.draining = False


class _AckBatch:
    """Per-inbound-connection cumulative-ack batching state."""

    __slots__ = ("flushed", "scheduled")

    def __init__(self):
        self.flushed = 0
        self.scheduled = False


class _FrameReader:
    """Buffered frame parser: one ``read()`` wakeup drains every frame
    already buffered on the socket (a corked burst arrives as one TCP
    segment run), instead of two ``readexactly`` awaits per frame.

    ``buffered=False`` reproduces the pre-round-8 receive shape (one
    header ``readexactly`` + one payload ``readexactly`` per frame) --
    the other half of the ``osd_msgr_cork`` baseline toggle, so the
    cluster-path bench A/Bs the whole wire architecture, not just the
    send side."""

    __slots__ = ("_reader", "_buf", "_pos", "_buffered", "_native",
                 "_pending", "_pending_idx", "_corrupt")

    def __init__(self, reader: asyncio.StreamReader, buffered: bool = True,
                 native=None):
        self._reader = reader
        self._buf = b""
        self._pos = 0
        self._buffered = buffered
        #: the loaded _wire_native extension (or None = pure Python):
        #: a whole received burst is scanned + crc-validated in ONE
        #: GIL-released pass and served frame by frame from _pending
        self._native = native
        self._pending: List[bytes] = []
        self._pending_idx = 0
        self._corrupt = False

    async def _next_frame_per_message(self) -> Optional[bytes]:
        try:
            header = await self._reader.readexactly(12)
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        _magic, length, _crc = struct.unpack("<III", header)
        try:
            payload = await self._reader.readexactly(length)
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        rec, _pos = unframe(header + payload, 0)
        return rec  # None if magic/crc check failed

    # cephlint: wire-hot-section msgr-frame-parse
    async def next_frame(self) -> Optional[bytes]:
        """The next framed record; None on EOF or a corrupt frame (the
        caller drops the connection either way)."""
        if not self._buffered:
            return await self._next_frame_per_message()
        if self._native is not None:
            return await self._next_frame_native()
        while True:
            buf, pos = self._buf, self._pos
            if len(buf) - pos >= 12:
                with _PS_PARSE:
                    _magic, length, _crc = struct.unpack_from(
                        "<III", buf, pos)
                    if len(buf) - pos >= 12 + length:
                        rec, _next = unframe(buf, pos)  # magic+crc checked
                        if rec is None:
                            return None  # corrupt/forged: drop the conn
                        pos += 12 + length
                        if pos >= len(buf):
                            self._buf, self._pos = b"", 0
                        else:
                            self._pos = pos
                        return rec
            try:
                chunk = await self._reader.read(1 << 16)
            except (ConnectionError, OSError):
                return None
            if not chunk:
                return None
            self._buf = buf[pos:] + chunk if pos < len(buf) else chunk
            self._pos = 0

    async def _next_frame_native(self) -> Optional[bytes]:
        """Native burst parse: every complete frame buffered on the
        socket is located and crc-validated in one GIL-released C pass
        (_wire_native.parse_burst); frames are then served from the
        pending list with zero per-frame Python parsing."""
        while True:
            if self._pending_idx < len(self._pending):
                rec = self._pending[self._pending_idx]
                self._pending_idx += 1
                return rec
            if self._corrupt:
                return None  # forged/torn frame: drop the conn
            buf, pos = self._buf, self._pos
            if len(buf) - pos >= 12:
                with _PS_PARSE:
                    frames, newpos, ok = self._native.parse_burst(buf, pos)
                if frames:
                    self._pending = frames
                    self._pending_idx = 0
                    self._corrupt = not ok
                    if newpos >= len(buf):
                        self._buf, self._pos = b"", 0
                    else:
                        self._pos = newpos
                    continue
                if not ok:
                    return None
            try:
                chunk = await self._reader.read(1 << 16)
            except (ConnectionError, OSError):
                return None
            if not chunk:
                return None
            self._buf = buf[pos:] + chunk if pos < len(buf) else chunk
            self._pos = 0
    # cephlint: end-wire-hot-section


async def _read_frame(framer) -> Optional[bytes]:
    """Read one framed record; None on EOF/corruption.  Accepts a
    :class:`_FrameReader` (the messenger's connections) or a bare
    StreamReader (compat for direct callers)."""
    if isinstance(framer, asyncio.StreamReader):
        framer = _FrameReader(framer)
    return await framer.next_frame()


class TCPMessenger:
    """API-compatible with ``osd.messenger.Messenger`` so OSDShard /
    ECBackend run unchanged over real sockets."""

    def __init__(
        self,
        node: str,
        addr_map: Dict[str, Tuple[str, int]],
        fault: Optional[FaultInjector] = None,
        keyring=None,
        cork: Optional[bool] = None,
    ):
        #: this process's node name; must appear in addr_map for serving
        self.node = node
        self.addr_map = dict(addr_map)
        self.fault = fault if fault is not None else \
            FaultInjector.from_config()
        #: cephx-style auth: when a KeyRing is given, every connection
        #: must pass the mutual challenge-response handshake and every
        #: frame is signed with the derived session key (ms_sign_messages)
        self.keyring = keyring
        self._local_queues: Dict[str, asyncio.Queue] = {}
        self._dispatchers: Dict[str, Callable] = {}
        self._tasks: Dict[str, asyncio.Task] = {}
        #: cached outgoing connections per peer node:
        #: (framer, writer, lock, session_key)
        self._conns: Dict[str, Tuple] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        #: administratively dead entities (mark_down -- the thrasher hook)
        self._marked_down: set = set()
        #: peers whose last connect/send failed, with WHEN it failed:
        #: unreachability is a cached observation, not a verdict, and it
        #: expires -- a revived daemon whose boot races one failed
        #: connect must not be treated as down forever (its primary
        #: would otherwise refuse reads with "only N shards" while every
        #: peer is in fact alive)
        self._unreachable: dict = {}
        self._unreachable_ttl = 3.0
        self._reprobing: set = set()
        #: live incoming-connection handler tasks (cancelled on shutdown;
        #: Server.wait_closed would otherwise block on them forever)
        self._serve_tasks: set = set()
        #: inbound dispatch byte budget (DispatchThrottler /
        #: osd_client_message_size_cap, default 500 MiB): budget is held
        #: from socket read until the dispatcher finishes, so a flood of
        #: large messages back-pressures the senders' sockets instead of
        #: ballooning memory
        from ceph_tpu.utils.config import get_config
        from ceph_tpu.utils.throttle import Throttle

        cfg = get_config()
        try:
            cap = int(cfg.get_val("osd_client_message_size_cap"))
        except (KeyError, ValueError, TypeError):
            cap = 500 * 1024 * 1024
        self.dispatch_throttle = Throttle(f"{node}.msgr-dispatch", cap)
        #: corked send path (osd_msgr_cork): queue outgoing frames per
        #: connection, flush as one writelines burst; off = one
        #: write/drain per message (the per-message baseline)
        self.cork = bool(cfg.get_val("osd_msgr_cork")) if cork is None \
            else bool(cork)
        self.cork_bytes = int(cfg.get_val("osd_msgr_cork_bytes"))
        #: shared-memory frame rings (osd_msgr_shm_ring): colocated
        #: peers whose accept endpoint is ring-registered in THIS
        #: process get a seqlock'd byte-ring conduit instead of the
        #: localhost TCP hop; the whole protocol above the byte
        #: transport (banner, auth, sessions, acks, replay) is
        #: unchanged.  False (default) = TCP everywhere, the A/B
        #: baseline.
        try:
            self.shm_ring = bool(cfg.get_val("osd_msgr_shm_ring"))
            self.ring_bytes = int(cfg.get_val("osd_shm_ring_bytes"))
        except KeyError:
            self.shm_ring = False
            self.ring_bytes = _shm.DEFAULT_RING_BYTES
        self._ring_registered = False
        #: batched native wire codec (_wire_native via
        #: ceph_tpu/native/wire_codec.py), resolved once per messenger:
        #: None = the pure-Python codec (gated off, no toolchain, or
        #: osd_wire_codec_native=false -- the A/B baseline).  Every
        #: native seam below falls back bit-exactly through msg/wire.py.
        self._native = wire_codec.native()
        self._cork_queues: Dict[str, _CorkQueue] = {}
        self._cork_seq = 0
        #: (src entity, dst entity) -> encoded kind|src|dst MSG head
        self._head_cache: Dict[tuple, bytes] = {}
        #: highest reverse-stream watermark piggybacked to each peer node
        #: on our own data frames (lets the inbound-side ack batcher skip
        #: standalone ACK frames the peer has already seen)
        self._piggy_acked: Dict[str, int] = {}
        #: wire-shape counters (the cluster-path bench trend metrics):
        #: frames per burst = frames_sent/bursts, bytes per drain =
        #: bytes_sent/max(drains,1), piggyback ratio =
        #: piggybacked/(piggybacked+standalone)
        self.counters: Dict[str, int] = {
            "msgs_sent": 0, "frames_sent": 0, "bursts": 0, "drains": 0,
            "bytes_sent": 0, "acks_piggybacked": 0, "acks_standalone": 0,
            "acks_elided": 0, "acks_piggybacked_recv": 0,
            "unknown_msg_dropped": 0, "ring_conns": 0, "tcp_conns": 0,
        }
        #: ack-lag attribution (observability): enqueue -> delivery-ack
        #: latency per pruned message, a prometheus histogram family
        from ceph_tpu.utils.perf import stage_histogram

        self._h_ack_lag = stage_histogram(f"{node}.ack_lag_usec")
        #: per-process instance id (the Pipe connect nonce): receive
        #: state is keyed by it, so a restarted peer's fresh stream
        #: never collides with its predecessor's sequence watermark
        self.instance_id = os.urandom(8)
        #: lossless-peer send sessions: peer node -> _SendSession
        self._sessions: Dict[str, _SendSession] = {}
        self._connect_locks: Dict[str, asyncio.Lock] = {}
        #: last instance id seen from each peer node (inbound banners):
        #: an accept pops our cached outgoing conn only on a CHANGE
        #: (peer restart), never on ordinary bidirectional traffic
        self._peer_instances: Dict[str, bytes] = {}
        self._closing = False
        #: receive watermarks: (peer node, instance id) -> delivered seq
        self._in_seqs: Dict[tuple, int] = {}
        #: backlog cap per lossless peer (beyond it, NEW messages drop
        #: like lossy sends -- an honest bound; the reference relies on
        #: its throttles for the same purpose)
        self.lossless_max_backlog = 64 << 20

    def _lossless(self, node: str) -> bool:
        """Lossless-peer policy: OSD<->OSD connections (the reference's
        cluster-messenger policy, src/msg/Policy.h lossless_peer);
        everything else is a lossy client."""
        return self.node.startswith("osd.") and node.startswith("osd.")

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        host, port = self.addr_map[self.node]
        self._server = await asyncio.start_server(
            self._serve_connection, host, port
        )
        if self.shm_ring:
            # announce our accept endpoint as ring-reachable: colocated
            # peers dialing (host, port) get a ring conduit whose server
            # side enters the SAME accept path as a TCP connection
            _shm.register((host, port), self._accept_ring,
                          ring_bytes=self.ring_bytes)
            self._ring_registered = True

    def _accept_ring(self, reader, writer) -> None:
        """Ring-conduit accept: the colocated analogue of the
        ``asyncio.start_server`` callback -- same serve coroutine, ring
        stream adapters instead of sockets."""
        self.adopt_task(
            f"ring-accept.{id(reader)}",
            asyncio.get_event_loop().create_task(
                self._serve_connection(reader, writer)))

    async def shutdown(self) -> None:
        self._closing = True  # stops lossless reconnect loops
        if self._ring_registered:
            _shm.unregister(tuple(self.addr_map[self.node]))
            self._ring_registered = False
        if self._server is not None:
            self._server.close()
        for conn in self._conns.values():
            conn[1].close()
        self._conns.clear()
        # cancel in ROUNDS (mirrors the in-process Messenger.shutdown):
        # under py<3.11 asyncio.wait_for can swallow a cancellation that
        # races its future's completion (bpo-42130); a tick loop that
        # lost its one cancel that way keeps running and a single
        # unbounded `await task` here then wedges the daemon inside its
        # SIGTERM handler -- the process never exits and the caller's
        # waitpid hangs.  Re-cancelling lands the next CancelledError at
        # the task's next await point; bounded rounds keep shutdown
        # finite no matter what.
        pending = [
            t for t in list(self._tasks.values()) + list(self._serve_tasks)
            if not t.done()
        ]
        for _ in range(50):
            if not pending:
                break
            for task in pending:
                task.cancel()
            _done, still = await asyncio.wait(pending, timeout=0.5)
            pending = list(still)
        if self._server is not None:
            await self._server.wait_closed()

    # -- entity registration (same surface as the in-process bus) ----------

    def register(
        self, name: str, dispatcher: Callable[[str, object], Awaitable[None]]
    ) -> None:
        self._local_queues[name] = asyncio.Queue()
        self._dispatchers[name] = dispatcher
        self._tasks[name] = asyncio.get_event_loop().create_task(
            self._dispatch_loop(name)
        )

    def adopt_task(self, name: str, task: "asyncio.Task") -> None:
        # completed tasks prune themselves (per-op tasks would otherwise
        # accumulate without bound on a long-lived daemon) and log any
        # unhandled exception on the way out
        from ceph_tpu.utils.aio import log_task_exception

        self._tasks[name] = task

        def _done(t, name=name):
            log_task_exception(t, name)
            if self._tasks.get(name) is t:
                self._tasks.pop(name, None)

        task.add_done_callback(_done)

    async def _dispatch_loop(self, name: str) -> None:
        queue = self._local_queues[name]
        while True:
            item = await queue.get()
            more = True
            while more:
                await self._dispatch_one(name, item)
                # drain everything already buffered without paying an
                # await round per item (a corked burst delivers as one)
                if queue.empty():
                    more = False
                else:
                    item = queue.get_nowait()

    async def _dispatch_one(self, name: str, item) -> None:
        # the fan-in bookkeeping (budget hand-off plumbing) is a
        # declared cost center; the dispatcher's own execution is the
        # event-loop arm's territory (it awaits)
        with _PS_FANIN:
            src, msg = item[0], item[1]
            cost = item[2] if len(item) > 2 else 0
            release = None
            claimed = [False]
            if cost:
                released = [False]

                def release(released=released, cost=cost):
                    if not released[0]:
                        released[0] = True
                        self.dispatch_throttle.put(cost)

                if isinstance(msg, dict) and "op" in msg:
                    # budget hand-off: a dispatcher that only ENQUEUES
                    # the op (OSDShard's QoS queue) may claim the budget
                    # and release it when the op actually executes --
                    # that is what makes the byte cap a real memory
                    # bound for daemons instead of a transit-only
                    # throttle.  Blocking here instead would deadlock:
                    # sub-op replies for in-flight ops arrive through
                    # this same loop.
                    msg["_budget_release"] = release
                    msg["_budget_claim"] = (
                        lambda claimed=claimed:
                        claimed.__setitem__(0, True))
        try:
            if name not in self._marked_down:
                try:
                    await self._dispatchers[name](src, msg)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 -- a dispatcher crash
                    # must not kill the loop (reference logs and drops)
                    import sys
                    import traceback

                    traceback.print_exc(file=sys.stderr)
        finally:
            if isinstance(msg, dict):
                msg.pop("_budget_claim", None)
            if cost and not claimed[0]:
                if isinstance(msg, dict):
                    msg.pop("_budget_release", None)
                release()

    # -- server side -------------------------------------------------------

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        self._serve_tasks.add(task)
        try:
            await self._serve_connection_inner(reader, writer)
        except (ConnectionError, OSError):
            pass  # peer vanished mid-serve (restart/teardown): normal
        finally:
            self._serve_tasks.discard(task)
            writer.close()

    async def _serve_connection_inner(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        framer = _FrameReader(reader, buffered=self.cork,
                              native=self._native)
        banner = await framer.next_frame()
        if banner is None:
            writer.close()
            return
        dec = Decoder(banner)
        if dec.string() != _BANNER:
            writer.close()
            return
        # banner negotiation: accept any peer whose dialect we can parse
        # (>= _MIN).  Old kinds keep their layout; the v4 additions are
        # a TRAILING field old receivers never read and a cumulative ACK
        # frame old senders already handle (prune() is cumulative), so a
        # v3 peer interops without a feature exchange.
        if not (_MIN_PROTOCOL_VERSION <= dec.varint() <= _PROTOCOL_VERSION):
            writer.close()  # unparseable dialect: refuse (reference -EXDEV)
            return
        peer_node = dec.string()
        client_nonce = dec.blob()
        peer_instance = dec.blob()
        session_key = None
        if self.keyring is not None:
            session_key = await self._auth_accept(
                framer, writer, peer_node, client_nonce
            )
            if session_key is None:
                writer.close()  # failed handshake: refuse (-EACCES)
                return
        self._unreachable.pop(peer_node, None)
        # the peer RESTARTED (new instance id): any cached outgoing
        # connection targets its dead predecessor (writes into it are
        # silently buffered by TCP, losing replies) -- drop it so the
        # next send dials the live process (Pipe.cc replaces the old
        # session on accept).  Same instance = ordinary bidirectional
        # traffic: the healthy outgoing conn must survive the accept,
        # or two chatty OSDs would tear each other's sessions down
        # forever (review r5 finding).
        prev_instance = self._peer_instances.get(peer_node)
        self._peer_instances[peer_node] = peer_instance
        if prev_instance != peer_instance:
            # first contact or a restart: drop the (possibly stale)
            # cached conn once; repeat accepts from the SAME instance
            # leave it alone.  _drop_conn re-arms the reconnect loop if
            # unacked lossless traffic is pending (a popped conn's ack
            # reader cannot, its currency check fails by then), and
            # dead-instance receive watermarks are pruned with their
            # incarnation.
            self._drop_conn(peer_node)
            for key in [k for k in self._in_seqs
                        if k[0] == peer_node and k[1] != peer_instance]:
                del self._in_seqs[key]
        in_key = (peer_node, peer_instance)
        acks = _AckBatch()
        #: known kind|src|dst frame heads on this connection: the prefix
        #: is byte-identical for every message of one (src, dst) stream,
        #: so after the first frame the envelope parse is one startswith
        heads: List[tuple] = []
        while True:
            rec = await framer.next_frame()
            if rec is None:
                break
            if session_key is not None:
                try:
                    rec = self._unseal(rec, session_key)
                except OSError:
                    break  # short/forged/tampered frame: drop the conn
            if not rec:
                break
            kind = rec[0]
            if kind == _K_SESSION:
                await self._reply_session(writer, session_key, in_key)
                continue
            if kind != _K_MSG:
                continue  # ACK frames never arrive on an inbound socket
            with _PS_ENVELOPE:
                for head, hsrc, hdst in heads:
                    if rec.startswith(head):
                        src, dst = hsrc, hdst
                        hlen = len(head)
                        break
                else:
                    dec = Decoder(rec, 1)
                    src = dec.string()
                    dst = dec.string()
                    heads.append((rec[:dec._pos], src, dst))
                    hlen = dec._pos
            # envelope tail + typed body: the native codec parses both
            # in one C pass straight from the record buffer; a decode
            # it cannot express (never a well-formed peer's frame, by
            # the interop property tests) re-parses through the pure
            # Python codec below, so behavior is identical either way.
            # ``msg is _UNDECODABLE`` = unknown body kind: the
            # watermark still advances and the frame is counted-and-
            # dropped -- forward compat for newer peers' frame kinds.
            nat = self._native
            seq = None
            if nat is not None:
                try:
                    with _PS_DECODE:
                        seq, msg, back_ack = nat.decode_msg(rec, hlen)
                    if msg is nat.UNKNOWN:
                        msg = _UNDECODABLE
                except ValueError:
                    seq = None
            if seq is None:
                with _PS_ENVELOPE:
                    dec = Decoder(rec, hlen)
                    seq = dec.varint()
                    body = dec.blob()
                    # v4 piggyback: a trailing cumulative ack for OUR
                    # reverse stream to this peer rides the data frame
                    # (v3 senders never append it; v3 receivers never
                    # read this far)
                    # cephlint: wire-optional -- v3 senders end at the
                    # blob
                    back_ack = dec.varint() if dec.remaining() else None
                try:
                    with _PS_DECODE:
                        msg = decode_message(body)
                except ValueError:
                    # a frame kind this build does not know (a NEWER
                    # peer's message type): ignore-and-count below,
                    # after the watermark bookkeeping
                    msg = _UNDECODABLE
            if back_ack:
                sess = self._sessions.get(peer_node)
                if sess is not None:
                    self._prune_acked(sess, back_ack)
                self.counters["acks_piggybacked_recv"] += 1
            if seq:
                # lossless stream (in order per TCP connection).  A dst
                # we do not host YET (the boot window between
                # messenger.start and entity registration) must NOT be
                # acked -- break the connection instead, so the sender
                # replays once the entity exists.  A marked-down dst is
                # an intentional kill: ack-and-drop.
                if dst not in self._local_queues and \
                        dst not in self._marked_down:
                    break
                if self.cork:
                    # batched cumulative ack: at most one ACK frame per
                    # _ACK_DELAY window, elided entirely when our own
                    # outgoing data frames piggyback the watermark first
                    if not acks.scheduled:
                        acks.scheduled = True
                        asyncio.get_event_loop().call_later(
                            _ACK_DELAY, self._ack_tick, acks, writer,
                            session_key, peer_node, in_key)
                else:
                    await self._ack_now(writer, session_key, seq)
                # The PR-3 invariant, now machine-enforced: the dup
                # check and the watermark advance are one indivisible
                # step, AFTER every await that can tear this connection
                # down (the per-message ack drain above).  An await
                # slipped between them lets the conn die with the
                # watermark past an undelivered message, so the
                # reconnect replay skips it -- silent loss.  The static
                # rule flags any yield inside; the runtime verifier
                # (analysis/runtime.py) asserts no task ever suspends
                # here under tier-1.
                # cephlint: atomic-section msgr-watermark-ordering
                if seq <= self._in_seqs.get(in_key, 0):
                    continue  # duplicate from a replay: already delivered
                self._in_seqs[in_key] = seq
                # cephlint: end-atomic-section
            if msg is _UNDECODABLE:
                # unknown frame kind (e.g. mgr report frames reaching a
                # pre-report daemon): the watermark already advanced, so
                # ignore-and-count is exactly "old daemon ignores report
                # frames" forward compat; tearing the connection down
                # here would make every protocol addition a flag day
                self.counters["unknown_msg_dropped"] += 1
                continue
            queue = self._local_queues.get(dst)
            if queue is not None and dst not in self._marked_down:
                if isinstance(msg, dict) and msg.get("op") == "client_op":
                    # throttle CLIENT ops only (the reference's
                    # DispatchThrottler guards the client messenger):
                    # sub-op replies must NEVER block here, or claimed
                    # client budget could wait on replies that are
                    # themselves stuck behind the throttle -- a
                    # distributed deadlock
                    cost = len(rec)
                    # deliberate budget HAND-OFF, not a leak: the cost
                    # rides the queue item and _dispatch_one releases
                    # it (or passes release to the claiming OSD) after
                    # the dispatcher runs -- that hand-off is what
                    # makes the byte cap a real memory bound
                    await self.dispatch_throttle.get(cost)  # cephlint: disable=async-lock-across-await
                    queue.put_nowait((src, msg, cost))
                else:
                    # unbounded queue: put() never blocks, put_nowait
                    # skips one coroutine round per delivered message
                    queue.put_nowait((src, msg))
        writer.close()

    async def _reply_session(self, writer, session_key, in_key) -> None:
        """Answer a reconnect watermark exchange (Pipe.cc connect reply):
        tell the peer what we have DELIVERED from this instance, so it
        replays everything after.  Once per (re)connect, never per
        message -- hence its own drain."""
        reply = Encoder().u8(_K_SESSION).varint(
            self._in_seqs.get(in_key, 0)).bytes()
        writer.write(frame(self._seal(reply, session_key)))
        await writer.drain()

    async def _ack_now(self, writer, session_key, seq: int) -> None:
        """Per-message ack write+drain (the uncorked / pre-v4 shape)."""
        ack = Encoder().u8(_K_ACK).varint(seq).bytes()
        writer.write(frame(self._seal(ack, session_key)))
        await writer.drain()
        self.counters["acks_standalone"] += 1

    def _ack_tick(self, acks: _AckBatch, writer, session_key,
                  peer_node: str, in_key: tuple) -> None:
        """Deferred cumulative ack (sync timer callback): skipped when a
        piggybacked watermark on our own data frames already covered it;
        otherwise one small ACK frame, written without a drain (acks
        gate nothing but sender-side queue pruning)."""
        acks.scheduled = False
        seq = self._in_seqs.get(in_key, 0)
        if seq <= acks.flushed:
            return
        if self._piggy_acked.get(peer_node, 0) >= seq:
            acks.flushed = seq  # rode one of our outgoing data frames
            self.counters["acks_elided"] += 1
            return
        acks.flushed = seq
        if self._closing or writer.is_closing():
            return  # sender reconnects and re-handshakes
        ack = Encoder().u8(_K_ACK).varint(seq).bytes()
        try:
            writer.write(frame(self._seal(ack, session_key)))
        except (ConnectionError, OSError, RuntimeError):
            return
        self.counters["acks_standalone"] += 1

    async def _auth_accept(self, framer, writer, peer_node: str,
                           client_nonce: bytes):
        """Acceptor half of the cephx-style handshake; returns the
        session key, or None to refuse."""
        from ceph_tpu.auth.cephx import AuthHandshake

        secret = self.keyring.get(peer_node)
        if secret is None or not client_nonce:
            return None  # unknown entity / peer not speaking auth
        hs = AuthHandshake(secret, client_nonce, AuthHandshake.new_nonce())
        writer.write(frame(
            Encoder().blob(hs.server_nonce).blob(hs.server_proof()).bytes()
        ))
        await writer.drain()
        reply = await framer.next_frame()
        if reply is None:
            return None
        if not hs.verify_client(Decoder(reply).blob()):
            return None
        return hs.session_key()

    # -- client side -------------------------------------------------------

    def _node_of(self, entity: str) -> Optional[str]:
        """The node hosting an entity: itself if it has an address,
        else -- for hub-multiplexed entities named ``<name>@<node>``
        (the loadgen scale harness: thousands of client Objecters
        sharing a handful of client-hub messengers/ports) -- the node
        after the ``@``.  A reply to ``c137@lg0`` then rides the ONE
        cached connection to node ``lg0`` instead of opening a socket
        per client, and the hub's dispatch fans it to the registered
        entity queue by full name."""
        if entity in self.addr_map:
            return entity
        if "@" in entity:
            node = entity.rsplit("@", 1)[1]
            if node in self.addr_map:
                return node
        return None

    async def _connect(self, node: str):
        from ceph_tpu.auth.cephx import AuthHandshake

        host, port = self.addr_map[node]
        ring = _shm.connect((host, port), fault=self.fault) \
            if self.shm_ring else None
        if ring is not None:
            reader, writer = ring
            self.counters["ring_conns"] += 1
        else:
            reader, writer = await asyncio.open_connection(host, port)
            self.counters["tcp_conns"] += 1
        framer = _FrameReader(reader, buffered=self.cork,
                              native=self._native)
        nonce = AuthHandshake.new_nonce() if self.keyring is not None else b""
        banner = (
            Encoder().string(_BANNER).varint(_PROTOCOL_VERSION)
            .string(self.node).blob(nonce).blob(self.instance_id).bytes()
        )
        writer.write(frame(banner))
        await writer.drain()
        session_key = None
        if self.keyring is not None:
            secret = self.keyring.get(self.node)
            if secret is None:
                writer.close()
                raise OSError(f"no key for {self.node} in keyring")
            try:
                # a no-auth peer never answers the handshake: time out
                # with a clear error instead of hanging every send
                reply = await asyncio.wait_for(framer.next_frame(), 3.0)
            except asyncio.TimeoutError:
                writer.close()
                raise OSError(
                    f"{node} did not answer the auth handshake "
                    "(auth-mode mismatch?)"
                )
            if reply is None:
                writer.close()
                raise OSError(f"auth refused by {node}")
            dec = Decoder(reply)
            server_nonce = dec.blob()
            hs = AuthHandshake(secret, nonce, server_nonce)
            if not hs.verify_server(dec.blob()):
                writer.close()
                raise OSError(f"{node} failed to prove keyring knowledge")
            writer.write(frame(Encoder().blob(hs.client_proof()).bytes()))
            await writer.drain()
            session_key = hs.session_key()
        return framer, writer, asyncio.Lock(), session_key

    def _drop_conn(self, node: str) -> None:
        """Pop + close the cached conn to ``node``; if unacked lossless
        traffic is queued, re-arm the reconnect loop (the popped conn's
        own ack reader can no longer do it -- its currency check fails
        once the conn left the cache)."""
        conn = self._conns.pop(node, None)
        if conn is not None:
            conn[1].close()
        # piggybacked acks recorded against the dead conn may never have
        # arrived: forget them so the ack batcher sends a standalone
        # cumulative ack on the next inbound traffic instead of assuming
        # coverage (the peer's unacked queue must not pin entries)
        self._piggy_acked.pop(node, None)
        sess = self._sessions.get(node)
        if sess is not None and sess.sent and not self._closing \
                and node not in self._marked_down:
            self._spawn_reconnect(node)

    def _conn_lock(self, node: str) -> asyncio.Lock:
        lock = self._connect_locks.get(node)
        if lock is None:
            lock = self._connect_locks[node] = asyncio.Lock()
        return lock

    async def _try_establish(self, node: str):
        """Connect to ``node`` and cache the connection; for a lossless
        peer, run the session watermark exchange + replay first (the
        Pipe.cc connect() path).  Returns the conn or None (peer down,
        unreachable mark refreshed).  Serialized per node so concurrent
        senders share one connection."""
        async with self._conn_lock(node):
            conn = self._conns.get(node)
            if conn is not None:
                return conn
            try:
                conn = await self._connect(node)
            except OSError:
                self._unreachable[node] = asyncio.get_event_loop().time()
                return None
            if self._lossless(node):
                try:
                    await self._session_handshake(node, conn)
                except (OSError, ConnectionError, asyncio.TimeoutError):
                    conn[1].close()
                    self._unreachable[node] = \
                        asyncio.get_event_loop().time()
                    return None
                except BaseException:
                    # cancellation (e.g. a probe's outer wait_for): the
                    # already-open socket must not leak
                    conn[1].close()
                    raise
                self._spawn_ack_reader(node, conn)
            self._conns[node] = conn
            self._unreachable.pop(node, None)
            return conn

    async def _session_handshake(self, node: str, conn) -> None:
        """Exchange delivered-watermarks with the peer and retransmit
        everything it has not delivered (Pipe.cc connect/replay).  The
        whole replay burst goes out as one scatter-gather writelines
        with a single drain per snapshot round."""
        framer, writer, lock, skey = conn
        sess = self._sessions.setdefault(node, _SendSession())
        writer.write(frame(self._seal(
            Encoder().u8(_K_SESSION).bytes(), skey)))
        await writer.drain()
        rec = await asyncio.wait_for(framer.next_frame(), 3.0)
        if rec is None:
            raise OSError(f"{node}: session handshake EOF")
        dec = Decoder(self._unseal(rec, skey))
        if dec.u8() != _K_SESSION:
            raise OSError(f"{node}: bad session reply")
        self._prune_acked(sess, dec.varint())  # peer delivered these
        async with lock:
            # re-snapshot until stable: a send that lands while the
            # drain below is awaiting appends to sess.sent and is
            # caught by the next iteration (review r5 finding)
            sent_upto = 0
            while True:
                pending = [e for e in sess.sent if e.seq > sent_upto]
                if not pending:
                    break
                bufs: List = []
                for entry in pending:
                    bufs.extend(self._entry_frames(entry, skey, 0))
                    sent_upto = entry.seq
                writer.writelines(bufs)
                await writer.drain()

    def _spawn_ack_reader(self, node: str, conn) -> None:
        """Consume ACK frames off a lossless outgoing connection,
        pruning the unacked queue; on EOF drop the cached conn and, if
        traffic is pending, start the reconnect loop."""

        async def ack_loop():
            framer, skey = conn[0], conn[3]
            while True:
                rec = await framer.next_frame()
                if rec is None:
                    break
                try:
                    dec = Decoder(self._unseal(rec, skey))
                except OSError:
                    break
                if dec.u8() == _K_ACK:
                    sess = self._sessions.get(node)
                    if sess is not None:
                        self._prune_acked(sess, dec.varint())
            if self._conns.get(node) is conn:
                self._drop_conn(node)
            else:
                conn[1].close()  # superseded conn: just release it

        self.adopt_task(
            f"ack.{node}.{id(conn)}",
            asyncio.get_event_loop().create_task(ack_loop()),
        )

    def _spawn_reconnect(self, node: str) -> None:
        """Lossless-peer reconnect loop: keep dialing (bounded backoff)
        until the peer answers and the queued messages replay, or the
        peer is administratively down / the queue drains."""
        sess = self._sessions.setdefault(node, _SendSession())
        if sess.reconnecting:
            return
        sess.reconnecting = True

        async def reconnect_loop():
            try:
                delay = 0.2
                while True:
                    if (self._closing or node in self._marked_down
                            or not sess.sent):
                        return
                    if self._conns.get(node) is not None:
                        return  # re-established elsewhere (replay done)
                    if await self._try_establish(node) is not None:
                        return
                    await asyncio.sleep(delay)
                    delay = min(delay * 1.7, 2.0)
            finally:
                sess.reconnecting = False

        self.adopt_task(
            f"reconnect.{node}",
            asyncio.get_event_loop().create_task(reconnect_loop()),
        )

    def _prune_acked(self, sess: _SendSession, acked_seq: int) -> None:
        """Observe delivery-ack lag (enqueue -> cumulative-ack arrival)
        for every entry this ack releases, then prune the unacked
        queue -- the "ack" leg of the op timeline at the wire layer."""
        target = max(sess.acked, acked_seq)
        now = _time.monotonic()
        for entry in sess.sent:
            if entry.seq > target:
                break
            self._h_ack_lag.inc((now - entry.t_enq) * 1e6, entry.nbytes)
        sess.prune(acked_seq)

    # -- frame assembly (zero-copy seal/frame at transmit time) ------------

    def _msg_entry(self, src: str, dst: str, seq: int, msg: object
                   ) -> _QueuedMsg:
        """Encode one MSG payload as a part list: the wire body's parts
        nest into the transport envelope by reference (a large blob --
        EC shard bytes -- is never joined or copied; sub-4 KiB payloads
        collapse into one buffer, where a short memcpy beats per-part
        bookkeeping)."""
        # the kind|src|dst head is byte-identical for every message on
        # one (src, dst) stream: encode it once and reuse (entity names
        # are a small fixed set per daemon)
        with _PS_ENCODE:
            head = self._head_cache.get((src, dst))
            if head is None:
                head = self._head_cache[(src, dst)] = (
                    Encoder().u8(_K_MSG).string(src).string(dst).bytes())
            nat = self._native
            if nat is not None:
                try:
                    # one C pass: head + seq/length varints + typed body
                    # as a scatter part list, payload crc folded along
                    # the way (the transmit-time seal only extends it)
                    parts, nbytes, crc = nat.encode_entry(head, seq, msg)
                except nat.FallbackError:
                    pass  # a value outside the C model: python encodes
                else:
                    entry = _QueuedMsg(seq, parts, crc=crc, nbytes=nbytes)
                    _PS_ENCODE.add_bytes(nbytes)
                    return entry
            body_parts = message_encoder(msg)._parts
            body_len = sum(map(len, body_parts))
            pre = head + _varint_bytes(seq) + _varint_bytes(body_len)
            if len(pre) + body_len <= _JOIN_BELOW:
                entry = _QueuedMsg(seq, [b"".join([pre, *body_parts])])
            else:
                enc = Encoder()
                enc._parts = [pre] + body_parts
                entry = _QueuedMsg(seq, enc.parts(_JOIN_BELOW))
            _PS_ENCODE.add_bytes(entry.nbytes)
            return entry

    # The per-frame seal/flush seams below are DECLARED wire hot
    # sections: payloads must cross as part lists (the zero-copy
    # contract, docs/messenger.md) -- the wire-hot-path-alloc rule
    # flags any provable per-frame bytes concatenation inside.
    # cephlint: wire-hot-section msgr-seal-flush
    def _entry_frames(self, entry: _QueuedMsg, session_key,
                      ack: int) -> List:
        """On-wire buffer list for one queued message: cached payload
        parts + per-transmission tail (piggyback ack, signature), with
        the frame crc EXTENDED over the tail instead of recomputed over
        the payload (the double-crc audit: each digest runs once per
        burst element, retransmits included)."""
        with _PS_SEAL:
            crc = entry.crc
            if crc is None:
                crc = entry.crc = crc32c_parts(entry.parts)
            parts = entry.parts
            if ack:
                tail = _varint_bytes(ack)
                parts = parts + [tail]
                crc = crc32c(tail, crc)
            if session_key is not None:
                from ceph_tpu.auth.cephx import sign_parts

                sig = sign_parts(session_key, parts)
                parts = parts + [sig]
                crc = crc32c(sig, crc)
            _PS_SEAL.add_bytes(entry.nbytes)
            return frame_parts(parts, crc)

    def _piggy_ack_value(self, node: str) -> int:
        """Cumulative delivered watermark of the reverse stream from
        ``node`` (what a data frame to it may piggyback)."""
        inst = self._peer_instances.get(node)
        if inst is None:
            return 0
        return self._in_seqs.get((node, inst), 0)

    # -- corked send queue (cork/flush; the wire-level coalescer) ----------

    def _enqueue_cork(self, node: str, entry: _QueuedMsg) -> None:
        """Queue one frame for ``node``; flush fires at end-of-tick
        (queue-drain: every already-runnable sender joins the burst) or
        immediately past the byte threshold -- the osd/coalescer.py
        flush discipline applied to the wire.  Deadlock-free for the
        same reason: a flush depends only on the event loop running,
        never on another message's completion."""
        with _PS_CORK:
            q = self._cork_queues.get(node)
            if q is None:
                q = self._cork_queues[node] = _CorkQueue()
            q.entries.append(entry)
            q.nbytes += entry.nbytes
            self.counters["msgs_sent"] += 1
            if q.flushing:
                return  # the slow-path flusher re-checks after its drain
            if q.nbytes >= self.cork_bytes:
                self._flush_now(node, q)
            elif not q.scheduled:
                q.scheduled = True
                asyncio.get_event_loop().call_soon(self._cork_tick, node)

    def _cork_tick(self, node: str) -> None:
        q = self._cork_queues.get(node)
        if q is None:
            return
        q.scheduled = False
        if q.entries and not q.flushing:
            self._flush_now(node, q)

    def _flush_now(self, node: str, q: _CorkQueue) -> None:
        """Synchronous fast path: seal + ``writelines`` the whole queue
        straight into the transport buffer -- no task, no lock, no
        drain.  ``drain()`` is flow control and runs (as a task) only
        once ``cork_bytes`` have been written since the last one.  Falls
        back to the async flusher when the connection is missing, mid-
        handshake (lock held: a replay is writing -- interleaving fresh
        seqs into a replay would break the receiver's dedup watermark),
        or already closing."""
        if self._closing:
            q.entries.clear()
            q.nbytes = 0
            return
        conn = self._conns.get(node)
        if conn is None or self._conn_lock(node).locked() or \
                conn[2].locked() or conn[1].is_closing():
            self._spawn_cork_flush(node)
            return
        batch, q.entries = q.entries, []
        q.nbytes = 0
        _framer, writer, _lock, skey = conn
        lossless = self._lossless(node)
        ack = self._piggy_ack_value(node) if lossless else 0
        last = len(batch) - 1
        bufs: List = []
        split = self.fault.conn_kill_split(len(batch))
        if split >= 0:
            # injected mid-burst kill: a prefix of the burst reaches the
            # wire, then the transport dies under the sender
            for entry in batch[:split]:
                bufs.extend(self._entry_frames(entry, skey, 0))
            if bufs:
                writer.writelines(bufs)
            writer.transport.abort()
            self._conn_failed(node, writer, lossless)
            self._requeue_lossy(node, q, batch, lossless)
            return
        prof_on = _profiler.enabled()
        t_burst = _time.perf_counter_ns() if prof_on else 0
        nat = self._native
        if nat is not None and skey is None:
            # the whole batch sealed in one C call: frame headers +
            # piggyback-ack tail composed natively, cached payload
            # crcs extended (signed connections keep the Python seal:
            # the hmac runs per transmission either way)
            with _PS_SEAL:
                bufs, nbytes = nat.seal_frames(batch, ack)
                _PS_SEAL.add_bytes(nbytes)
        else:
            for i, entry in enumerate(batch):
                # the cumulative piggyback rides the LAST frame of the
                # burst; the receiver processes in order, one watermark
                # covers every earlier frame too
                bufs.extend(self._entry_frames(
                    entry, skey, ack if i == last else 0))
            nbytes = -1
        try:
            with _PS_WRITE:
                writer.writelines(bufs)
        except (ConnectionError, OSError, RuntimeError):
            self._conn_failed(node, writer, lossless)
            self._requeue_lossy(node, q, batch, lossless)
            return
        if nbytes < 0:
            nbytes = sum(len(b) for b in bufs)
        if prof_on:
            # per-connection per-burst sub-accounting: frames/burst,
            # bytes/burst, ns/frame percentiles (the decomposition's
            # syscall-shape evidence)
            _profiler.note_burst(node, len(batch), nbytes,
                                 _time.perf_counter_ns() - t_burst)
        self.counters["bursts"] += 1
        self.counters["frames_sent"] += len(batch)
        self.counters["bytes_sent"] += nbytes
        if ack:
            self._piggy_acked[node] = max(
                self._piggy_acked.get(node, 0), ack)
            self.counters["acks_piggybacked"] += 1
        q.since_drain += nbytes
        if q.since_drain >= self.cork_bytes and not q.draining:
            q.draining = True
            self._cork_seq += 1
            task = asyncio.get_event_loop().create_task(
                self._drain_conn(node, q, conn))
            self.adopt_task(f"drain.{node}.{self._cork_seq}", task)
    # cephlint: end-wire-hot-section

    def _requeue_lossy(self, node: str, q: _CorkQueue, batch,
                       lossless: bool) -> None:
        """A LOSSY conn died mid-burst in the sync fast path: hand the
        batch back to the queue and the slow-path flusher, which
        re-establishes and retries once before dropping -- the same
        one-shot redelivery courtesy ``_cork_flush`` already gives its
        own failures.  Without this the fast path silently loses the
        unsent tail of the burst while the peer stays up, and the
        client's probe loop -- which only demotes DEAD primaries --
        waits out the whole op deadline (the ring transport made this
        reachable: conns establish fast enough that the sync path, not
        the slow path, consumes mid-burst kills).  Lossless conns skip
        this: their entries live on ``sess.sent`` and the session
        replay machinery owns redelivery."""
        if lossless or self._closing:
            return
        q.entries = batch + q.entries
        q.nbytes = sum(e.nbytes for e in q.entries)
        self._spawn_cork_flush(node)

    def _conn_failed(self, node: str, writer, lossless: bool) -> None:
        """Shared dead-connection handling for the sync send path."""
        self._conns.pop(node, None)
        writer.close()
        self._piggy_acked.pop(node, None)
        self._unreachable[node] = asyncio.get_event_loop().time()
        if lossless:
            # unacked entries live on sess.sent: replay redelivers
            self._spawn_reconnect(node)

    async def _drain_conn(self, node: str, q: _CorkQueue, conn) -> None:
        """Flow-control drain: awaited once per ``cork_bytes`` written,
        not once per message."""
        try:
            await conn[1].drain()
            self.counters["drains"] += 1
            q.since_drain = 0
        except (ConnectionError, OSError):
            if self._conns.get(node) is conn:
                self._conn_failed(node, conn[1], self._lossless(node))
        finally:
            q.draining = False

    def _spawn_cork_flush(self, node: str) -> None:
        self._cork_seq += 1
        task = asyncio.get_event_loop().create_task(self._cork_flush(node))
        self.adopt_task(f"cork.{node}.{self._cork_seq}", task)

    async def _cork_flush(self, node: str) -> None:
        """Slow-path flusher (first contact, contended lock): drains the
        cork queue under the connection lock with a drain per pass;
        messages enqueued while a pass awaits are picked up by the next
        pass."""
        q = self._cork_queues.get(node)
        if q is None or q.flushing:
            return
        q.flushing = True
        lossless = self._lossless(node)
        attempts = 0
        try:
            while q.entries and not self._closing:
                conn = self._conns.get(node)
                if conn is None:
                    conn = await self._try_establish(node)
                if conn is None:
                    # peer down: lossy frames drop (lossy policy);
                    # lossless ones already sit on sess.sent -- the
                    # reconnect loop replays them
                    q.entries.clear()
                    q.nbytes = 0
                    if lossless:
                        self._spawn_reconnect(node)
                    return
                batch, q.entries = q.entries, []
                q.nbytes = 0
                _framer, writer, lock, skey = conn
                ack = self._piggy_ack_value(node) if lossless else 0
                last = len(batch) - 1
                try:
                    async with lock:
                        split = self.fault.conn_kill_split(len(batch))
                        if split >= 0:
                            prefix: List = []
                            for entry in batch[:split]:
                                prefix.extend(
                                    self._entry_frames(entry, skey, 0))
                            if prefix:
                                writer.writelines(prefix)
                            writer.transport.abort()
                            raise ConnectionResetError(
                                "injected mid-burst connection kill")
                        prof_on = _profiler.enabled()
                        t_burst = _time.perf_counter_ns() if prof_on \
                            else 0
                        bufs: List = []
                        nat = self._native
                        if nat is not None and skey is None:
                            with _PS_SEAL:
                                bufs, _nb = nat.seal_frames(batch, ack)
                                _PS_SEAL.add_bytes(_nb)
                        else:
                            for i, entry in enumerate(batch):
                                bufs.extend(self._entry_frames(
                                    entry, skey, ack if i == last else 0))
                        with _PS_WRITE:
                            writer.writelines(bufs)
                        if prof_on:
                            _profiler.note_burst(
                                node, len(batch),
                                sum(len(b) for b in bufs),
                                _time.perf_counter_ns() - t_burst)
                        await writer.drain()
                except (ConnectionError, OSError, RuntimeError):
                    self._conn_failed(node, writer, lossless)
                    if lossless:
                        q.entries.clear()
                        q.nbytes = 0
                        return  # replay machinery owns redelivery
                    attempts += 1
                    if attempts > 1:
                        return  # lossy: one reconnect retry, then drop
                    q.entries = batch + q.entries
                    q.nbytes = sum(e.nbytes for e in q.entries)
                    continue
                self._unreachable.pop(node, None)
                self.counters["bursts"] += 1
                self.counters["drains"] += 1
                self.counters["frames_sent"] += len(batch)
                self.counters["bytes_sent"] += sum(len(b) for b in bufs)
                if ack:
                    self._piggy_acked[node] = max(
                        self._piggy_acked.get(node, 0), ack)
                    self.counters["acks_piggybacked"] += 1
        finally:
            q.flushing = False

    # -- send surface ------------------------------------------------------

    async def send_message(self, src: str, dst: str, msg: object) -> None:
        if src in self._marked_down or dst in self._marked_down:
            return
        # local short-circuit
        queue = self._local_queues.get(dst)
        if queue is not None:
            if self.fault.maybe_drop():
                return
            if self.fault.delay_probability:
                await self.fault.maybe_delay()
            queue.put_nowait((src, msg))
            return
        node = self._node_of(dst)
        if node is None:
            return  # unknown peer: lossy
        if self.fault.maybe_drop():
            return
        if self.fault.delay_probability:
            await self.fault.maybe_delay()
        lossless = self._lossless(node)
        if not self.cork:
            # per-message baseline: join, seal, frame, write, drain --
            # one write + one drain per message (the pre-v4 shape)
            if lossless:
                await self._send_lossless(src, dst, node, msg)
            else:
                entry = self._msg_entry(src, dst, 0, msg)
                await self._send_lossy(node, self._join_entry(entry))
            return
        if lossless:
            sess = self._sessions.setdefault(node, _SendSession())
            if sess.sent_bytes >= self.lossless_max_backlog:
                return  # honest bound: beyond the backlog, drop
            sess.out_seq += 1
            entry = self._msg_entry(src, dst, sess.out_seq, msg)
            sess.sent.append(entry)
            sess.sent_bytes += entry.nbytes
        else:
            entry = self._msg_entry(src, dst, 0, msg)
        if self._conns.get(node) is None:
            # first contact (or a dropped conn): establish NOW so a down
            # peer is discovered -- and marked unreachable -- by the
            # send that hit it, exactly like the per-message path
            if await self._try_establish(node) is None:
                if lossless:
                    self._spawn_reconnect(node)  # queued; keep dialing
                return
            # the establishing handshake may already have replayed a
            # lossless entry (it was queued first); the receiver's
            # watermark swallows the duplicate -- double-send is safe,
            # silent loss is not
        self._enqueue_cork(node, entry)

    async def send_messages(
        self, src: str, pairs: Iterable[Tuple[str, object]]
    ) -> None:
        """Multi-destination submit: publish a whole fan-out (every EC
        sub-op of one client write) in one call.  Sequential enqueues
        stay within one event-loop tick once connections exist, so each
        peer's cork queue gathers its share of the fan-out into a single
        burst."""
        for dst, msg in pairs:
            await self.send_message(src, dst, msg)

    @staticmethod
    def _join_entry(entry: _QueuedMsg) -> bytes:
        return b"".join(
            p if type(p) is bytes else bytes(p) for p in entry.parts)

    async def _send_lossy(self, node: str, payload: bytes) -> None:
        conn = self._conns.get(node)
        if conn is None:
            conn = await self._try_establish(node)
            if conn is None:
                return
        _, writer, lock, skey = conn
        rec = frame(self._seal(payload, skey))
        async with lock:
            try:
                writer.write(rec)
                await writer.drain()
                self._count_single(len(rec))
                self._unreachable.pop(node, None)
            except (ConnectionError, OSError):
                self._conns.pop(node, None)
                writer.close()
                # one reconnect attempt (peer may have restarted)
                conn = await self._try_establish(node)
                if conn is None:
                    return
                try:
                    rec = frame(self._seal(payload, conn[3]))
                    conn[1].write(rec)
                    await conn[1].drain()
                    self._count_single(len(rec))
                except (ConnectionError, OSError):
                    self._conns.pop(node, None)
                    conn[1].close()
                    self._unreachable[node] = \
                        asyncio.get_event_loop().time()

    async def _send_lossless(self, src: str, dst: str, node: str,
                             msg: object) -> None:
        """Queue-then-send with replay-on-reconnect (lossless peer);
        per-message write+drain -- the uncorked baseline path."""
        sess = self._sessions.setdefault(node, _SendSession())
        if sess.sent_bytes >= self.lossless_max_backlog:
            return  # honest bound: beyond the backlog, drop like lossy
        sess.out_seq += 1
        entry = self._msg_entry(src, dst, sess.out_seq, msg)
        sess.sent.append(entry)
        sess.sent_bytes += entry.nbytes
        payload = self._join_entry(entry)
        conn = self._conns.get(node)
        if conn is None:
            conn = await self._try_establish(node)
            if conn is None:
                # queued; keep dialing in the background
                self._spawn_reconnect(node)
                return
            # fall through and send: the establishing handshake may
            # already have replayed this payload (it was queued first),
            # in which case the receiver's watermark swallows the
            # duplicate -- double-send is safe, silent loss is not
        _, writer, lock, skey = conn
        async with lock:
            try:
                if self.fault.conn_kill_split(1) == 0:
                    writer.transport.abort()
                    raise ConnectionResetError("injected connection kill")
                rec = frame(self._seal(payload, skey))
                writer.write(rec)
                await writer.drain()
                self._count_single(len(rec))
                self._unreachable.pop(node, None)
            except (ConnectionError, OSError):
                self._conns.pop(node, None)
                writer.close()
                self._unreachable[node] = asyncio.get_event_loop().time()
                self._spawn_reconnect(node)

    def _count_single(self, nbytes: int) -> None:
        """Counter update for a one-frame write+drain (baseline path)."""
        self.counters["msgs_sent"] += 1
        self.counters["frames_sent"] += 1
        self.counters["bursts"] += 1
        self.counters["drains"] += 1
        self.counters["bytes_sent"] += nbytes

    @staticmethod
    def _seal(payload: bytes, session_key) -> bytes:
        if session_key is None:
            return payload
        from ceph_tpu.auth.cephx import sign

        return payload + sign(session_key, payload)

    @staticmethod
    def _unseal(rec: bytes, session_key) -> bytes:
        if session_key is None:
            return rec
        from ceph_tpu.auth.cephx import verify as _verify

        if len(rec) < _SIG_LEN:
            raise OSError("short signed frame")
        body, sig = rec[:-_SIG_LEN], rec[-_SIG_LEN:]
        if not _verify(session_key, body, sig):
            raise OSError("bad frame signature")
        return body

    async def probe(self, entity: str, timeout: float = 1.0) -> bool:
        """Liveness probe: can we (re)connect to the entity's node?
        Updates the unreachable set -- the heartbeat role."""
        node = self._node_of(entity)
        if node is None or entity in self._marked_down:
            return False
        # drop any cached connection: it may be a dead socket whose peer
        # was SIGKILLed -- a probe must test the wire, not the cache
        self._drop_conn(node)
        try:
            conn = await asyncio.wait_for(
                self._try_establish(node), timeout)
        except asyncio.TimeoutError:
            self._unreachable[node] = asyncio.get_event_loop().time()
            return False
        return conn is not None

    # -- liveness view (thrasher + _shard_up hooks) ------------------------

    def mark_down(self, name: str) -> None:
        self._marked_down.add(name)

    def mark_up(self, name: str) -> None:
        self._marked_down.discard(name)
        self._unreachable.pop(self._node_of(name) or name, None)

    def is_down(self, name: str) -> bool:
        if name in self._marked_down:
            return True
        node = self._node_of(name)
        if node is None:
            return False
        t = self._unreachable.get(node)
        if t is None:
            return False
        if asyncio.get_event_loop().time() - t > self._unreachable_ttl:
            # stale observation: still report down (a genuinely dead
            # peer must not flap back up on a timer) but re-probe in the
            # background -- a live peer clears itself, a dead one
            # refreshes the timestamp
            self._schedule_reprobe(node)
        return True

    def _schedule_reprobe(self, node: str) -> None:
        if node in self._reprobing:
            return
        self._reprobing.add(node)

        async def reprobe():
            try:
                await self.probe(node)
            finally:
                self._reprobing.discard(node)

        task = asyncio.get_event_loop().create_task(reprobe())
        self.adopt_task(f"reprobe.{node}", task)
