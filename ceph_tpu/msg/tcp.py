"""TCP messenger: the in-process bus semantics over real sockets.

Reference: src/msg/async/AsyncMessenger.{h,cc} with the posix NetworkStack
(src/msg/async/Stack.h:287, PosixStack.h) -- a listening socket per
daemon, cached outgoing connections, a banner handshake naming the peer
node, framed messages.  Policy is the reference's "lossy client": a send
to an unreachable peer is dropped and the peer marked unreachable; later
sends retry the connect, so a restarted daemon becomes reachable again
(the reconnect role of the lossless-peer policy, minus replay).

One ``TCPMessenger`` per process ("node").  A node hosts one or more
named entities (e.g. ``osd.3``); the address book maps every entity name
in the cluster to its node's (host, port).  Entity names co-hosted on
this node short-circuit delivery in process (the reference's local
fast-dispatch for self-sends, ECBackend.cc:2025-2032).

Frames on the socket are ``encoding.frame`` records (magic+len+crc32c)
whose payload is ``string src | string dst | encode_message(msg)``; the
first frame on every outgoing connection is a banner naming the sender
node and protocol version (Pipe.cc banner exchange).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Awaitable, Callable, Dict, Optional, Tuple

from ceph_tpu.msg.wire import decode_message, encode_message
from ceph_tpu.osd.messenger import FaultInjector
from ceph_tpu.utils.encoding import Decoder, Encoder, frame, unframe

_PROTOCOL_VERSION = 2
_BANNER = "ceph-tpu-msgr"
_SIG_LEN = 16


async def _read_frame(reader: asyncio.StreamReader) -> Optional[bytes]:
    """Read one framed record off the stream; None on EOF/corruption."""
    try:
        header = await reader.readexactly(12)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    magic, length, crc = struct.unpack("<III", header)
    try:
        payload = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    rec, pos = unframe(header + payload, 0)
    return rec  # None if magic/crc check failed


class TCPMessenger:
    """API-compatible with ``osd.messenger.Messenger`` so OSDShard /
    ECBackend run unchanged over real sockets."""

    def __init__(
        self,
        node: str,
        addr_map: Dict[str, Tuple[str, int]],
        fault: Optional[FaultInjector] = None,
        keyring=None,
    ):
        #: this process's node name; must appear in addr_map for serving
        self.node = node
        self.addr_map = dict(addr_map)
        self.fault = fault if fault is not None else \
            FaultInjector.from_config()
        #: cephx-style auth: when a KeyRing is given, every connection
        #: must pass the mutual challenge-response handshake and every
        #: frame is signed with the derived session key (ms_sign_messages)
        self.keyring = keyring
        self._local_queues: Dict[str, asyncio.Queue] = {}
        self._dispatchers: Dict[str, Callable] = {}
        self._tasks: Dict[str, asyncio.Task] = {}
        #: cached outgoing connections per peer node: (reader, writer, lock)
        self._conns: Dict[str, Tuple] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        #: administratively dead entities (mark_down -- the thrasher hook)
        self._marked_down: set = set()
        #: peers whose last connect/send failed, with WHEN it failed:
        #: unreachability is a cached observation, not a verdict, and it
        #: expires -- a revived daemon whose boot races one failed
        #: connect must not be treated as down forever (its primary
        #: would otherwise refuse reads with "only N shards" while every
        #: peer is in fact alive)
        self._unreachable: dict = {}
        self._unreachable_ttl = 3.0
        self._reprobing: set = set()
        #: live incoming-connection handler tasks (cancelled on shutdown;
        #: Server.wait_closed would otherwise block on them forever)
        self._serve_tasks: set = set()
        #: inbound dispatch byte budget (DispatchThrottler /
        #: osd_client_message_size_cap, default 500 MiB): budget is held
        #: from socket read until the dispatcher finishes, so a flood of
        #: large messages back-pressures the senders' sockets instead of
        #: ballooning memory
        from ceph_tpu.utils.config import get_config
        from ceph_tpu.utils.throttle import Throttle

        try:
            cap = int(get_config().get_val("osd_client_message_size_cap"))
        except (KeyError, ValueError, TypeError):
            cap = 500 * 1024 * 1024
        self.dispatch_throttle = Throttle(f"{node}.msgr-dispatch", cap)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        host, port = self.addr_map[self.node]
        self._server = await asyncio.start_server(
            self._serve_connection, host, port
        )

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
        for conn in self._conns.values():
            conn[1].close()
        self._conns.clear()
        pending = list(self._tasks.values()) + list(self._serve_tasks)
        for task in pending:
            task.cancel()
        for task in pending:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        if self._server is not None:
            await self._server.wait_closed()

    # -- entity registration (same surface as the in-process bus) ----------

    def register(
        self, name: str, dispatcher: Callable[[str, object], Awaitable[None]]
    ) -> None:
        self._local_queues[name] = asyncio.Queue()
        self._dispatchers[name] = dispatcher
        self._tasks[name] = asyncio.get_event_loop().create_task(
            self._dispatch_loop(name)
        )

    def adopt_task(self, name: str, task: "asyncio.Task") -> None:
        # completed tasks prune themselves (per-op tasks would otherwise
        # accumulate without bound on a long-lived daemon)
        self._tasks[name] = task
        task.add_done_callback(
            lambda t, name=name: self._tasks.pop(name, None)
            if self._tasks.get(name) is t else None
        )

    async def _dispatch_loop(self, name: str) -> None:
        queue = self._local_queues[name]
        while True:
            item = await queue.get()
            src, msg = item[0], item[1]
            cost = item[2] if len(item) > 2 else 0
            released = [False]

            def release(released=released, cost=cost):
                if not released[0]:
                    released[0] = True
                    self.dispatch_throttle.put(cost)

            claimed = [False]
            if cost and isinstance(msg, dict) and "op" in msg:
                # budget hand-off: a dispatcher that only ENQUEUES the
                # op (OSDShard's QoS queue) may claim the budget and
                # release it when the op actually executes -- that is
                # what makes the byte cap a real memory bound for
                # daemons instead of a transit-only throttle.  Blocking
                # here instead would deadlock: sub-op replies for
                # in-flight ops arrive through this same loop.
                msg["_budget_release"] = release
                msg["_budget_claim"] = (
                    lambda claimed=claimed: claimed.__setitem__(0, True))
            try:
                if name in self._marked_down:
                    continue
                try:
                    await self._dispatchers[name](src, msg)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 -- a dispatcher crash
                    # must not kill the loop (reference logs and drops)
                    import sys
                    import traceback

                    traceback.print_exc(file=sys.stderr)
            finally:
                if isinstance(msg, dict):
                    msg.pop("_budget_claim", None)
                if cost and not claimed[0]:
                    if isinstance(msg, dict):
                        msg.pop("_budget_release", None)
                    release()

    # -- server side -------------------------------------------------------

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        self._serve_tasks.add(task)
        try:
            await self._serve_connection_inner(reader, writer)
        finally:
            self._serve_tasks.discard(task)
            writer.close()

    async def _serve_connection_inner(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        banner = await _read_frame(reader)
        if banner is None:
            writer.close()
            return
        dec = Decoder(banner)
        if dec.string() != _BANNER or dec.varint() != _PROTOCOL_VERSION:
            writer.close()  # protocol mismatch: refuse (reference -EXDEV)
            return
        peer_node = dec.string()
        client_nonce = dec.blob()
        session_key = None
        if self.keyring is not None:
            session_key = await self._auth_accept(
                reader, writer, peer_node, client_nonce
            )
            if session_key is None:
                writer.close()  # failed handshake: refuse (-EACCES)
                return
        self._unreachable.pop(peer_node, None)
        # the peer (re)connected: any cached outgoing connection to it may
        # be a dead socket from its previous incarnation (writes into one
        # are silently buffered by TCP, losing replies) -- drop it so the
        # next send dials the live process (reference: lossy policy
        # reconnect, Pipe.cc replaces the old session on accept)
        stale = self._conns.pop(peer_node, None)
        if stale is not None:
            stale[1].close()
        while True:
            rec = await _read_frame(reader)
            if rec is None:
                break
            if session_key is not None:
                if len(rec) < _SIG_LEN:
                    break
                from ceph_tpu.auth.cephx import verify as _verify

                rec, sig = rec[:-_SIG_LEN], rec[-_SIG_LEN:]
                if not _verify(session_key, rec, sig):
                    break  # forged/tampered frame: drop the connection
            dec = Decoder(rec)
            src = dec.string()
            dst = dec.string()
            msg = decode_message(dec.blob())
            queue = self._local_queues.get(dst)
            if queue is not None and dst not in self._marked_down:
                if isinstance(msg, dict) and msg.get("op") == "client_op":
                    # throttle CLIENT ops only (the reference's
                    # DispatchThrottler guards the client messenger):
                    # sub-op replies must NEVER block here, or claimed
                    # client budget could wait on replies that are
                    # themselves stuck behind the throttle -- a
                    # distributed deadlock
                    cost = len(rec)
                    await self.dispatch_throttle.get(cost)
                    await queue.put((src, msg, cost))
                else:
                    await queue.put((src, msg))
        writer.close()

    async def _auth_accept(self, reader, writer, peer_node: str,
                           client_nonce: bytes):
        """Acceptor half of the cephx-style handshake; returns the
        session key, or None to refuse."""
        from ceph_tpu.auth.cephx import AuthHandshake

        secret = self.keyring.get(peer_node)
        if secret is None or not client_nonce:
            return None  # unknown entity / peer not speaking auth
        hs = AuthHandshake(secret, client_nonce, AuthHandshake.new_nonce())
        writer.write(frame(
            Encoder().blob(hs.server_nonce).blob(hs.server_proof()).bytes()
        ))
        await writer.drain()
        reply = await _read_frame(reader)
        if reply is None:
            return None
        if not hs.verify_client(Decoder(reply).blob()):
            return None
        return hs.session_key()

    # -- client side -------------------------------------------------------

    def _node_of(self, entity: str) -> Optional[str]:
        """The node hosting an entity: itself if it has an address, else
        its 'osd.N'-style name IS the node name in the default layout."""
        return entity if entity in self.addr_map else None

    async def _connect(self, node: str):
        from ceph_tpu.auth.cephx import AuthHandshake

        host, port = self.addr_map[node]
        reader, writer = await asyncio.open_connection(host, port)
        nonce = AuthHandshake.new_nonce() if self.keyring is not None else b""
        banner = (
            Encoder().string(_BANNER).varint(_PROTOCOL_VERSION)
            .string(self.node).blob(nonce).bytes()
        )
        writer.write(frame(banner))
        await writer.drain()
        session_key = None
        if self.keyring is not None:
            secret = self.keyring.get(self.node)
            if secret is None:
                writer.close()
                raise OSError(f"no key for {self.node} in keyring")
            try:
                # a no-auth peer never answers the handshake: time out
                # with a clear error instead of hanging every send
                reply = await asyncio.wait_for(_read_frame(reader), 3.0)
            except asyncio.TimeoutError:
                writer.close()
                raise OSError(
                    f"{node} did not answer the auth handshake "
                    "(auth-mode mismatch?)"
                )
            if reply is None:
                writer.close()
                raise OSError(f"auth refused by {node}")
            dec = Decoder(reply)
            server_nonce = dec.blob()
            hs = AuthHandshake(secret, nonce, server_nonce)
            if not hs.verify_server(dec.blob()):
                writer.close()
                raise OSError(f"{node} failed to prove keyring knowledge")
            writer.write(frame(Encoder().blob(hs.client_proof()).bytes()))
            await writer.drain()
            session_key = hs.session_key()
        return reader, writer, asyncio.Lock(), session_key

    async def send_message(self, src: str, dst: str, msg: object) -> None:
        if src in self._marked_down or dst in self._marked_down:
            return
        # local short-circuit
        queue = self._local_queues.get(dst)
        if queue is not None:
            if self.fault.maybe_drop():
                return
            await self.fault.maybe_delay()
            await queue.put((src, msg))
            return
        node = self._node_of(dst)
        if node is None:
            return  # unknown peer: lossy
        if self.fault.maybe_drop():
            return
        await self.fault.maybe_delay()
        payload = (
            Encoder().string(src).string(dst)
            .blob(encode_message(msg)).bytes()
        )
        conn = self._conns.get(node)
        if conn is None:
            try:
                conn = await self._connect(node)
            except OSError:
                self._unreachable[node] = asyncio.get_event_loop().time()
                return
            self._conns[node] = conn
            self._unreachable.pop(node, None)
        _, writer, lock, skey = conn
        rec = frame(self._seal(payload, skey))
        async with lock:
            try:
                writer.write(rec)
                await writer.drain()
                self._unreachable.pop(node, None)
            except (ConnectionError, OSError):
                self._conns.pop(node, None)
                writer.close()
                # one reconnect attempt (peer may have restarted)
                try:
                    conn = await self._connect(node)
                    self._conns[node] = conn
                    rec = frame(self._seal(payload, conn[3]))
                    conn[1].write(rec)
                    await conn[1].drain()
                    self._unreachable.pop(node, None)
                except OSError:
                    self._unreachable[node] = asyncio.get_event_loop().time()

    @staticmethod
    def _seal(payload: bytes, session_key) -> bytes:
        if session_key is None:
            return payload
        from ceph_tpu.auth.cephx import sign

        return payload + sign(session_key, payload)

    async def probe(self, entity: str, timeout: float = 1.0) -> bool:
        """Liveness probe: can we (re)connect to the entity's node?
        Updates the unreachable set -- the heartbeat role."""
        node = self._node_of(entity)
        if node is None or entity in self._marked_down:
            return False
        # drop any cached connection: it may be a dead socket whose peer
        # was SIGKILLed -- a probe must test the wire, not the cache
        old = self._conns.pop(node, None)
        if old is not None:
            old[1].close()
        try:
            conn = await asyncio.wait_for(self._connect(node), timeout)
        except (OSError, asyncio.TimeoutError):
            self._unreachable[node] = asyncio.get_event_loop().time()
            return False
        self._conns[node] = conn
        self._unreachable.pop(node, None)
        return True

    # -- liveness view (thrasher + _shard_up hooks) ------------------------

    def mark_down(self, name: str) -> None:
        self._marked_down.add(name)

    def mark_up(self, name: str) -> None:
        self._marked_down.discard(name)
        self._unreachable.pop(self._node_of(name) or name, None)

    def is_down(self, name: str) -> bool:
        if name in self._marked_down:
            return True
        node = self._node_of(name)
        if node is None:
            return False
        t = self._unreachable.get(node)
        if t is None:
            return False
        if asyncio.get_event_loop().time() - t > self._unreachable_ttl:
            # stale observation: still report down (a genuinely dead
            # peer must not flap back up on a timer) but re-probe in the
            # background -- a live peer clears itself, a dead one
            # refreshes the timestamp
            self._schedule_reprobe(node)
        return True

    def _schedule_reprobe(self, node: str) -> None:
        if node in self._reprobing:
            return
        self._reprobing.add(node)

        async def reprobe():
            try:
                await self.probe(node)
            finally:
                self._reprobing.discard(node)

        task = asyncio.get_event_loop().create_task(reprobe())
        self.adopt_task(f"reprobe.{node}", task)
