"""Communication layer (reference: src/msg -- Messenger/Connection stack).

* in-process bus: ``ceph_tpu.osd.messenger.Messenger`` (asyncio queues)
* real transport: ``ceph_tpu.msg.tcp.TCPMessenger`` (loopback/LAN TCP with
  framed, crc-guarded typed messages -- the AsyncMessenger posix-stack role)
* wire codecs: ``ceph_tpu.msg.wire``
"""

from ceph_tpu.msg.tcp import TCPMessenger
from ceph_tpu.msg.wire import decode_message, encode_message

__all__ = ["TCPMessenger", "encode_message", "decode_message"]
