"""Mesh-path benchmark: cluster-path scaling vs mesh shard count.

The cluster-path bench (``msg/cluster_bench.py``) measures the wire
architecture at a fixed topology; this stage measures what ROADMAP
item 1 is for -- how the SAME full-stack path (client Objecter ->
primary OSD -> k+m sub-op fan-out over real localhost TCP) scales as
the OSD data plane is sharded over a growing device mesh
(``osd_mesh_data_plane``, ``ceph_tpu/parallel/mesh_plane.py``):

* ``tcp_only``   -- the A/B baseline: plane off, every chunk payload
  serialized through the corked TCP messenger;
* ``mesh_N``     -- the plane spans N devices, the first N OSDs are
  mesh-bound: their coalesced encode batches ride ONE PG-sliced SPMD
  dispatch and chunk payloads destined for them cross as delivery-board
  references (tiny frames) instead of serialized bytes.

As N grows, more of the fan-out's payload bytes leave the wire --
``wire_bytes_avoided`` (board claims) rises and the messengers'
``bytes_sent`` falls -- which is exactly the per-op host/wire gap
BENCH_r05 measured on cluster_path ("Understanding System
Characteristics of Online Erasure Coding": the wire fan-out, not the
codec, dominates online EC).  A separate encode-only stage times the
PG-sliced SPMD dispatch itself at each mesh size.

Correctness-gated like every bench stage: every cycle round-trips every
payload bit-exactly, stored shard bytes must be identical across every
configuration, wire-bytes-avoided must be monotone in N, and the timed
write pass must run at ZERO steady-state retraces (the PR-8 ledger
contract -- the encode bucket ladder is pre-warmed, so a retrace in the
timed region means the bucketing regressed).

Used by bench.py (``mesh_path_*`` headline keys),
``tools/ec_benchmark.py --workload mesh-path``, the MULTICHIP dryrun
harness, and the tier-1 smoke gate (tests/test_mesh_plane.py) at tiny
shapes.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ceph_tpu.msg.cluster_bench import ClusterHarness, make_payloads


def _warm_encode_buckets(plane, ec, chunk_bytes: int) -> None:
    """Compile the encode program ladder OUTSIDE the timed region --
    both dispatch lanes (fused shard_map for balanced batches, per-slot
    mesh-local for partial ones), every pow2 rows bucket, every slot --
    so the steady-state pass retraces nothing (the gate below)."""
    k = ec.get_data_chunk_count()
    for rows in (1, 2, 4, 8):
        # fused lane: every slot occupied at this rows bucket
        n = plane.n_devices * rows
        blocks = [np.zeros((k, chunk_bytes), np.uint8) for _ in range(n)]
        plane.encode_shard_major_many(ec, blocks, list(range(n)))
        # slot lane: each slot alone (per-device programs compile
        # separately on some backends)
        for slot in range(plane.n_devices):
            blocks = [np.zeros((k, chunk_bytes), np.uint8)
                      for _ in range(rows)]
            plane.encode_shard_major_many(ec, blocks, [slot] * rows)


async def _one_cycle(ec, n_osds: int, payloads: Dict[str, bytes],
                     writers: int, plane) -> dict:
    """One full-stack write+read cycle over real TCP; returns walls,
    wire counters, stored shard bytes, and the steady-retrace delta of
    the timed write pass."""
    from ceph_tpu.analysis import residency

    h = ClusterHarness(ec, n_osds, cork=True)
    await h.start()
    try:
        for oid in payloads:
            h.objecter.acting_set(oid)  # placement outside the timing
        if plane is not None:
            chunk = len(next(iter(payloads.values()))) \
                // ec.get_data_chunk_count()
            _warm_encode_buckets(plane, ec, chunk)
        # warm pass: connections, handshakes, and every jit bucket
        await h.run_writes(dict(payloads), writers)
        before = residency.counters().snapshot()
        write_s = await h.run_writes(dict(payloads), writers)
        after = residency.counters().snapshot()
        read_s, got = await h.run_reads(payloads, writers)
        for oid, data in payloads.items():
            if got.get(oid) != data:
                raise AssertionError(
                    f"mesh-path: read-back of {oid} mismatched")
        counters = h.wire_counters()
        shards = h.shard_bytes()
    finally:
        await h.shutdown()
    nbytes = sum(len(p) for p in payloads.values())
    return {
        "wall_write_s": round(write_s, 6),
        "wall_read_s": round(read_s, 6),
        "write_MiBs": round(nbytes / write_s / (1 << 20), 3),
        "wire_bytes_sent": counters.get("bytes_sent", 0),
        "wire_msgs_sent": counters.get("msgs_sent", 0),
        "steady_jit_retraces":
            after["jit_retraces"] - before["jit_retraces"],
        "_shards": shards,
    }


def _encode_stage(ec, plane, n_stripes: int, chunk_bytes: int,
                  iters: int) -> float:
    """PG-sliced SPMD encode throughput (GiB/s) at this mesh size: the
    coalescer's fused dispatch isolated from the wire."""
    k = ec.get_data_chunk_count()
    rng = np.random.RandomState(7)
    blocks = [rng.randint(0, 256, size=(k, chunk_bytes), dtype=np.uint8)
              for _ in range(n_stripes)]
    pgids = list(range(n_stripes))
    plane.encode_shard_major_many(ec, blocks, pgids)  # warm/compile
    nbytes = sum(b.nbytes for b in blocks)
    t0 = time.perf_counter()
    for _ in range(iters):
        plane.encode_shard_major_many(ec, blocks, pgids)
    dt = time.perf_counter() - t0
    return iters * nbytes / dt / (1 << 30)


def run_mesh_path_bench(
    *, n_objects: int = 48, obj_bytes: int = 32 << 10, writers: int = 8,
    mesh_sizes: Sequence[int] = (1, 2, 4, 8), iters: int = 1,
    k: int = 2, m: int = 2, seed: int = 1717,
    encode_stripes: int = 32,
) -> dict:
    """Sweep the mesh shard count over the full TCP cluster path and
    the encode-only dispatch; returns the JSON-ready dict.  Raises on
    any correctness-gate violation (bit-exactness, cross-config shard
    bytes, wire-avoided monotonicity, steady retraces)."""
    from ceph_tpu.parallel import mesh_plane
    from ceph_tpu.plugins import registry as registry_mod
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    prior_gate = bool(cfg.get_val("osd_mesh_data_plane"))
    n_osds = k + m
    payloads = make_payloads(n_objects, obj_bytes, seed)
    chunk_bytes = obj_bytes // k

    def _fresh_ec():
        return registry_mod.instance().factory(
            "tpu", {"technique": "reed_sol_van",
                    "k": str(k), "m": str(m)}, "")

    results: Dict[str, dict] = {}
    avoided: Dict[str, int] = {}
    encode_gibps: Dict[str, Optional[float]] = {}
    shards: Dict[str, dict] = {}
    try:
        # -- A/B baseline: plane off, every byte over TCP --------------
        cfg.set_val("osd_mesh_data_plane", False)
        mesh_plane.reset()
        loop = asyncio.new_event_loop()
        try:
            best = None
            for _ in range(max(1, iters)):
                r = loop.run_until_complete(_one_cycle(
                    _fresh_ec(), n_osds, payloads, writers, None))
                shards["tcp_only"] = r.pop("_shards")
                if best is None or r["wall_write_s"] < best["wall_write_s"]:
                    best = r
            results["tcp_only"] = best
            avoided["tcp_only"] = 0
        finally:
            loop.close()

        # -- mesh sweep ------------------------------------------------
        cfg.set_val("osd_mesh_data_plane", True)
        for n in mesh_sizes:
            plane = mesh_plane.configure(n)
            name = f"mesh_{n}"
            loop = asyncio.new_event_loop()
            try:
                best = None
                for _ in range(max(1, iters)):
                    r = loop.run_until_complete(_one_cycle(
                        _fresh_ec(), n_osds, payloads, writers, plane))
                    shards[name] = r.pop("_shards")
                    if best is None or \
                            r["wall_write_s"] < best["wall_write_s"]:
                        best = r
                results[name] = best
            finally:
                loop.close()
            avoided[name] = plane.counters["mesh_wire_bytes_avoided"]
            encode_gibps[name] = _encode_stage(
                _fresh_ec(), plane, encode_stripes, chunk_bytes,
                max(1, iters))
            results[name]["sharding_builds"] = plane.sharding_builds
            results[name]["board"] = plane.board.stats()
    finally:
        cfg.set_val("osd_mesh_data_plane", prior_gate)
        mesh_plane.reset()

    # -- gates ---------------------------------------------------------
    base_key = "tcp_only"
    for name, stored in shards.items():
        if set(stored) != set(shards[base_key]):
            raise AssertionError(
                f"mesh-path: shard sets differ ({name} vs {base_key})")
        for key in stored:
            if stored[key] != shards[base_key][key]:
                raise AssertionError(
                    f"mesh-path: shard {key} differs between {name} "
                    f"and {base_key}")
    last = -1
    for n in mesh_sizes:
        cur = avoided[f"mesh_{n}"]
        if cur < last:
            raise AssertionError(
                "mesh-path: wire_bytes_avoided not monotone in mesh "
                f"size (mesh_{n}: {cur} < {last})")
        last = cur
    steady = sum(r.get("steady_jit_retraces", 0)
                 for r in results.values())
    if steady:
        raise AssertionError(
            f"mesh-path: {steady} steady-state retraces in the timed "
            "write pass (the bucket ladder must cover every shape)")

    walls = {name: r["wall_write_s"] for name, r in results.items()}
    sizes = list(mesh_sizes)
    first, biggest = f"mesh_{sizes[0]}", f"mesh_{max(sizes)}"
    speedup_vs_first = {
        f"mesh_{n}": round(walls[first] / walls[f"mesh_{n}"], 3)
        for n in sizes if walls.get(f"mesh_{n}")
    }
    return {
        "n_objects": n_objects,
        "obj_bytes": obj_bytes,
        "writers": writers,
        "k": k,
        "m": m,
        "mesh_sizes": sizes,
        "bit_exact": True,  # the gates raised otherwise
        "results": results,
        "wire_bytes_avoided": avoided,
        "wire_bytes_sent": {
            name: r["wire_bytes_sent"] for name, r in results.items()},
        "encode_GiBs": encode_gibps,
        "write_MiBs": {
            name: r["write_MiBs"] for name, r in results.items()},
        "speedup_vs_mesh1": speedup_vs_first,
        "speedup_4x": speedup_vs_first.get("mesh_4"),
        "speedup_max": round(walls[first] / walls[biggest], 3)
        if walls.get(biggest) else None,
        "tcp_only_vs_mesh_max": round(
            walls["tcp_only"] / walls[biggest], 3)
        if walls.get(biggest) else None,
        "steady_jit_retraces": steady,
    }
