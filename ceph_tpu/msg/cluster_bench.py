"""Cluster-path benchmark: the EC write/read cycle over REAL TCP sockets.

The storage-path bench (``osd/storage_bench.py``) measures the host codec
cycle in-process; this stage measures the DISTRIBUTED path the ROADMAP
north star actually serves: a client Objecter sends each op over
localhost TCP to the primary OSD daemon, which fans k+m EC sub-ops out to
its peers over lossless OSD<->OSD connections and gathers the commit
quorum -- every byte crossing a real socket through ``msg/tcp.py``.

Two wire modes, same daemons, same payloads:

* ``cork=False`` -- the per-message baseline: one frame join + one
  ``writer.write`` + one ``drain()`` per message, one standalone ACK
  frame + drain per received lossless message (the pre-round-8 shape);
* ``cork=True``  -- corked scatter-gather: per-connection frame queues
  flushed as single ``writelines`` bursts, zero-copy part-list payloads,
  piggybacked/batched cumulative acks.

Bit-exactness is gated BEFORE timing: both modes must store identical
shard bytes and round-trip every payload.  The JSON result carries the
wall times plus the messenger wire-shape counters (frames per burst,
bytes per drain, piggybacked-ack ratio) summed over every daemon.

Used by bench.py (round JSON fields ``cluster_path_host_*``),
``tools/ec_benchmark.py --workload cluster-path``, and the tier-1 smoke
gate (tests/test_cluster_path.py) at tiny shapes.
"""

from __future__ import annotations

import asyncio
import socket
import time
from typing import Dict, List, Optional

import numpy as np


def free_ports(n: int) -> List[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def make_payloads(n_objects: int, obj_bytes: int, seed: int = 0
                  ) -> Dict[str, bytes]:
    rng = np.random.RandomState(seed)
    return {
        f"cp{i}": rng.randint(0, 256, size=obj_bytes,
                              dtype=np.uint8).tobytes()
        for i in range(n_objects)
    }


class ClusterHarness:
    """One localhost TCP cluster: n_osds OSDShard daemons (each on its
    own TCPMessenger/port) + a client Objecter messenger."""

    def __init__(self, ec, n_osds: int, *, cork: bool,
                 pool: str = "ecpool"):
        self.ec = ec
        self.n_osds = n_osds
        self.cork = cork
        self.pool = pool
        self.messengers = []
        self.osds = []
        self.client = None
        self.objecter = None

    async def start(self) -> None:
        from ceph_tpu.msg.fault import FaultInjector
        from ceph_tpu.msg.tcp import TCPMessenger
        from ceph_tpu.osd.placement import CrushPlacement
        from ceph_tpu.osd.shard import OSDShard

        ports = free_ports(self.n_osds + 1)
        addr = {f"osd.{i}": ("127.0.0.1", ports[i])
                for i in range(self.n_osds)}
        addr["client"] = ("127.0.0.1", ports[self.n_osds])
        km = self.ec.get_chunk_count()
        placement = CrushPlacement(self.n_osds, km)
        for i in range(self.n_osds):
            m = TCPMessenger(f"osd.{i}", addr, fault=FaultInjector(),
                             cork=self.cork)
            await m.start()
            shard = OSDShard(i, m)
            shard.host_pool(self.pool, self.ec, self.n_osds, placement)
            self.messengers.append(m)
            self.osds.append(shard)
        self.client = TCPMessenger("client", addr, fault=FaultInjector(),
                                   cork=self.cork)
        await self.client.start()
        from ceph_tpu.osd.objecter import Objecter

        self.objecter = Objecter(self.client, km, self.n_osds,
                                 placement=placement, pool=self.pool)
        self.messengers.append(self.client)

    async def run_writes(self, payloads: Dict[str, bytes],
                         writers: int, batch: int = 0) -> float:
        """Write every payload with ``writers`` concurrent client
        workers; returns the wall time.  ``batch`` > 1 drives the
        vectorized submit path: each worker hands ``batch``-sized op
        chunks to ``Objecter.write_many`` -- one submit stage crossing
        and one wire burst per chunk instead of per op."""
        queue = list(payloads.items())
        t0 = time.perf_counter()

        async def worker():
            while queue:
                if batch > 1:
                    chunk = [queue.pop() for _ in
                             range(min(batch, len(queue)))]
                    if chunk:
                        await self.objecter.write_many(chunk)
                else:
                    oid, data = queue.pop()
                    await self.objecter.write(oid, data)

        await asyncio.gather(*(worker() for _ in range(max(1, writers))))
        return time.perf_counter() - t0

    async def run_reads(self, payloads: Dict[str, bytes],
                        readers: int, batch: int = 0) -> tuple:
        """Read every object back; returns (wall, {oid: bytes})."""
        queue = list(payloads)
        got: Dict[str, bytes] = {}
        t0 = time.perf_counter()

        async def worker():
            while queue:
                if batch > 1:
                    chunk = [queue.pop() for _ in
                             range(min(batch, len(queue)))]
                    if chunk:
                        for oid, data in zip(
                                chunk, await self.objecter.read_many(chunk)):
                            got[oid] = data
                else:
                    oid = queue.pop()
                    got[oid] = await self.objecter.read(oid)

        await asyncio.gather(*(worker() for _ in range(max(1, readers))))
        return time.perf_counter() - t0, got

    def shard_bytes(self) -> Dict[tuple, bytes]:
        """Every stored shard object's data bytes (the bit-exactness
        contract; attrs carry version stamps and are excluded)."""
        out = {}
        for osd in self.osds:
            for soid in osd.store.list_objects():
                if soid.rpartition("@")[2] == "meta":
                    continue
                out[(osd.osd_id, soid)] = osd.store.read(soid)
        return out

    def wire_counters(self) -> Dict[str, int]:
        """Messenger wire-shape counters summed over every daemon."""
        total: Dict[str, int] = {}
        for m in self.messengers:
            for key, val in m.counters.items():
                total[key] = total.get(key, 0) + val
        return total

    async def shutdown(self) -> None:
        for m in self.messengers:
            await m.shutdown()


class WireHarness:
    """Messenger-level stage: the k+m sub-op fan-out message shape over
    real sockets, with the OSD op pipeline out of the way.

    One ``primary`` messenger fans a shard-sized payload out to every
    peer (the ECSubWrite shape: one message per peer per op, lossless
    OSD<->OSD policy) and an op completes when every peer's reply
    arrives -- the commit-quorum round trip.  ``inflight`` models a
    loaded primary (many PGs, many concurrent client ops), which is
    what gives the per-peer cork queues real bursts to gather.  This is
    the stage where the corked/zero-copy architecture is isolated from
    the (mode-independent) codec and OSD costs the full-stack stage
    also pays."""

    def __init__(self, n_peers: int, *, cork: bool):
        self.n_peers = n_peers
        self.cork = cork
        self.messengers = []
        self.primary = None
        self._replies: Dict[int, int] = {}
        self._done: Dict[int, asyncio.Future] = {}

    async def start(self) -> None:
        from ceph_tpu.msg.fault import FaultInjector
        from ceph_tpu.msg.tcp import TCPMessenger

        ports = free_ports(self.n_peers + 1)
        addr = {f"osd.{i}": ("127.0.0.1", ports[i])
                for i in range(self.n_peers + 1)}
        # peers echo a tiny committed-reply per received sub-op payload
        for i in range(1, self.n_peers + 1):
            m = TCPMessenger(f"osd.{i}", addr, fault=FaultInjector(),
                             cork=self.cork)
            await m.start()

            async def echo(src, msg, m=m):
                await m.send_message(m.node, src, ("committed", msg[0]))

            m.register(f"osd.{i}", echo)
            self.messengers.append(m)
        self.primary = TCPMessenger("osd.0", addr, fault=FaultInjector(),
                                    cork=self.cork)
        await self.primary.start()

        async def gather(src, msg):
            tid = msg[1]
            left = self._replies.get(tid, 0) - 1
            self._replies[tid] = left
            if left <= 0:
                fut = self._done.pop(tid, None)
                if fut is not None and not fut.done():
                    fut.set_result(True)

        self.primary.register("osd.0", gather)
        self.messengers.append(self.primary)

    async def run_ops(self, n_ops: int, shard_bytes: int,
                      inflight: int) -> float:
        """``n_ops`` fan-out/commit rounds with ``inflight`` concurrent
        ops; returns the wall."""
        payload = bytes(shard_bytes)
        loop = asyncio.get_event_loop()
        queue = list(range(n_ops))
        t0 = time.perf_counter()

        async def op_worker():
            while queue:
                tid = queue.pop()
                self._replies[tid] = self.n_peers
                fut = self._done[tid] = loop.create_future()
                await self.primary.send_messages("osd.0", [
                    (f"osd.{i}", (tid, s, payload))
                    for s, i in enumerate(range(1, self.n_peers + 1))
                ])
                await fut
                self._replies.pop(tid, None)

        await asyncio.gather(*(op_worker() for _ in range(inflight)))
        return time.perf_counter() - t0

    async def shutdown(self) -> None:
        for m in self.messengers:
            await m.shutdown()


async def _wire_cycle(n_peers: int, n_ops: int, shard_bytes: int,
                      inflight: int, *, cork: bool) -> dict:
    h = WireHarness(n_peers, cork=cork)
    await h.start()
    try:
        # warm: connections + session handshakes outside the timed region
        await h.run_ops(max(4, inflight), shard_bytes, inflight)
        wall = await h.run_ops(n_ops, shard_bytes, inflight)
        counters = {}
        for m in h.messengers:
            for key, val in m.counters.items():
                counters[key] = counters.get(key, 0) + val
    finally:
        await h.shutdown()
    msgs = n_ops * n_peers
    return {
        "wall_write_s": round(wall, 6),
        "msgs_per_s": round(2 * msgs / wall),  # sub-ops + replies
        "sub_op_bytes": shard_bytes,
        "inflight": inflight,
        "counters": dict(counters, **_counter_ratios(counters)),
    }


def _counter_ratios(c: Dict[str, int]) -> Dict[str, float]:
    acks = c.get("acks_piggybacked", 0) + c.get("acks_standalone", 0)
    return {
        "frames_per_burst": round(
            c["frames_sent"] / c["bursts"], 3) if c.get("bursts") else None,
        "bytes_per_drain": round(
            c["bytes_sent"] / c["drains"], 1) if c.get("drains") else None,
        "ack_piggyback_ratio": round(
            c.get("acks_piggybacked", 0) / acks, 3) if acks else None,
    }


async def _one_cycle(ec, n_osds: int, payloads: Dict[str, bytes],
                     writers: int, *, cork: bool) -> dict:
    h = ClusterHarness(ec, n_osds, cork=cork)
    await h.start()
    try:
        # warm the CRUSH placement cache outside the timed region (pure
        # host math, identical in both modes -- the wire is what this
        # stage measures; a real cluster computes placement from a
        # long-lived map, not per first-touch)
        for oid in payloads:
            h.objecter.acting_set(oid)
        write_s = await h.run_writes(payloads, writers)
        read_s, got = await h.run_reads(payloads, writers)
        for oid, data in payloads.items():
            if got.get(oid) != data:
                raise AssertionError(
                    f"cluster-path: read-back of {oid} mismatched")
        counters = h.wire_counters()
        shards = h.shard_bytes()
    finally:
        await h.shutdown()
    nbytes = sum(len(p) for p in payloads.values())
    return {
        "wall_write_s": round(write_s, 6),
        "wall_read_s": round(read_s, 6),
        "write_MiBs": round(nbytes / write_s / (1 << 20), 3),
        "read_MiBs": round(nbytes / read_s / (1 << 20), 3),
        "counters": dict(counters, **_counter_ratios(counters)),
        "_shards": shards,
    }


def run_cluster_path_bench(ec, *, n_objects: int = 64,
                           obj_bytes: int = 16 << 10, writers: int = 8,
                           iters: int = 2, seed: int = 4321,
                           n_osds: Optional[int] = None) -> dict:
    """Full comparison: per-message vs corked over real localhost TCP,
    bit-exactness gated (read-back inside every cycle + shard bytes
    compared across modes), best-of-``iters`` walls; returns the
    JSON-ready dict."""
    if n_osds is None:
        n_osds = ec.get_chunk_count()
    payloads = make_payloads(n_objects, obj_bytes, seed)
    loop = asyncio.new_event_loop()
    best: Dict[str, dict] = {}
    shards: Dict[str, dict] = {}
    try:
        for mode, cork in (("per_message", False), ("corked", True)):
            for it in range(max(1, iters)):
                r = loop.run_until_complete(_one_cycle(
                    ec, n_osds, payloads, writers, cork=cork))
                shards[mode] = r.pop("_shards")
                if mode not in best or \
                        r["wall_write_s"] < best[mode]["wall_write_s"]:
                    best[mode] = r
    finally:
        loop.close()
    # bit-exactness across modes: identical shard bytes, object for
    # object (read-back equality was already gated inside each cycle)
    if set(shards["per_message"]) != set(shards["corked"]):
        raise AssertionError("cluster-path: shard sets differ across modes")
    for key in shards["per_message"]:
        if shards["per_message"][key] != shards["corked"][key]:
            raise AssertionError(
                f"cluster-path: shard {key} differs between corked and "
                "per-message modes")
    # messenger-level wire stage: same fan-out shape (k+m sub-ops +
    # commit replies per op), shard-sized payloads, loaded-primary
    # concurrency -- the corked-vs-per-message architecture isolated
    # from the mode-independent codec/OSD costs above
    k = ec.get_data_chunk_count()
    m = ec.get_chunk_count() - k
    shard_bytes = max(1, obj_bytes // max(1, k))
    wire: Dict[str, dict] = {}
    loop = asyncio.new_event_loop()
    try:
        for mode, cork in (("per_message", False), ("corked", True)):
            for _ in range(max(1, iters)):
                r = loop.run_until_complete(_wire_cycle(
                    ec.get_chunk_count(), 4 * n_objects, shard_bytes,
                    4 * writers, cork=cork))
                if mode not in wire or \
                        r["wall_write_s"] < wire[mode]["wall_write_s"]:
                    wire[mode] = r
    finally:
        loop.close()
    per_msg, corked = best["per_message"], best["corked"]
    return {
        "n_objects": n_objects,
        "obj_bytes": obj_bytes,
        "writers": writers,
        "n_osds": n_osds,
        "k": k,
        "m": m,
        "bit_exact": True,  # the gates raised otherwise
        "per_message": per_msg,
        "corked": corked,
        "write_speedup": round(
            per_msg["wall_write_s"] / corked["wall_write_s"], 3)
        if corked["wall_write_s"] else None,
        "read_speedup": round(
            per_msg["wall_read_s"] / corked["wall_read_s"], 3)
        if corked["wall_read_s"] else None,
        "wire_per_message": wire["per_message"],
        "wire_corked": wire["corked"],
        "wire_write_speedup": round(
            wire["per_message"]["wall_write_s"]
            / wire["corked"]["wall_write_s"], 3)
        if wire["corked"]["wall_write_s"] else None,
    }
