"""Messenger fault injection (ms_inject_* analogue).

Reference: the ms_inject_socket_failures / ms_inject_delay knobs in
src/common/options.cc:735-756 drive the messenger layer directly.  This
module lives in ``ceph_tpu.msg`` because the TRANSPORT owns failure
injection; it predates the TCP messenger and used to live in
``ceph_tpu.osd.messenger`` (an osd -> msg layering inversion fixed in
round 8 -- the OSD layer re-exports it for compatibility).

Besides per-message drop/delay, the injector can kill a CONNECTION
mid-burst (``schedule_conn_kill``): the corked send path asks
``conn_kill_split`` how many frames of the next burst may be written
before the transport must be torn down, which is how the lossless-replay
tests manufacture a torn burst deterministically.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional


class FaultInjector:
    """ms_inject_* analogue; probabilities in [0, 1]."""

    def __init__(self, drop_probability: float = 0.0,
                 delay_probability: float = 0.0,
                 max_delay: float = 0.0, seed: int = 0):
        self.drop_probability = drop_probability
        self.delay_probability = delay_probability
        self.max_delay = max_delay
        self._rng = random.Random(seed)
        self.dropped = 0
        #: one-shot connection kill: abort the wire after this many more
        #: frames have been written (None = disarmed)
        self._conn_kill_countdown: Optional[int] = None
        self.conn_kills = 0
        #: one-shot primary kill in the dup-detection window: the OSD
        #: daemon consults this after a client op APPLIES but before the
        #: reply frame is sent (None = disarmed, "*" = any op kind)
        self._kill_after_apply: Optional[str] = None
        self.apply_kills = 0
        #: one-shot shared-memory ring tear: the Nth next ring record
        #: push publishes a TORN record (header out, body half-written
        #: -- the producer "died" mid-publish) and the producer writes
        #: nothing further (None = disarmed)
        self._ring_tear_countdown: Optional[int] = None
        self.ring_tears = 0

    @classmethod
    def from_config(cls) -> "FaultInjector":
        """Build from the ms_inject_* options AND track runtime changes
        through a config observer (reference: the injection knobs in
        src/common/options.cc drive the messenger directly and respond
        to injectargs; qa suites just set the config, before OR after
        the daemons boot)."""
        import weakref

        from ceph_tpu.utils.config import get_config

        cfg = get_config()
        inj = cls()

        def _sync(target):
            n = int(cfg.get_val("ms_inject_socket_failures") or 0)
            delay_p = float(cfg.get_val("ms_inject_internal_delays")
                            or 0.0)
            target.drop_probability = (1.0 / n) if n > 0 else 0.0
            target.delay_probability = delay_p
            target.max_delay = 0.05 if delay_p else 0.0

        _sync(inj)
        # the observer must not keep the injector (and its messenger)
        # alive forever: hold it weakly and self-remove once the owner
        # is gone, or a harness that churns clusters would grow the
        # global observer list without bound
        ref = weakref.ref(inj)

        def _obs(changed):
            target = ref()
            if target is None:
                try:
                    cfg._observers.remove(_obs)
                except ValueError:
                    pass
                return
            if changed & {"ms_inject_socket_failures",
                          "ms_inject_internal_delays"}:
                _sync(target)

        cfg.add_observer(_obs)
        return inj

    def maybe_drop(self) -> bool:
        if self.drop_probability and \
                self._rng.random() < self.drop_probability:
            self.dropped += 1
            return True
        return False

    async def maybe_delay(self) -> None:
        if self.delay_probability and \
                self._rng.random() < self.delay_probability:
            await asyncio.sleep(self._rng.random() * self.max_delay)

    # -- apply/reply-window injection (dup-detection manufacture) ----------

    def schedule_kill_after_apply(self, kind: Optional[str] = None) -> None:
        """Arm a one-shot primary kill in the exactly-once window: the
        next client op (of ``kind``, or any kind when None) executes and
        APPLIES fully, then its primary OSD is marked down BEFORE the
        reply frame goes out -- the deterministic reproducer for reqid
        dup detection (the client must resend and receive the ORIGINAL
        result from the PG log, never a second application)."""
        self._kill_after_apply = kind if kind is not None else "*"

    def kill_after_apply_fire(self, kind: str) -> bool:
        """Consulted by the OSD between apply and reply; True exactly
        once when armed for ``kind`` (firing disarms)."""
        armed = self._kill_after_apply
        if armed is None or (armed != "*" and armed != kind):
            return False
        self._kill_after_apply = None
        self.apply_kills += 1
        self._notify_tear(f"apply-window kill ({kind})")
        return True

    @staticmethod
    def _notify_tear(kind: str) -> None:
        """Report an injected tear to the runtime atomic-section
        verifier (tier-1 asserts tears only cross watermark-safe
        states: no task parked inside a declared section).  A no-op
        when the verifier is not installed."""
        try:
            from ceph_tpu.analysis import runtime as _runtime
        except ImportError:  # analysis stripped from a deploy: fine
            return
        _runtime.on_tear(kind)

    # -- ring-level injection (torn-record manufacture) --------------------

    def schedule_ring_tear(self, after_records: int = 0) -> None:
        """Arm a one-shot shared-memory ring tear: after ``after_records``
        more ring records publish cleanly, the NEXT record goes out torn
        (length header published, body incomplete -- a producer crash
        mid-``memcpy``) and the producing side writes nothing further.
        The consumer's record crc turns the torn record into a
        ``RingTear`` (a ConnectionResetError), driving the messenger's
        ordinary drop + reconnect + session-replay path."""
        self._ring_tear_countdown = max(0, after_records)

    def ring_tear_fire(self) -> bool:
        """Consulted by the ring writer before each record push; True
        exactly once when the armed countdown reaches the record about
        to be pushed (firing disarms)."""
        if self._ring_tear_countdown is None:
            return False
        if self._ring_tear_countdown > 0:
            self._ring_tear_countdown -= 1
            return False
        self._ring_tear_countdown = None
        self.ring_tears += 1
        self._notify_tear("shm ring torn record")
        return True

    # -- connection-level injection (torn-burst manufacture) ---------------

    def schedule_conn_kill(self, after_frames: int) -> None:
        """Arm a one-shot kill: the connection carrying the Nth next
        frame is aborted BEFORE that frame is written (a burst is torn
        mid-flight, the replay tests' worst case)."""
        self._conn_kill_countdown = max(0, after_frames)

    def conn_kill_split(self, nframes: int) -> int:
        """How many of the next ``nframes`` frames may be written before
        an armed kill fires; -1 when no kill is due within the burst.
        Firing disarms the injector (one-shot) and counts the kill."""
        if self._conn_kill_countdown is None:
            return -1
        if self._conn_kill_countdown >= nframes:
            self._conn_kill_countdown -= nframes
            return -1
        split = self._conn_kill_countdown
        self._conn_kill_countdown = None
        self.conn_kills += 1
        self._notify_tear("mid-burst connection kill")
        return split
