"""Telemetry-plane benchmark stage + the wire-fed chaos health gate.

Round-18 shippability contract, three parts:

1. **Overhead** -- the MgrClient report loop (beacon + MgrReport frames
   at tightened intervals, per-PG stats + perf slice + histogram
   marginals per frame) must cost <= ``overhead_limit_pct`` on the
   storage-path workload vs reports-off.  Modes run interleaved
   best-of-iters (the trace-bench discipline) and the gate retries
   against scheduler noise before failing.
2. **Scrape-parse roundtrip** -- the aggregated mgr exposition is
   parsed back as prometheus text and ``ceph_degraded_objects`` plus
   the io-rate series must equal the PGMap's own numbers (the
   exposition is an artifact, not a printf).
3. **Chaos health gate** -- a loadgen scenario with a mid-run OSD wipe
   under concurrent client load (telemetry=True: a real mgr endpoint
   fed over real TCP) must show PG_DEGRADED with a NONZERO degraded
   count that drains monotonically (bounded transient upticks from
   concurrent writes) back to HEALTH_OK once the round-14 recovery
   plane finishes.

``--vstart-smoke`` runs the whole story against REAL PROCESSES:
tools/vstart boots OSD + mgr daemons, an OSD is killed and revived
empty (the replacement-disk wipe), and the degraded->clean transition
is asserted end-to-end from the mgr's admin socket -- the CI smoke
tools/ci_lint.sh runs.

Used by bench.py (``telemetry_path_host`` + headline keys),
``tools/ec_benchmark.py --workload telemetry-path``, and
tests/test_telemetry.py (smoke shape, loose limit).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

_MODES = ("off", "on")


def _cfg():
    from ceph_tpu.utils.config import get_config

    return get_config()


async def _cluster_cycle(cluster, payloads: Dict[str, bytes],
                         writers: int) -> float:
    """One timed storage-path cycle: concurrent writes then verified
    concurrent reads through the in-process cluster."""
    sem = asyncio.Semaphore(writers)

    async def put(oid, data):
        async with sem:
            await cluster.write(oid, data)

    async def get(oid):
        async with sem:
            return oid, await cluster.read(oid)

    t0 = time.perf_counter()
    await asyncio.gather(*(put(o, d) for o, d in payloads.items()))
    got = dict(await asyncio.gather(*(get(o) for o in payloads)))
    dt = time.perf_counter() - t0
    for oid, data in payloads.items():
        if got.get(oid) != data:
            raise AssertionError(
                f"telemetry-path: read-back of {oid} mismatched")
    return dt


async def _overhead_stage(n_osds: int, k: int, m: int,
                          payloads: Dict[str, bytes], writers: int,
                          iters: int) -> dict:
    """Interleaved off/on cycles over ONE cluster pair; returns per-mode
    best times + the folded-report evidence + the scrape roundtrip."""
    from ceph_tpu.mgr.pgmap import PGMap
    from ceph_tpu.mgr.report import ReportSender
    from ceph_tpu.osd.cluster import ECCluster

    prior = {key: _cfg().get_val(key)
             for key in ("mgr_beacon_interval", "mgr_report_interval")}
    # tighter than production defaults: the gate measures the loop's
    # cost at 5-10x its default duty cycle, so a pass here bounds the
    # default well under the limit
    _cfg().apply_changes({"mgr_beacon_interval": 0.05,
                          "mgr_report_interval": 0.1})
    try:
        clusters = {}
        senders: List = []
        pgmap = None
        for mode in _MODES:
            cluster = ECCluster(
                n_osds, {"k": str(k), "m": str(m), "plugin": "jerasure"})
            clusters[mode] = cluster
            if mode == "on":
                pgmap = PGMap(
                    expected=[o.name for o in cluster.osds])

                async def mgr_dispatch(src, msg, _pgmap=pgmap):
                    _pgmap.apply(msg)

                cluster.messenger.register("mgr.0", mgr_dispatch)
                for osd in cluster.osds:
                    sender = ReportSender(
                        osd.name, cluster.messenger,
                        osd.mgr_report_stats, ["mgr.0"], perf=osd.perf)
                    sender.start()
                    senders.append(sender)
        best: Dict[str, float] = {}
        for _ in range(iters):
            for mode in _MODES:
                dt = await _cluster_cycle(clusters[mode], payloads,
                                          writers)
                best[mode] = min(best.get(mode, dt), dt)
        # give the report loop one more interval so the folded map holds
        # the final state, then roundtrip the exposition
        await asyncio.sleep(0.25)
        assert pgmap.reports_folded > 0, \
            "telemetry-path: no reports folded in on-mode"
        scrape = _scrape_roundtrip(pgmap)
        for sender in senders:
            sender.stop()
        for cluster in clusters.values():
            await cluster.shutdown()
        return {"best": best, "reports_folded": pgmap.reports_folded,
                "beacons_folded": pgmap.beacons_folded,
                "scrape": scrape}
    finally:
        _cfg().apply_changes(prior)


def _parse_prometheus(text: str) -> Dict[str, float]:
    """series-name{labels} -> value for every sample line (the parse
    half of the roundtrip; raises on any malformed sample)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        out[name] = float(value)  # ValueError = malformed exposition
    return out


def _scrape_roundtrip(pgmap) -> dict:
    """Parse the aggregated exposition and pin the headline series to
    the PGMap's own numbers."""
    samples = _parse_prometheus(pgmap.prometheus_text())
    degraded = samples.get("ceph_degraded_objects")
    ops_rate = samples.get("ceph_client_ops_per_sec")
    recovery_rate = samples.get("ceph_recovery_bytes_per_sec")
    totals = pgmap.totals()
    io = pgmap.io_rates()
    assert degraded == totals["degraded"], \
        (degraded, totals["degraded"])
    assert ops_rate == io["client_ops_per_sec"], \
        (ops_rate, io["client_ops_per_sec"])
    assert recovery_rate == io["recovery_bytes_per_sec"]
    return {"degraded": degraded, "client_ops_per_sec": ops_rate,
            "recovery_bytes_per_sec": recovery_rate,
            "series_parsed": len(samples)}


async def _chaos_stage(*, clients: int, duration_s: float,
                       n_osds: int) -> dict:
    """The wipe -> PG_DEGRADED -> monotone drain -> HEALTH_OK gate over
    real TCP with the report plane live."""
    from ceph_tpu.loadgen.scenario import (ClientGroup, Scenario,
                                           run_scenario)

    scenario = Scenario(
        name="telemetry-chaos", duration_s=duration_s,
        groups=(ClientGroup(count=clients, profile="put8k"),),
        chaos=("rebuild",),
    )
    res = await run_scenario(
        scenario, n_osds=n_osds, k=2, m=1, telemetry=True,
        tuning={"osd_recovery_sleep": 0.05,
                "osd_recovery_batch_bytes": 64 << 10},
    )
    assert res.wipes >= 1, "chaos stage never wiped an OSD"
    assert res.degraded_max > 0, \
        "wipe raised no degraded count on the wire-fed map"
    assert res.degraded_final == 0, \
        f"degraded count never drained: {res.health_timeline[-5:]}"
    assert res.health_final == "HEALTH_OK", res.health_final
    assert res.degraded_monotonic_violations <= 2, (
        f"degraded drain not monotone "
        f"({res.degraded_monotonic_violations} upticks): "
        f"{[d for _, _, d in res.health_timeline]}")
    assert res.cas_exact, "exactly-once audit failed under the wipe"
    return {
        "clients": res.n_clients,
        "ops": res.ops,
        "wipes": res.wipes,
        "degraded_max": res.degraded_max,
        "degraded_monotonic_violations":
            res.degraded_monotonic_violations,
        "health_final": res.health_final,
        "drain_samples": len(res.health_timeline),
    }


def run_telemetry_bench(*, n_osds: int = 6, k: int = 2, m: int = 1,
                        n_objects: int = 48, obj_bytes: int = 16 << 10,
                        writers: int = 8, iters: int = 2,
                        overhead_limit_pct: float = 3.0,
                        overhead_retries: int = 3,
                        chaos_clients: int = 24,
                        chaos_duration_s: float = 6.0,
                        smoke: bool = False) -> dict:
    """The full stage; raises on any gate violation (bench.py then
    reports the stage as failed instead of shipping a bad number)."""
    import os

    import numpy as np

    if smoke:
        n_objects, obj_bytes, iters = 16, 8 << 10, 1
        chaos_clients, chaos_duration_s = 12, 3.0
        overhead_limit_pct = max(overhead_limit_pct, 25.0)
    rng = np.random.RandomState(1812)
    payloads = {
        f"tel{i}": rng.randint(0, 256, size=obj_bytes,
                               dtype=np.uint8).tobytes()
        for i in range(n_objects)
    }
    total_bytes = sum(len(v) for v in payloads.values())

    async def main() -> dict:
        overhead_pct = None
        stage = None
        for attempt in range(overhead_retries):
            stage = await _overhead_stage(n_osds, k, m, payloads,
                                          writers, iters)
            t_off, t_on = stage["best"]["off"], stage["best"]["on"]
            overhead_pct = (t_on - t_off) / t_off * 100.0
            if overhead_pct <= overhead_limit_pct:
                break
        assert overhead_pct is not None and \
            overhead_pct <= overhead_limit_pct, (
                f"report-loop overhead {overhead_pct:.1f}% > "
                f"{overhead_limit_pct}% after {overhead_retries} "
                "attempts")
        chaos = await _chaos_stage(clients=chaos_clients,
                                   duration_s=chaos_duration_s,
                                   n_osds=n_osds)
        gibps = {
            mode: round(
                2 * total_bytes / stage["best"][mode] / (1 << 30), 4)
            for mode in _MODES
        }
        return {
            "telemetry_overhead_pct": round(overhead_pct, 2),
            "overhead_limit_pct": overhead_limit_pct,
            "reports_off_GiBs": gibps["off"],
            "reports_on_GiBs": gibps["on"],
            "reports_folded": stage["reports_folded"],
            "beacons_folded": stage["beacons_folded"],
            "scrape": stage["scrape"],
            "chaos": chaos,
            "n_objects": n_objects,
            "obj_bytes": obj_bytes,
        }

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    return asyncio.new_event_loop().run_until_complete(main())


# -- the real-process CI smoke ----------------------------------------------


def run_vstart_smoke(run_dir: Optional[str] = None,
                     n_osds: int = 4, n_objects: int = 30,
                     obj_bytes: int = 16 << 10) -> dict:
    """Boot a REAL multi-process cluster (tools/vstart: OSD + mgr
    daemons), prove HEALTH_OK arrives from wire-fed reports alone, wipe
    an OSD (SIGKILL + empty revive), and assert the
    OSD_DOWN -> PG_DEGRADED(>0, draining) -> HEALTH_OK transition from
    the mgr's admin socket.  The tools/ci_lint.sh telemetry smoke."""
    import os
    import shutil
    import sys
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, os.path.join(repo, "tools"))
    import vstart  # noqa: E402  (tools/ module, path-injected)

    from ceph_tpu.utils.admin_socket import admin_command

    tmp = run_dir or tempfile.mkdtemp(prefix="ceph-tpu-telemetry-")
    # daemon processes inherit env: shrink the chaos time scale and
    # throttle the rebuild so the degraded drain is OBSERVABLE (several
    # report intervals long) instead of completing between two frames
    tuned = {
        "CEPH_TPU_MGR_BEACON_INTERVAL": "0.1",
        "CEPH_TPU_MGR_REPORT_INTERVAL": "0.2",
        "CEPH_TPU_MGR_DAEMON_BEACON_GRACE": "1.5",
        "CEPH_TPU_MGR_PG_STALE_GRACE": "3.0",
        "CEPH_TPU_OSD_TICK_INTERVAL": "0.4",
        "CEPH_TPU_OSD_RECOVERY_SLEEP": "0.1",
        "CEPH_TPU_OSD_RECOVERY_BATCH_BYTES": str(48 << 10),
        "CEPH_TPU_OSD_RECOVERY_MAX_ACTIVE": "1",
    }
    prior_env = {key: os.environ.get(key) for key in tuned}
    os.environ.update(tuned)
    mgr_asok = os.path.join(tmp, "data", "mgr.0.asok")

    async def mgr_health() -> dict:
        return await admin_command(mgr_asok, "health")

    async def mgr_degraded() -> int:
        stat = await admin_command(mgr_asok, "pg stat")
        return int(stat["degraded"])

    async def wait_status(want: str, deadline_s: float,
                          check=None) -> None:
        deadline = time.time() + deadline_s
        last = None
        while time.time() < deadline:
            try:
                health = await mgr_health()
            except (OSError, ValueError):
                await asyncio.sleep(0.2)
                continue
            last = health
            if health["status"] == want and (
                    check is None or check(health)):
                return
            await asyncio.sleep(0.2)
        raise AssertionError(
            f"mgr never reached {want}: last {last}")

    async def drive() -> dict:
        from ceph_tpu.daemon.client import RemoteClient

        client = await RemoteClient.connect(
            os.path.join(tmp, "addr_map.json"),
            {"plugin": "jerasure", "k": "2", "m": "1"})
        await client.probe_osds()
        for i in range(n_objects):
            await client.write(f"smoke{i}", bytes([i % 251]) * obj_bytes)
        await client.close()
        # wire-fed HEALTH_OK: every daemon beaconing, no degraded PGs
        await wait_status("HEALTH_OK", 20.0)
        # the wipe: SIGKILL, then an EMPTY revive (memstore daemons
        # lose their store -- replacement-disk semantics); the beacon
        # gap must surface as OSD_DOWN first
        vstart.kill_osd(tmp, 1)
        await wait_status(
            "HEALTH_WARN", 15.0,
            check=lambda h: "OSD_DOWN" in h["checks"])
        vstart.revive_osd(tmp, 1)
        # the revived incarnation forces peers onto the backfill path
        # (boot_id change): degraded must rise above zero, then drain
        series: List[int] = []
        deadline = time.time() + 90.0
        while time.time() < deadline:
            try:
                series.append(await mgr_degraded())
            except (OSError, ValueError):
                pass
            if series and series[-1] == 0 and max(series) > 0:
                health = await mgr_health()
                if health["status"] == "HEALTH_OK":
                    break
            await asyncio.sleep(0.15)
        assert series and max(series) > 0, (
            f"wipe never raised a degraded count: {series[-20:]}")
        assert series[-1] == 0, f"degraded never drained: {series[-20:]}"
        peak_at = series.index(max(series))
        upticks = sum(
            1 for a, b in zip(series[peak_at:], series[peak_at + 1:])
            if b > a)
        assert upticks <= 1, f"drain not monotone: {series[peak_at:]}"
        health = await mgr_health()
        assert health["status"] == "HEALTH_OK", health
        return {"degraded_series": series, "degraded_max": max(series),
                "upticks": upticks, "health_final": health["status"]}

    try:
        vstart.start_cluster(tmp, n_osds,
                             {"plugin": "jerasure", "k": "2", "m": "1"},
                             wait=20.0)
        result = asyncio.new_event_loop().run_until_complete(drive())
    finally:
        try:
            vstart.stop_cluster(tmp)
        finally:
            for key, val in prior_env.items():
                if val is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = val
            if run_dir is None:
                shutil.rmtree(tmp, ignore_errors=True)
    return result


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="shrunk shapes, loose overhead limit")
    ap.add_argument("--vstart-smoke", action="store_true",
                    help="real-process end-to-end health gate "
                         "(the ci_lint.sh telemetry smoke)")
    args = ap.parse_args(argv)
    if args.vstart_smoke:
        result = run_vstart_smoke()
    else:
        result = run_telemetry_bench(smoke=args.smoke)
    json.dump(result, sys.stdout)
    print()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
