"""Wire-fed cluster map: PGMap fold + the mgr daemon server role.

Reference: src/mon/PGMap.{h,cc} + src/mgr/DaemonServer.cc -- the mgr
folds every daemon's MMgrReport/MPGStats into an INCREMENTAL PGMap
(apply_incremental), derives health from the map plus staleness rules
(an OSD whose beacon went silent is down; a PG whose stats stopped
arriving is stale), and computes the ``ceph -s`` io block from
consecutive report deltas.  Nothing here ever touches another process's
memory: the map is built purely from :class:`~ceph_tpu.mgr.report`
frames arriving over the messenger, which is what makes health work
against a real multi-process cluster (daemon/, vstart, loadgen).

* :class:`PGMap` -- the fold + rate engine + staleness health.
  Staleness is evaluated lazily against the injected clock at read
  time, so there is no tick task to leak and tests drive it with a
  virtual clock.
* :class:`MgrServer` -- binds a PGMap to a messenger entity
  (``mgr.N``), serves /metrics /health /status over HTTP and the
  pg-stat/health verbs over the admin socket (daemon/mgr.py wires
  them), and renders the aggregated one-scrape-per-cluster prometheus
  exposition from the per-daemon report series.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Dict, List, Optional

from ceph_tpu.mgr.report import MgrBeacon, MgrReport


class ClusterLog:
    """Bounded mgr-local cluster event log (the ``clog`` analogue).

    Health transitions and slow-op WARNINGs append here as they are
    OBSERVED by the mgr (health is computed lazily at read time, so a
    transition lands on the first health read that sees it; slow-op
    deltas land at report fold).  Mgr-local only -- no new wire frames;
    ``rados_cli log last [n]`` renders it over the admin socket."""

    def __init__(self, keep: int = 256, clock=None):
        self.clock = clock if clock is not None else time.time
        self._ring: deque = deque(maxlen=keep)
        self._seq = 0

    def append(self, severity: str, message: str) -> None:
        self._seq += 1
        self._ring.append({
            "seq": self._seq,
            "stamp": round(self.clock(), 3),
            "severity": severity,  # INF | WRN | ERR
            "message": message,
        })

    def last(self, n: int = 20) -> List[dict]:
        entries = list(self._ring)
        return entries[-max(0, int(n)):]

    def __len__(self) -> int:
        return len(self._ring)

#: perf counters whose per-interval deltas become rates (the io block):
#: key -> (rate name, unit scale note)
RATE_COUNTERS = ("client_ops", "client_wr_bytes", "client_rd_bytes",
                 "recovery_bytes")


def fold_health(checks: Dict[str, dict]) -> dict:
    """Severity fold shared by the in-process health_checks and the
    wire-fed map (src/mon/health_check.h semantics)."""
    status = "HEALTH_OK"
    for c in checks.values():
        if c["severity"] == "HEALTH_ERR":
            status = "HEALTH_ERR"
            break
        status = "HEALTH_WARN"
    return {"status": status, "checks": checks}


class _DaemonState:
    __slots__ = ("name", "kind", "last_beacon", "last_report", "seq",
                 "lag_ms", "lag_over", "stats", "rates", "prev",
                 "slow_ops_seen")

    def __init__(self, name: str):
        self.name = name
        self.kind = name.split(".", 1)[0]
        self.last_beacon: float = 0.0
        self.last_report: float = 0.0
        self.seq = 0
        self.lag_ms: float = 0.0
        #: consecutive over-threshold lag samples (DAEMON_LAG sustain)
        self.lag_over = 0
        self.stats: dict = {}
        self.rates: Dict[str, float] = {}
        #: (clock, {rate counter: value}) of the previous report
        self.prev: Optional[tuple] = None
        #: slow_ops counter watermark (clog slow-op WARNING deltas)
        self.slow_ops_seen = 0


class PGMap:
    """Incremental cluster map folded from beacon/report frames."""

    def __init__(self, expected=None, clock=None):
        from ceph_tpu.utils.config import get_config

        cfg = get_config()
        self.beacon_grace = float(cfg.get_val("mgr_daemon_beacon_grace"))
        self.pg_stale_grace = float(cfg.get_val("mgr_pg_stale_grace"))
        self.lag_warn_ms = float(cfg.get_val("mgr_lag_warn_ms"))
        self.lag_sustain = int(cfg.get_val("mgr_lag_sustain"))
        self.clock = clock if clock is not None else time.monotonic
        #: daemons that SHOULD be beaconing (the cluster address book):
        #: one that never has is down, not unknown -- health cannot be
        #: OK before every expected daemon has proven liveness
        self.expected = set(expected or ())
        self.daemons: Dict[str, _DaemonState] = {}
        #: pool -> reporting daemon -> {pg stat fields + "t" fold time}
        self.pgs: Dict[str, Dict[str, dict]] = {}
        self.reports_folded = 0
        self.beacons_folded = 0
        #: mgr-local cluster event log: health transitions + slow-op
        #: warnings (rados_cli `log last [n]`)
        self.clog = ClusterLog()
        #: last health view this map rendered (transition detection)
        self._health_prev: Dict[str, str] = {}
        self._status_prev: Optional[str] = None

    # -- fold ---------------------------------------------------------------

    def _daemon(self, name: str) -> _DaemonState:
        d = self.daemons.get(name)
        if d is None:
            d = self.daemons[name] = _DaemonState(name)
        return d

    def _note_lag(self, d: _DaemonState, lag_ms) -> None:
        if lag_ms is None:
            return
        d.lag_ms = float(lag_ms)
        if d.lag_ms >= self.lag_warn_ms:
            d.lag_over += 1
        else:
            d.lag_over = 0

    def apply(self, msg) -> bool:
        """Fold one beacon/report frame; False for foreign messages."""
        now = self.clock()
        if isinstance(msg, MgrBeacon):
            d = self._daemon(msg.name)
            d.last_beacon = now
            d.seq = max(d.seq, msg.seq)
            self._note_lag(d, msg.lag_ms)
            self.beacons_folded += 1
            return True
        if isinstance(msg, MgrReport):
            d = self._daemon(msg.name)
            d.last_beacon = now  # a report proves liveness too
            d.last_report = now
            d.seq = max(d.seq, msg.seq)
            d.stats = msg.stats or {}
            self._note_lag(d, msg.lag_ms)
            self._fold_rates(d, now)
            # slow-op WARNINGs ride the event log: a report whose
            # slow_ops counter advanced logs the delta (counter going
            # BACKWARD = daemon restart: re-baseline silently)
            slow = (d.stats.get("perf") or {}).get("slow_ops", 0)
            if isinstance(slow, (int, float)):
                if slow > d.slow_ops_seen:
                    self.clog.append(
                        "WRN",
                        f"{int(slow - d.slow_ops_seen)} slow op(s) on "
                        f"{msg.name} ({int(slow)} total)")
                d.slow_ops_seen = slow
            for pool, stat in (d.stats.get("pgs") or {}).items():
                entry = dict(stat)
                entry["t"] = now
                self.pgs.setdefault(pool, {})[msg.name] = entry
            self.reports_folded += 1
            return True
        return False

    def _fold_rates(self, d: _DaemonState, now: float) -> None:
        """The time-series rate engine: consecutive report deltas of the
        RATE_COUNTERS become this daemon's ops/s + B/s contributions
        (the `ceph -s` io block).  A counter that went BACKWARD means
        the daemon restarted: reset the baseline, report zero."""
        perf = d.stats.get("perf") or {}
        cur = {k: perf.get(k, 0) for k in RATE_COUNTERS
               if isinstance(perf.get(k, 0), (int, float))}
        if d.prev is not None:
            t0, old = d.prev
            dt = now - t0
            if dt > 0:
                for key, val in cur.items():
                    delta = val - old.get(key, 0)
                    d.rates[key] = max(0.0, delta) / dt
        d.prev = (now, cur)

    # -- staleness ----------------------------------------------------------

    def daemon_up(self, name: str, now: Optional[float] = None) -> bool:
        d = self.daemons.get(name)
        if d is None or d.last_beacon == 0.0:
            return False
        now = self.clock() if now is None else now
        return (now - d.last_beacon) < self.beacon_grace

    def down_daemons(self, kind: Optional[str] = None) -> List[str]:
        now = self.clock()
        names = set(self.expected) | set(self.daemons)
        out = []
        for name in sorted(names):
            if kind is not None and not name.startswith(kind + "."):
                continue
            if name.startswith("mgr."):
                continue  # we ARE the mgr
            if not self.daemon_up(name, now):
                out.append(name)
        return out

    def stale_pgs(self) -> List[tuple]:
        """(pool, daemon) slices whose per-PG stats stopped arriving."""
        now = self.clock()
        out = []
        for pool, by_daemon in sorted(self.pgs.items()):
            for name, entry in sorted(by_daemon.items()):
                if now - entry["t"] >= self.pg_stale_grace:
                    out.append((pool, name))
        return out

    # -- aggregation --------------------------------------------------------

    def totals(self) -> Dict[str, int]:
        agg = {"degraded": 0, "misplaced": 0, "recovering": 0,
               "scrub_errors": 0}
        for by_daemon in self.pgs.values():
            for entry in by_daemon.values():
                for key in agg:
                    agg[key] += int(entry.get(key, 0) or 0)
        return agg

    def pg_states(self) -> Dict[str, int]:
        """ceph-style state histogram ("active+clean" -> count)."""
        out: Dict[str, int] = {}
        stale = set(self.stale_pgs())
        for pool, by_daemon in self.pgs.items():
            for name, entry in by_daemon.items():
                state = entry.get("state", "unknown")
                if (pool, name) in stale:
                    state = "stale+" + state
                out[state] = out.get(state, 0) + 1
        return out

    def io_rates(self) -> Dict[str, float]:
        agg = {k: 0.0 for k in RATE_COUNTERS}
        for d in self.daemons.values():
            for key, val in d.rates.items():
                agg[key] += val
        return {
            "client_ops_per_sec": round(agg["client_ops"], 3),
            "client_wr_bytes_per_sec": round(agg["client_wr_bytes"], 1),
            "client_rd_bytes_per_sec": round(agg["client_rd_bytes"], 1),
            "recovery_bytes_per_sec": round(agg["recovery_bytes"], 1),
        }

    def store_totals(self) -> Dict[str, int]:
        agg = {"objects": 0, "shards": 0, "metas": 0, "bytes": 0}
        for d in self.daemons.values():
            store = d.stats.get("store") or {}
            for key in agg:
                agg[key] += int(store.get(key, 0) or 0)
        return agg

    # -- health -------------------------------------------------------------

    def health(self) -> dict:
        checks: Dict[str, dict] = {}
        for kind, check in (("osd", "OSD_DOWN"), ("mon", "MON_DOWN")):
            down = self.down_daemons(kind)
            if down:
                checks[check] = {
                    "severity": "HEALTH_WARN",
                    "summary": f"{len(down)} {kind} daemons down or "
                               f"beacon-silent past "
                               f"{self.beacon_grace:g}s: "
                               + " ".join(down),
                }
        stale = self.stale_pgs()
        if stale:
            checks["PG_STALE"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(stale)} pg slices have stale reports "
                           "(primary not reporting)",
            }
        agg = self.totals()
        if agg["degraded"]:
            checks["PG_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{agg['degraded']} objects degraded "
                           f"({agg['recovering']} rebuilding)",
            }
        if agg["misplaced"]:
            checks["OBJECT_MISPLACED"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{agg['misplaced']} objects misplaced",
            }
        if agg["scrub_errors"]:
            checks["OSD_SCRUB_ERRORS"] = {
                "severity": "HEALTH_ERR",
                "summary": f"{agg['scrub_errors']} scrub inconsistencies",
            }
        lagging = sorted(
            d.name for d in self.daemons.values()
            if d.lag_over >= self.lag_sustain
        )
        if lagging:
            checks["DAEMON_LAG"] = {
                "severity": "HEALTH_WARN",
                "summary": f"event-loop lag >= {self.lag_warn_ms:g}ms "
                           f"sustained on: " + " ".join(lagging),
            }
        folded = fold_health(checks)
        self._note_health_transitions(folded)
        return folded

    def _note_health_transitions(self, folded: dict) -> None:
        """Append health-state changes to the event log.  Health is
        computed lazily, so a transition lands on the first health read
        that observes it; repeated reads of the same state append
        nothing (idempotent by construction)."""
        checks = folded["checks"]
        cur = {name: chk["summary"] for name, chk in checks.items()}
        for name in sorted(set(cur) - set(self._health_prev)):
            sev = "ERR" if checks[name]["severity"] == "HEALTH_ERR" \
                else "WRN"
            self.clog.append(sev, f"{name}: {cur[name]}")
        for name in sorted(set(self._health_prev) - set(cur)):
            self.clog.append("INF", f"{name} cleared")
        self._health_prev = cur
        status = folded["status"]
        if status != self._status_prev:
            if self._status_prev is not None:
                self.clog.append(
                    "INF" if status == "HEALTH_OK" else "WRN",
                    f"cluster health {self._status_prev} -> {status}")
            self._status_prev = status

    # -- renderings ---------------------------------------------------------

    def dump(self) -> dict:
        now = self.clock()
        osds = {}
        for name, d in sorted(self.daemons.items()):
            osds[name] = {
                "kind": d.kind,
                "up": self.daemon_up(name, now),
                "beacon_age_s": round(now - d.last_beacon, 3)
                if d.last_beacon else None,
                "lag_ms": round(d.lag_ms, 3),
                "seq": d.seq,
                "store": d.stats.get("store"),
                "tier": d.stats.get("tier"),
                "ops_in_flight": d.stats.get("ops_in_flight"),
                "rates": {k: round(v, 3) for k, v in d.rates.items()},
            }
        return {
            "daemons": osds,
            "expected": sorted(self.expected),
            "down": self.down_daemons(),
            "pgs": {pool: {name: dict(entry)
                           for name, entry in by_daemon.items()}
                    for pool, by_daemon in self.pgs.items()},
            "pg_states": self.pg_states(),
            "totals": self.totals(),
            "io": self.io_rates(),
            "store": self.store_totals(),
            "health": self.health(),
            "reports_folded": self.reports_folded,
            "beacons_folded": self.beacons_folded,
        }

    def pg_stat(self) -> dict:
        """The ``ceph pg stat`` one-liner's data."""
        states = self.pg_states()
        agg = self.totals()
        return {
            "num_pg_slices": sum(states.values()),
            "by_state": states,
            "degraded": agg["degraded"],
            "misplaced": agg["misplaced"],
            "recovering": agg["recovering"],
            "io": self.io_rates(),
        }

    def status_text(self) -> str:
        """`ceph -s`-shaped plain text (rados_cli status renders it)."""
        health = self.health()
        states = self.pg_states()
        agg = self.totals()
        io = self.io_rates()
        store = self.store_totals()
        osd_names = [n for n in (set(self.expected) | set(self.daemons))
                     if n.startswith("osd.")]
        mon_names = [n for n in (set(self.expected) | set(self.daemons))
                     if n.startswith("mon.")]
        up_osds = [n for n in osd_names if self.daemon_up(n)]
        lines = ["  cluster:",
                 f"    health: {health['status']}"]
        for name, chk in sorted(health["checks"].items()):
            lines.append(f"            {name}: {chk['summary']}")
        lines.append("  services:")
        if mon_names:
            up_mons = [n for n in mon_names if self.daemon_up(n)]
            lines.append(f"    mon: {len(mon_names)} daemons, "
                         f"{len(up_mons)} up")
        lines.append(f"    osd: {len(osd_names)} osds: "
                     f"{len(up_osds)} up")
        lines.append("  data:")
        lines.append(f"    shards: {store['shards']} shard objects, "
                     f"{store['bytes']} bytes")
        pg_bits = ", ".join(f"{n} {state}"
                            for state, n in sorted(states.items()))
        lines.append(f"    pgs: {pg_bits or 'none reported'}")
        if agg["degraded"] or agg["misplaced"]:
            lines.append(f"    degraded: {agg['degraded']} objects; "
                         f"misplaced: {agg['misplaced']}")
        lines.append("  io:")
        lines.append(
            f"    client: {io['client_ops_per_sec']} op/s, "
            f"{io['client_wr_bytes_per_sec']} B/s wr, "
            f"{io['client_rd_bytes_per_sec']} B/s rd")
        lines.append(
            f"    recovery: {io['recovery_bytes_per_sec']} B/s")
        return "\n".join(lines) + "\n"

    def prometheus_text(self) -> str:
        """ONE cluster scrape aggregated from the per-daemon report
        series (the reference prometheus module reads the mgr's PGMap
        the same way -- daemons are never scraped individually)."""
        now = self.clock()
        lines = ["# HELP ceph_osd_up daemon liveness from beacon "
                 "staleness (wire-fed)",
                 "# TYPE ceph_osd_up gauge"]
        names = sorted(set(self.expected) | set(self.daemons))
        for name in names:
            if not name.startswith("osd."):
                continue
            lines.append(f'ceph_osd_up{{ceph_daemon="{name}"}} '
                         f"{1 if self.daemon_up(name, now) else 0}")
        lines += ["# HELP ceph_daemon_lag_ms sampled event-loop "
                  "sleep-drift EWMA per daemon",
                  "# TYPE ceph_daemon_lag_ms gauge"]
        for name, d in sorted(self.daemons.items()):
            lines.append(f'ceph_daemon_lag_ms{{ceph_daemon="{name}"}} '
                         f"{round(d.lag_ms, 3)}")
        lines += ["# HELP ceph_osd_bytes_used bytes stored per OSD "
                  "(incremental store totals)",
                  "# TYPE ceph_osd_bytes_used gauge",
                  "# HELP ceph_osd_num_shards shard objects per OSD",
                  "# TYPE ceph_osd_num_shards gauge"]
        for name, d in sorted(self.daemons.items()):
            store = d.stats.get("store")
            if store:
                lines.append(
                    f'ceph_osd_bytes_used{{ceph_daemon="{name}"}} '
                    f"{store.get('bytes', 0)}")
                lines.append(
                    f'ceph_osd_num_shards{{ceph_daemon="{name}"}} '
                    f"{store.get('objects', 0)}")
        agg = self.totals()
        lines += [
            "# HELP ceph_degraded_objects objects with missing/stale "
            "copies (incremental per-PG counters, wire-fed)",
            "# TYPE ceph_degraded_objects gauge",
            f"ceph_degraded_objects {agg['degraded']}",
            "# HELP ceph_misplaced_objects objects whose copies live "
            "on non-acting OSDs",
            "# TYPE ceph_misplaced_objects gauge",
            f"ceph_misplaced_objects {agg['misplaced']}",
        ]
        for pool, by_daemon in sorted(self.pgs.items()):
            for name, entry in sorted(by_daemon.items()):
                lines.append(
                    f'ceph_pg_degraded{{pool="{pool}",'
                    f'ceph_daemon="{name}"}} '
                    f"{entry.get('degraded', 0)}")
        io = self.io_rates()
        lines += [
            "# HELP ceph_client_ops_per_sec cluster client op rate "
            "(consecutive-report deltas)",
            "# TYPE ceph_client_ops_per_sec gauge",
            f"ceph_client_ops_per_sec {io['client_ops_per_sec']}",
            "# HELP ceph_client_bytes_per_sec cluster client "
            "throughput by direction",
            "# TYPE ceph_client_bytes_per_sec gauge",
            f'ceph_client_bytes_per_sec{{direction="wr"}} '
            f"{io['client_wr_bytes_per_sec']}",
            f'ceph_client_bytes_per_sec{{direction="rd"}} '
            f"{io['client_rd_bytes_per_sec']}",
            "# HELP ceph_recovery_bytes_per_sec cluster rebuild "
            "throughput",
            "# TYPE ceph_recovery_bytes_per_sec gauge",
            f"ceph_recovery_bytes_per_sec "
            f"{io['recovery_bytes_per_sec']}",
        ]
        # elastic-membership migration traffic: bytes re-pushed because
        # the copy sat on a non-acting osd (expansion/contraction
        # backfill), distinct from rebuild bytes after data loss
        lines += [
            "# HELP ceph_osd_backfill_bytes_moved_total bytes migrated "
            "by backfill to re-placed acting positions (wire-fed)",
            "# TYPE ceph_osd_backfill_bytes_moved_total counter",
        ]
        for name, d in sorted(self.daemons.items()):
            moved = (d.stats.get("perf") or {}).get(
                "recovery_backfill_bytes")
            if isinstance(moved, (int, float)):
                lines.append(
                    f'ceph_osd_backfill_bytes_moved_total{{'
                    f'ceph_daemon="{name}"}} {moved}')
        # per-daemon perf counters, flattened (the report-schema slice)
        lines += ["# HELP ceph_osd_perf per-daemon perf counters "
                  "(report-schema slice)",
                  "# TYPE ceph_osd_perf counter"]
        for name, d in sorted(self.daemons.items()):
            for counter, value in sorted(
                    (d.stats.get("perf") or {}).items()):
                if isinstance(value, (int, float)):
                    lines.append(
                        f'ceph_osd_perf{{ceph_daemon="{name}",'
                        f'counter="{counter}"}} {value}')
        lines.extend(self._histogram_lines())
        lines.extend(self._profile_lines())
        return "\n".join(lines) + "\n"

    def _profile_lines(self) -> List[str]:
        """Wire-fed wire-tax profiler exposition: per-daemon per-stage
        cumulative seconds from the report frames' ``profile`` slice
        (daemons with profiling off ship no slice and render nothing)."""
        lines: List[str] = []
        rows = []
        for name, d in sorted(self.daemons.items()):
            prof = d.stats.get("profile")
            if not isinstance(prof, dict):
                continue
            for stage, ns in sorted((prof.get("stages") or {}).items()):
                if isinstance(ns, (int, float)):
                    rows.append((name, stage, ns))
        if not rows:
            return lines
        lines += [
            "# HELP ceph_profile_stage_seconds_total exclusive seconds "
            "per wire-tax profiler stage (wire-fed report slice)",
            "# TYPE ceph_profile_stage_seconds_total counter",
        ]
        for name, stage, ns in rows:
            lines.append(
                f'ceph_profile_stage_seconds_total{{'
                f'ceph_daemon="{name}",stage="{stage}"}} '
                f"{ns / 1e9:.6f}")
        return lines

    def _histogram_lines(self) -> List[str]:
        """Reported histogram marginals as real prometheus histogram
        series, family-grouped like utils/perf.py's in-process
        renderer (``osd.N.stage`` -> family ``ceph_hist_stage`` with a
        ceph_daemon label)."""
        families: Dict[str, List[tuple]] = {}
        for name, d in sorted(self.daemons.items()):
            for hname, h in sorted((d.stats.get("hist") or {}).items()):
                parts = hname.split(".")
                if len(parts) >= 3 and parts[0] == "osd" and \
                        parts[1].isdigit():
                    daemon = f"{parts[0]}.{parts[1]}"
                    family = ".".join(parts[2:])
                elif len(parts) >= 2:
                    daemon, family = parts[0], ".".join(parts[1:])
                else:
                    daemon, family = name, hname
                metric = "ceph_hist_" + "".join(
                    c if c.isalnum() else "_" for c in family)
                families.setdefault(metric, []).append((daemon, h))
        lines: List[str] = []
        for metric in sorted(families):
            lines.append(f"# HELP {metric} per-stage latency histogram "
                         "(wire-fed marginal)")
            lines.append(f"# TYPE {metric} histogram")
            for daemon, h in families[metric]:
                marginal = list(h.get("marginal") or ())
                bounds = list(h.get("bounds") or ())
                cum = 0
                for ub, count in zip(bounds, marginal):
                    cum += count
                    lines.append(
                        f'{metric}_bucket{{ceph_daemon="{daemon}",'
                        f'le="{ub}"}} {cum}')
                cum += sum(marginal[len(bounds):])
                lines.append(
                    f'{metric}_bucket{{ceph_daemon="{daemon}",'
                    f'le="+Inf"}} {cum}')
                lines.append(f'{metric}_sum{{ceph_daemon="{daemon}"}} '
                             f"{h.get('sum', 0)}")
                lines.append(
                    f'{metric}_count{{ceph_daemon="{daemon}"}} '
                    f"{h.get('count', 0)}")
        return lines


class MgrServer:
    """One mgr daemon: a messenger entity folding beacon/report frames
    into a PGMap, plus the HTTP endpoint (the MgrDaemon shape, wire-fed).
    """

    def __init__(self, name: str, messenger, addr_map=None,
                 http_host: str = "127.0.0.1", http_port: int = 0,
                 clock=None):
        self.name = name
        self.messenger = messenger
        expected = [k for k in (addr_map or {})
                    if k.startswith(("osd.", "mon."))]
        self.pgmap = PGMap(expected=expected, clock=clock)
        self.http_host = http_host
        self.http_port = http_port
        self._server: Optional[asyncio.AbstractServer] = None
        messenger.register(name, self.dispatch)

    async def dispatch(self, src: str, msg) -> None:
        self.pgmap.apply(msg)

    # -- HTTP ---------------------------------------------------------------

    async def start_http(self) -> int:
        self._server = await asyncio.start_server(
            self._serve, self.http_host, self.http_port)
        self.http_port = self._server.sockets[0].getsockname()[1]
        return self.http_port

    async def stop(self) -> None:
        # claim-then-await: the attribute is cleared BEFORE the yield so
        # a concurrent stop() cannot double-close (asyncsan rmw rule)
        server, self._server = self._server, None
        if server is not None:
            server.close()
            await server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            request = await reader.readline()
            while True:  # drain headers
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            path = request.split()[1].decode() if request.split() else "/"
            if path == "/metrics":
                body = self.pgmap.prometheus_text()
                ctype, code = "text/plain; version=0.0.4", "200 OK"
            elif path == "/health":
                import json

                body = json.dumps(self.pgmap.health())
                ctype, code = "application/json", "200 OK"
            elif path == "/status":
                import json

                body = json.dumps(self.pgmap.dump())
                ctype, code = "application/json", "200 OK"
            else:
                body, ctype, code = ("not found\n", "text/plain",
                                     "404 Not Found")
            data = body.encode()
            writer.write(
                f"HTTP/1.1 {code}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(data)}\r\n"
                "Connection: close\r\n\r\n".encode() + data
            )
            await writer.drain()
        finally:
            writer.close()
