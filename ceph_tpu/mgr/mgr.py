"""Cluster state aggregation, health checks, prometheus exposition."""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional


class ClusterState:
    """Aggregated cluster view (PGMap / DaemonStateIndex role).

    Round-18 contract: every per-scrape read here is O(daemons +
    degraded), NEVER O(objects).  Store totals come from the object
    stores' incremental counters, degraded accounting from the per-PG
    ``pg_stats`` trackers maintained at the mutation / liveness /
    recovery seams (osd/pg_stats.py).  The old full-object census
    survives only behind ``degraded_objects(deep=True)`` as the verify
    path (``rados_cli health detail --deep`` role)."""

    def __init__(self, cluster):
        self.cluster = cluster  # ECCluster

    def osd_stats(self) -> Dict[str, dict]:
        out = {}
        for osd in self.cluster.osds:
            store_stats = osd.store.stats()
            tier = getattr(osd, "tier", None)
            out[osd.name] = {
                "up": not self.cluster.messenger.is_down(osd.name),
                "num_shards": store_stats["objects"],
                "bytes_used": store_stats["bytes"],
                "perf": osd.perf.snapshot(),
                "ops_in_flight": osd.optracker.num_inflight(),
                # device cache-tier residency (budget + hit/miss ride
                # along so /metrics can expose them as gauges)
                "tier": tier.status() if tier is not None else None,
            }
        return out

    def pool_stats(self) -> dict:
        b = self.cluster.backend
        shards = metas = 0
        for osd in self.cluster.osds:
            st = osd.store.stats()
            shards += st["shards"]
            metas += st["metas"]
        ec = self.cluster.ec
        km = b.km or 1
        return {
            # shard-derived estimate: every logical object stores km
            # shard copies (EC chunks or full replicas) and a meta twin
            # replicates km ways, so distinct objects ~= shards/km +
            # metas/km.  An object holding BOTH data and omap counts
            # twice here -- the honest price of never walking stores on
            # the scrape path (the exact census is degraded-path-only,
            # behind deep=True).
            "num_objects": shards // km + metas // km,
            "k": (km if ec is None else ec.get_data_chunk_count()),
            "m": (0 if ec is None
                  else ec.get_chunk_count() - ec.get_data_chunk_count()),
            "client_perf": b.perf.snapshot(),
        }

    def degraded_objects(self, deep: bool = False) -> List[str]:
        """Objects currently degraded (the PG_DEGRADED accounting role).

        Default: union of the hosted engines' incremental pg_stats
        sets -- O(degraded) per call.  ``deep=True`` runs the original
        full acting-set scan over every stored object as an audit/verify
        pass (O(objects x shards); never on the scrape path)."""
        if not deep:
            out: set = set()
            for osd in self.cluster.osds:
                for backend in osd.pools.values():
                    out |= backend.pg_stats.degraded_oids()
            return sorted(out)
        b = self.cluster.backend
        degraded = []
        oids = sorted({
            soid.rsplit("@", 1)[0]
            for osd in self.cluster.osds
            for soid in osd.store.list_objects()
        })
        for oid in oids:
            if oid.endswith("@meta"):
                continue
            acting = b.acting_set(oid)
            if any(not b._shard_up(acting, s) for s in range(b.km)):
                degraded.append(oid)
        return degraded

    def scrub_inconsistent(self) -> List[str]:
        """Objects whose last deep scrub found inconsistencies and which
        have not yet re-scrubbed clean (ScrubStore aggregation role)."""
        out = set()
        for osd in self.cluster.osds:
            for backend in osd.pools.values():
                out.update(backend.scrub_errors)
        return sorted(out)

    def dump(self) -> dict:
        osds = self.osd_stats()
        n_up = sum(1 for s in osds.values() if s["up"])
        return {
            "osdmap": {"num_osds": len(osds), "num_up_osds": n_up},
            "osd_stats": osds,
            "pools": self.pool_stats(),
            "degraded_objects": self.degraded_objects(),
            "scrub_inconsistent": self.scrub_inconsistent(),
        }


def health_checks(state: dict) -> dict:
    """Health evaluation (src/mon/health_check.h severities)."""
    checks = {}
    osdmap = state["osdmap"]
    down = osdmap["num_osds"] - osdmap["num_up_osds"]
    if down:
        checks["OSD_DOWN"] = {
            "severity": "HEALTH_WARN",
            "summary": f"{down} osds down",
        }
    degraded = state["degraded_objects"]
    if degraded:
        checks["PG_DEGRADED"] = {
            "severity": "HEALTH_WARN",
            "summary":
                f"{len(degraded)} objects have shards on down OSDs",
        }
    inconsistent = state.get("scrub_inconsistent") or []
    if inconsistent:
        checks["OSD_SCRUB_ERRORS"] = {
            "severity": "HEALTH_ERR",
            "summary":
                f"{len(inconsistent)} objects have scrub inconsistencies",
        }
    from ceph_tpu.mgr.pgmap import fold_health

    return fold_health(checks)


def prometheus_text(state: dict) -> str:
    """Prometheus exposition (pybind/mgr/prometheus module role)."""
    lines = [
        "# HELP ceph_osd_up OSD liveness",
        "# TYPE ceph_osd_up gauge",
    ]
    for name, s in sorted(state["osd_stats"].items()):
        osd_id = name.split(".")[1]
        lines.append(f'ceph_osd_up{{ceph_daemon="{name}"}} '
                     f"{1 if s['up'] else 0}")
    lines += ["# HELP ceph_osd_bytes_used bytes stored per OSD",
              "# TYPE ceph_osd_bytes_used gauge"]
    for name, s in sorted(state["osd_stats"].items()):
        lines.append(f'ceph_osd_bytes_used{{ceph_daemon="{name}"}} '
                     f"{s['bytes_used']}")
    lines += ["# HELP ceph_osd_num_shards shard objects per OSD",
              "# TYPE ceph_osd_num_shards gauge"]
    for name, s in sorted(state["osd_stats"].items()):
        lines.append(f'ceph_osd_num_shards{{ceph_daemon="{name}"}} '
                     f"{s['num_shards']}")
    lines += ["# HELP ceph_osd_tier_resident_bytes device-resident "
              "cache-tier bytes per OSD",
              "# TYPE ceph_osd_tier_resident_bytes gauge"]
    for name, s in sorted(state["osd_stats"].items()):
        tier = s.get("tier")
        if tier is not None:
            lines.append(
                f'ceph_osd_tier_resident_bytes{{ceph_daemon="{name}"}} '
                f"{tier['resident_bytes']}")
    lines += ["# HELP ceph_osd_tier_hbm_budget_bytes device byte budget "
              "(osd_tier_hbm_bytes)",
              "# TYPE ceph_osd_tier_hbm_budget_bytes gauge"]
    for name, s in sorted(state["osd_stats"].items()):
        tier = s.get("tier")
        if tier is not None:
            lines.append(
                f'ceph_osd_tier_hbm_budget_bytes{{ceph_daemon="{name}"}} '
                f"{tier['budget']}")
    # exactly-once / client-retry health (docs/resilience.md): replayed
    # ops answered from the PG log, client resends, and PG backoffs --
    # the triple that separates "failover is invisible" from "clients
    # are lying or spinning"
    lines += ["# HELP ceph_osd_dup_op_hit replayed client ops answered "
              "from the PG log dup entries",
              "# TYPE ceph_osd_dup_op_hit counter"]
    for name, s in sorted(state["osd_stats"].items()):
        lines.append(f'ceph_osd_dup_op_hit{{ceph_daemon="{name}"}} '
                     f"{s['perf'].get('dup_op_hit', 0)}")
    # background data plane health (osd/recovery.py): batched rebuild
    # volume, scrub cursor progress, throttle preemptions, and the
    # promote-on-recovery proof counter -- a rebuild storm that starves
    # clients shows up here as recovery_bytes rising with
    # recovery_preempted flat (the throttle not engaging)
    for counter, help_text in (
        ("recovery_bytes", "bytes re-pushed by shard recovery"),
        ("recovery_ops_batched",
         "objects rebuilt through the batched recovery coalescer"),
        ("scrub_chunks",
         "batched deep-scrub read-cursor rounds issued"),
        ("recovery_preempted",
         "background batches that backed off for client traffic"),
        ("tier_promote_from_recovery",
         "rebuilt objects landed hot in the device tier by "
         "promote-on-recovery"),
    ):
        lines += [f"# HELP ceph_osd_{counter} {help_text}",
                  f"# TYPE ceph_osd_{counter} counter"]
        for name, s in sorted(state["osd_stats"].items()):
            lines.append(f'ceph_osd_{counter}{{ceph_daemon="{name}"}} '
                         f"{s['perf'].get(counter, 0)}")
    # repair-bandwidth win of the sub-extent/regenerating gather
    # (docs/ec-regenerating.md): classic-gather bytes minus what the
    # coalescer actually read; flat-at-zero on pools whose codec only
    # speaks whole-shard plans
    lines += ["# HELP ceph_osd_recovery_bytes_saved_total gather bytes "
              "avoided by sub-extent/regenerating repair plans",
              "# TYPE ceph_osd_recovery_bytes_saved_total counter"]
    for name, s in sorted(state["osd_stats"].items()):
        lines.append(
            f'ceph_osd_recovery_bytes_saved_total{{ceph_daemon="{name}"}} '
            f"{s['perf'].get('recovery_bytes_saved', 0)}")
    # unified QoS admission (osd/qos.py, docs/qos.md): per-class
    # admitted ops/bytes and throttle waits (client classes counted per
    # op, recovery/scrub per batch), plus the load-generator-published
    # per-class fairness spread (max/min achieved per-client throughput
    # within the class; 1.0 = perfectly fair)
    try:
        qos_rows = {"ops": [], "bytes": [], "throttle_waits": []}
        for name, s in sorted(state["osd_stats"].items()):
            for counter, value in sorted(s["perf"].items()):
                if not counter.startswith("qos_") or \
                        not isinstance(value, (int, float)):
                    continue
                for suffix in ("throttle_waits", "bytes", "ops"):
                    if counter.endswith(f"_{suffix}"):
                        klass = counter[len("qos_"):-len(suffix) - 1]
                        if klass:
                            qos_rows[suffix].append((name, klass, value))
                        break
        for suffix, help_text in (
            ("ops", "batches/ops admitted per QoS class"),
            ("bytes", "stripe bytes admitted per QoS class"),
            ("throttle_waits",
             "admissions that waited for a dmClock grant per QoS class"),
        ):
            if not qos_rows[suffix]:
                continue
            lines += [f"# HELP ceph_qos_class_{suffix} {help_text}",
                      f"# TYPE ceph_qos_class_{suffix} counter"]
            for name, klass, value in qos_rows[suffix]:
                lines.append(
                    f'ceph_qos_class_{suffix}{{ceph_daemon="{name}",'
                    f'qos_class="{klass}"}} {value}')
        from ceph_tpu.osd import qos as _qos_mod

        spreads = _qos_mod.fairness_spreads()
        if spreads:
            lines += [
                "# HELP ceph_qos_fairness_spread max/min achieved "
                "per-client throughput within a QoS class (loadgen-"
                "published; 1.0 = perfectly fair)",
                "# TYPE ceph_qos_fairness_spread gauge",
            ]
            for klass in sorted(spreads):
                lines.append(
                    f'ceph_qos_fairness_spread{{qos_class="{klass}"}} '
                    f"{spreads[klass]}")
    except Exception:  # noqa: BLE001 -- exposition must never fail
        pass
    client_perf = state["pools"].get("client_perf", {})
    for counter in ("op_resend", "backoff_received"):
        lines += [f"# HELP ceph_client_{counter} client-side {counter} "
                  "events (Objecter retry/backoff path)",
                  f"# TYPE ceph_client_{counter} counter",
                  f"ceph_client_{counter} "
                  f"{client_perf.get(counter, 0)}"]
    # device-residency health (analysis/residency.py): transfers the
    # storage layer performed through the counted seams and XLA
    # recompiles.  Process-global (all in-process daemons share the one
    # device), so these carry no ceph_daemon label -- a rising
    # jit_retraces under steady traffic means the batch-shape bucketing
    # regressed; a rising d2h on the write path means residency broke.
    # native wire codec availability (the degraded-build gauge: 0 means
    # the pure-Python codec is serving the wire -- gated off or no
    # toolchain; wire bytes identical, serialization share is not)
    try:
        from ceph_tpu.native import wire_codec as _wire_codec

        _wc = _wire_codec.status()
        lines += [
            "# HELP ceph_wire_codec_native whether the batched native "
            "wire codec (_wire_native) is serving the frame path",
            "# TYPE ceph_wire_codec_native gauge",
            f'ceph_wire_codec_native{{enabled='
            f'"{"true" if _wc["enabled"] else "false"}"}} '
            f'{1 if _wc["enabled"] else 0}',
        ]
    except Exception:  # noqa: BLE001 -- the scrape must never break on
        pass           # an optional native-extension probe
    try:
        from ceph_tpu.analysis import residency as _residency

        rc = _residency.counters().snapshot()
        lines += [
            "# HELP ceph_jit_retraces_total XLA compilations observed "
            "(one per jit retrace; cache hits emit none)",
            "# TYPE ceph_jit_retraces_total counter",
            f"ceph_jit_retraces_total {rc['jit_retraces']}",
            "# HELP ceph_transfer_bytes_total bytes moved through the "
            "storage layer's counted transfer seams",
            "# TYPE ceph_transfer_bytes_total counter",
            f'ceph_transfer_bytes_total{{direction="h2d"}} '
            f"{rc['h2d_bytes']}",
            f'ceph_transfer_bytes_total{{direction="d2h"}} '
            f"{rc['d2h_bytes']}",
            "# HELP ceph_transfer_ops_total transfer operations through "
            "the counted seams",
            "# TYPE ceph_transfer_ops_total counter",
            f'ceph_transfer_ops_total{{direction="h2d"}} '
            f"{rc['h2d_ops']}",
            f'ceph_transfer_ops_total{{direction="d2h"}} '
            f"{rc['d2h_ops']}",
        ]
        # per-mesh-axis sharded-dispatch ledger (the mesh data plane)
        axes = sorted(k[len("mesh_"):-len("_bytes")] for k in rc
                      if k.startswith("mesh_") and k.endswith("_bytes"))
        if axes:
            lines += [
                "# HELP ceph_mesh_dispatch_bytes_total bytes placed "
                "along each mesh axis by sharded dispatches",
                "# TYPE ceph_mesh_dispatch_bytes_total counter",
            ]
            for ax in axes:
                lines.append(
                    f'ceph_mesh_dispatch_bytes_total{{axis="{ax}"}} '
                    f"{rc[f'mesh_{ax}_bytes']}")
        from ceph_tpu.parallel import mesh_plane as _mesh_mod

        plane = _mesh_mod.current_plane()
        if plane is not None:
            lines += [
                "# HELP ceph_mesh_wire_bytes_avoided_total chunk bytes "
                "delivered in-collective instead of over the wire",
                "# TYPE ceph_mesh_wire_bytes_avoided_total counter",
                f"ceph_mesh_wire_bytes_avoided_total "
                f"{plane.counters['mesh_wire_bytes_avoided']}",
            ]
    except Exception:  # noqa: BLE001 -- exposition must never fail
        pass
    # observability plane (utils/trace.py + optracker): slow ops per
    # daemon, trace collector health, and every registered
    # PerfHistogram as REAL prometheus histogram series
    # (_bucket/_sum/_count over the latency marginal) -- the per-stage
    # queue-wait / dispatch / wire-rtt / ack-lag / tier hit-vs-miss
    # attribution ROADMAP items 2-3 read
    lines += ["# HELP ceph_osd_slow_ops ops slower than "
              "osd_op_complaint_time (slow-op forensics)",
              "# TYPE ceph_osd_slow_ops counter"]
    for name, s in sorted(state["osd_stats"].items()):
        lines.append(f'ceph_osd_slow_ops{{ceph_daemon="{name}"}} '
                     f"{s['perf'].get('slow_ops', 0)}")
    try:
        from ceph_tpu.utils import trace as _trace
        from ceph_tpu.utils.perf import histograms_prometheus_text

        ts = _trace.status()
        lines += [
            "# HELP ceph_trace_spans_finished finished trace spans "
            "collected (bounded ring)",
            "# TYPE ceph_trace_spans_finished counter",
            f"ceph_trace_spans_finished {ts['finished']}",
            "# HELP ceph_trace_spans_dropped finished spans dropped "
            "past the trace_keep ring bound",
            "# TYPE ceph_trace_spans_dropped counter",
            f"ceph_trace_spans_dropped {ts['dropped']}",
            "# HELP ceph_trace_spans_unfinished started-but-unfinished "
            "spans right now (a leak detector: quiesced == 0)",
            "# TYPE ceph_trace_spans_unfinished gauge",
            f"ceph_trace_spans_unfinished {ts['unfinished']}",
        ]
        hist_text = histograms_prometheus_text()
        if hist_text:
            lines.append(hist_text)
    except Exception:  # noqa: BLE001 -- exposition must never fail
        pass
    try:
        # wire-tax profiler cost centers (ceph_tpu/profiling/): empty
        # string when profile_mode is off
        from ceph_tpu import profiling as _profiling

        prof_text = _profiling.prometheus_text()
        if prof_text:
            lines.append(prof_text)
    except Exception:  # noqa: BLE001 -- exposition must never fail
        pass
    lines += ["# HELP ceph_pool_objects logical objects in the pool",
              "# TYPE ceph_pool_objects gauge",
              f"ceph_pool_objects {state['pools']['num_objects']}",
              "# HELP ceph_degraded_objects objects with shards on down "
              "OSDs",
              "# TYPE ceph_degraded_objects gauge",
              f"ceph_degraded_objects {len(state['degraded_objects'])}"]
    # per-daemon perf counters, flattened
    lines += ["# HELP ceph_osd_perf per-OSD perf counters",
              "# TYPE ceph_osd_perf counter"]
    for name, s in sorted(state["osd_stats"].items()):
        for counter, value in sorted(s["perf"].items()):
            if isinstance(value, (int, float)):
                lines.append(
                    f'ceph_osd_perf{{ceph_daemon="{name}",'
                    f'counter="{counter}"}} {value}'
                )
    return "\n".join(lines) + "\n"


class MgrDaemon:
    """HTTP endpoint: /metrics (prometheus), /health, /status (JSON)."""

    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 0,
                 registry=None):
        self.state = ClusterState(cluster)
        if registry is None:
            from ceph_tpu.mgr.module_host import PyModuleRegistry

            registry = PyModuleRegistry(cluster)
        self.registry = registry
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        # host the modules: serve() loops + map-change notifications
        # (ActivePyModules lifecycle; the reference notifies modules on
        # every map/health epoch -- polled here, the in-process cluster
        # has no mgr subscription channel)
        self.registry.start()
        self._notify_task = asyncio.get_event_loop().create_task(
            self._notify_loop()
        )
        return self.port

    async def _notify_loop(self, interval: float = 1.0) -> None:
        last_up = None
        while True:
            up = tuple(
                not self.state.cluster.messenger.is_down(o.name)
                for o in self.state.cluster.osds
            )
            if up != last_up:
                last_up = up
                self.registry.notify_all("osd_map")
            await asyncio.sleep(interval)

    async def stop(self) -> None:
        task = getattr(self, "_notify_task", None)
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        await self.registry.stop()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            request = await reader.readline()
            while True:  # drain headers
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            path = request.split()[1].decode() if request.split() else "/"
            if path == "/metrics":
                # served BY the prometheus module through the host surface
                prom = self.registry.modules.get("prometheus")
                body = (prom.metrics() if prom is not None
                        else prometheus_text(self.state.dump()))
                ctype = "text/plain; version=0.0.4"
                code = "200 OK"
            elif path == "/health":
                import json

                body = json.dumps(self.registry.gather_health())
                ctype = "application/json"
                code = "200 OK"
            elif path == "/status":
                import json

                body = json.dumps(self.state.dump())
                ctype = "application/json"
                code = "200 OK"
            else:
                body, ctype, code = "not found\n", "text/plain", "404 Not Found"
            data = body.encode()
            writer.write(
                f"HTTP/1.1 {code}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(data)}\r\n"
                "Connection: close\r\n\r\n".encode() + data
            )
            await writer.drain()
        finally:
            writer.close()
