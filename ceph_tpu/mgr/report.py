"""MgrClient-style daemon telemetry: beacon + report frames.

Reference: src/mgr/MgrClient.cc -- every daemon opens a session to the
active mgr and ships (a) a lightweight beacon proving liveness and (b) a
periodic ``MMgrReport`` carrying its perf-counter deltas and, for OSDs,
``MPGStats`` per-PG statistics.  The mgr's DaemonServer folds those into
the PGMap; health is derived from the *wire-fed* map, never from
in-process introspection -- which is what lets ``ceph -s`` work against
a cluster of separate processes.

Same split here:

* :class:`MgrBeacon` / :class:`MgrReport` -- typed wire messages
  (``msg/wire.py`` codecs) with the repo's trailing-optional-field
  compat discipline: the ``lag_ms`` tail is remaining()-guarded, so
  pre-lag peers interop both ways (the reqid/trace/qos_class pattern).
* :class:`ReportSender` -- the per-daemon report loop: one beacon per
  ``mgr_beacon_interval``, one report per ``mgr_report_interval``, both
  to every ``mgr.*`` entity in the address map.  Lossy by design: a
  dead mgr costs nothing but the send attempt, and a restarted mgr
  rebuilds its map from the next round of reports.
* :class:`LoopLagProbe` -- the sampled event-loop lag gauge shipped in
  every beacon/report: a sleeper task measures its own scheduling
  drift (requested vs actual sleep), EWMA-smoothed.  This is the
  direct per-daemon forcing metric for the Python-wire-loop ceiling
  (ROADMAP item 2): under loadgen saturation the lag attributes the
  stall to a specific daemon.

The ``REPORTED_COUNTERS`` / ``REPORTED_COUNTER_PREFIXES`` tables below
are the report *schema*: the subset of each daemon's perf counters that
ships in report frames (bounded frame size) and therefore reaches the
aggregated mgr exposition.  The cephlint rule ``perf-counter-unexported``
(analysis/rules_perf.py) enforces that every counter a daemon increments
is either named here, matches a prefix, or carries a justified inline
disable -- so new counters cannot silently stay invisible to operators.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Callable, Dict, Iterable, List, Optional

#: report schema version (bumped when the ``stats`` dict shape changes;
#: the decoder keeps old fields readable -- consumers .get() everything)
REPORT_SCHEMA_VERSION = 1

#: exact counter names shipped in MgrReport frames.  The PGMap rate
#: engine reads client_ops / client_wr_bytes / client_rd_bytes /
#: recovery_bytes deltas for the ``ceph -s`` io block.
REPORTED_COUNTERS = frozenset({
    "client_ops", "client_wr_bytes", "client_rd_bytes",
    "sub_write", "sub_read", "sub_write_stale", "sub_write_missed_base",
    "sub_write_rollback",
    "write", "read", "write_range", "read_range", "read_cache_hit",
    "write_conflict", "degraded_read", "stale_shards_dropped",
    "rolled_back_version_skipped", "remove_torn_copy",
    "read_crc_error", "deep_scrub", "snap_trim",
    "slow_ops", "cap_denied", "queued_client_op",
    "mesh_claim_miss", "pglog_rollback", "obj_versions_serve",
    # regenerating-repair lane: beta-sized helper symbols computed by
    # survivors (the repair-bandwidth story's survivor-side half)
    "regen_helpers_served",
    # client-side Objecter counters (exported through the in-process
    # ClusterState client_perf block and any client-side scrape)
    "primary_failover", "write_conflict_retry", "client_inflight_hwm",
})

#: counter-name prefixes shipped wholesale (whole families: QoS classes,
#: recovery/scrub/tier/peering/backoff/dup machinery, op-queue kinds)
REPORTED_COUNTER_PREFIXES = (
    "qos_", "recovery_", "recover", "scrub_", "tier_", "peering_",
    "pg_", "backoff_", "dup_", "queued_", "op_", "notify_", "watch_",
    "probe_", "false_demotion", "loop_lag_",
)


def counter_reported(key: str) -> bool:
    """Is ``key`` part of the report schema (ships in MgrReport frames)?"""
    return key in REPORTED_COUNTERS or key.startswith(
        REPORTED_COUNTER_PREFIXES)


def filter_counters(snapshot: Dict[str, object]) -> Dict[str, object]:
    """The report-schema slice of a PerfCounters snapshot (plain ints
    and tinc {avgcount, sum} dicts only -- everything value()-encodable)."""
    out: Dict[str, object] = {}
    for key, val in snapshot.items():
        if not counter_reported(key):
            continue
        if isinstance(val, (int, float)) or (
            isinstance(val, dict)
            and set(val) <= {"avgcount", "sum"}
        ):
            out[key] = val
    return out


# -- typed wire messages ----------------------------------------------------


@dataclasses.dataclass
class MgrBeacon:
    """Liveness proof (the MMgrBeacon role): tiny, frequent, lossy.
    ``lag_ms`` is a trailing optional wire field -- pre-lag senders end
    at ``seq`` and pre-lag decoders ignore the tail."""

    name: str
    seq: int
    lag_ms: Optional[float] = None


@dataclasses.dataclass
class MgrReport:
    """Periodic daemon statistics (MMgrReport + MPGStats in one frame).

    ``stats`` is the schema-versioned payload dict -- per-PG stats under
    ``"pgs"``, store totals under ``"store"``, the perf-counter slice
    under ``"perf"``, histogram marginals under ``"hist"`` (see
    ``OSDShard.mgr_report_stats``).  ``lag_ms`` is the same trailing
    optional tail as the beacon's."""

    name: str
    seq: int
    interval: float
    stats: dict
    lag_ms: Optional[float] = None


# -- the sampled event-loop lag probe ---------------------------------------


class LoopLagProbe:
    """Event-loop lag gauge with ONE source of truth per daemon.

    When the wire-tax profiler's event-loop arm is active
    (``profile_mode`` on/full, ``ceph_tpu/profiling/loopmon.py``), the
    probe reads ITS scheduling-latency EWMA -- every timer callback's
    oversleep, not a 10 Hz sample -- and spawns nothing: one lag number
    feeds both the MgrReport ``lag_ms`` field and the profiler ledger,
    and there is no second sampled-sleep task competing with the loop
    it measures.  With profiling off, the round-18 sampled sleeper is
    the fallback: sleep ``interval``, measure oversleep, EWMA-smooth.

    Oversleep is exactly the time this daemon's event loop spent unable
    to schedule a ready task -- the per-daemon Python-wire-loop stall
    metric.  The hwm also lands in the perf registry
    (``loop_lag_hwm_us``) so it rides the normal counter plumbing."""

    def __init__(self, perf=None, interval: float = 0.1,
                 alpha: float = 0.25):
        self.perf = perf
        self.interval = interval
        self.alpha = alpha
        self._lag_ms = 0.0
        self._lag_hwm_ms = 0.0
        self._task: Optional[asyncio.Task] = None

    @staticmethod
    def _monitor():
        from ceph_tpu import profiling

        return profiling.loop_monitor()

    @property
    def lag_ms(self) -> float:
        mon = self._monitor()
        return mon.lag_ms if mon is not None else self._lag_ms

    @property
    def lag_hwm_ms(self) -> float:
        mon = self._monitor()
        return mon.lag_hwm_ms if mon is not None else self._lag_hwm_ms

    async def _run(self) -> None:
        loop = asyncio.get_event_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval)
            if self._monitor() is not None:
                continue  # the profiler arm took over mid-run: idle
            drift_ms = max(0.0, (loop.time() - t0 - self.interval) * 1e3)
            self._lag_ms += self.alpha * (drift_ms - self._lag_ms)
            if drift_ms > self._lag_hwm_ms:
                self._lag_hwm_ms = drift_ms
                if self.perf is not None:
                    self.perf.hwm("loop_lag_hwm_us", int(drift_ms * 1e3))

    def start(self, messenger=None, name: str = "lagprobe") -> None:
        if self._task is not None:
            return
        if self._monitor() is not None:
            return  # profiler loop arm active: it IS the lag source
        self._task = asyncio.get_event_loop().create_task(self._run())
        if messenger is not None:
            messenger.adopt_task(f"{name}.lagprobe", self._task)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


# -- the per-daemon report loop ---------------------------------------------


class ReportSender:
    """The MgrClient role: beacon + report loop for one daemon.

    ``build_stats`` returns the report payload dict (must contain only
    value()-encodable data); it runs once per report interval, so it
    must stay O(counters), never O(objects) -- the incremental per-PG
    accounting exists precisely so this holds."""

    def __init__(self, name: str, messenger,
                 build_stats: Callable[[], dict],
                 mgr_targets: Iterable[str],
                 perf=None, lag_probe: Optional[LoopLagProbe] = None):
        from ceph_tpu.utils.config import get_config

        self.name = name
        self.messenger = messenger
        self.build_stats = build_stats
        self.targets: List[str] = sorted(mgr_targets)
        cfg = get_config()
        self.beacon_interval = float(cfg.get_val("mgr_beacon_interval"))
        self.report_interval = float(cfg.get_val("mgr_report_interval"))
        self.lag_probe = lag_probe or LoopLagProbe(perf=perf)
        self._seq = 0
        self._task: Optional[asyncio.Task] = None

    async def _send(self, msg) -> None:
        for target in self.targets:
            try:
                await self.messenger.send_message(self.name, target, msg)
            except (OSError, asyncio.TimeoutError):
                pass  # mgr down: beacons/reports are lossy by contract

    async def send_report_now(self) -> None:
        """One report frame immediately (tests + the pre-shutdown
        flush)."""
        self._seq += 1
        await self._send(MgrReport(
            name=self.name, seq=self._seq,
            interval=self.report_interval,
            stats=self.build_stats(),
            lag_ms=round(self.lag_probe.lag_ms, 3),
        ))

    async def _run(self) -> None:
        last_report = 0.0
        loop = asyncio.get_event_loop()
        while True:
            await asyncio.sleep(self.beacon_interval)
            self._seq += 1
            await self._send(MgrBeacon(
                name=self.name, seq=self._seq,
                lag_ms=round(self.lag_probe.lag_ms, 3),
            ))
            now = loop.time()
            if now - last_report >= self.report_interval:
                last_report = now
                await self.send_report_now()

    def start(self) -> None:
        """Start the loop (idempotent); the task is adopted by the
        messenger so shutdown cancels it with everything else."""
        if self._task is not None or not self.targets:
            return
        self.lag_probe.start(self.messenger, self.name)
        self._task = asyncio.get_event_loop().create_task(self._run())
        self.messenger.adopt_task(f"{self.name}.mgr-report", self._task)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.lag_probe.stop()


def mgr_targets_from(addr_map: Dict[str, object]) -> List[str]:
    """The mgr entities a daemon should report to (``mgr.*`` keys of the
    cluster address book; empty = telemetry off, zero overhead)."""
    return sorted(k for k in addr_map if k.startswith("mgr."))
