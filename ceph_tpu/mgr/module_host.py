"""Mgr python-module host (reference: src/mgr/PyModuleRegistry.cc +
ActivePyModules.cc + src/pybind/mgr/mgr_module.py).

The reference mgr is a *platform*: modules (prometheus, status, balancer,
dashboard...) subclass ``MgrModule``, are loaded by name from the
``mgr_modules`` config option, and talk to the cluster exclusively
through the host surface -- ``get(what)`` for cluster state, ``notify``
for event push, ``set_health_checks`` to raise module-owned health,
``handle_command`` for CLI verbs, and an optional long-running
``serve()`` loop.  Same contract here; third-party modules load from any
importable dotted path (the pybind/mgr sys.path role), builtin modules
from ``ceph_tpu.mgr.mgr_modules.<name>``.  Each module's entry point is
a class named ``Module`` subclassing ``MgrModule``.
"""

from __future__ import annotations

import asyncio
import importlib
from typing import Dict, List, Optional

from ceph_tpu.mgr.mgr import ClusterState, health_checks, prometheus_text

BUILTIN_PACKAGE = "ceph_tpu.mgr.mgr_modules"


class MgrModule:
    """Base class every mgr module subclasses (mgr_module.py MgrModule)."""

    NAME = "module"

    def __init__(self, host: "PyModuleRegistry"):
        self._host = host
        self._health: Dict[str, dict] = {}

    # -- host surface ------------------------------------------------------

    def get(self, what: str):
        """Cluster state by key ("osd_stats", "pools", "health",
        "degraded_objects", "scrub_inconsistent", "dump" for everything)
        -- the ActivePyModules::get_python role."""
        return self._host.get(what)

    def get_config(self, key: str, default=None):
        return self._host.module_config.get(self.NAME, {}).get(key, default)

    def set_config(self, key: str, value) -> None:
        self._host.module_config.setdefault(self.NAME, {})[key] = value

    def set_health_checks(self, checks: Dict[str, dict]) -> None:
        """Module-owned health checks merged into the cluster health
        (MgrModule.set_health_checks)."""
        self._health = dict(checks)

    # -- module hooks ------------------------------------------------------

    def notify(self, what: str, ident) -> None:
        """Event push ("osd_map", "health", "pg_summary"...)."""

    def handle_command(self, cmd: dict):
        """CLI verb dispatch; return (retcode, out, status_string)."""
        return -22, "", f"module {self.NAME} has no commands"

    async def serve(self) -> None:
        """Optional long-running loop (dashboard/prometheus server role)."""

    def shutdown(self) -> None:
        """Called when the host stops."""


class PyModuleRegistry:
    """Loads, hosts and routes to mgr modules (PyModuleRegistry +
    ActivePyModules)."""

    def __init__(self, cluster, modules: Optional[List[str]] = None):
        self.state = ClusterState(cluster)
        self.module_config: Dict[str, dict] = {}
        self.modules: Dict[str, MgrModule] = {}
        self._serve_tasks: List[asyncio.Task] = []
        if modules is None:
            from ceph_tpu.utils.config import get_config

            modules = str(get_config().get_val("mgr_modules")).split()
        for name in modules:
            self.load(name)

    # -- loading -----------------------------------------------------------

    def load(self, name: str) -> MgrModule:
        """Load a module by bare name (builtin) or dotted path
        (third-party); its ``Module`` class is instantiated against this
        host.  Raises ImportError/TypeError on a broken module -- the
        registry's error paths are testable like the EC plugin loader's."""
        target = name if "." in name else f"{BUILTIN_PACKAGE}.{name}"
        py = importlib.import_module(target)
        cls = getattr(py, "Module", None)
        if cls is None or not issubclass(cls, MgrModule):
            raise TypeError(
                f"mgr module {name!r} has no Module(MgrModule) class"
            )
        mod = cls(self)
        # NAME from the subclass itself; an inherited default means the
        # module didn't set one -> use the dotted-path tail
        mod.NAME = cls.__dict__.get("NAME") or name.rsplit(".", 1)[-1]
        self.modules[mod.NAME] = mod
        return mod

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for mod in self.modules.values():
            self._serve_tasks.append(
                asyncio.get_event_loop().create_task(mod.serve())
            )

    async def stop(self) -> None:
        for t in self._serve_tasks:
            t.cancel()
        for t in self._serve_tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._serve_tasks.clear()
        for mod in self.modules.values():
            mod.shutdown()

    # -- host services -----------------------------------------------------

    def get(self, what: str):
        if what == "dump":
            return self.state.dump()
        if what == "osd_stats":
            return self.state.osd_stats()
        if what == "pools":
            return self.state.pool_stats()
        if what == "degraded_objects":
            return self.state.degraded_objects()
        if what == "scrub_inconsistent":
            return self.state.scrub_inconsistent()
        if what == "health":
            return self.gather_health()
        raise KeyError(what)

    def gather_health(self, dump: Optional[dict] = None) -> dict:
        """Cluster health = base checks merged with every module's
        raised checks (ClusterState::update + module health).  Pass an
        already-computed ``dump`` to avoid a second full state walk."""
        from ceph_tpu.mgr.pgmap import fold_health

        base = health_checks(dump if dump is not None else self.state.dump())
        checks = dict(base["checks"])
        for mod in self.modules.values():
            checks.update(mod._health)
        return fold_health(checks)

    def notify_all(self, what: str, ident=None) -> None:
        for mod in self.modules.values():
            try:
                mod.notify(what, ident)
            except Exception:  # noqa: BLE001 -- a module crash must not
                pass          # take down the host (ActivePyModules)

    def handle_command(self, cmd: dict):
        """Route ``{"prefix": "<module> <verb>", ...}`` to its module."""
        prefix = cmd.get("prefix", "")
        mod_name = prefix.split(" ", 1)[0]
        mod = self.modules.get(mod_name)
        if mod is None:
            return -2, "", f"no mgr module {mod_name!r}"
        return mod.handle_command(cmd)
