"""Manager daemon (reference: src/mgr + src/pybind/mgr).

The reference mgr aggregates daemon state (DaemonServer/ClusterState +
the mon's PGMap), evaluates health checks, and exports metrics through
python modules (prometheus, status, ...).  Same roles here:

* ``ClusterState`` -- pulls per-OSD perf counters + store usage and the
  cluster's liveness/placement view (the PGMap/DaemonState role);
* ``health_checks`` -- OSD_DOWN / PG_DEGRADED-style checks with the
  reference's HEALTH_OK/WARN/ERR severities (src/mon/health_check.h);
* ``prometheus_text`` -- Prometheus exposition (pybind/mgr/prometheus);
* ``MgrDaemon`` -- an asyncio HTTP endpoint serving /metrics and
  /health (the mgr module HTTP server role).
"""

from ceph_tpu.mgr.mgr import ClusterState, MgrDaemon, health_checks, \
    prometheus_text
from ceph_tpu.mgr.module_host import MgrModule, PyModuleRegistry
from ceph_tpu.mgr.pgmap import MgrServer, PGMap
from ceph_tpu.mgr.report import (LoopLagProbe, MgrBeacon, MgrReport,
                                 ReportSender)

__all__ = ["ClusterState", "MgrDaemon", "health_checks", "prometheus_text",
           "MgrModule", "PyModuleRegistry", "PGMap", "MgrServer",
           "MgrBeacon", "MgrReport", "ReportSender", "LoopLagProbe"]
