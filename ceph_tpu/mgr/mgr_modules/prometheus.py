"""prometheus module: exposition text (pybind/mgr/prometheus role).

Renders through the module-host ``get()`` surface only -- the module
sees exactly what any third-party module would.
"""

from __future__ import annotations

from ceph_tpu.mgr.mgr import prometheus_text
from ceph_tpu.mgr.module_host import MgrModule


class Module(MgrModule):
    NAME = "prometheus"

    def metrics(self) -> str:
        return prometheus_text(self.get("dump"))

    def handle_command(self, cmd: dict):
        verb = cmd.get("prefix", "").split(" ", 1)[-1]
        if verb == "metrics":
            return 0, self.metrics(), ""
        return -22, "", f"unknown prometheus verb {verb!r}"
