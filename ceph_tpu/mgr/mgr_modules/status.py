"""status module: cluster summary text (pybind/mgr/status role)."""

from __future__ import annotations

from ceph_tpu.mgr.module_host import MgrModule


class Module(MgrModule):
    NAME = "status"

    def handle_command(self, cmd: dict):
        verb = cmd.get("prefix", "").split(" ", 1)[-1]
        if verb == "status":
            state = self.get("dump")
            health = self._host.gather_health(dump=state)
            osdmap = state["osdmap"]
            lines = [
                f"health: {health['status']}",
                f"osd: {osdmap['num_osds']} osds: "
                f"{osdmap['num_up_osds']} up",
                f"pools: {state['pools']['num_objects']} objects",
            ]
            for name, chk in health["checks"].items():
                lines.append(f"  {name}: {chk['summary']}")
            if state["degraded_objects"]:
                lines.append(
                    f"degraded: {len(state['degraded_objects'])} objects"
                )
            return 0, "\n".join(lines) + "\n", ""
        return -22, "", f"unknown status verb {verb!r}"
