"""Builtin mgr modules (the src/pybind/mgr tree's role)."""
