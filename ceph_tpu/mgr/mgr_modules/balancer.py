"""balancer module: even out shard placement by CRUSH reweighting.

Reference: src/pybind/mgr/balancer (crush-compat mode) -- score the
distribution of placements over OSDs, and nudge CRUSH weights of
overloaded OSDs down (bounded per step) so the mapper moves work away;
recovery then migrates the data.  Commands mirror the reference's
``ceph balancer status / eval / optimize``.
"""

from __future__ import annotations

from ceph_tpu.mgr.module_host import MgrModule


class Module(MgrModule):
    NAME = "balancer"

    #: largest single-step weight change (balancer max_misplaced role:
    #: bound churn per optimization round)
    MAX_STEP = 0.25
    MIN_WEIGHT = 0.25

    def _distribution(self):
        stats = self.get("osd_stats")
        up = {name: st for name, st in stats.items() if st["up"]}
        return {name: st["num_shards"] for name, st in up.items()}

    def _score(self, dist) -> float:
        """0 = perfectly even; the reference's eval score is also a
        deviation-from-ideal measure."""
        if not dist or sum(dist.values()) == 0:
            return 0.0
        mean = sum(dist.values()) / len(dist)
        if mean == 0:
            return 0.0
        var = sum((v - mean) ** 2 for v in dist.values()) / len(dist)
        return (var ** 0.5) / mean

    def handle_command(self, cmd: dict):
        verb = cmd.get("prefix", "").split(" ", 1)[-1]
        dist = self._distribution()
        if verb == "status":
            return 0, (
                f"balancer score {self._score(dist):.4f} "
                f"(0 = even) over {len(dist)} up osds\n"
            ), ""
        if verb == "eval":
            mean = (sum(dist.values()) / len(dist)) if dist else 0
            lines = [f"ideal shards/osd: {mean:.1f}"]
            for name in sorted(dist):
                lines.append(f"{name}\t{dist[name]}")
            lines.append(f"score {self._score(dist):.4f}")
            return 0, "\n".join(lines) + "\n", ""
        if verb == "optimize":
            placement = self._host.state.cluster.placement
            if placement is None:
                return -22, "", "cluster has no CRUSH placement"
            if not dist or sum(dist.values()) == 0:
                return 0, "nothing to balance\n", ""
            mean = sum(dist.values()) / len(dist)
            changed = []
            for name, shards in dist.items():
                osd_id = int(name.split(".")[1])
                cur = placement.weights[osd_id] / 0x10000
                if cur <= self.MIN_WEIGHT:
                    # never RAISE a weight: an admin-drained or already-
                    # floored OSD must not be pulled back into placement
                    continue
                if mean == 0:
                    continue
                # dampened correction toward the ideal, bounded per
                # step, in BOTH directions within (MIN_WEIGHT, 1.0] --
                # under-loaded OSDs recover headroom so repeated rounds
                # never ratchet the whole cluster to the floor
                target = cur * (mean / shards) ** 0.5 if shards else 1.0
                new = min(1.0, max(self.MIN_WEIGHT,
                                   max(cur - self.MAX_STEP,
                                       min(cur + self.MAX_STEP, target))))
                if abs(new - cur) < 1e-3:
                    continue
                placement.reweight(osd_id, new)
                changed.append(f"{name}: {cur:.2f} -> {new:.2f}")
            if not changed:
                return 0, "distribution already within bounds\n", ""
            # reweight bumped the placement epoch: the OSDs' background
            # peering ticks observe it and migrate remapped shards
            return 0, "reweighted " + ", ".join(changed) + "\n", ""
        return -22, "", f"unknown balancer verb {verb!r}"
