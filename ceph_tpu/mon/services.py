"""Monitor services beyond the OSDMonitor (the PaxosService family).

Reference: src/mon/PaxosService.{h,cc} -- every monitor hosts a set of
services that share the one paxos instance; each service owns a slice
of replicated state and applies committed increments to it.  Here the
slices are plain objects on the Monitor and increments are routed by
their ``op`` prefix in ``Monitor._on_commit``:

- ``ConfigKeyStore`` -- src/mon/ConfigKeyService.cc: a replicated
  key/value store (``ceph config-key set/get/rm/ls``), used by mgr
  modules and deployment tooling for small blobs.
- ``ConfigStore`` -- the centralized daemon-config service
  (src/mon/ConfigMonitor.cc role): ``ceph config set <who> <opt> <val>``
  stores options by section (global / daemon-type / daemon-name); each
  commit pushes the merged view to subscribers so daemons pick up
  changes at runtime (MonClient config notifications).
- ``ClusterLog`` -- src/mon/LogMonitor.cc + src/common/LogClient.cc:
  daemons send cluster-log entries (clog) to the monitors; the leader
  sequences them through paxos into a bounded replicated ring served
  by ``ceph log last``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional


class ConfigKeyStore:
    """Replicated flat KV (ConfigKeyService)."""

    def __init__(self):
        self.kv: Dict[str, str] = {}

    def apply(self, inc: dict) -> None:
        if inc["op"] == "kv_set":
            self.kv[inc["key"]] = inc["value"]
        elif inc["op"] == "kv_rm":
            self.kv.pop(inc["key"], None)


class ConfigStore:
    """Centralized daemon configuration by section (ConfigMonitor).

    Sections, most-generic first: ``global``, a daemon type (``osd``,
    ``mon``, ``mds``, ``mgr``), or a full daemon name (``osd.3``).
    ``entity_view`` merges them in that order, so the most specific
    section wins -- the reference's mask/section precedence."""

    def __init__(self):
        self.sections: Dict[str, Dict[str, str]] = {}
        self.version = 0

    def apply(self, inc: dict) -> None:
        self.version += 1
        sec = self.sections.setdefault(inc["who"], {})
        if inc["op"] == "config_set":
            sec[inc["name"]] = inc["value"]
        elif inc["op"] == "config_rm":
            sec.pop(inc["name"], None)
            if not sec:
                self.sections.pop(inc["who"], None)

    def entity_view(self, entity: str) -> Dict[str, str]:
        """The merged option map one daemon should run with."""
        merged: Dict[str, str] = {}
        sections = ["global"]
        if "." in entity:
            sections.append(entity.split(".")[0])
        sections.append(entity)
        for s in sections:
            merged.update(self.sections.get(s, {}))
        return merged

    def dump(self) -> Dict[str, Dict[str, str]]:
        return {s: dict(kv) for s, kv in self.sections.items()}


class ClusterLog:
    """Bounded replicated cluster log ring (LogMonitor)."""

    MAX_ENTRIES = 10_000
    LEVELS = ("debug", "info", "warn", "error")

    def __init__(self):
        self.entries: List[dict] = []
        self.seq = 0

    def apply(self, inc: dict) -> None:
        self.seq += 1
        level = inc.get("level", "info")
        if level not in self.LEVELS:
            level = "info"  # a bad replicated entry must never poison
            # LEVELS.index() in every future filtered query
        self.entries.append({
            "seq": self.seq,
            "stamp": inc.get("stamp", 0.0),
            "who": inc.get("who", "?"),
            "level": level,
            "message": inc.get("message", ""),
        })
        if len(self.entries) > self.MAX_ENTRIES:
            del self.entries[: len(self.entries) - self.MAX_ENTRIES]

    def last(self, n: int = 20, level: Optional[str] = None) -> List[dict]:
        """The newest ``n`` entries at or above ``level`` (the
        ``ceph log last [n] [level]`` surface), oldest first."""
        if level is None:
            picked = self.entries
        else:
            floor = self.LEVELS.index(level)
            picked = [e for e in self.entries
                      if self.LEVELS.index(e.get("level", "info")) >= floor]
        return [dict(e) for e in picked[-n:]]


class LogClient:
    """Daemon-side clog sender (src/common/LogClient.cc): queues one
    cluster-log entry per call through the mon command path (any mon
    forwards to the leader)."""

    def __init__(self, mon_client, who: str):
        self.monc = mon_client
        self.who = who

    async def _log(self, level: str, message: str):
        return await self.monc.command({
            "prefix": "log", "who": self.who, "level": level,
            "message": message, "stamp": time.time(),
        })

    async def debug(self, message: str):
        return await self._log("debug", message)

    async def info(self, message: str):
        return await self._log("info", message)

    async def warn(self, message: str):
        return await self._log("warn", message)

    async def error(self, message: str):
        return await self._log("error", message)


class AuthDB:
    """Replicated entity/key/caps store (src/mon/AuthMonitor.cc role).

    Entities (``client.admin``, ``osd.3``, ...) each hold a secret and a
    caps map; ``auth get-or-create`` mints a key exactly once, ``auth
    rotate`` replaces it (the reference's rotating service keys reduced
    to explicit per-entity rotation -- ticket renewal then picks up the
    new secret on the next handshake)."""

    def __init__(self):
        self.entities: Dict[str, dict] = {}
        self.version = 0

    def apply(self, inc: dict) -> None:
        self.version += 1
        op = inc["op"]
        if op == "auth_add":
            self.entities[inc["entity"]] = {
                "key": inc["key"], "caps": dict(inc.get("caps") or {}),
            }
        elif op == "auth_caps":
            ent = self.entities.get(inc["entity"])
            if ent is not None:
                ent["caps"] = dict(inc.get("caps") or {})
        elif op == "auth_rotate":
            ent = self.entities.get(inc["entity"])
            if ent is not None:
                ent["key"] = inc["key"]
        elif op == "auth_rm":
            self.entities.pop(inc["entity"], None)


class MgrMap:
    """Active/standby manager map (src/mon/MgrMonitor.cc role).

    Daemons send ``mgr beacon``; the first becomes active, later ones
    queue as standbys; ``mgr fail`` (or a beacon arriving while the
    active's beacons are stale past the grace) promotes a standby."""

    def __init__(self):
        self.epoch = 0
        self.active: Optional[str] = None
        self.standbys: List[str] = []

    def apply(self, inc: dict) -> None:
        self.epoch += 1
        op = inc["op"]
        if op == "mgr_register":
            name = inc["name"]
            if self.active is None:
                self.active = name
            elif name != self.active and name not in self.standbys:
                self.standbys.append(name)
        elif op == "mgr_failover":
            failed = inc.get("failed")
            if failed == self.active:
                self.active = self.standbys.pop(0) if self.standbys \
                    else None
            elif failed in self.standbys:
                self.standbys.remove(failed)

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "active": self.active,
                "standbys": list(self.standbys)}


class FSMap:
    """Filesystem / MDS rank map (src/mon/MDSMonitor.cc FSMap role).

    ``fs new`` creates a filesystem with ``max_mds`` ranks; ``mds
    beacon`` registers daemons (filling vacant ranks first, then the
    standby pool); ``mds_failover`` vacates a rank and promotes a
    standby -- the standby-takeover flow the MDS cluster tests drive."""

    def __init__(self):
        self.epoch = 0
        self.filesystems: Dict[str, dict] = {}
        self.standbys: List[str] = []

    def apply(self, inc: dict) -> None:
        self.epoch += 1
        op = inc["op"]
        if op == "fs_new":
            self.filesystems[inc["name"]] = {
                "name": inc["name"],
                "max_mds": int(inc.get("max_mds", 1)),
                "ranks": {},
            }
            self._fill_ranks()
        elif op == "fs_rm":
            fs = self.filesystems.pop(inc["name"], None)
            if fs:
                for mds in fs["ranks"].values():
                    if mds not in self.standbys:
                        self.standbys.append(mds)
        elif op == "fs_set_max_mds":
            fs = self.filesystems.get(inc["name"])
            if fs:
                fs["max_mds"] = int(inc["max_mds"])
                if fs["max_mds"] < len(fs["ranks"]):
                    # shrink: highest ranks stop and return to standby
                    for r in sorted(fs["ranks"], reverse=True):
                        if len(fs["ranks"]) <= fs["max_mds"]:
                            break
                        self.standbys.append(fs["ranks"].pop(r))
                self._fill_ranks()
        elif op == "mds_register":
            name = inc["name"]
            if name not in self.standbys and not any(
                name in fs["ranks"].values()
                for fs in self.filesystems.values()
            ):
                self.standbys.append(name)
            self._fill_ranks()
        elif op == "mds_failover":
            failed = inc["name"]
            if failed in self.standbys:
                self.standbys.remove(failed)
            for fs in self.filesystems.values():
                for r, mds in list(fs["ranks"].items()):
                    if mds == failed:
                        del fs["ranks"][r]
            self._fill_ranks()

    def _fill_ranks(self) -> None:
        """Vacant ranks claim standbys (rank order, fs name order)."""
        for fs in sorted(self.filesystems.values(),
                         key=lambda f: f["name"]):
            for r in range(fs["max_mds"]):
                if r not in fs["ranks"] and self.standbys:
                    fs["ranks"][r] = self.standbys.pop(0)

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "filesystems": {
                n: {"name": f["name"], "max_mds": f["max_mds"],
                    "ranks": {str(r): m for r, m in f["ranks"].items()}}
                for n, f in self.filesystems.items()
            },
            "standbys": list(self.standbys),
        }
