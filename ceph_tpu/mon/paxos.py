"""Paxos as the monitors run it: one leader, versioned committed values.

Reference: src/mon/Paxos.cc — phase 1 collect/last (recovery after
election), phase 2 begin/accept (one in-flight proposal at a time, the
"updating" state), commit broadcast; proposal numbers grow by 100 with the
proposer's rank in the low digits (Paxos::get_new_proposal_number).

The store is the MonitorDBStore analogue: a dict of version -> value with
last_committed/accepted_pn markers; every mutation lands there before a
message goes out, which is what makes crash-recovery sound.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class PaxosStore:
    values: Dict[int, dict] = field(default_factory=dict)
    last_committed: int = 0
    accepted_pn: int = 0
    # uncommitted value carried across recovery (Paxos.cc handle_last)
    uncommitted_v: Optional[int] = None
    uncommitted_value: Optional[dict] = None
    #: optional durable backing (MonitorDBStore over the LSM KeyValueDB;
    #: the reference's store.db RocksDB).  When attached, every paxos
    #: state change lands on disk before the next message goes out.
    db: object = None

    # kv layout: prefix "P" version -> value, prefix "T" paxos metadata
    def attach(self, db) -> None:
        from ceph_tpu.utils.encoding import Decoder

        self.db = db
        for key, raw in db.get_iterator("P"):
            self.values[int(key)] = Decoder(raw).value()
        meta = db.get("T", "meta")
        if meta is not None:
            m = Decoder(meta).value()
            self.last_committed = m["last_committed"]
            self.accepted_pn = m["accepted_pn"]
            self.uncommitted_v = m["uncommitted_v"]
            self.uncommitted_value = m["uncommitted_value"]

    def persist_meta(self, txn=None) -> None:
        if self.db is None:
            return
        from ceph_tpu.kv.keyvaluedb import KVTransaction
        from ceph_tpu.utils.encoding import Encoder

        batch = txn or KVTransaction()
        batch.set("T", "meta", Encoder().value({
            "last_committed": self.last_committed,
            "accepted_pn": self.accepted_pn,
            "uncommitted_v": self.uncommitted_v,
            "uncommitted_value": self.uncommitted_value,
        }).bytes())
        if txn is None:
            self.db.submit_transaction(batch)

    def persist_commit(self, v: int) -> None:
        """Committed value + metadata in ONE batch (the reference's
        single MonitorDBStore transaction per commit)."""
        if self.db is None:
            return
        from ceph_tpu.kv.keyvaluedb import KVTransaction
        from ceph_tpu.utils.encoding import Encoder

        batch = KVTransaction()
        batch.set("P", str(v), Encoder().value(self.values[v]).bytes())
        self.persist_meta(batch)
        self.db.submit_transaction(batch)


class Paxos:
    """One monitor's paxos state machine.  Message I/O is delegated to the
    owning Monitor (send(rank, msg)); commit application via on_commit."""

    def __init__(
        self,
        rank: int,
        n_mons: int,
        send: Callable,
        on_commit: Callable[[int, dict], None],
    ):
        self.rank = rank
        self.n_mons = n_mons
        self.send = send
        self.on_commit = on_commit
        self.store = PaxosStore()
        self._accepts: set = set()
        self._lasts: Dict[int, dict] = {}
        self._proposal_done: Optional[asyncio.Future] = None
        self._collect_done: Optional[asyncio.Future] = None
        self._pending_value: Optional[dict] = None

    @property
    def majority(self) -> int:
        return self.n_mons // 2 + 1

    def new_pn(self) -> int:
        """reference: Paxos.cc get_new_proposal_number — multiple of 100
        plus rank, strictly above anything seen."""
        base = (self.store.accepted_pn // 100 + 1) * 100
        return base + self.rank

    # -- leader: recovery (phase 1) ---------------------------------------

    async def collect(self, quorum: List[int], timeout: float = 1.0) -> bool:
        """Run the collect/last round (retrying at a higher pn when a peon
        has promised a newer one — reference: handle_last's
        "uncommitted_pn > accepted_pn -> bootstrap" path); re-commits any
        uncommitted value learned from a peer.  True on success."""
        for _ in range(3):
            if await self._collect_once(quorum, timeout):
                return True
        return False

    async def _collect_once(self, quorum: List[int], timeout: float) -> bool:
        pn = self.new_pn()
        self.store.accepted_pn = pn
        self.store.persist_meta()
        self._lasts = {
            self.rank: {
                "last_committed": self.store.last_committed,
                "uncommitted_v": self.store.uncommitted_v,
                "uncommitted_value": self.store.uncommitted_value,
            }
        }
        self._collect_done = asyncio.get_event_loop().create_future()
        for r in quorum:
            if r != self.rank:
                await self.send(
                    r,
                    {
                        "type": "paxos_collect",
                        "pn": pn,
                        "last_committed": self.store.last_committed,
                    },
                )
        if len(self._lasts) < self.majority:
            try:
                ok = await asyncio.wait_for(self._collect_done, timeout)
            except asyncio.TimeoutError:
                return False
            if not ok:
                return False  # nacked: retry at a higher pn
        # adopt the newest uncommitted value seen (ours included)
        best = None
        for info in self._lasts.values():
            if info.get("uncommitted_v") is not None:
                if best is None or info["uncommitted_v"] > best[0]:
                    best = (info["uncommitted_v"], info["uncommitted_value"])
        if best is not None and best[0] == self.store.last_committed + 1:
            await self.propose(best[1], quorum)
        return True

    def handle_collect(self, src_rank: int, msg: dict) -> List[tuple]:
        """Peon side; returns [(rank, reply)] to send.  A stale pn gets a
        nack carrying our promised pn (so the caller can retry higher) but
        still shares committed values for catch-up."""
        reply = {
            "type": "paxos_last",
            "pn": msg["pn"],
            "last_committed": self.store.last_committed,
            "uncommitted_v": self.store.uncommitted_v,
            "uncommitted_value": self.store.uncommitted_value,
            "values": {
                v: self.store.values[v]
                for v in range(
                    msg["last_committed"] + 1, self.store.last_committed + 1
                )
                if v in self.store.values
            },
        }
        if msg["pn"] >= self.store.accepted_pn:
            self.store.accepted_pn = msg["pn"]
            self.store.persist_meta()
        else:
            reply["nack_pn"] = self.store.accepted_pn
        return [(src_rank, reply)]

    def handle_last(self, src_rank: int, msg: dict) -> List[tuple]:
        """Leader side; returns [(rank, msg)] share traffic to send.
        Catches up on commits the peer has and we lack AND shares our
        commits with a lagging peer (Paxos.cc share_state both ways --
        without the leader->peon half, a mon that missed commits while
        down would stay behind forever unless it won an election)."""
        for v, val in sorted(msg.get("values", {}).items()):
            v = int(v)
            if v == self.store.last_committed + 1:
                self._commit(v, val)
        if msg["pn"] != self.store.accepted_pn:
            return []  # stale round (incl. late nacks): ignore
        if "nack_pn" in msg:
            # a peon promised newer: adopt, so new_pn() goes above it and
            # the collect retry loop can win the next round
            if msg["nack_pn"] > self.store.accepted_pn:
                self.store.accepted_pn = msg["nack_pn"]
                self.store.persist_meta()
            if self._collect_done and not self._collect_done.done():
                self._collect_done.set_result(False)
            return []
        self._lasts[src_rank] = msg
        if (
            len(self._lasts) >= self.majority
            and self._collect_done
            and not self._collect_done.done()
        ):
            self._collect_done.set_result(True)
        out = []
        for v in range(int(msg["last_committed"]) + 1,
                       self.store.last_committed + 1):
            if v in self.store.values:
                out.append((src_rank, {
                    "type": "paxos_commit", "pn": msg["pn"],
                    "v": v, "value": self.store.values[v],
                }))
        return out

    # -- leader: proposal (phase 2) ---------------------------------------

    async def propose(
        self, value: dict, quorum: List[int], timeout: float = 1.0
    ) -> bool:
        """Begin/accept/commit one value at version last_committed+1."""
        v = self.store.last_committed + 1
        pn = self.store.accepted_pn
        # leader accepts its own proposal first (begin writes to store)
        self.store.uncommitted_v = v
        self.store.uncommitted_value = value
        self.store.persist_meta()
        self._accepts = {self.rank}
        self._proposal_done = asyncio.get_event_loop().create_future()
        for r in quorum:
            if r != self.rank:
                await self.send(
                    r,
                    {"type": "paxos_begin", "pn": pn, "v": v, "value": value},
                )
        if len(self._accepts) < self.majority:
            try:
                ok = await asyncio.wait_for(self._proposal_done, timeout)
            except asyncio.TimeoutError:
                return False
            if not ok:
                return False  # nacked: a newer pn exists; caller re-collects
        # majority accepted: commit locally and broadcast
        self._commit(v, value)
        for r in quorum:
            if r != self.rank:
                await self.send(
                    r, {"type": "paxos_commit", "pn": pn, "v": v, "value": value}
                )
        return True

    def handle_begin(self, src_rank: int, msg: dict) -> List[tuple]:
        if msg["pn"] < self.store.accepted_pn:
            # promised a newer leader: nack so the proposer re-collects
            return [
                (
                    src_rank,
                    {
                        "type": "paxos_accept",
                        "pn": msg["pn"],
                        "v": msg["v"],
                        "nack_pn": self.store.accepted_pn,
                    },
                )
            ]
        self.store.accepted_pn = msg["pn"]
        self.store.uncommitted_v = msg["v"]
        self.store.uncommitted_value = msg["value"]
        self.store.persist_meta()
        return [
            (src_rank, {"type": "paxos_accept", "pn": msg["pn"], "v": msg["v"]})
        ]

    def handle_accept(self, src_rank: int, msg: dict) -> None:
        if "nack_pn" in msg:
            if msg["nack_pn"] > self.store.accepted_pn:
                self.store.accepted_pn = msg["nack_pn"]
                self.store.persist_meta()
            if self._proposal_done and not self._proposal_done.done():
                self._proposal_done.set_result(False)
            return
        if msg["pn"] != self.store.accepted_pn:
            return
        if msg.get("v") != self.store.uncommitted_v:
            return  # delayed accept for an earlier value under the same pn
        self._accepts.add(src_rank)
        if (
            len(self._accepts) >= self.majority
            and self._proposal_done
            and not self._proposal_done.done()
        ):
            self._proposal_done.set_result(True)

    def handle_commit(self, src_rank: int, msg: dict) -> None:
        v = msg["v"]
        if v == self.store.last_committed + 1:
            self._commit(v, msg["value"])

    def _commit(self, v: int, value: dict) -> None:
        self.store.values[v] = value
        self.store.last_committed = v
        if self.store.uncommitted_v == v:
            self.store.uncommitted_v = None
            self.store.uncommitted_value = None
        # durable BEFORE application/broadcast (one MonitorDBStore batch)
        self.store.persist_commit(v)
        self.on_commit(v, value)
