"""Monitor daemon: election, paxos-replicated OSDMap, command handling.

Reference: src/mon/Monitor.cc (daemon + command dispatch),
src/mon/Elector.cc (rank-based election: lowest reachable rank wins),
src/mon/OSDMonitor.cc (profile set :5232, pool create :5529, get_erasure_code
:5353 — profiles validated by instantiating the plugin), map broadcast to
subscribers (Monitor::send_latest).  Clients may address any monitor;
non-leaders forward to the leader the way peons forward proposals.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ceph_tpu.mon.osdmap import OSDMap
from ceph_tpu.mon.paxos import Paxos
import copy

from ceph_tpu.mon.services import (AuthDB, ClusterLog, ConfigKeyStore,
                                   ConfigStore, FSMap, MgrMap)
from ceph_tpu.osd.messenger import Messenger
from ceph_tpu.utils.log import dout


class Monitor:
    def __init__(self, rank: int, n_mons: int, messenger: Messenger,
                 store_path: Optional[str] = None):
        self.rank = rank
        self.n_mons = n_mons
        self.name = f"mon.{rank}"
        self.messenger = messenger
        self.paxos = Paxos(rank, n_mons, self._send_to_rank, self._on_commit)
        self.osdmap = OSDMap()
        # the PaxosService family: slices of replicated state sharing
        # the one paxos instance (src/mon/PaxosService.h)
        self.kvstore = ConfigKeyStore()
        self.configdb = ConfigStore()
        self.clog = ClusterLog()
        self.authdb = AuthDB()
        self.mgrmap = MgrMap()
        self.fsmap = FSMap()
        #: leader-local beacon liveness (the reference keeps pending
        #: beacon state outside paxos too): daemon name -> last stamp
        self._beacons: Dict[str, float] = {}
        self._store_db = None
        if store_path is not None:
            # MonitorDBStore role: paxos state on an LSM KeyValueDB; a
            # restarted mon rebuilds its services by replaying the
            # committed values (Monitor::preinit + PaxosService
            # update_from_paxos)
            from ceph_tpu.kv.lsm import LSMStore

            self._store_db = LSMStore(store_path)
            self._store_db.open()
            self.paxos.store.attach(self._store_db)
            for v in sorted(self.paxos.store.values):
                if v <= self.paxos.store.last_committed:
                    self._apply_commit(self.paxos.store.values[v])
        self.leader: Optional[int] = None
        self.quorum: List[int] = []
        self.election_epoch = 0
        self._election_acks: set = set()
        self._election_done: Optional[asyncio.Future] = None
        self._subscribers: set = set()
        #: names spawned tasks uniquely (commands, subscriber pushes)
        self._cmd_seq = 0
        self._cmd_lock = asyncio.Lock()
        self._last_lease = 0.0
        #: pending OSD failure reports: osd id -> {reporter: stamp}
        #: (leader-local, like the reference's failure_info_t pending
        #: map).  Entries EXPIRE (see "osd failure" handling) so a
        #: reporter's one transient probe stall can never combine with an
        #: unrelated stall hours later to mark a healthy OSD down.
        self._failure_reports: Dict[int, Dict[str, float]] = {}
        messenger.register(self.name, self.dispatch)

    def close_store(self) -> None:
        """Release the durable store (a stopped mon; the tool can then
        open it offline)."""
        if self._store_db is not None:
            self._store_db.close()
            self._store_db = None
            self.paxos.store.db = None

    def start_tick(self, interval: float = 0.1, miss_factor: float = 4.0):
        """Lease probing (reference: Paxos lease extend/ack + Elector
        timers): peons probe the leader; on miss_factor*interval of
        silence they call an election."""
        loop = asyncio.get_event_loop()
        self._last_lease = loop.time()

        async def tick():
            while True:
                await asyncio.sleep(interval)
                if self.is_leader() or self.leader is None:
                    continue
                await self._send_to_rank(self.leader, {"type": "mon_lease_probe"})
                if loop.time() - self._last_lease > interval * miss_factor:
                    self._last_lease = loop.time()  # back off before retry
                    await self.start_election()

        self.messenger.adopt_task(f"{self.name}.tick", loop.create_task(tick()))

    # -- plumbing ----------------------------------------------------------

    async def _send_to_rank(self, rank: int, msg: dict) -> None:
        await self.messenger.send_message(self.name, f"mon.{rank}", msg)

    def is_leader(self) -> bool:
        return self.leader == self.rank

    @property
    def majority(self) -> int:
        return self.n_mons // 2 + 1

    # -- election (Elector.cc analogue) ------------------------------------

    async def start_election(self, timeout: float = 0.5) -> bool:
        """Propose self; lower-rank live mons preempt (they nack and run
        their own).  Victory on majority of defer-acks."""
        self.election_epoch += 1
        self.leader = None
        self._election_acks = {self.rank}
        self._election_done = asyncio.get_event_loop().create_future()
        for r in range(self.n_mons):
            if r != self.rank:
                await self._send_to_rank(
                    r, {"type": "election_propose", "epoch": self.election_epoch}
                )
        if len(self._election_acks) < self.majority:
            try:
                await asyncio.wait_for(self._election_done, timeout)
            except asyncio.TimeoutError:
                return False
        if self.leader is not None and self.leader != self.rank:
            return False  # preempted by a lower rank mid-election
        quorum = sorted(self._election_acks)
        for r in range(self.n_mons):
            if r != self.rank:
                await self._send_to_rank(
                    r,
                    {
                        "type": "election_victory",
                        "epoch": self.election_epoch,
                        "leader": self.rank,
                        "quorum": quorum,
                    },
                )
        if self.leader is not None and self.leader != self.rank:
            # a lower rank's victory landed while we were broadcasting
            # ours: writing self.rank here would clobber the real leader
            # (asyncsan rmw-across-await: the victory sends above yield)
            return False
        self.leader = self.rank
        self.quorum = quorum
        # recovery: bring the quorum's stores into agreement
        await self.paxos.collect(quorum)
        dout("mon", 5, f"{self.name} won election epoch {self.election_epoch}")
        return True

    async def _handle_election(self, src: str, msg: dict) -> None:
        src_rank = int(src.split(".")[1])
        t = msg["type"]
        if t == "election_propose":
            if msg["epoch"] > self.election_epoch:
                self.election_epoch = msg["epoch"]
            if src_rank < self.rank:
                # defer to the lower rank
                await self._send_to_rank(
                    src_rank,
                    {"type": "election_ack", "epoch": msg["epoch"]},
                )
            else:
                # I outrank them: run my own election (spawned -- it
                # awaits acks that arrive through this dispatch loop;
                # adopt_task retains it and logs a crash).  Unique name:
                # re-using one would untrack a still-running
                # predecessor, hiding it from shutdown's cancel rounds.
                self._cmd_seq += 1
                self.messenger.adopt_task(
                    f"{self.name}.election{self._cmd_seq}",
                    asyncio.get_event_loop().create_task(
                        self.start_election()),
                )
        elif t == "election_ack":
            if msg["epoch"] == self.election_epoch:
                self._election_acks.add(src_rank)
                if (
                    len(self._election_acks) >= self.majority
                    and self._election_done
                    and not self._election_done.done()
                ):
                    self._election_done.set_result(True)
        elif t == "election_victory":
            if msg["epoch"] >= self.election_epoch:
                self.election_epoch = msg["epoch"]
                self.leader = msg["leader"]
                self.quorum = msg["quorum"]

    # -- dispatch ----------------------------------------------------------

    async def dispatch(self, src: str, msg) -> None:
        if not isinstance(msg, dict):
            return
        t = msg.get("type", "")
        if t.startswith("election_"):
            await self._handle_election(src, msg)
        elif t == "paxos_collect":
            for rank, reply in self.paxos.handle_collect(
                int(src.split(".")[1]), msg
            ):
                await self._send_to_rank(rank, reply)
        elif t == "paxos_last":
            for rank, reply in self.paxos.handle_last(
                int(src.split(".")[1]), msg
            ):
                await self._send_to_rank(rank, reply)
        elif t == "paxos_begin":
            for rank, reply in self.paxos.handle_begin(
                int(src.split(".")[1]), msg
            ):
                await self._send_to_rank(rank, reply)
        elif t == "paxos_accept":
            self.paxos.handle_accept(int(src.split(".")[1]), msg)
        elif t == "paxos_commit":
            self.paxos.handle_commit(int(src.split(".")[1]), msg)
        elif t == "mon_lease_probe":
            if self.is_leader():
                await self.messenger.send_message(
                    self.name, src, {"type": "mon_lease"}
                )
        elif t == "mon_lease":
            if src == f"mon.{self.leader}":
                self._last_lease = asyncio.get_event_loop().time()
        elif t == "mon_subscribe":
            self._subscribers.add(src)
            await self.messenger.send_message(
                self.name,
                src,
                {"type": "osdmap", "map": self.osdmap.to_dict()},
            )
        elif t == "mon_command":
            # spawn: a proposal awaits peer accepts, which arrive through
            # this same dispatch loop — handling inline would deadlock.
            # adopt_task retains the task (collectable mid-flight
            # otherwise) and logs a handler crash.
            self._cmd_seq += 1
            self.messenger.adopt_task(
                f"{self.name}.cmd{self._cmd_seq}",
                asyncio.get_event_loop().create_task(
                    self._handle_command(src, msg)),
            )

    # -- committed-state application ---------------------------------------

    def _apply_commit(self, value: dict) -> str:
        """Route one committed increment to its service slice; returns
        the slice name (also used for startup replay from the durable
        store, where nothing is pushed)."""
        inc = value["inc"]
        op = inc.get("op", "")
        if op.startswith("kv_"):
            self.kvstore.apply(inc)
            return "kv"
        if op.startswith("config_"):
            self.configdb.apply(inc)
            return "config"
        if op == "clog_append":
            self.clog.apply(inc)
            return "clog"
        if op.startswith("auth_"):
            # membership BEFORE apply: the keyring hook below must only
            # revoke AuthDB-managed entities, never file-provisioned
            # quorum/admin keys (mon.*, client) that share the ring
            was_managed = inc.get("entity") in self.authdb.entities
            self.authdb.apply(inc)
            # a mon running with cephx verifies CONNECTING peers against
            # its own keyring: keys minted/rotated through the AuthDB
            # must flow into it, or daemons provisioned via
            # `auth get-or-create` could never connect (the reference
            # mon validates against its auth database the same way)
            ring = getattr(self.messenger, "keyring", None)
            if ring is not None:
                ent = inc.get("entity")
                if op in ("auth_add", "auth_rotate") and ent is not None:
                    have = self.authdb.entities.get(ent)
                    if have is not None:
                        try:
                            ring.add(ent, bytes.fromhex(have["key"]))
                        except ValueError:
                            pass  # non-hex externally-set key: skip
                elif op == "auth_rm" and ent is not None and was_managed:
                    # revocation must bite: a removed entity can no
                    # longer complete the cephx handshake (store replay
                    # re-applies add THEN rm, converging removed)
                    ring.remove(ent)
            return "auth"
        if op.startswith("mgr_"):
            self.mgrmap.apply(inc)
            return "mgrmap"
        if op.startswith(("fs_", "mds_")):
            self.fsmap.apply(inc)
            return "fsmap"
        self.osdmap.apply(inc)
        return "osdmap"

    def _on_commit(self, v: int, value: dict) -> None:
        kind = self._apply_commit(value)
        if kind == "config":
            # runtime config distribution: every commit pushes the new
            # sections to subscribers (MonClient config notifications);
            # daemons pick their own entity_view out of it
            self._push_to_subscribers({
                "type": "config",
                "version": self.configdb.version,
                "sections": self.configdb.dump(),
            })
        elif kind == "osdmap":
            # every mon pushes to its own subscribers (clients subscribe
            # to all mons and dedup by epoch) — gating on is_leader()
            # here would drop broadcasts when leadership flickers
            # mid-commit during elections
            self._push_to_subscribers(
                {"type": "osdmap", "map": self.osdmap.to_dict()}
            )
        elif kind == "mgrmap":
            self._push_to_subscribers(
                {"type": "mgrmap", "map": self.mgrmap.to_dict()}
            )
        elif kind == "fsmap":
            self._push_to_subscribers(
                {"type": "fsmap", "map": self.fsmap.to_dict()}
            )

    def _push_to_subscribers(self, msg: dict) -> None:
        for sub in list(self._subscribers):
            # deep copy per subscriber: the in-process messenger passes
            # dicts by reference, and a receiver mutating its nested
            # map must not corrupt what the others see
            self._cmd_seq += 1
            self.messenger.adopt_task(
                f"{self.name}.push{self._cmd_seq}",
                asyncio.get_event_loop().create_task(
                    self.messenger.send_message(self.name, sub,
                                                copy.deepcopy(msg))),
            )

    # -- commands (OSDMonitor analogue) ------------------------------------

    async def _handle_command(self, src: str, msg: dict) -> None:
        cmd = msg["cmd"]
        if not self.is_leader():
            if self.leader is None:
                await self.messenger.send_message(
                    self.name,
                    src,
                    {
                        "type": "mon_command_reply",
                        "id": msg["id"],
                        "rc": -11,  # EAGAIN: no quorum
                        "out": "no leader",
                    },
                )
            else:
                # forward to the leader (Monitor.cc forward_request_leader)
                fwd = dict(msg)
                fwd["reply_to"] = src
                await self._send_to_rank(self.leader, fwd)
            return
        # authenticated caller for cap checks: the wire source, or the
        # original requester when a peer mon forwarded.  A reply_to set
        # by anything that is NOT a quorum peer is a spoof attempt and
        # is ignored for authorization purposes.
        caller = msg.get("reply_to") if src.startswith("mon.") else src
        rc, out = await self.do_command(cmd, caller=caller or src)
        await self.messenger.send_message(
            self.name,
            msg.get("reply_to", src),
            {"type": "mon_command_reply", "id": msg["id"], "rc": rc, "out": out},
        )

    _pid_counter = 0

    async def _propose(self, inc: dict) -> bool:
        async with self._cmd_lock:  # one in-flight proposal (paxos updating)
            Monitor._pid_counter += 1
            value = {"inc": inc, "pid": f"{self.rank}:{Monitor._pid_counter}"}
            for _ in range(3):
                if await self.paxos.propose(value, self.quorum):
                    return True
                # stale pn (a competing election promised newer): recover
                if not await self.paxos.collect(self.quorum):
                    return False
                # recovery may have re-proposed and committed our value
                if any(
                    v.get("pid") == value["pid"]
                    for v in self.paxos.store.values.values()
                ):
                    return True
            return False

    #: AuthMonitor mutations: minting, rotating, revoking or re-capping
    #: keys needs mon admin capability (reference: MonCap gates on
    #: 'allow *' / 'allow profile admin'; an osd.* service key minted via
    #: get-or-create must NOT be able to mint or revoke other keys)
    _AUTH_MUTATIONS = ("auth get-or-create", "auth rotate", "auth rm",
                      "auth caps")

    def _caller_admin_capable(self, caller: Optional[str]) -> bool:
        """Minimal mon-cap check mirroring the OSDCap enforcement model
        (ceph_tpu/osd/shard.py client_caps): entities with a registered
        AuthDB record are confined to their mon caps; unregistered
        entities (file-provisioned admin/bootstrap keys, open clusters
        without cephx) keep the open default; quorum peers and local
        (in-process, caller=None) invocations are trusted."""
        if caller is None:
            return True
        ent = caller.split("[")[0]
        if ent.startswith("mon."):
            return True
        rec = self.authdb.entities.get(ent)
        if rec is None:
            return True
        from ceph_tpu.auth.caps import MonCap

        return MonCap.parse((rec.get("caps") or {}).get("mon", "")).is_admin()

    async def do_command(self, cmd: dict, caller: Optional[str] = None):
        """Returns (rc, out).  Command names follow the ceph CLI.
        ``caller`` is the authenticated wire entity (None for local
        invocations); AuthMonitor mutations are gated on its mon caps."""
        prefix = cmd.get("prefix", "")
        if prefix in self._AUTH_MUTATIONS and \
                not self._caller_admin_capable(caller):
            self.clog.apply({
                "op": "clog_append", "who": self.name, "level": "warn",
                "message": f"denied '{prefix}' from {caller}: no mon "
                           f"admin capability", "stamp": 0.0,
            })
            return -13, f"access denied: {caller} lacks mon admin caps"
        if prefix == "status":
            return 0, {
                "quorum": self.quorum,
                "leader": self.leader,
                "election_epoch": self.election_epoch,
                "osdmap_epoch": self.osdmap.epoch,
                "pools": sorted(self.osdmap.pools),
                "num_osds": self.osdmap.max_osd,
                "up_osds": sorted(
                    i for i, up in self.osdmap.up.items() if up
                ),
            }
        if prefix == "osd create":
            ok = await self._propose({"op": "create_osds", "n": cmd["n"]})
            return (0, f"created {cmd['n']} osds") if ok else (-11, "no quorum")
        if prefix == "osd boot":
            # an OSD daemon reporting for duty (reference OSD::_send_boot
            # -> OSDMonitor::prepare_boot, src/osd/OSD.cc:5386): mark it
            # up, clear pending failure reports against it, bump the
            # epoch so subscribers re-peer onto it
            osd = int(cmd["osd"])
            if osd >= self.osdmap.max_osd:
                ok = await self._propose({"op": "create_osds", "n": osd + 1})
                if not ok:
                    return -11, "no quorum"
            self._failure_reports.pop(osd, None)
            if self.osdmap.up.get(osd):
                return 0, {"epoch": self.osdmap.epoch, "already_up": True}
            ok = await self._propose({"op": "osd_up", "osd": osd})
            return (0, {"epoch": self.osdmap.epoch}) if ok \
                else (-11, "no quorum")
        if prefix == "osd failure":
            # peer-reported failure (reference MOSDFailure ->
            # OSDMonitor::check_failure, src/mon/OSDMonitor.cc): collect
            # DISTINCT reporters; at mon_osd_min_down_reporters the
            # target is marked down and the epoch bump broadcasts.
            # Report state is leader-local, like the reference's pending
            # failure_info_t (not paxos state).
            from ceph_tpu.utils.config import get_config

            osd = int(cmd["osd"])
            if not self.osdmap.up.get(osd):
                return 0, {"already_down": True}
            now = asyncio.get_event_loop().time()
            reporters = self._failure_reports.setdefault(osd, {})
            reporters[cmd.get("from", "?")] = now
            # expire reports older than ~4 heartbeat-grace windows: a
            # genuinely-down OSD is re-reported every grace interval, so
            # live reports refresh; stale ones age out (reference
            # OSDMonitor expires failure_info_t / handles cancellations)
            expiry = 4 * float(get_config().get_val("osd_heartbeat_grace"))
            for rep, stamp in list(reporters.items()):
                if now - stamp > expiry:
                    del reporters[rep]
            need = int(get_config().get_val("mon_osd_min_down_reporters"))
            if len(reporters) < need:
                return 0, {"reports": len(reporters), "need": need}
            self._failure_reports.pop(osd, None)
            ok = await self._propose({"op": "osd_down", "osd": osd})
            if ok:
                self.clog.apply({
                    "op": "clog_append", "who": self.name,
                    "level": "warn",
                    "message": f"osd.{osd} failed "
                               f"({len(reporters)} reporters)",
                    "stamp": 0.0,
                })
            return (0, {"marked_down": True}) if ok else (-11, "no quorum")
        if prefix == "osd erasure-code-profile set":
            name, profile = cmd["name"], dict(cmd["profile"])
            # validate by instantiating the codec (OSDMonitor.cc:5353)
            from ceph_tpu.plugins import registry as registry_mod

            plugin = profile.get("plugin", "jerasure")
            try:
                registry_mod.instance().factory(
                    plugin, {k: v for k, v in profile.items() if k != "plugin"}
                )
            except Exception as e:  # noqa: BLE001 -- validation surface
                return -22, f"invalid profile: {e}"
            ok = await self._propose(
                {"op": "profile_set", "name": name, "profile": profile}
            )
            return (0, name) if ok else (-11, "no quorum")
        if prefix == "osd erasure-code-profile ls":
            return 0, sorted(self.osdmap.ec_profiles)
        if prefix == "osd erasure-code-profile get":
            p = self.osdmap.ec_profiles.get(cmd["name"])
            return (0, p) if p is not None else (-2, "not found")
        if prefix == "osd erasure-code-profile rm":
            for pool in self.osdmap.pools.values():
                if pool.profile_name == cmd["name"]:
                    return -16, f"profile in use by pool {pool.name}"  # EBUSY
            ok = await self._propose({"op": "profile_rm", "name": cmd["name"]})
            return (0, "") if ok else (-11, "no quorum")
        if prefix == "osd pool create":
            name = cmd["name"]
            if name in self.osdmap.pools:
                return -17, "pool exists"  # EEXIST
            if cmd.get("pool_type") == "replicated":
                # TYPE_REPLICATED arm (reference OSDMonitor::prepare_new_pool,
                # src/mon/OSDMonitor.cc:5529; pg_pool_t size/min_size)
                size = int(cmd.get("size", 3))
                if size < 1 or (
                    self.osdmap.max_osd and size > self.osdmap.max_osd
                ):
                    return -22, f"bad replicated size {size}"
                min_size = int(
                    cmd.get("min_size", max(1, size - size // 2)))
                if not 1 <= min_size <= size:
                    # reference OSDMonitor rejects min_size outside
                    # [1, size] (a pool that could never accept a write)
                    return -22, f"bad min_size {min_size} (size {size})"
                pool = {
                    "name": name,
                    "pool_type": "replicated",
                    "size": size,
                    "min_size": min_size,
                    "pg_num": cmd.get("pg_num", 128),
                    "hosts": cmd.get("hosts"),
                }
                ok = await self._propose({"op": "pool_create", "pool": pool})
                return (0, pool) if ok else (-11, "no quorum")
            pname = cmd["profile"]
            profile = self.osdmap.ec_profiles.get(pname)
            if profile is None:
                return -2, f"no profile {pname}"
            from ceph_tpu.plugins import registry as registry_mod

            plugin = profile.get("plugin", "jerasure")
            ec = registry_mod.instance().factory(
                plugin, {k: v for k, v in profile.items() if k != "plugin"}
            )
            ec_k = ec.get_data_chunk_count()
            ec_m = ec.get_chunk_count() - ec_k
            # EC min_size default k + min(1, m-1) (reference
            # OSDMonitor::prepare_new_pool pg_pool_t): a write accepted
            # with exactly k shards up has zero redundancy
            min_size = int(cmd.get(
                "min_size", ec_k + min(1, max(0, ec_m - 1))))
            if not ec_k <= min_size <= ec_k + ec_m:
                return -22, f"bad min_size {min_size} (k={ec_k} m={ec_m})"
            pool = {
                "name": name,
                "pool_type": "erasure",
                "profile_name": pname,
                "k": ec_k,
                "m": ec_m,
                "min_size": min_size,
                "pg_num": cmd.get("pg_num", 128),
                "hosts": cmd.get("hosts"),
            }
            ok = await self._propose({"op": "pool_create", "pool": pool})
            return (0, pool) if ok else (-11, "no quorum")
        # -- cache tiering (OSDMonitor `osd tier` subset re-targeted at
        # device residency: the cache device is HBM, so the commands set
        # the pool's mode rather than overlay a second pool) -----------
        if prefix == "osd tier cache-mode":
            from ceph_tpu.tier import CACHE_MODES

            name, mode = cmd["pool"], cmd["mode"]
            if name not in self.osdmap.pools:
                return -2, f"no pool {name}"
            if mode not in CACHE_MODES:
                return -22, (f"bad cache mode {mode!r} (want one of "
                             f"{'/'.join(CACHE_MODES)})")
            ok = await self._propose(
                {"op": "pool_tier", "name": name, "cache_mode": mode}
            )
            return (0, {"pool": name, "cache_mode": mode}) if ok \
                else (-11, "no quorum")
        if prefix == "osd tier status":
            from ceph_tpu.utils.config import get_config as _gc

            return 0, {
                "hbm_budget_bytes": int(_gc().get_val(
                    "osd_tier_hbm_bytes")),
                "pools": {
                    name: {"cache_mode": p.cache_mode}
                    for name, p in sorted(self.osdmap.pools.items())
                },
            }
        # -- ConfigKeyService (src/mon/ConfigKeyService.cc) ----------------
        if prefix == "config-key set":
            ok = await self._propose(
                {"op": "kv_set", "key": cmd["key"], "value": cmd["value"]})
            return (0, "") if ok else (-11, "no quorum")
        if prefix == "config-key get":
            v = self.kvstore.kv.get(cmd["key"])
            return (0, v) if v is not None else (-2, "not found")
        if prefix == "config-key rm":
            ok = await self._propose({"op": "kv_rm", "key": cmd["key"]})
            return (0, "") if ok else (-11, "no quorum")
        if prefix == "config-key ls":
            return 0, sorted(self.kvstore.kv)
        if prefix == "config-key exists":
            return (0, "") if cmd["key"] in self.kvstore.kv \
                else (-2, "not found")
        # -- centralized config (ConfigMonitor role) -----------------------
        if prefix == "config set":
            ok = await self._propose({
                "op": "config_set", "who": cmd["who"],
                "name": cmd["name"], "value": str(cmd["value"]),
            })
            return (0, "") if ok else (-11, "no quorum")
        if prefix == "config rm":
            ok = await self._propose({
                "op": "config_rm", "who": cmd["who"], "name": cmd["name"]})
            return (0, "") if ok else (-11, "no quorum")
        if prefix == "config get":
            return 0, self.configdb.entity_view(cmd["who"])
        if prefix == "config dump":
            return 0, self.configdb.dump()
        # -- cluster log (LogMonitor) --------------------------------------
        if prefix == "log":
            level = cmd.get("level", "info")
            if level not in ClusterLog.LEVELS:
                return -22, f"bad level {level!r} (want one of " \
                            f"{'/'.join(ClusterLog.LEVELS)})"
            ok = await self._propose({
                "op": "clog_append", "who": cmd.get("who", "client"),
                "level": level,
                "message": cmd.get("message", ""),
                "stamp": cmd.get("stamp", 0.0),
            })
            return (0, "logged") if ok else (-11, "no quorum")
        if prefix == "log last":
            level = cmd.get("level")
            if level is not None and level not in ClusterLog.LEVELS:
                return -22, f"bad level {level!r}"
            return 0, self.clog.last(cmd.get("num", 20), level)
        # -- AuthMonitor (src/mon/AuthMonitor.cc subset) -------------------
        if prefix == "auth get-or-create":
            ent = cmd["entity"]
            have = self.authdb.entities.get(ent)
            if have is not None:
                return 0, {"entity": ent, "key": have["key"],
                           "caps": dict(have["caps"])}
            import secrets as _secrets

            key = _secrets.token_hex(16)
            ok = await self._propose({
                "op": "auth_add", "entity": ent, "key": key,
                "caps": cmd.get("caps") or {},
            })
            return (0, {"entity": ent, "key": key,
                        "caps": dict(cmd.get("caps") or {})}) if ok \
                else (-11, "no quorum")
        if prefix == "auth get":
            have = self.authdb.entities.get(cmd["entity"])
            if have is None:
                return -2, "not found"
            return 0, {"entity": cmd["entity"], "key": have["key"],
                       "caps": dict(have["caps"])}
        if prefix == "auth caps":
            if cmd["entity"] not in self.authdb.entities:
                return -2, "not found"
            ok = await self._propose({
                "op": "auth_caps", "entity": cmd["entity"],
                "caps": cmd.get("caps") or {},
            })
            return (0, "") if ok else (-11, "no quorum")
        if prefix == "auth rotate":
            # key rotation (the reference's rotating secrets role): a
            # fresh secret replaces the old; clients re-key on their
            # next handshake
            if cmd["entity"] not in self.authdb.entities:
                return -2, "not found"
            import secrets as _secrets

            key = _secrets.token_hex(16)
            ok = await self._propose({
                "op": "auth_rotate", "entity": cmd["entity"], "key": key})
            return (0, {"key": key}) if ok else (-11, "no quorum")
        if prefix == "auth rm":
            if cmd["entity"] not in self.authdb.entities:
                return -2, "not found"  # never strips file-provisioned keys
            ok = await self._propose(
                {"op": "auth_rm", "entity": cmd["entity"]})
            return (0, "") if ok else (-11, "no quorum")
        if prefix == "auth list":
            return 0, {
                e: {"caps": dict(v["caps"])}  # keys never leave via list
                for e, v in sorted(self.authdb.entities.items())
            }
        # -- MgrMonitor (src/mon/MgrMonitor.cc subset) ---------------------
        if prefix == "mgr beacon":
            name = cmd["name"]
            now = asyncio.get_event_loop().time()
            self._beacons[f"mgr.{name}"] = now
            known = (name == self.mgrmap.active
                     or name in self.mgrmap.standbys)
            if not known:
                ok = await self._propose({"op": "mgr_register",
                                          "name": name})
                if not ok:
                    return -11, "no quorum"
            # a standby's beacon checks the active's liveness (lazy
            # failover; the reference's beacon grace)
            active = self.mgrmap.active
            if active is not None and active != name:
                last = self._beacons.get(f"mgr.{active}")
                from ceph_tpu.utils.config import get_config as _gc

                grace = float(_gc().get_val("mon_mgr_beacon_grace"))
                if last is not None and now - last > grace:
                    await self._propose({"op": "mgr_failover",
                                         "failed": active})
            return 0, self.mgrmap.to_dict()
        if prefix == "mgr fail":
            who = cmd.get("who", self.mgrmap.active)
            if who is None:
                return -2, "no active mgr"
            ok = await self._propose({"op": "mgr_failover", "failed": who})
            return (0, self.mgrmap.to_dict()) if ok else (-11, "no quorum")
        if prefix == "mgr stat":
            return 0, self.mgrmap.to_dict()
        # -- MDSMonitor (src/mon/MDSMonitor.cc subset) ---------------------
        if prefix == "fs new":
            if cmd["name"] in self.fsmap.filesystems:
                return -17, "fs exists"
            ok = await self._propose({
                "op": "fs_new", "name": cmd["name"],
                "max_mds": cmd.get("max_mds", 1),
            })
            return (0, self.fsmap.to_dict()) if ok else (-11, "no quorum")
        if prefix == "fs rm":
            if cmd["name"] not in self.fsmap.filesystems:
                return -2, "no such fs"
            ok = await self._propose({"op": "fs_rm", "name": cmd["name"]})
            return (0, "") if ok else (-11, "no quorum")
        if prefix == "fs set max_mds":
            if cmd["name"] not in self.fsmap.filesystems:
                return -2, "no such fs"
            ok = await self._propose({
                "op": "fs_set_max_mds", "name": cmd["name"],
                "max_mds": int(cmd["max_mds"]),
            })
            return (0, self.fsmap.to_dict()) if ok else (-11, "no quorum")
        if prefix == "fs ls":
            return 0, sorted(self.fsmap.filesystems)
        if prefix == "mds beacon":
            name = cmd["name"]
            self._beacons[f"mds.{name}"] = asyncio.get_event_loop().time()
            known = (name in self.fsmap.standbys or any(
                name in fs["ranks"].values()
                for fs in self.fsmap.filesystems.values()
            ))
            if not known:
                ok = await self._propose({"op": "mds_register",
                                          "name": name})
                if not ok:
                    return -11, "no quorum"
            return 0, self.fsmap.to_dict()
        if prefix == "mds fail":
            ok = await self._propose({"op": "mds_failover",
                                      "name": cmd["name"]})
            return (0, self.fsmap.to_dict()) if ok else (-11, "no quorum")
        if prefix == "fs dump":
            return 0, self.fsmap.to_dict()
        if prefix == "osd add":
            # elastic expansion (reference `osd new`, OSDMonitor.cc
            # prepare_command_osd_new): one new id enters the map up+in;
            # the epoch bump broadcasts and subscribers grow their
            # placements through apply_map_view
            osd = int(cmd["osd"])
            if osd in self.osdmap.up:
                return -17, f"osd.{osd} already exists"  # EEXIST
            inc = {"op": "osd_add", "osd": osd}
            if "weight" in cmd:
                from ceph_tpu.crush.map import weight_fp

                inc["weight"] = weight_fp(cmd["weight"])  # float -> 16.16
            ok = await self._propose(inc)
            return (0, {"epoch": self.osdmap.epoch}) if ok \
                else (-11, "no quorum")
        if prefix == "osd rm":
            # elastic contraction; refuse to drop any pool below its
            # mappable floor (registry-validation parity: same EBUSY
            # shape as profile-in-use)
            osd = int(cmd["osd"])
            if osd not in self.osdmap.up:
                return -2, f"osd.{osd} does not exist"  # ENOENT
            blocked = self._min_size_block(osd)
            if blocked:
                return -16, blocked  # EBUSY
            ok = await self._propose({"op": "osd_rm", "osd": osd})
            return (0, {"epoch": self.osdmap.epoch}) if ok \
                else (-11, "no quorum")
        if prefix in ("osd out", "osd in", "osd down", "osd up"):
            if prefix == "osd out":
                blocked = self._min_size_block(int(cmd["osd"]))
                if blocked:
                    return -16, blocked  # EBUSY
            inc = {"op": f"osd_{prefix.split()[1]}", "osd": cmd["osd"]}
            if prefix == "osd in" and "weight" in cmd:
                from ceph_tpu.crush.map import weight_fp

                inc["weight"] = weight_fp(cmd["weight"])  # float -> 16.16
            ok = await self._propose(inc)
            return (0, "") if ok else (-11, "no quorum")
        return -38, f"unknown command {prefix}"  # ENOSYS

    def _min_size_block(self, victim: int) -> Optional[str]:
        """Would taking ``victim`` out of the data plane drop any pool's
        mappable positions below min_size?  Returns the refusal message
        (EBUSY text) or None when safe."""
        survivors = sum(
            1 for o, w in self.osdmap.weights.items()
            if w > 0 and o != victim
        )
        for pool in self.osdmap.pools.values():
            need = pool.min_size or (pool.k + pool.m if pool.k else pool.size)
            if survivors < need:
                return (
                    f"removing osd.{victim} would leave {survivors} "
                    f"mappable osds < min_size {need} for pool {pool.name}"
                )
        return None


class MonClient:
    """Client-side handle: send commands to any live monitor, subscribe to
    map updates (reference: src/mon/MonClient.cc hunting + subscriptions)."""

    def __init__(self, messenger: Messenger, n_mons: int, name: str):
        self.messenger = messenger
        self.n_mons = n_mons
        self.name = name
        self._id = 0
        self._replies: Dict[int, asyncio.Future] = {}
        self._active = 0  # last monitor that answered (hunting state)

    async def handle_reply(self, msg: dict) -> bool:
        """Feed mon_command_reply dicts here from the owner's dispatcher."""
        if msg.get("type") != "mon_command_reply":
            return False
        fut = self._replies.pop(msg["id"], None)
        if fut and not fut.done():
            fut.set_result((msg["rc"], msg["out"]))
        return True

    async def command(self, cmd: dict, timeout: float = 2.0):
        """Try each monitor until one answers (hunting)."""
        last = (-110, "timeout")  # ETIMEDOUT
        for attempt in range(self.n_mons):
            rank = (self._active + attempt) % self.n_mons
            if self.messenger.is_down(f"mon.{rank}"):
                continue  # don't burn a timeout on a known-dead mon
            self._id += 1
            mid = self._id
            fut = asyncio.get_event_loop().create_future()
            self._replies[mid] = fut
            await self.messenger.send_message(
                self.name,
                f"mon.{rank}",
                {"type": "mon_command", "cmd": cmd, "id": mid},
            )
            try:
                rc, out = await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                self._replies.pop(mid, None)
                continue
            if rc == -11:  # EAGAIN: that mon has no leader yet; try next
                last = (rc, out)
                continue
            # affinity hint only: concurrent command() calls may each
            # stick a different answering mon and ANY of them is a
            # valid next-attempt start -- no invariant to clobber
            self._active = rank  # cephlint: disable=async-rmw-across-await
            return rc, out
        return last

    async def subscribe(self) -> None:
        for r in range(self.n_mons):
            await self.messenger.send_message(
                self.name, f"mon.{r}", {"type": "mon_subscribe"}
            )


class MonCluster:
    """n monitors on one messenger (the mon side of a vstart cluster)."""

    def __init__(self, n_mons: int, messenger: Messenger, tick: bool = True,
                 store_dir: Optional[str] = None):
        self.messenger = messenger
        self.mons = [
            Monitor(r, n_mons, messenger,
                    store_path=(f"{store_dir}/mon.{r}" if store_dir
                                else None))
            for r in range(n_mons)
        ]
        self._tick = tick

    async def form_quorum(self, timeout: float = 3.0) -> Monitor:
        """Kick an election from the lowest live rank and wait for quorum."""
        for mon in self.mons:
            if not self.messenger.is_down(mon.name):
                mon._cmd_seq += 1
                self.messenger.adopt_task(
                    f"{mon.name}.election{mon._cmd_seq}",
                    asyncio.get_event_loop().create_task(
                        mon.start_election()),
                )
                break
        leader = await self.wait_for_leader(timeout)
        if self._tick:
            for mon in self.mons:
                if f"{mon.name}.tick" not in self.messenger._tasks:
                    mon.start_tick()
        return leader

    async def wait_for_leader(self, timeout: float = 3.0) -> Monitor:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            for mon in self.mons:
                if mon.is_leader() and not self.messenger.is_down(mon.name):
                    return mon
            await asyncio.sleep(0.02)
        raise TimeoutError("no monitor quorum")

    def kill(self, rank: int) -> None:
        self.messenger.mark_down(f"mon.{rank}")

    def revive(self, rank: int) -> None:
        self.messenger.mark_up(f"mon.{rank}")

    def close_stores(self) -> None:
        for mon in self.mons:
            mon.close_store()
