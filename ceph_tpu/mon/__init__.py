"""Monitor cluster: Paxos-replicated cluster maps + control plane.

Reference: src/mon — Monitor.cc (daemon), Paxos.cc (the consensus core),
PaxosService subclasses (OSDMonitor for osdmaps/profiles/pools), Elector.cc
(rank-based leader election), MonitorDBStore.h (the replicated KV).
Reimplemented as asyncio daemons over the framework messenger.
"""

from ceph_tpu.mon.monitor import MonCluster, Monitor
from ceph_tpu.mon.osdmap import OSDMap
from ceph_tpu.mon.paxos import Paxos

__all__ = ["MonCluster", "Monitor", "OSDMap", "Paxos"]
