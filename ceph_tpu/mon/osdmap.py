"""OSDMap: the epoch-versioned cluster map the monitors replicate.

Reference: src/osd/OSDMap.{h,cc} — epoch, per-osd up/down + in/out
(weight) state, pools with their erasure-code profiles and crush rules;
src/mon/OSDMonitor.cc applies incrementals under paxos.  Here the map is a
plain dict-serializable object; incrementals are shallow command dicts
applied in `apply`.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class PoolInfo:
    name: str
    # pg_pool_t type (reference src/osd/osd_types.h TYPE_REPLICATED /
    # TYPE_ERASURE): erasure pools carry a profile + k/m, replicated
    # pools carry size/min_size
    profile_name: str = ""
    k: int = 0
    m: int = 0
    pool_type: str = "erasure"
    size: int = 0
    min_size: int = 0
    pg_num: int = 128
    # crush failure-domain spec: None -> flat over osds
    hosts: Optional[List[List[int]]] = None
    # device cache-tier mode (pg_pool_t cache_mode role, re-targeted at
    # HBM residency): "writeback" | "readproxy" | "none"; flows to the
    # daemons with every map broadcast (`osd tier cache-mode`)
    cache_mode: str = "none"


def apply_map_view(m: dict, state: dict, messenger=None, placements=(),
                   skip_entity: Optional[str] = None) -> bool:
    """Apply one broadcast osdmap dict to a subscriber-side view -- the
    epoch gate, up/down marks on the messenger, and CRUSH weight pushes
    every daemon/client subscriber needs (shared so the three consumers
    cannot drift; round-5 review finding).  ``state`` accumulates
    {"epoch", "up"}; ``placements`` get weights + an epoch bump; pass
    ``messenger=None`` to skip up/down marks (in-process harnesses own
    their liveness view).  Returns False when the epoch is stale."""
    if m["epoch"] <= state.get("epoch", 0):
        return False
    state["epoch"] = m["epoch"]
    state["up"] = {int(k): v for k, v in m["up"].items()}
    if messenger is not None:
        for osd_id, up in state["up"].items():
            entity = f"osd.{osd_id}"
            if entity == skip_entity:
                continue
            if up and messenger.is_down(entity):
                messenger.mark_up(entity)
            elif not up and not messenger.is_down(entity):
                messenger.mark_down(entity)
    for placement in placements:
        if placement is None:
            continue
        broadcast = {int(k): w for k, w in m["weights"].items()}
        for osd_id, w in sorted(broadcast.items()):
            # elastic growth: a weight for an id the placement has never
            # seen grows the crush map first (a fixed-size assignment
            # here IndexError'd every subscriber on the first osd_add)
            if osd_id >= len(placement.weights):
                placement.ensure_osd(osd_id, w)
            else:
                placement.weights[osd_id] = w
        # an id the mon dropped from the map (osd_rm) no longer
        # broadcasts a weight: zero it so CRUSH remaps away
        for osd_id in range(len(placement.weights)):
            if osd_id not in broadcast:
                placement.weights[osd_id] = 0
        placement.epoch += 1  # invalidate pg cache
    return True


@dataclass
class OSDMap:
    epoch: int = 0
    max_osd: int = 0
    # osd id -> up? (down osds keep acting positions; CRUSH ignores this)
    up: Dict[int, bool] = field(default_factory=dict)
    # osd id -> 16.16 in/out weight (0 == out); drives CRUSH placement
    weights: Dict[int, int] = field(default_factory=dict)
    ec_profiles: Dict[str, Dict[str, str]] = field(default_factory=dict)
    pools: Dict[str, PoolInfo] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "max_osd": self.max_osd,
            "up": {str(k): v for k, v in self.up.items()},
            "weights": {str(k): v for k, v in self.weights.items()},
            "ec_profiles": copy.deepcopy(self.ec_profiles),
            "pools": {
                name: {
                    "name": p.name,
                    "profile_name": p.profile_name,
                    "k": p.k,
                    "m": p.m,
                    "pool_type": p.pool_type,
                    "size": p.size,
                    "min_size": p.min_size,
                    "pg_num": p.pg_num,
                    "hosts": p.hosts,
                    "cache_mode": p.cache_mode,
                }
                for name, p in self.pools.items()
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "OSDMap":
        m = cls(
            epoch=d["epoch"],
            max_osd=d["max_osd"],
            up={int(k): v for k, v in d["up"].items()},
            weights={int(k): v for k, v in d["weights"].items()},
            ec_profiles=copy.deepcopy(d["ec_profiles"]),
        )
        for name, p in d["pools"].items():
            m.pools[name] = PoolInfo(**p)
        return m

    # -- incremental application (OSDMonitor::update_from_paxos analogue) --

    def apply(self, inc: dict) -> None:
        """Apply one committed incremental; bumps epoch."""
        op = inc["op"]
        if op == "create_osds":
            n = inc["n"]
            for i in range(n):
                self.up.setdefault(i, True)
                self.weights.setdefault(i, 0x10000)
            self.max_osd = max(self.max_osd, n)
        elif op == "osd_down":
            self.up[inc["osd"]] = False
        elif op == "osd_up":
            self.up[inc["osd"]] = True
        elif op == "osd_out":
            self.weights[inc["osd"]] = 0
        elif op == "osd_in":
            self.weights[inc["osd"]] = inc.get("weight", 0x10000)
        elif op == "osd_add":
            # elastic expansion: one new device, up + weighted in
            osd = inc["osd"]
            if osd in self.up:
                raise ValueError(f"osd_add for existing osd {osd}")
            self.up[osd] = True
            self.weights[osd] = inc.get("weight", 0x10000)
            self.max_osd = max(self.max_osd, osd + 1)
        elif op == "osd_rm":
            # elastic contraction: the id leaves the map entirely;
            # subscribers zero any weight for ids absent from the
            # broadcast (apply_map_view), so CRUSH remaps away
            osd = inc["osd"]
            if osd not in self.up:
                raise ValueError(f"osd_rm for unknown osd {osd}")
            self.up.pop(osd, None)
            self.weights.pop(osd, None)
        elif op == "profile_set":
            self.ec_profiles[inc["name"]] = dict(inc["profile"])
        elif op == "profile_rm":
            self.ec_profiles.pop(inc["name"], None)
        elif op == "pool_create":
            p = inc["pool"]
            self.pools[p["name"]] = PoolInfo(**p)
        elif op == "pool_rm":
            self.pools.pop(inc["name"], None)
        elif op == "pool_tier":
            # cache-tier mode change (OSDMonitor `osd tier cache-mode`)
            pool = self.pools.get(inc["name"])
            if pool is None:
                raise ValueError(f"pool_tier for unknown pool {inc['name']}")
            pool.cache_mode = inc["cache_mode"]
        else:
            raise ValueError(f"unknown incremental op {op}")
        self.epoch += 1
