"""RGW multisite sync: replicate one zone's object store into another.

Reference: src/rgw/rgw_sync.cc + rgw_data_sync.cc -- a secondary zone
tails the master's metadata/data logs and converges its buckets.  This
subset is the COMPARE-based converge (the `radosgw-admin bucket sync
run` full-sync role): each pass reconciles users, the bucket directory,
and per-bucket state (index entries by size+etag, version instances,
ACL stores, versioning config), copying changed objects and deleting
vanished ones.  Incremental efficiency comes from the etag
short-circuit instead of the reference's bilog tailing -- an unchanged
object costs one index-entry comparison, no data I/O.

One agent per direction, like rbd-mirror's daemon; run it from a cron /
mgr module / test loop.  Multipart uploads IN PROGRESS are not synced
(the reference's data sync also only ships completed objects).
"""

from __future__ import annotations

from typing import Dict

from ceph_tpu.rgw.gateway import (BUCKETS_OID, USERS_OID, acl_oid,
                                  bucket_index_oid, obj_oid, ver_obj_oid,
                                  versions_oid)


class RGWSyncAgent:
    """Converge ``dst`` (a secondary zone's pools) toward ``src``.

    ``src``/``dst`` are (data_backend, index_backend) pairs -- the same
    two handles an RGWGateway takes (index may equal data)."""

    def __init__(self, src, dst):
        self.src_data, self.src_index = src
        self.dst_data, self.dst_index = dst

    async def sync_once(self) -> Dict[str, int]:
        """One converge pass; returns op counts (test/ops surface)."""
        stats = {"users": 0, "buckets": 0, "acls": 0,
                 "objects_copied": 0, "objects_deleted": 0,
                 "versions_copied": 0}
        await self._sync_omap(USERS_OID, stats, "users")
        src_buckets = await self.src_index.omap_get(BUCKETS_OID)
        dst_buckets = await self.dst_index.omap_get(BUCKETS_OID)
        for name, raw in src_buckets.items():
            if dst_buckets.get(name) != raw:
                await self.dst_index.omap_set(BUCKETS_OID, {name: raw})
                stats["buckets"] += 1
            await self._sync_bucket(name, stats)
        # buckets deleted on the master vanish on the secondary
        for name in set(dst_buckets) - set(src_buckets):
            await self._purge_bucket(name, stats)
        return stats

    async def _sync_omap(self, oid: str, stats, counter: str) -> None:
        src = await self.src_index.omap_get(oid)
        dst = await self.dst_index.omap_get(oid)
        delta = {k: v for k, v in src.items() if dst.get(k) != v}
        if delta:
            await self.dst_index.omap_set(oid, delta)
            stats[counter] += len(delta)
        gone = [k for k in dst if k not in src]
        if gone:
            await self.dst_index.omap_rm(oid, gone)

    @staticmethod
    def _version_data_oid(bucket: str, vk: str, raw: bytes):
        """Data oid backing one versions-omap entry, or None (markers
        have no body).  'put' bodies live at the version oid, archived
        pre-versioning 'plain' bodies at the plain oid."""
        key, _, vid = vk.rpartition("\x00")
        kind = raw.decode().split("\x00")[3]
        if kind == "put":
            return ver_obj_oid(bucket, key, vid)
        if kind == "plain":
            return obj_oid(bucket, key)
        return None

    async def _sync_bucket(self, bucket: str, stats) -> None:
        # ACL store + versioning config converge wholesale (small omaps)
        await self._sync_omap(acl_oid(bucket), stats, "acls")
        # VERSION INSTANCES FIRST: they own version bodies ('put' AND the
        # archived pre-versioning 'plain' bodies), and the index entries
        # written below must never point at data not yet shipped
        src_vers = await self.src_index.omap_get(versions_oid(bucket))
        dst_vers = await self.dst_index.omap_get(versions_oid(bucket))
        for vk, raw in src_vers.items():
            if dst_vers.get(vk) == raw:
                continue
            if vk != "_seq":
                data_oid = self._version_data_oid(bucket, vk, raw)
                if data_oid is not None:
                    try:
                        data = await self.src_data.read(data_oid)
                    except IOError:
                        continue  # deleted on the live master mid-pass:
                        # the next pass converges (entry not recorded)
                    await self.dst_data.write(data_oid, data)
                    stats["versions_copied"] += 1
            await self.dst_index.omap_set(versions_oid(bucket), {vk: raw})
        gone = [vk for vk in dst_vers if vk not in src_vers]
        if gone:
            for vk in gone:
                if vk == "_seq":
                    continue
                data_oid = self._version_data_oid(bucket, vk, dst_vers[vk])
                if data_oid is not None:
                    try:
                        await self.dst_data.remove_object(data_oid)
                    except IOError:
                        pass
            await self.dst_index.omap_rm(versions_oid(bucket), gone)
        # BUCKET INDEX: plain (no-vid) entries carry their own data;
        # vid-pointing entries reference bodies the version pass shipped
        src_idx = await self.src_index.omap_get(bucket_index_oid(bucket))
        dst_idx = await self.dst_index.omap_get(bucket_index_oid(bucket))
        for key, raw in src_idx.items():
            if dst_idx.get(key) == raw:
                continue  # etag/size/vid unchanged: no data I/O
            parts = raw.decode().split("\x00")
            if len(parts) <= 3:  # plain object: ship the body
                try:
                    data = await self.src_data.read(obj_oid(bucket, key))
                except IOError:
                    continue  # deleted on the live master mid-pass
                await self.dst_data.write(obj_oid(bucket, key), data)
            stats["objects_copied"] += 1
            await self.dst_index.omap_set(bucket_index_oid(bucket),
                                          {key: raw})
        gone_keys = set(dst_idx) - set(src_idx)
        # plain bodies still referenced by an archived 'plain' version
        # (the null-version role) must survive their index entry; only
        # worth computing when there are deletions to guard
        plain_archived = set() if not gone_keys else {
            vk.rpartition("\x00")[0] for vk, vraw in src_vers.items()
            if vk != "_seq" and vraw.decode().split("\x00")[3] == "plain"
        }
        for key in gone_keys:
            parts = dst_idx[key].decode().split("\x00")
            if len(parts) <= 3 and key not in plain_archived:
                # plain body owned by the index entry; version bodies
                # (incl. plain-archived ones) stay -- a delete marker on
                # the master hides the key but ?versionId reads must
                # keep working (review r5)
                try:
                    await self.dst_data.remove_object(obj_oid(bucket, key))
                except IOError:
                    pass
            await self.dst_index.omap_rm(bucket_index_oid(bucket), [key])
            stats["objects_deleted"] += 1

    async def _purge_bucket(self, bucket: str, stats) -> None:
        idx = await self.dst_index.omap_get(bucket_index_oid(bucket))
        for key in idx:
            parts = idx[key].decode().split("\x00")
            if len(parts) <= 3:
                try:
                    await self.dst_data.remove_object(obj_oid(bucket, key))
                except IOError:
                    pass
            stats["objects_deleted"] += 1
        # every archived version body goes with the bucket (they are
        # unreachable once the versions omap is cleared -- review r5)
        vers = await self.dst_index.omap_get(versions_oid(bucket))
        for vk, vraw in vers.items():
            if vk == "_seq":
                continue
            data_oid = self._version_data_oid(bucket, vk, vraw)
            if data_oid is not None:
                try:
                    await self.dst_data.remove_object(data_oid)
                except IOError:
                    pass
        await self.dst_index.omap_clear(bucket_index_oid(bucket))
        await self.dst_index.omap_clear(acl_oid(bucket))
        await self.dst_index.omap_clear(versions_oid(bucket))
        await self.dst_index.omap_rm(BUCKETS_OID, [bucket])
        stats["buckets"] += 1
