"""S3 gateway frontend + RADOS mapping (reference src/rgw/rgw_main.cc,
rgw_rest_s3.cc, rgw_rados.cc).

Supported S3 surface: service list (GET /), bucket create/delete/list
(PUT/DELETE/GET /<bucket>), object put/get/head/delete
(PUT/GET/HEAD/DELETE /<bucket>/<key>), prefix-filtered listing, ETags
(md5, as S3 defines for single-part uploads), multipart uploads
(initiate/part/complete/abort/list with the md5-of-md5s "-N" ETag),
AWS-v2 HMAC auth AND AWS SigV4 (AWS4-HMAC-SHA256 canonical
request/signing-key chain, signed or UNSIGNED-PAYLOAD), and the
matching S3 XML error envelopes (NoSuchBucket, NoSuchKey, NoSuchUpload,
SignatureDoesNotMatch, BucketAlreadyExists, BucketNotEmpty,
AccessDenied).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import re
import time
from typing import Dict, Optional, Tuple
from xml.sax.saxutils import escape

USERS_OID = "rgw.users"
BUCKETS_OID = "rgw.buckets"


def bucket_index_oid(bucket: str) -> str:
    return f"rgw.bucket.{bucket}"


def obj_oid(bucket: str, key: str) -> str:
    return f"rgw.obj.{bucket}/{key}"


def uploads_oid(bucket: str) -> str:
    # disjoint prefix: "rgw.bucket.<b>.uploads" would collide with the
    # index of a bucket literally named "<b>.uploads" (dots are legal)
    return f"rgw.uploads.{bucket}"


def acl_oid(bucket: str) -> str:
    """Per-bucket ACL store: omap key "@bucket" holds the bucket ACL,
    key "<obj>" an object ACL (reference: ACLs ride the bucket/object
    attrs, src/rgw/rgw_acl.h:1; stored form here is JSON).  Key
    "@versioning" holds the bucket versioning status."""
    return f"rgw.aclstore.{bucket}"


def versions_oid(bucket: str) -> str:
    """Per-bucket version index: omap key "<key>\\x00<vid>" -> metadata
    "<size>\\x00<etag>\\x00<ts>\\x00put|marker" (the reference keeps
    version instances as bucket-index olh entries, rgw_rados.cc
    RGWRados::Bucket::UpdateIndex + rgw_obj_key instances)."""
    return f"rgw.versions.{bucket}"


def ver_obj_oid(bucket: str, key: str, vid: str) -> str:
    return f"rgw.objver.{bucket}/{key}\x00{vid}"


#: canned ACLs -> grant lists (reference rgw_acl_s3.cc canned-ACL table)
CANNED_ACLS = {
    "private": [],
    "public-read": [{"grantee": "*", "perm": "READ"}],
    "public-read-write": [{"grantee": "*", "perm": "READ"},
                          {"grantee": "*", "perm": "WRITE"}],
    "authenticated-read": [{"grantee": "authenticated", "perm": "READ"}],
}


def acl_from_headers(headers: Dict[str, str], owner: str):
    """Build an ACL dict from x-amz-acl / x-amz-grant-* headers
    (rgw_acl_s3.cc create_canned + grant-header parsing); None when the
    request carries no ACL headers (keep default private)."""
    canned = headers.get("x-amz-acl", "")
    if canned and canned not in CANNED_ACLS:
        raise S3Error("InvalidRequest", f"bad canned acl {canned!r}")
    grants = list(CANNED_ACLS.get(canned, []))
    had_grant_hdr = bool(canned)
    for hdr, perm in (("x-amz-grant-read", "READ"),
                      ("x-amz-grant-write", "WRITE"),
                      ("x-amz-grant-full-control", "FULL_CONTROL")):
        for part in headers.get(hdr, "").split(","):
            part = part.strip()
            if not part:
                continue
            had_grant_hdr = True
            if part.startswith("id="):
                grants.append({"grantee": part[3:].strip('"'),
                               "perm": perm})
            elif part.endswith("AllUsers"):
                grants.append({"grantee": "*", "perm": perm})
            elif part.endswith("AuthenticatedUsers"):
                grants.append({"grantee": "authenticated", "perm": perm})
            else:
                raise S3Error("InvalidRequest", f"bad grantee {part!r}")
    if not had_grant_hdr:
        return None
    return {"owner": owner, "canned": canned or "custom", "grants": grants}


def acl_allows(acl: Optional[dict], requester: Optional[str],
               perm: str) -> bool:
    """Does ``acl`` grant ``perm`` to ``requester`` (None = anonymous)?
    The acl's own owner always has FULL_CONTROL."""
    if not acl:
        return False
    if requester is not None and acl.get("owner") == requester:
        return True
    for g in acl.get("grants", []):
        if g["perm"] not in (perm, "FULL_CONTROL"):
            continue
        gr = g["grantee"]
        if gr == "*" or gr == requester or (
            gr == "authenticated" and requester is not None
        ):
            return True
    return False


def acl_to_xml(acl: Optional[dict], owner: str) -> str:
    """AccessControlPolicy XML (GET ?acl; rgw_acl_s3.cc to_xml)."""
    grants = (acl or {}).get("grants", [])
    body = "".join(
        "<Grant><Grantee>"
        + (f"<URI>http://acs.amazonaws.com/groups/global/"
           f"{'AllUsers' if g['grantee'] == '*' else 'AuthenticatedUsers'}"
           "</URI>"
           if g["grantee"] in ("*", "authenticated")
           else f"<ID>{escape(g['grantee'])}</ID>")
        + f"</Grantee><Permission>{g['perm']}</Permission></Grant>"
        for g in grants
    )
    return (
        '<?xml version="1.0" encoding="UTF-8"?>'
        "<AccessControlPolicy>"
        f"<Owner><ID>{escape((acl or {}).get('owner') or owner)}</ID></Owner>"
        f"<AccessControlList>{body}</AccessControlList>"
        "</AccessControlPolicy>"
    )


def sign_v2(secret: str, method: str, resource: str, date: str,
            content_type: str = "", content_md5: str = "") -> str:
    """AWS signature v2 (the rgw_auth_s3.cc canonical string)."""
    to_sign = "\n".join([method, content_md5, content_type, date, resource])
    mac = hmac.new(secret.encode(), to_sign.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


def _hmac256(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(secret: str, method: str, path: str, params: Dict[str, str],
            headers: Dict[str, str], signed_headers: str,
            payload_hash: str, amz_date: str,
            region: str = "default") -> str:
    """AWS signature v4 (rgw_auth_s3.cc get_v4_canonical_* chain).
    ``signed_headers`` is the semicolon-joined lowercase header list;
    ``payload_hash`` is the value of x-amz-content-sha256 (a hex digest
    or the UNSIGNED-PAYLOAD literal)."""
    canonical_q = "&".join(
        f"{k}={v}" for k, v in sorted(params.items()))
    names = signed_headers.split(";")
    canonical_h = "".join(
        f"{h}:{headers.get(h, '').strip()}\n" for h in names)
    creq = "\n".join([method, path, canonical_q, canonical_h,
                      signed_headers, payload_hash])
    scope = f"{amz_date[:8]}/{region}/s3/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    k = _hmac256(b"AWS4" + secret.encode(), amz_date[:8])
    for piece in (region, "s3", "aws4_request"):
        k = _hmac256(k, piece)
    return hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()


def _xml_error(code: str, message: str) -> str:
    return (
        '<?xml version="1.0" encoding="UTF-8"?>'
        f"<Error><Code>{code}</Code>"
        f"<Message>{escape(message)}</Message></Error>"
    )


_ERROR_STATUS = {
    "NoSuchBucket": "404 Not Found",
    "NoSuchKey": "404 Not Found",
    "BucketAlreadyExists": "409 Conflict",
    "BucketNotEmpty": "409 Conflict",
    "SignatureDoesNotMatch": "403 Forbidden",
    "AccessDenied": "403 Forbidden",
    "InvalidRequest": "400 Bad Request",
    "NoSuchUpload": "404 Not Found",
}


class S3Error(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code


class RGWGateway:
    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 index_backend=None):
        self.backend = backend  # an Objecter (object-data pool, often EC)
        #: metadata plane (users / bucket list / bucket indexes / upload
        #: state): a SEPARATE pool handle when provided -- the reference
        #: keeps rgw metadata on replicated pools while data rides EC
        #: (rgw_rados.cc pool layout: .rgw.buckets.index et al.)
        self.index = index_backend if index_backend is not None else backend
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        #: Swift auth tokens (X-Auth-Token -> (account, issued_at));
        #: the reference keeps these in its expiring token cache
        #: (rgw_swift_auth.cc)
        self._swift_tokens: Dict[str, tuple] = {}

    SWIFT_TOKEN_TTL = 3600.0

    # -- user admin (radosgw-admin user create role) -----------------------

    async def create_user(self, access: str, secret: str,
                          display: str = "") -> None:
        await self.index.omap_set(USERS_OID, {
            access: f"{secret}\x00{display}".encode(),
        })

    async def _secret_for(self, access: str) -> Optional[str]:
        got = await self.index.omap_get(USERS_OID, [access])
        if access not in got:
            return None
        return got[access].decode().split("\x00", 1)[0]

    # -- HTTP server -------------------------------------------------------

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            req = await reader.readline()
            parts = req.split()
            if len(parts) < 2:
                return
            method, target = parts[0].decode(), parts[1].decode()
            headers: Dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            n = int(headers.get("content-length", "0") or "0")
            if n:
                body = await reader.readexactly(n)
            try:
                status, ctype, out, extra = await self._handle(
                    method, target, headers, body
                )
            except S3Error as e:
                status = _ERROR_STATUS.get(e.code, "400 Bad Request")
                ctype = "application/xml"
                out = _xml_error(e.code, str(e)).encode()
                extra = {}
            except Exception as e:  # noqa: BLE001 -- internal error
                status, ctype = "500 Internal Server Error", "application/xml"
                out = _xml_error("InternalError", str(e)).encode()
                extra = {}
            hdr = (
                f"HTTP/1.1 {status}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(out)}\r\nConnection: close\r\n"
            )
            for k, v in extra.items():
                hdr += f"{k}: {v}\r\n"
            writer.write(hdr.encode() + b"\r\n" + out)
            await writer.drain()
        finally:
            writer.close()

    # -- request routing (RGWHandler_REST_S3 dispatch) ---------------------

    async def _auth(self, method: str, resource: str,
                    headers: Dict[str, str],
                    path: str = "", params: Optional[Dict[str, str]] = None,
                    body: bytes = b"") -> str:
        auth = headers.get("authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256 "):
            return await self._auth_v4(auth, method, path, params or {},
                                       headers, body)
        if not auth:
            # anonymous request (reference: rgw's anonymous user): only
            # resources with a public-read/-write grant will authorize
            return None
        if not auth.startswith("AWS "):
            raise S3Error("AccessDenied", "missing AWS authorization")
        try:
            access, sig = auth[4:].split(":", 1)
        except ValueError:
            raise S3Error("InvalidRequest", "malformed authorization")
        secret = await self._secret_for(access)
        if secret is None:
            raise S3Error("AccessDenied", f"no such access key {access!r}")
        want = sign_v2(
            secret, method, resource, headers.get("date", ""),
            headers.get("content-type", ""), headers.get("content-md5", ""),
        )
        if not hmac.compare_digest(want, sig):
            raise S3Error("SignatureDoesNotMatch", "bad signature")
        return access

    async def _auth_v4(self, auth: str, method: str, path: str,
                       params: Dict[str, str], headers: Dict[str, str],
                       body: bytes) -> str:
        """AWS SigV4 verification (rgw_auth_s3.cc AWSv4ComplMulti /
        get_v4_canonical_method): rebuild the canonical request from
        what actually arrived and compare signatures."""
        fields: Dict[str, str] = {}
        for piece in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = piece.strip().partition("=")
            fields[k] = v
        try:
            cred = fields["Credential"]
            signed_headers = fields["SignedHeaders"]
            sig = fields["Signature"]
            access, datestamp, region, svc, term = cred.split("/")
        except (KeyError, ValueError):
            raise S3Error("InvalidRequest", "malformed v4 authorization")
        if (svc, term) != ("s3", "aws4_request"):
            raise S3Error("InvalidRequest", f"bad credential scope {cred!r}")
        secret = await self._secret_for(access)
        if secret is None:
            raise S3Error("AccessDenied", f"no such access key {access!r}")
        amz_date = headers.get("x-amz-date", "")
        if not amz_date.startswith(datestamp):
            raise S3Error("InvalidRequest", "x-amz-date outside scope")
        payload_hash = headers.get("x-amz-content-sha256",
                                   "UNSIGNED-PAYLOAD")
        if payload_hash not in ("UNSIGNED-PAYLOAD",
                                hashlib.sha256(body).hexdigest()):
            raise S3Error("SignatureDoesNotMatch", "payload hash mismatch")
        want = sign_v4(secret, method, path, params, headers,
                       signed_headers, payload_hash, amz_date, region)
        if not hmac.compare_digest(want, sig):
            raise S3Error("SignatureDoesNotMatch", "bad v4 signature")
        return access

    @staticmethod
    def _split_target(target: str) -> Tuple[str, str, Dict[str, str]]:
        path, _, query = target.partition("?")
        params = {}
        for kv in query.split("&"):
            if kv:
                k, _, v = kv.partition("=")
                params[k] = v
        path = path.lstrip("/")
        bucket, _, key = path.partition("/")
        return bucket, key, params

    async def _bucket_owner(self, bucket: str) -> str:
        got = await self.index.omap_get(BUCKETS_OID, [bucket])
        if bucket not in got:
            raise S3Error("NoSuchBucket", bucket)
        return got[bucket].decode().split("\x00", 1)[0]

    async def _check_owner(self, bucket: str, owner) -> None:
        """Bucket-owner-only authorization (bucket delete, ACL writes)."""
        if owner is None or await self._bucket_owner(bucket) != owner:
            raise S3Error(
                "AccessDenied", f"bucket {bucket!r} is not yours"
            )

    async def _check_access(self, bucket: str, owner, perm: str,
                            key: str = None) -> None:
        """ACL authorization (reference src/rgw/rgw_acl.h:1 +
        rgw_op.cc verify_permission): the bucket owner has full
        control; otherwise the object ACL (if any), then the bucket
        ACL, must grant ``perm`` to ``owner`` (None = anonymous)."""
        import json as _json

        if owner is not None and await self._bucket_owner(bucket) == owner:
            return
        # keyed fetch: only the two relevant ACLs, never the whole store
        want = ["@bucket"] + ([key] if key is not None else [])
        acls = await self.index.omap_get(acl_oid(bucket), want)

        def load(k):
            raw = acls.get(k)
            return _json.loads(raw) if raw else None

        if key is not None and acl_allows(load(key), owner, perm):
            return
        if acl_allows(load("@bucket"), owner, perm):
            return
        raise S3Error(
            "AccessDenied",
            f"{owner or 'anonymous'} has no {perm} on "
            f"{bucket + ('/' + key if key else '')!r}"
        )

    async def _store_acl(self, bucket: str, key: str,
                         acl: Optional[dict]) -> None:
        import json as _json

        if acl is not None:
            await self.index.omap_set(
                acl_oid(bucket),
                {key or "@bucket": _json.dumps(acl).encode()},
            )

    async def _load_acl(self, bucket: str, key: str):
        import json as _json

        got = await self.index.omap_get(
            acl_oid(bucket), [key or "@bucket"])
        raw = got.get(key or "@bucket")
        return _json.loads(raw) if raw else None

    async def _handle(self, method, target, headers, body):
        # Swift routing needs more than the path prefix: an S3 bucket
        # may legitimately be NAMED "v1" or "auth", and its signed
        # requests must not be diverted into the Swift stack
        auth = headers.get("authorization", "")
        if target.startswith(("/auth/", "/v1/")) and not \
                auth.startswith(("AWS ", "AWS4-HMAC-SHA256 ")):
            return await self._handle_swift(method, target, headers, body)
        bucket, key, params = self._split_target(target)
        resource = "/" + bucket + ("/" + key if key else "")
        path = target.partition("?")[0]
        owner = await self._auth(method, resource, headers,
                                 path=path, params=params, body=body)
        if not bucket:
            if method == "GET" and owner is not None:
                return await self._list_buckets(owner)
            raise S3Error("AccessDenied" if owner is None else
                          "InvalidRequest", f"{method} on service root")
        if not key:
            if "versioning" in params:
                # bucket versioning config (reference rgw olh versioning;
                # `PUT ?versioning` owner-only, like S3)
                if method == "PUT":
                    await self._check_owner(bucket, owner)
                    status = (b"Enabled" if b"Enabled" in body
                              else b"Suspended")
                    await self.index.omap_set(
                        acl_oid(bucket), {"@versioning": status})
                    return "200 OK", "application/xml", b"", {}
                if method == "GET":
                    await self._check_owner(bucket, owner)
                    got = await self.index.omap_get(
                        acl_oid(bucket), ["@versioning"])
                    status = (got.get("@versioning") or b"").decode()
                    xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                           "<VersioningConfiguration>"
                           + (f"<Status>{status}</Status>" if status
                              else "")
                           + "</VersioningConfiguration>")
                    return "200 OK", "application/xml", xml.encode(), {}
            if method == "GET" and "versions" in params:
                await self._check_access(bucket, owner, "READ")
                return await self._list_versions(bucket)
            if method == "PUT" and "acl" in params:
                # PUT /bucket?acl: replace the bucket ACL (owner only)
                await self._check_owner(bucket, owner)
                acl = acl_from_headers(headers, owner)
                await self._store_acl(
                    bucket, "",
                    acl or {"owner": owner, "canned": "private",
                            "grants": []})
                return "200 OK", "application/xml", b"", {}
            if method == "GET" and "acl" in params:
                await self._check_access(bucket, owner, "FULL_CONTROL")
                xml = acl_to_xml(await self._load_acl(bucket, ""),
                                 await self._bucket_owner(bucket))
                return "200 OK", "application/xml", xml.encode(), {}
            if method == "PUT":
                if owner is None:
                    raise S3Error("AccessDenied", "anonymous create")
                out = await self._create_bucket(bucket, owner)
                await self._store_acl(
                    bucket, "", acl_from_headers(headers, owner))
                return out
            if method == "DELETE":
                await self._check_owner(bucket, owner)
                return await self._delete_bucket(bucket)
            if method == "GET":
                # listing needs a READ grant (canned public-read /
                # authenticated-read / explicit x-amz-grant-read)
                await self._check_access(bucket, owner, "READ")
                if "uploads" in params:
                    return await self._list_uploads(bucket)
                return await self._list_objects(
                    bucket, params.get("prefix", "")
                )
            raise S3Error("InvalidRequest", f"{method} on bucket")
        if "acl" in params:
            # object ACL subresource: owner or FULL_CONTROL grantee
            if owner is None or await self._bucket_owner(bucket) != owner:
                await self._check_access(bucket, owner, "FULL_CONTROL", key)
            if method == "PUT":
                acl = acl_from_headers(headers, owner)
                await self._store_acl(
                    bucket, key,
                    acl or {"owner": owner, "canned": "private",
                            "grants": []})
                return "200 OK", "application/xml", b"", {}
            if method == "GET":
                xml = acl_to_xml(await self._load_acl(bucket, key),
                                 await self._bucket_owner(bucket))
                return "200 OK", "application/xml", xml.encode(), {}
            raise S3Error("InvalidRequest", f"{method} on ?acl")
        if method in ("GET", "HEAD"):
            await self._check_access(bucket, owner, "READ", key)
        else:
            # PUT/POST/DELETE on objects need a WRITE grant on the bucket
            await self._check_access(bucket, owner, "WRITE")
        # multipart upload surface (rgw_multipart: initiate/part/
        # complete/abort)
        if method == "POST" and "uploads" in params:
            return await self._initiate_multipart(bucket, key)
        if method == "POST" and "uploadId" in params:
            return await self._complete_multipart(
                bucket, key, params["uploadId"], body)
        if method == "PUT" and "uploadId" in params:
            try:
                part = int(params.get("partNumber", "0"))
            except ValueError:
                raise S3Error("InvalidRequest",
                              f"bad partNumber {params['partNumber']!r}")
            return await self._upload_part(
                bucket, key, params["uploadId"], part, body)
        if method == "DELETE" and "uploadId" in params:
            return await self._abort_multipart(
                bucket, key, params["uploadId"])
        if method == "PUT":
            out = await self._put_object(bucket, key, body)
            acl = acl_from_headers(headers, owner)
            if acl is not None:
                await self._store_acl(bucket, key, acl)
            else:
                # S3 semantics: an overwrite without ACL headers resets
                # the object to default-private -- the previous object's
                # grants must not apply to the new content
                await self.index.omap_rm(acl_oid(bucket), [key])
            return out
        if method == "GET":
            return await self._get_object(
                bucket, key, version_id=params.get("versionId"))
        if method == "HEAD":
            return await self._head_object(
                bucket, key, version_id=params.get("versionId"))
        if method == "DELETE":
            return await self._delete_object(
                bucket, key, version_id=params.get("versionId"))
        raise S3Error("InvalidRequest", f"{method} on object")

    # -- Swift API (rgw_rest_swift.cc + rgw_swift_auth.cc subset) ----------
    #
    # TempAuth flow: GET /auth/v1.0 with X-Storage-User "<account>:<user>"
    # (the access key) + X-Storage-Pass (the secret) returns X-Auth-Token
    # and X-Storage-Url; data ops are /v1/AUTH_<account>/<container>[/obj]
    # with the token header.  Containers map onto the same bucket
    # objects the S3 side uses, so both protocols see one namespace
    # (the reference stores Swift containers as rgw buckets too).

    async def _handle_swift(self, method, target, headers, body):
        path = target.partition("?")[0]
        if path == "/auth/v1.0":
            user = headers.get("x-storage-user", "")
            access = user.split(":", 1)[0]
            secret = await self._secret_for(access)
            if secret is None or not hmac.compare_digest(
                    headers.get("x-storage-pass", ""), secret):
                raise S3Error("AccessDenied", "bad swift credentials")
            now = time.time()
            # expire old tokens (the reference's token cache ages
            # entries out; an immortal dict would leak AND keep stolen
            # tokens valid forever)
            self._swift_tokens = {
                t: (acct, ts) for t, (acct, ts) in
                self._swift_tokens.items()
                if now - ts < self.SWIFT_TOKEN_TTL
            }
            tok = "AUTH_tk" + hashlib.sha256(
                f"{access}:{secret}:{now}".encode()).hexdigest()[:32]
            self._swift_tokens[tok] = (access, now)
            return "200 OK", "text/plain", b"", {
                "X-Auth-Token": tok,
                "X-Storage-Url": f"http://{self.host}:{self.port}"
                                 f"/v1/AUTH_{access}",
            }
        ent = self._swift_tokens.get(headers.get("x-auth-token", ""))
        if ent is None or time.time() - ent[1] >= self.SWIFT_TOKEN_TTL:
            raise S3Error("AccessDenied", "missing or expired auth token")
        owner = ent[0]
        parts = path.split("/", 4)  # ['', 'v1', 'AUTH_x', container, obj]
        if len(parts) < 3 or not parts[2].startswith("AUTH_"):
            raise S3Error("AccessDenied", "bad storage path")
        container = parts[3] if len(parts) > 3 else ""
        obj = parts[4] if len(parts) > 4 else ""
        if parts[2] != f"AUTH_{owner}":
            # another account's path: readable iff its container/object
            # ACL grants READ (the X-Container-Read role,
            # rgw_rest_swift.cc + rgw_acl_swift.cc)
            if method not in ("GET", "HEAD") or not container:
                raise S3Error("AccessDenied",
                              "token does not match account")
            await self._check_access(container, owner, "READ",
                                     obj or None)
            if not obj:
                return await self._swift_list_container(container)
            if method == "GET":
                return await self._get_object(container, obj)
            return await self._head_object(container, obj)
        if not container:
            if method == "GET":  # account listing: containers, plain text
                buckets = await self.index.omap_get(BUCKETS_OID)
                mine = sorted(
                    n for n, raw in buckets.items()
                    if raw.decode().split("\x00", 1)[0] == owner)
                return "200 OK", "text/plain", \
                    ("\n".join(mine) + "\n" if mine else "").encode(), {}
            raise S3Error("InvalidRequest", f"{method} on account")
        if not obj:
            if method in ("PUT", "POST"):
                if method == "PUT":
                    try:
                        await self._create_bucket(container, owner)
                    except S3Error as e:
                        if e.code != "BucketAlreadyExists":
                            raise
                        # idempotent ONLY for the owner: 201 on someone
                        # else's container would be a silent false success
                        await self._check_owner(container, owner)
                else:
                    await self._check_owner(container, owner)
                # X-Container-Read (rgw_acl_swift.cc): ".r:*" = public
                # read, otherwise a comma list of granted accounts
                read_acl = headers.get("x-container-read", "")
                if read_acl:
                    grants = []
                    for part in read_acl.split(","):
                        part = part.strip()
                        if part in (".r:*", ".rlistings"):
                            grants.append(
                                {"grantee": "*", "perm": "READ"})
                        elif part:
                            grants.append(
                                {"grantee": part.split(":")[-1],
                                 "perm": "READ"})
                    await self._store_acl(container, "", {
                        "owner": owner, "canned": "swift",
                        "grants": grants})
                return ("201 Created" if method == "PUT"
                        else "204 No Content"), "text/plain", b"", {}
            await self._check_owner(container, owner)
            if method == "DELETE":
                await self._delete_bucket(container)
                return "204 No Content", "text/plain", b"", {}
            if method == "GET":  # object listing, plain text
                return await self._swift_list_container(container)
            raise S3Error("InvalidRequest", f"{method} on container")
        await self._check_owner(container, owner)
        if method == "PUT":
            status, ctype, out, extra = await self._put_object(
                container, obj, body)
            return "201 Created", ctype, out, extra
        if method == "GET":
            return await self._get_object(container, obj)
        if method == "HEAD":
            return await self._head_object(container, obj)
        if method == "DELETE":
            return await self._delete_object(container, obj)
        raise S3Error("InvalidRequest", f"{method} on object")

    async def _swift_list_container(self, container: str):
        """Plain-text Swift object listing (shared by the own-account and
        cross-account read paths so the format cannot diverge)."""
        index = await self.index.omap_get(bucket_index_oid(container))
        names = sorted(index)
        return "200 OK", "text/plain", \
            ("\n".join(names) + "\n" if names else "").encode(), {}

    # -- bucket ops (rgw_bucket.cc) ----------------------------------------

    async def _bucket_exists(self, bucket: str) -> bool:
        got = await self.index.omap_get(BUCKETS_OID, [bucket])
        return bucket in got

    async def _list_buckets(self, owner: str):
        buckets = await self.index.omap_get(BUCKETS_OID)
        mine = [
            n for n, raw in buckets.items()
            if raw.decode().split("\x00", 1)[0] == owner
        ]
        items = "".join(
            f"<Bucket><Name>{escape(n)}</Name></Bucket>"
            for n in sorted(mine)
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            "<ListAllMyBucketsResult>"
            f"<Owner><ID>{escape(owner)}</ID></Owner>"
            f"<Buckets>{items}</Buckets></ListAllMyBucketsResult>"
        )
        return "200 OK", "application/xml", xml.encode(), {}

    async def _create_bucket(self, bucket: str, owner: str):
        if await self._bucket_exists(bucket):
            raise S3Error("BucketAlreadyExists", bucket)
        await self.index.omap_set(BUCKETS_OID, {
            bucket: f"{owner}\x00{int(time.time())}".encode(),
        })
        return "200 OK", "application/xml", b"", {}

    async def _delete_bucket(self, bucket: str):
        if not await self._bucket_exists(bucket):
            raise S3Error("NoSuchBucket", bucket)
        index = await self.index.omap_get(bucket_index_oid(bucket))
        if index:
            raise S3Error("BucketNotEmpty", bucket)
        vers = await self.index.omap_get(versions_oid(bucket))
        if any(k != "_seq" for k in vers):
            # versions (incl. delete markers) still exist: S3 refuses
            raise S3Error("BucketNotEmpty", f"{bucket} (versions remain)")
        await self.index.omap_clear(versions_oid(bucket))
        # abort any in-progress multipart uploads: leaving their parts
        # behind would let a future same-name bucket's owner complete
        # the previous tenant's upload and read its data
        try:
            ups = await self.index.omap_get(uploads_oid(bucket))
        except (FileNotFoundError, IOError):
            ups = {}
        for upload_id, raw_key in ups.items():
            key = raw_key.decode()
            try:
                meta = await self.index.omap_get(
                    self._mp_meta_oid(bucket, key, upload_id))
                await self._drop_upload(bucket, key, upload_id, meta)
            except (FileNotFoundError, IOError):
                pass
        await self.index.omap_rm(BUCKETS_OID, [bucket])
        # drop the ACL store with the bucket: a future same-name bucket
        # must not inherit the previous tenant's grants
        await self.index.omap_clear(acl_oid(bucket))
        return "204 No Content", "application/xml", b"", {}

    async def _list_objects(self, bucket: str, prefix: str):
        if not await self._bucket_exists(bucket):
            raise S3Error("NoSuchBucket", bucket)
        index = await self.index.omap_get(bucket_index_oid(bucket))
        items = []
        for k in sorted(index):
            if not k.startswith(prefix):
                continue
            # versioned entries carry a 4th (vid) field
            size, etag = index[k].decode().split("\x00")[:2]
            items.append(
                f"<Contents><Key>{escape(k)}</Key><Size>{size}</Size>"
                f'<ETag>"{etag}"</ETag></Contents>'
            )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<ListBucketResult><Name>{escape(bucket)}</Name>"
            f"<Prefix>{escape(prefix)}</Prefix>"
            f"{''.join(items)}</ListBucketResult>"
        )
        return "200 OK", "application/xml", xml.encode(), {}

    # -- object ops (rgw_rados.cc put/get paths) ---------------------------

    # -- versioning (reference rgw olh/versioning, rgw_rados.cc) ----------

    async def _versioning_enabled(self, bucket: str) -> bool:
        got = await self.index.omap_get(acl_oid(bucket), ["@versioning"])
        return got.get("@versioning") == b"Enabled"

    async def _next_vid(self, bucket: str) -> str:
        """Monotonic per-bucket version id (CAS-allocated, so racing
        PUTs get distinct ids; zero-padded so lexicographic order is
        chronological)."""
        while True:
            cur = await self.index.omap_get(versions_oid(bucket), ["_seq"])
            have = int(cur["_seq"]) if "_seq" in cur else 0
            ok, _ = await self.index.omap_cas(
                versions_oid(bucket), "_seq", cur.get("_seq"),
                str(have + 1).encode())
            if ok:
                return f"{have + 1:010d}"

    async def _archive_plain_current(self, bucket: str, key: str) -> None:
        """A pre-versioning (plain) current object must survive as a
        version when versioning operations replace or delete it (the S3
        'null version' role): it becomes a version whose data stays at
        the plain oid (kind 'plain')."""
        got = await self.index.omap_get(bucket_index_oid(bucket), [key])
        if key not in got:
            return
        parts = got[key].decode().split("\x00")
        if len(parts) > 3:
            return  # already version-pointing
        avid = await self._next_vid(bucket)
        await self.index.omap_set(versions_oid(bucket), {
            f"{key}\x00{avid}":
                f"{parts[0]}\x00{parts[1]}\x00{parts[2]}\x00plain".encode(),
        })

    async def _store_version(self, bucket: str, key: str, body: bytes,
                             etag: str) -> str:
        """Archive ``body`` as a new version and point the bucket index
        at it (every PUT to a versioned bucket creates a version)."""
        await self._archive_plain_current(bucket, key)
        vid = await self._next_vid(bucket)
        ts = int(time.time())
        await self.backend.write(ver_obj_oid(bucket, key, vid), body)
        await self.index.omap_set(versions_oid(bucket), {
            f"{key}\x00{vid}":
                f"{len(body)}\x00{etag}\x00{ts}\x00put".encode(),
        })
        await self.index.omap_set(bucket_index_oid(bucket), {
            key: f"{len(body)}\x00{etag}\x00{ts}\x00{vid}".encode(),
        })
        return vid

    async def _put_object(self, bucket: str, key: str, body: bytes):
        if not await self._bucket_exists(bucket):
            raise S3Error("NoSuchBucket", bucket)
        etag = hashlib.md5(body).hexdigest()
        if await self._versioning_enabled(bucket):
            vid = await self._store_version(bucket, key, body, etag)
            return "200 OK", "application/xml", b"", {
                "ETag": f'"{etag}"', "x-amz-version-id": vid}
        # data first, then the index entry (the reference's bucket-index
        # prepare/complete keeps the index authoritative)
        await self.backend.write(obj_oid(bucket, key), body)
        await self.index.omap_set(bucket_index_oid(bucket), {
            key: f"{len(body)}\x00{etag}\x00{int(time.time())}".encode(),
        })
        return "200 OK", "application/xml", b"", {"ETag": f'"{etag}"'}

    async def _index_entry(self, bucket: str, key: str):
        """-> (size, etag, current version id | None for plain objects)."""
        if not await self._bucket_exists(bucket):
            raise S3Error("NoSuchBucket", bucket)
        got = await self.index.omap_get(bucket_index_oid(bucket), [key])
        if key not in got:
            raise S3Error("NoSuchKey", key)
        parts = got[key].decode().split("\x00")
        return int(parts[0]), parts[1], parts[3] if len(parts) > 3 else None

    async def _version_meta(self, bucket: str, key: str, vid: str):
        got = await self.index.omap_get(
            versions_oid(bucket), [f"{key}\x00{vid}"])
        raw = got.get(f"{key}\x00{vid}")
        if raw is None:
            raise S3Error("NoSuchKey", f"{key} versionId={vid}")
        size_s, etag, ts, kind = raw.decode().split("\x00")
        return int(size_s), etag, kind

    async def _get_object(self, bucket: str, key: str,
                          version_id: Optional[str] = None):
        if version_id is not None:
            _size, etag, kind = await self._version_meta(
                bucket, key, version_id)
            if kind == "marker":
                raise S3Error("NoSuchKey", f"{key} (delete marker)")
            data = await self.backend.read(
                obj_oid(bucket, key) if kind == "plain"
                else ver_obj_oid(bucket, key, version_id))
            return "200 OK", "application/octet-stream", data, {
                "ETag": f'"{etag}"', "x-amz-version-id": version_id}
        size, etag, vid = await self._index_entry(bucket, key)
        data = await self.backend.read(
            ver_obj_oid(bucket, key, vid) if vid else obj_oid(bucket, key))
        hdrs = {"ETag": f'"{etag}"'}
        if vid:
            hdrs["x-amz-version-id"] = vid
        return "200 OK", "application/octet-stream", data, hdrs

    async def _head_object(self, bucket: str, key: str,
                           version_id: Optional[str] = None):
        if version_id is not None:
            size, etag, kind = await self._version_meta(
                bucket, key, version_id)
            if kind == "marker":
                raise S3Error("NoSuchKey", f"{key} (delete marker)")
        else:
            size, etag, _vid = await self._index_entry(bucket, key)
        return "200 OK", "application/octet-stream", b"", {
            "ETag": f'"{etag}"', "X-Object-Size": str(size),
        }

    async def _list_versions(self, bucket: str):
        """GET /bucket?versions -> ListVersionsResult (Version +
        DeleteMarker entries, newest first per key)."""
        if not await self._bucket_exists(bucket):
            raise S3Error("NoSuchBucket", bucket)
        vers = await self.index.omap_get(versions_oid(bucket))
        newest: Dict[str, str] = {}
        for vk in vers:
            if vk == "_seq":
                continue
            key, _, vid = vk.rpartition("\x00")
            if vid > newest.get(key, ""):
                newest[key] = vid
        items = []
        for vk in sorted(vers, reverse=True):
            if vk == "_seq":
                continue
            key, _, vid = vk.rpartition("\x00")
            size_s, etag, ts, kind = vers[vk].decode().split("\x00")
            latest = "true" if newest.get(key) == vid else "false"
            if kind == "marker":
                items.append(
                    f"<DeleteMarker><Key>{escape(key)}</Key>"
                    f"<VersionId>{vid}</VersionId>"
                    f"<IsLatest>{latest}</IsLatest></DeleteMarker>")
            else:
                items.append(
                    f"<Version><Key>{escape(key)}</Key>"
                    f"<VersionId>{vid}</VersionId>"
                    f"<IsLatest>{latest}</IsLatest>"
                    f"<Size>{size_s}</Size>"
                    f'<ETag>"{etag}"</ETag></Version>')
        xml = ('<?xml version="1.0" encoding="UTF-8"?>'
               f"<ListVersionsResult><Name>{escape(bucket)}</Name>"
               + "".join(items) + "</ListVersionsResult>")
        return "200 OK", "application/xml", xml.encode(), {}

    # -- multipart upload (reference rgw multipart meta objects:
    # RGWMultipartUpload in rgw_multi.cc -- an upload id names a meta
    # object tracking parts; complete concatenates them and the S3
    # multipart ETag is md5-of-part-md5s with a part count suffix) -----

    _upload_counter = 0

    @staticmethod
    def _mp_meta_oid(bucket: str, key: str, upload_id: str) -> str:
        return f"rgw.mp.{bucket}/{key}.{upload_id}"

    @staticmethod
    def _mp_part_oid(bucket: str, key: str, upload_id: str,
                     part: int) -> str:
        return f"rgw.mp.{bucket}/{key}.{upload_id}.{part:05d}"

    async def _initiate_multipart(self, bucket: str, key: str):
        RGWGateway._upload_counter += 1
        upload_id = hashlib.md5(
            f"{bucket}/{key}/{time.time()}/{self._upload_counter}".encode()
        ).hexdigest()
        await self.index.omap_set(
            self._mp_meta_oid(bucket, key, upload_id),
            {"_meta": f"{int(time.time())}".encode()})
        # track in-progress uploads on the bucket (list-uploads surface)
        await self.index.omap_set(uploads_oid(bucket), {
            upload_id: key.encode()})
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            "<InitiateMultipartUploadResult>"
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId>"
            "</InitiateMultipartUploadResult>"
        )
        return "200 OK", "application/xml", xml.encode(), {}

    async def _upload_meta(self, bucket: str, key: str, upload_id: str):
        meta = await self.index.omap_get(
            self._mp_meta_oid(bucket, key, upload_id))
        if "_meta" not in meta:
            raise S3Error("NoSuchUpload", upload_id)
        return meta

    async def _upload_part(self, bucket: str, key: str, upload_id: str,
                           part: int, body: bytes):
        if part < 1 or part > 10000:
            raise S3Error("InvalidRequest", f"bad part number {part}")
        await self._upload_meta(bucket, key, upload_id)
        etag = hashlib.md5(body).hexdigest()
        await self.backend.write(
            self._mp_part_oid(bucket, key, upload_id, part), body)
        await self.index.omap_set(
            self._mp_meta_oid(bucket, key, upload_id),
            {f"part.{part:05d}": f"{len(body)}\x00{etag}".encode()})
        return "200 OK", "application/xml", b"", {"ETag": f'"{etag}"'}

    async def _complete_multipart(self, bucket: str, key: str,
                                  upload_id: str, body: bytes):
        meta = await self._upload_meta(bucket, key, upload_id)
        parts = sorted(
            (int(k.split(".")[1]), v.decode().split("\x00"))
            for k, v in meta.items() if k.startswith("part."))
        if not parts:
            raise S3Error("InvalidRequest", "no parts uploaded")
        # honor the client's part list when provided (S3 allows
        # completing with a subset); minimal XML scrape
        listed = [int(n) for n in re.findall(
            r"<PartNumber>(\d+)</PartNumber>", body.decode("utf-8",
                                                           "ignore"))]
        if listed:
            chosen = set(listed)
            missing = chosen - {p for p, _ in parts}
            if missing:
                raise S3Error("InvalidRequest",
                              f"parts never uploaded: {sorted(missing)}")
            parts = [(p, info) for p, info in parts if p in chosen]
        blob = bytearray()
        md5s = b""
        for part, (size, etag) in parts:
            data = await self.backend.read(
                self._mp_part_oid(bucket, key, upload_id, part))
            blob += data
            md5s += bytes.fromhex(etag)
        final_etag = f"{hashlib.md5(md5s).hexdigest()}-{len(parts)}"
        extra_hdrs = {}
        if await self._versioning_enabled(bucket):
            extra_hdrs["x-amz-version-id"] = await self._store_version(
                bucket, key, bytes(blob), final_etag)
        else:
            await self.backend.write(obj_oid(bucket, key), bytes(blob))
            await self.index.omap_set(bucket_index_oid(bucket), {
                key: f"{len(blob)}\x00{final_etag}\x00"
                     f"{int(time.time())}".encode(),
            })
        # a completed upload REPLACES the object: default-private, the
        # previous object's grants must not carry over
        await self.index.omap_rm(acl_oid(bucket), [key])
        await self._drop_upload(bucket, key, upload_id, meta)
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            "<CompleteMultipartUploadResult>"
            f"<Key>{escape(key)}</Key>"
            f'<ETag>"{final_etag}"</ETag>'
            "</CompleteMultipartUploadResult>"
        )
        return "200 OK", "application/xml", xml.encode(), extra_hdrs

    async def _abort_multipart(self, bucket: str, key: str,
                               upload_id: str):
        meta = await self._upload_meta(bucket, key, upload_id)
        await self._drop_upload(bucket, key, upload_id, meta)
        return "204 No Content", "application/xml", b"", {}

    async def _drop_upload(self, bucket: str, key: str, upload_id: str,
                           meta: Dict[str, bytes]) -> None:
        for k in meta:
            if k.startswith("part."):
                try:
                    await self.backend.remove_object(self._mp_part_oid(
                        bucket, key, upload_id, int(k.split(".")[1])))
                except IOError:
                    pass
        await self.index.omap_rm(
            self._mp_meta_oid(bucket, key, upload_id), list(meta))
        await self.index.omap_rm(
            uploads_oid(bucket), [upload_id])

    async def _list_uploads(self, bucket: str):
        try:
            ups = await self.index.omap_get(
                uploads_oid(bucket))
        except (FileNotFoundError, IOError):
            ups = {}
        items = "".join(
            f"<Upload><Key>{escape(v.decode())}</Key>"
            f"<UploadId>{u}</UploadId></Upload>"
            for u, v in sorted(ups.items()))
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            "<ListMultipartUploadsResult>"
            f"<Bucket>{escape(bucket)}</Bucket>{items}"
            "</ListMultipartUploadsResult>"
        )
        return "200 OK", "application/xml", xml.encode(), {}

    async def _delete_object(self, bucket: str, key: str,
                             version_id: Optional[str] = None):
        if version_id is not None:
            # permanent removal of ONE version (S3 DELETE ?versionId);
            # if it was current, the newest surviving put-version is
            # promoted (or the key disappears from the plain namespace)
            _s, _e, kind = await self._version_meta(bucket, key, version_id)
            await self.index.omap_rm(
                versions_oid(bucket), [f"{key}\x00{version_id}"])
            if kind != "marker":
                try:
                    await self.backend.remove_object(
                        obj_oid(bucket, key) if kind == "plain"
                        else ver_obj_oid(bucket, key, version_id))
                except IOError:
                    pass
            have_entry, cur = False, None
            try:
                _size, _etag, cur = await self._index_entry(bucket, key)
                have_entry = True
            except S3Error:
                pass
            if (have_entry and cur == version_id) or not have_entry:
                # the removed version was current -- or a delete marker
                # was on top (no plain-namespace entry): surface the
                # newest surviving version.  A PLAIN current entry
                # (have_entry, cur None) stays untouched.
                await self._promote_latest_version(bucket, key)
            return "204 No Content", "application/xml", b"", {}
        if await self._versioning_enabled(bucket):
            # versioned delete: a DELETE MARKER becomes the latest
            # version; older versions stay readable by id (S3 semantics,
            # reference olh delete-marker instances).  Idempotent like
            # S3: deleting an already-hidden (or never-written) key
            # still answers 204 and stacks a marker.
            if not await self._bucket_exists(bucket):
                raise S3Error("NoSuchBucket", bucket)
            await self._archive_plain_current(bucket, key)
            vid = await self._next_vid(bucket)
            await self.index.omap_set(versions_oid(bucket), {
                f"{key}\x00{vid}":
                    f"0\x00\x00{int(time.time())}\x00marker".encode(),
            })
            await self.index.omap_rm(bucket_index_oid(bucket), [key])
            await self.index.omap_rm(acl_oid(bucket), [key])
            return "204 No Content", "application/xml", b"", {
                "x-amz-version-id": vid, "x-amz-delete-marker": "true"}
        await self._index_entry(bucket, key)  # NoSuchKey check
        await self.index.omap_rm(bucket_index_oid(bucket), [key])
        await self.index.omap_rm(acl_oid(bucket), [key])  # its object ACL
        try:
            await self.backend.remove_object(obj_oid(bucket, key))
        except IOError:
            pass  # zero-byte object: nothing was written
        return "204 No Content", "application/xml", b"", {}

    async def _promote_latest_version(self, bucket: str, key: str) -> None:
        """Re-point the plain-namespace index at the newest surviving
        put-version of ``key`` (after its current version was removed);
        a marker or nothing on top hides the key."""
        vers = await self.index.omap_get(versions_oid(bucket))
        best = None  # (vid, meta)
        for vk, raw in vers.items():
            if vk == "_seq":
                continue
            k, _, vid = vk.rpartition("\x00")
            if k != key:
                continue
            if best is None or vid > best[0]:
                best = (vid, raw)
        if best is None:
            await self.index.omap_rm(bucket_index_oid(bucket), [key])
            return
        size_s, etag, ts, kind = best[1].decode().split("\x00")
        if kind == "marker":
            await self.index.omap_rm(bucket_index_oid(bucket), [key])
            return
        if kind == "plain":
            # the archived pre-versioning object resurfaces as a plain
            # current (its data still lives at the plain oid)
            await self.index.omap_set(bucket_index_oid(bucket), {
                key: f"{size_s}\x00{etag}\x00{ts}".encode()})
            await self.index.omap_rm(
                versions_oid(bucket), [f"{key}\x00{best[0]}"])
            return
        await self.index.omap_set(bucket_index_oid(bucket), {
            key: f"{size_s}\x00{etag}\x00{ts}\x00{best[0]}".encode(),
        })
