"""RGW: S3-compatible object gateway over RADOS (reference: src/rgw).

The reference radosgw terminates S3/Swift HTTP, authenticates requests
(AWS signatures against users kept in RADOS), and maps the bucket/object
model onto RADOS objects: bucket indexes are omap objects, object data
lands in data-pool objects, user/bucket metadata lives in meta objects
(rgw_main.cc, rgw_rados.cc, rgw_bucket.cc).  Same decomposition here:

* ``RGWGateway``   -- asyncio HTTP frontend (the civetweb/beast role)
  serving S3 (AWS-v2 HMAC + SigV4 signing, multipart uploads) and
  Swift (TempAuth tokens, account/container/object ops) over ONE
  bucket namespace, like the reference's dual REST stacks;
* users            -- omap on ``rgw.users`` (access -> secret, display);
* buckets          -- omap on ``rgw.buckets`` (the bucket.instance
  metadata role) + one ``rgw.bucket.<name>`` index object per bucket
  whose omap is the bucket index (key -> size/etag/mtime);
* object data      -- one RADOS object ``rgw.obj.<bucket>/<key>`` on
  the (EC) data pool.
"""

from ceph_tpu.rgw.gateway import RGWGateway, sign_v2, sign_v4
from ceph_tpu.rgw.sync import RGWSyncAgent

__all__ = ["RGWGateway", "RGWSyncAgent", "sign_v2", "sign_v4"]
