"""Authentication (reference: src/auth -- cephx).

The reference's cephx protocol: every entity shares a secret with the
monitors (keyring), proves identity via challenge-response without
sending the secret, gets a session key, and (with ``ms_sign_messages``)
signs every message with it.  This module keeps that shape, reduced to
the two-party case our messenger needs:

* ``KeyRing`` -- entity name -> secret, loadable from the same
  ``[entity] key = base64`` INI format ceph keyrings use;
* mutual challenge-response handshake (``AuthHandshake``): both sides
  prove knowledge of the shared secret via HMAC-SHA256 over the paired
  nonces; neither secret nor its hash crosses the wire;
* per-connection session key = HMAC(secret, client_nonce || server_nonce)
  -- both sides derive it, nothing key-like is transmitted;
* per-frame signatures (``sign``/``verify``) with the session key -- the
  ``ms_sign_messages`` role (reference src/auth/cephx/CephxSessionHandler).

Reduction vs the reference (documented): no ticket-granting service /
rotating tickets -- every entity authenticates straight against the
shared keyring, i.e. the auth topology of a cephx cluster collapsed to
one realm.
"""

from ceph_tpu.auth.cephx import AuthError, AuthHandshake, KeyRing

__all__ = ["KeyRing", "AuthHandshake", "AuthError"]
