"""cephx-style keyring + handshake + message signing."""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
from typing import Dict, Optional


class AuthError(Exception):
    pass


class KeyRing:
    """Entity -> secret map (reference: src/auth/KeyRing.cc).

    File format is the ceph keyring INI subset::

        [osd.0]
            key = <base64>
        [client]
            key = <base64>
    """

    def __init__(self, keys: Optional[Dict[str, bytes]] = None):
        self._keys: Dict[str, bytes] = dict(keys or {})

    @staticmethod
    def generate_key() -> bytes:
        return os.urandom(32)

    def add(self, entity: str, key: Optional[bytes] = None) -> bytes:
        key = key if key is not None else self.generate_key()
        self._keys[entity] = key
        return key

    def get(self, entity: str) -> Optional[bytes]:
        return self._keys.get(entity)

    def remove(self, entity: str) -> None:
        """Revoke an entity's key (the `auth rm` flow)."""
        self._keys.pop(entity, None)

    def entities(self):
        return sorted(self._keys)

    # -- file I/O (ceph keyring INI subset) --------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for entity in sorted(self._keys):
                f.write(f"[{entity}]\n")
                key = base64.b64encode(self._keys[entity]).decode()
                f.write(f"\tkey = {key}\n")
        os.chmod(path, 0o600)

    @classmethod
    def load(cls, path: str) -> "KeyRing":
        keys: Dict[str, bytes] = {}
        entity = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith("[") and line.endswith("]"):
                    entity = line[1:-1]
                elif line.startswith("key") and "=" in line and entity:
                    keys[entity] = base64.b64decode(
                        line.split("=", 1)[1].strip()
                    )
        return cls(keys)


def _mac(key: bytes, *parts: bytes) -> bytes:
    h = hmac.new(key, digestmod=hashlib.sha256)
    for p in parts:
        h.update(len(p).to_bytes(4, "little"))
        h.update(p)
    return h.digest()


class AuthHandshake:
    """Mutual challenge-response for one connection.

    Flow (client = connector, server = acceptor)::

        client -> server:  entity, client_nonce
        server -> client:  server_nonce, server_proof
        client -> server:  client_proof

    ``server_proof  = HMAC(secret, "srv", client_nonce, server_nonce)``
    ``client_proof  = HMAC(secret, "cli", client_nonce, server_nonce)``
    ``session_key   = HMAC(secret, "ses", client_nonce, server_nonce)``

    Each side verifies the other's proof before trusting the connection;
    the session key never crosses the wire.
    """

    def __init__(self, secret: bytes, client_nonce: bytes,
                 server_nonce: bytes):
        self.secret = secret
        self.client_nonce = client_nonce
        self.server_nonce = server_nonce

    @staticmethod
    def new_nonce() -> bytes:
        return os.urandom(16)

    def server_proof(self) -> bytes:
        return _mac(self.secret, b"srv", self.client_nonce,
                    self.server_nonce)

    def client_proof(self) -> bytes:
        return _mac(self.secret, b"cli", self.client_nonce,
                    self.server_nonce)

    def verify_server(self, proof: bytes) -> bool:
        return hmac.compare_digest(proof, self.server_proof())

    def verify_client(self, proof: bytes) -> bool:
        return hmac.compare_digest(proof, self.client_proof())

    def session_key(self) -> bytes:
        return _mac(self.secret, b"ses", self.client_nonce,
                    self.server_nonce)


def sign(session_key: bytes, payload: bytes) -> bytes:
    """Per-frame signature (ms_sign_messages role), truncated like the
    reference's 64-bit message signatures -- 16 bytes here."""
    return _mac(session_key, payload)[:16]


def sign_parts(session_key: bytes, parts) -> bytes:
    """:func:`sign` over a scatter-gather part list without joining it:
    the digest streams over each buffer, so
    ``sign_parts(k, [a, b]) == sign(k, a + b)`` (one pass, zero copies
    -- the corked messenger signs sealed frames straight off the part
    list)."""
    h = hmac.new(session_key, digestmod=hashlib.sha256)
    h.update(sum(len(p) for p in parts).to_bytes(4, "little"))
    for p in parts:
        h.update(p)
    return h.digest()[:16]


def verify(session_key: bytes, payload: bytes, sig: bytes) -> bool:
    return hmac.compare_digest(sig, sign(session_key, payload))
