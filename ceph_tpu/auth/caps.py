"""OSD capability grammar + checks (reference: src/osd/OSDCap.{h,cc}).

The reference parses cap strings like ``allow rwx pool=data
object_prefix rbd_`` (boost::spirit grammar, OSDCapParser) into grants
and answers ``is_capable(pool, object, r, w, class_call)`` by OR-ing
grants.  Same model here for the subset the framework enforces:

  caps      := grant { "," grant }
  grant     := "allow" ( "*" | "all" | rwx-spec ) { match }
  rwx-spec  := subset of "r" "w" "x" (x = object-class call, exec)
  match     := "pool=" name | "object_prefix" prefix

A mon keyring entry's ``caps osd`` string rides the cephx ticket; the
OSD checks every client op against it (PrimaryLogPG op_has_sufficient_
caps, src/osd/PrimaryLogPG.cc).
"""

from __future__ import annotations

from typing import List, Optional


class CapGrant:
    def __init__(self, allow_all: bool = False, r: bool = False,
                 w: bool = False, x: bool = False,
                 pool: Optional[str] = None,
                 object_prefix: Optional[str] = None):
        self.allow_all = allow_all
        self.r, self.w, self.x = r, w, x
        self.pool = pool
        self.object_prefix = object_prefix

    def _matches(self, pool: str, obj: str) -> bool:
        if self.pool is not None and self.pool != pool:
            return False
        if self.object_prefix is not None and \
                not obj.startswith(self.object_prefix):
            return False
        return True

    def covers(self, pool: str, obj: str, need_r: bool, need_w: bool,
               need_x: bool) -> bool:
        if not self._matches(pool, obj):
            return False
        if self.allow_all:
            return True
        if need_r and not self.r:
            return False
        if need_w and not self.w:
            return False
        if need_x and not self.x:
            return False
        return True


class OSDCap:
    def __init__(self, grants: List[CapGrant]):
        self.grants = grants

    @classmethod
    def parse(cls, caps: str) -> "OSDCap":
        grants: List[CapGrant] = []
        for clause in caps.split(","):
            toks = clause.split()
            if not toks:
                continue
            if toks[0] != "allow":
                raise ValueError(f"cap clause must start with allow: "
                                 f"{clause!r}")
            g = CapGrant()
            i = 1
            if i < len(toks) and toks[i] in ("*", "all"):
                g.allow_all = True
                i += 1
            elif i < len(toks) and set(toks[i]) <= set("rwx"):
                g.r = "r" in toks[i]
                g.w = "w" in toks[i]
                g.x = "x" in toks[i]
                i += 1
            else:
                raise ValueError(f"bad rwx spec in {clause!r}")
            while i < len(toks):
                t = toks[i]
                if t.startswith("pool="):
                    g.pool = t[len("pool="):]
                    i += 1
                elif t == "object_prefix" and i + 1 < len(toks):
                    g.object_prefix = toks[i + 1]
                    i += 2
                else:
                    raise ValueError(f"bad match clause {t!r} in {clause!r}")
            grants.append(g)
        if not grants:
            raise ValueError("empty cap string")
        return cls(grants)

    def is_capable(self, pool: str, obj: str, need_r: bool = False,
                   need_w: bool = False, need_x: bool = False) -> bool:
        """True when some grant covers the op.  An exec (x) op also
        implies read access in the reference; callers pass the
        fine-grained needs and this ORs grants exactly like
        OSDCap::is_capable."""
        return any(g.covers(pool, obj, need_r, need_w, need_x)
                   for g in self.grants)


#: which framework op kinds need which access bits (PrimaryLogPG
#: op_has_sufficient_caps' may_read/may_write/may_exec classification)
OP_NEEDS = {
    "read": (True, False, False),
    "read_range": (True, False, False),
    "stat": (True, False, False),
    "omap_get": (True, False, False),
    "list_snaps": (True, False, False),
    "write": (False, True, False),
    "write_range": (False, True, False),
    "remove": (False, True, False),
    "omap_set": (False, True, False),
    "omap_rm": (False, True, False),
    "omap_clear": (False, True, False),
    "omap_cas": (False, True, False),
    "snap_trim": (False, True, False),
    "snap_rollback": (False, True, False),
    # watch mutates primary-side watcher state: the reference's
    # CEPH_OSD_OP_WATCH is a write-mode op (may_write), and unwatch must
    # mirror it so a watcher can always unregister what it registered
    "exec": (True, False, True),
    "watch": (False, True, False),
    "unwatch": (False, True, False),
    "notify": (True, False, False),
    "scrub": (True, False, False),
    "recover": (False, True, False),
}


def op_capable(cap: OSDCap, pool: str, obj: str, op_kind: str) -> bool:
    need_r, need_w, need_x = OP_NEEDS.get(op_kind, (True, True, False))
    return cap.is_capable(pool, obj, need_r, need_w, need_x)


class MonCap:
    """Minimal monitor capability (reference: src/mon/MonCap.{h,cc}).

    Only the decision the AuthMonitor needs is modeled: does this entity
    hold mon ADMIN authority (``allow *`` / ``allow all`` / ``allow
    profile admin``)?  Service profiles (``allow profile osd`` etc.) and
    r/w grants parse without error but confer no admin authority --
    exactly the property that stops a minted osd.* key from minting or
    revoking other keys.
    """

    def __init__(self, admin: bool = False):
        self.admin = admin

    @classmethod
    def parse(cls, caps: str) -> "MonCap":
        admin = False
        for clause in (caps or "").split(","):
            toks = clause.split()
            if toks[:2] in (["allow", "*"], ["allow", "all"]):
                admin = True
            elif toks[:3] == ["allow", "profile", "admin"]:
                admin = True
        return cls(admin)

    def is_admin(self) -> bool:
        return self.admin
