"""Multi-chip (mesh) erasure coding: the SPMD codec and the OSD data
plane built on it.

* ``distributed`` -- :class:`DistributedCodec`: a matrix code compiled
  for SPMD execution over a ``jax.sharding.Mesh`` (psum / psum_scatter
  parity, sharded reconstruction).
* ``mesh_plane`` -- :class:`MeshDataPlane`: PG-slice ownership over the
  local mesh, the coalescer's sharded encode dispatch, and the
  in-collective delivery board (``osd_mesh_data_plane``).

Submodules import lazily: ``distributed`` needs a jax backend at import
time, and the OSD layer must keep degrading (plane off, wire delivery)
when none exists.
"""
