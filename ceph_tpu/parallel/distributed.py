"""Multi-chip erasure coding over a jax.sharding.Mesh.

The reference distributes EC over OSD processes with hand-built fan-out /
gather on its messenger (reference: src/osd/ECBackend.cc:1976-2030 write
fan-out, :1142-1313 read gather; SURVEY.md section 5 "Distributed
communication backend").  TPU-native, the same roles map onto mesh axes and
XLA collectives over ICI:

    data  axis -- stripe batches (the PG/data-parallel analogue)
    shard axis -- the k+m chunk dimension (the acting-set/OSD analogue);
                  encode is a GF(2) contraction over data bits that live on
                  different devices, accumulated with a psum (integer sums
                  commute with the trailing mod-2)
    sub   axis -- positions *within* a chunk (the sub-chunk / sequence-
                  parallel analogue, ErasureCodeInterface.h:251-300)

Everything here is shard_map'd and jit-compiled: one program, SPMD over the
mesh, collectives riding ICI instead of the reference's TCP messenger.

Since round 15 this codec is no longer a standalone plugin surface: the
OSD data plane proper routes through it via
``ceph_tpu/parallel/mesh_plane.py`` (``osd_mesh_data_plane``) -- the
per-PG coalescer's fused batches are placed PG-sliced over the mesh,
``encode_scatter`` is the in-collective parity delivery half, and
:meth:`DistributedCodec.parity_owner_slots` tells the delivery split
which shard-axis device each parity slice is born on.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # pre-0.6 jax exposes it under experimental only
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.matrices.bitmatrix import matrix_to_bitmatrix


def make_mesh(
    n_data: int = 1, n_shard: int = 1, n_sub: int = 1, devices=None
) -> Mesh:
    """Build a (data, shard, sub) mesh from the available devices."""
    if devices is None:
        devices = jax.devices()
    need = n_data * n_shard * n_sub
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    dev = np.array(devices[:need]).reshape(n_data, n_shard, n_sub)
    return Mesh(dev, axis_names=("data", "shard", "sub"))


def _unpack_bits(words: jax.Array, w: int) -> jax.Array:
    """[..., c, n] words -> [..., c*w, n] bf16 bit-planes."""
    shifts = jnp.arange(w, dtype=words.dtype)
    bits = ((words[..., :, None, :] >> shifts[None, :, None]) & 1).astype(
        jnp.bfloat16
    )
    shape = words.shape[:-2] + (words.shape[-2] * w, words.shape[-1])
    return bits.reshape(shape)


def _pack_bits(bits: jax.Array, w: int, dtype) -> jax.Array:
    """[..., r*w, n] int bits -> [..., r, n] words."""
    r = bits.shape[-2] // w
    n = bits.shape[-1]
    b = bits.reshape(bits.shape[:-2] + (r, w, n)).astype(jnp.uint32)
    shifts = jnp.arange(w, dtype=jnp.uint32)
    return jnp.sum(b << shifts[None, :, None], axis=-2).astype(dtype)


class DistributedCodec:
    """A matrix code (w=8) compiled for SPMD execution over a mesh.

    Data layout: words [batch, k, n] with batch sharded over 'data', k over
    'shard', n over 'sub'.  Parity and reconstruction are GF(2) contractions
    over the sharded k axis, psum-accumulated over ICI.
    """

    def __init__(self, matrix: np.ndarray, w: int, mesh: Mesh):
        self.m, self.k = matrix.shape
        self.w = w
        self.mesh = mesh
        self.B = matrix_to_bitmatrix(np.asarray(matrix, np.uint32), w)
        n_shard = mesh.shape["shard"]
        if self.k % n_shard:
            raise ValueError(
                f"k={self.k} must divide over shard axis {n_shard}"
            )
        self._encode = self._build_encode()
        self._verify = self._build_verify()

    # -- encode: parity = (B . data_bits) mod 2, contraction over 'shard' --

    def _build_encode(self):
        w = self.w
        mesh = self.mesh

        def local(B_blk, words):  # B_blk [m*w, (k/s)*w]; words [b, k/s, n]
            bits = _unpack_bits(words, w)  # [b, kw_loc, n]
            part = jnp.einsum(
                "rc,bcn->brn",
                B_blk.astype(jnp.bfloat16),
                bits,
                preferred_element_type=jnp.float32,
            )
            total = jax.lax.psum(part, "shard")
            obits = total.astype(jnp.int32) & 1
            return _pack_bits(obits, w, words.dtype)  # [b, m, n]

        f = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(None, "shard"), P("data", "shard", "sub")),
            out_specs=P("data", None, "sub"),
        )
        return jax.jit(f)

    def _B_dev(self) -> jax.Array:
        """Device-resident coding bitmatrix via the accounted upload
        cache (ops/pipeline.py): B is instance-constant, so shipping it
        per encode/verify call was a pure per-call H2D of the same
        bytes -- the jax-loop-invariant-transfer class."""
        from ceph_tpu.ops.pipeline import accounted_device_matrix

        return accounted_device_matrix(self.B)

    def encode(self, words: jax.Array) -> jax.Array:
        """words [batch, k, n] -> parity [batch, m, n] (replicated on shard)."""
        return self._encode(self._B_dev(), words)

    # -- scatter variant: each device ends up owning its parity slice ------

    def _build_encode_scatter(self):
        w = self.w
        mesh = self.mesh
        n_shard = mesh.shape["shard"]
        if self.m % n_shard:
            return None

        def local(B_blk, words):  # [m*w, kw_loc], [b, k/s, n]
            bits = _unpack_bits(words, w)
            part = jnp.einsum(
                "rc,bcn->brn",
                B_blk.astype(jnp.bfloat16),
                bits,
                preferred_element_type=jnp.float32,
            )  # [b, m*w, n]
            # reduce_scatter over ICI: integer partial sums land sliced on
            # their owner device (the write-fan-out-to-owner analogue);
            # mod-2 commutes with the sum so it runs post-scatter, locally
            total = jax.lax.psum_scatter(
                part, "shard", scatter_dimension=1, tiled=True
            )  # [b, (m/s)*w, n]
            obits = total.astype(jnp.int32) & 1
            return _pack_bits(obits, w, words.dtype)  # [b, m/s, n]

        f = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(None, "shard"), P("data", "shard", "sub")),
            out_specs=P("data", "shard", "sub"),
        )
        return jax.jit(f)

    def encode_scatter(self, words: jax.Array) -> jax.Array:
        """words [batch, k, n] -> parity [batch, m, n] with the m axis
        SHARDED over 'shard' (each device owns its parity shards), using
        reduce_scatter instead of all-reduce -- half the ICI traffic and
        the natural layout when parity shards live on distinct devices."""
        if not hasattr(self, "_encode_scatter_fn"):
            self._encode_scatter_fn = self._build_encode_scatter()
        if self._encode_scatter_fn is None:
            raise ValueError("m must divide the shard axis size")
        return self._encode_scatter_fn(self._B_dev(), words)

    def parity_owner_slots(self) -> Sequence[int]:
        """Shard-axis device index each parity row is BORN on under the
        :meth:`encode_scatter` layout (``psum_scatter`` tiles the m*w
        output rows across the shard axis, so parity row j lands on
        device ``j // (m / n_shard)``).  The mesh data plane's delivery
        split uses this to decide which chunks are already resident on
        their owner and can skip the wire."""
        n_shard = self.mesh.shape["shard"]
        if self.m % n_shard:
            raise ValueError("m must divide the shard axis size")
        per = self.m // n_shard
        return [j // per for j in range(self.m)]

    # -- scrub: recompute parity, compare against stored (deep-scrub role) --

    def _build_verify(self):
        def verify(B, words, parity):
            fresh = self._encode(B, words)
            return jnp.all(fresh == parity, axis=(1, 2))  # per-stripe ok

        return jax.jit(verify)

    def verify(self, words: jax.Array, parity: jax.Array) -> jax.Array:
        return self._verify(self._B_dev(), words, parity)

    # -- reconstruct: decode rows are another GF(2) contraction ------------

    @functools.lru_cache(maxsize=128)
    def _reconstruct_fn(self, n_rows: int):
        w = self.w
        mesh = self.mesh

        def local(rows_blk, words):  # rows_blk [e*w, kw_loc]
            bits = _unpack_bits(words, w)
            part = jnp.einsum(
                "rc,bcn->brn",
                rows_blk.astype(jnp.bfloat16),
                bits,
                preferred_element_type=jnp.float32,
            )
            total = jax.lax.psum(part, "shard")
            obits = total.astype(jnp.int32) & 1
            return _pack_bits(obits, w, words.dtype)

        f = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(None, "shard"), P("data", "shard", "sub")),
            out_specs=P("data", None, "sub"),
        )
        return jax.jit(f)

    def reconstruct(self, rows: np.ndarray, survivors: jax.Array) -> jax.Array:
        """Apply host-computed decode rows [e, k] to survivor words
        [batch, k, n] (the degraded-read / recovery path,
        reference ECBackend.cc:2284 objects_read_and_reconstruct)."""
        bits_rows = matrix_to_bitmatrix(np.asarray(rows, np.uint32), self.w)
        fn = self._reconstruct_fn(rows.shape[0])
        # repair signatures repeat across a rebuild: the content-keyed
        # upload cache turns the per-call H2D of the decode rows into
        # one upload per signature
        from ceph_tpu.ops.pipeline import accounted_device_matrix

        return fn(accounted_device_matrix(bits_rows), survivors)
