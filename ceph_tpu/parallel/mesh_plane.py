"""Mesh-sharded OSD data plane: PG-sliced encode + in-collective delivery.

ROADMAP item 1: the ``mesh_shard`` codec profile and the
``parallel/distributed.py`` psum_scatter parity placement existed only at
the plugin surface -- the cluster path (coalescer -> encode -> tier ->
messenger fan-out) ran single-device.  This module is the data-plane
half: a process-wide :class:`MeshDataPlane` over the local
``jax.sharding.Mesh`` that

* **slices PG ownership over the mesh's ``pg`` axis** -- each device
  hosts the PG-shard slice of one in-mesh OSD (``bind``/``owner_slot``)
  and the per-PG coalescer's fused encode batches are placed with a
  cached ``NamedSharding`` so every device encodes the stripes of the
  PGs it owns, mesh-locally (`"Large Scale Distributed Linear Algebra
  With TPUs"`: express the partitioning as sharding specs, not host
  loops);
* **scatters parity in-collective where the backend supports it** --
  with ``osd_mesh_scatter`` on (or a TPU backend), the GF(2)
  contraction additionally shards the chunk axis over the mesh's
  ``shard`` axis and ``psum_scatter`` lands each parity slice on its
  owner device (``parallel/distributed.py`` ``encode_scatter``), so
  parity is *born* on the device that will store it;
* **delivers in-mesh chunk payloads off the wire** -- a sub-write whose
  destination OSD is mesh-bound carries a tiny board reference instead
  of the chunk bytes (the bytes already live on the owner's device
  slice); the TCP messenger still frames/orders/replays the sub-op,
  but the payload never crosses a socket ("Understanding System
  Characteristics of Online Erasure Coding": the wire fan-out, not the
  coding kernel, dominates online EC at cluster scale).  Out-of-mesh
  peers keep the full wire path, chosen per-chunk from CRUSH placement.

Gated by ``osd_mesh_data_plane`` (default off -- the single-device path
is the A/B baseline).  Steady state constructs ZERO sharding objects
per dispatch: ``NamedSharding``/``PartitionSpec`` instances are cached
content-keyed (:meth:`MeshDataPlane.sharding`), coding tables ride the
accounted matrix cache (``ops/pipeline.py``), and batch/width shapes
are bucketed (pow2 rows per device x the shared rung ladder) so the
jit program set is bounded -- the PR-8 zero-retrace contract, enforced
by the mesh bench and the ``jax-percall-sharding-construction`` lint
rule.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu.native.gf_native import crc32c

#: payloads below this stay inline on the wire: a board round-trip
#: (deposit + claim + crc) costs more than serializing a few bytes
MIN_DETACH_BYTES = 1024


def _pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class DeliveryBoard:
    """Process-wide in-collective chunk handoff between in-mesh OSDs.

    The primary deposits a chunk's bytes (conceptually: the slice the
    collective left on the owner's device) and the sub-write frame
    carries only ``(key, nbytes, crc32c)``; the receiving OSD claims the
    bytes at apply time.  Byte-bounded (``osd_mesh_board_bytes``):
    beyond the cap the oldest unclaimed deposits drop and the affected
    sub-write fails over to recovery -- the same lossy-bound stance the
    messenger takes on its lossless backlog."""

    def __init__(self, cap_bytes: Optional[int] = None):
        self._lock = threading.Lock()
        self._entries: "Dict[int, bytes]" = {}
        self._order: List[int] = []
        self._bytes = 0
        self._next_key = 0
        self._cap = cap_bytes
        self.deposits = 0
        self.claims = 0
        self.claimed_bytes = 0
        self.misses = 0
        self.evictions = 0

    def _cap_bytes(self) -> int:
        if self._cap is not None:
            return self._cap
        try:
            from ceph_tpu.utils.config import get_config

            return int(get_config().get_val("osd_mesh_board_bytes"))
        except Exception:  # noqa: BLE001 -- no config layer
            return 64 << 20

    def deposit(self, data) -> Tuple[int, int, int]:
        """Park one chunk payload; returns ``(key, nbytes, crc32c)`` --
        the reference the mesh-delivery frame carries instead of the
        bytes."""
        buf = bytes(data)
        crc = crc32c(buf)
        with self._lock:
            self._next_key += 1
            key = self._next_key
            self._entries[key] = buf
            self._order.append(key)
            self._bytes += len(buf)
            self.deposits += 1
            cap = self._cap_bytes()
            while self._bytes > cap and self._order:
                old = self._order.pop(0)
                dropped = self._entries.pop(old, None)
                if dropped is not None:
                    self._bytes -= len(dropped)
                    self.evictions += 1
        return key, len(buf), crc

    def claim(self, key: int) -> Optional[bytes]:
        """Pop a deposited payload (single-shot); None when evicted or
        never deposited in this process (an out-of-mesh replay)."""
        with self._lock:
            buf = self._entries.pop(key, None)
            if buf is None:
                self.misses += 1
                return None
            self._bytes -= len(buf)
            self.claims += 1
            self.claimed_bytes += len(buf)
        return buf

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "deposits": self.deposits,
                "claims": self.claims,
                "claimed_bytes": self.claimed_bytes,
                "misses": self.misses,
                "evictions": self.evictions,
                "pending_bytes": self._bytes,
            }


class _PoolCodec:
    """Per-(coding matrix) jitted SPMD programs on the plane's mesh.

    Local mode (the default off-TPU): the batch axis is sharded over
    BOTH mesh axes (pure PG slicing) and each device runs the GF(2^8)
    row-table gather kernel (``ops/xla_gf`` byte lane) on its slice --
    encode is entirely mesh-local, no collective.  Scatter mode
    (``osd_mesh_scatter``): the chunk axis shards over the ``shard``
    axis and parity is reduce-scattered to its owner device
    (``DistributedCodec.encode_scatter`` -- half the ICI traffic of an
    all-reduce and the natural layout when parity shards live on
    distinct devices)."""

    def __init__(self, plane: "MeshDataPlane", matrix: np.ndarray,
                 k: int, m: int, w: int):
        import jax
        from ceph_tpu.ops.xla_gf import gf8_row_tables
        from ceph_tpu.parallel.distributed import shard_map

        self.plane = plane
        self.k, self.m, self.w = k, m, w
        self.matrix = np.asarray(matrix, dtype=np.uint32)
        #: [m, k, 256] GF(2^8) row-times-value tables, uploaded once
        #: through the accounted cache, replicated over the mesh
        self._enc_tab = gf8_row_tables(self.matrix)
        self._scatter_codec = None

        def _apply(tab, words):
            # words [b_loc, k, n] u8; tab [rows, k, 256]
            from ceph_tpu.ops.xla_gf import _encode_bytes

            b, kk, n = words.shape
            flat = words.transpose(1, 0, 2).reshape(kk, b * n)
            out = _encode_bytes(tab, flat)  # [rows, b*n]
            return out.reshape(tab.shape[0], b, n).transpose(1, 0, 2)

        # two dispatch lanes, one program each per codec instance (jit
        # caches per bucketed-shape after that):
        # * fused -- a FULL balanced batch rides one shard_map program,
        #   placed with the cached NamedSharding over (pg, shard);
        # * slot -- a partial/skewed batch dispatches per owner slot
        #   onto that slot's device alone (mesh-LOCAL encode: no
        #   cross-slot zero padding, and the per-device launches are
        #   async so distinct slots overlap on real silicon)
        self._fused_fn = jax.jit(shard_map(
            _apply,
            mesh=plane.mesh,
            in_specs=(plane.pspec(None, None, None),
                      plane.pspec(("pg", "shard"), None, None)),
            out_specs=plane.pspec(("pg", "shard"), None, None),
        ))
        self._slot_fn = jax.jit(_apply)

    def _tab_dev(self, tab: np.ndarray):
        from ceph_tpu.ops.pipeline import accounted_device_matrix

        return accounted_device_matrix(
            tab, sharding=self.plane.sharding(None, None, None))

    def _tab_on_slot(self, tab: np.ndarray, slot: int):
        from ceph_tpu.ops.pipeline import accounted_device_matrix

        return accounted_device_matrix(
            tab, sharding=self.plane.devices[slot])

    def scatter_codec(self):
        """The psum_scatter path (``parallel/distributed.py``) on the
        plane's collective mesh; None when k/m do not divide the shard
        axis (the local path covers those pools)."""
        if self._scatter_codec is None:
            mesh = self.plane.collective_mesh
            ns = mesh.shape["shard"]
            if self.k % ns or self.m % ns:
                return None
            from ceph_tpu.parallel.distributed import DistributedCodec

            self._scatter_codec = DistributedCodec(
                self.matrix, self.w, mesh)
        return self._scatter_codec

    # -- dispatch ----------------------------------------------------------

    def apply_fused(self, tab: np.ndarray, stacks: np.ndarray) -> np.ndarray:
        """Run ``tab`` ([rows, k, 256]) over ``stacks`` ([B, k, n] u8,
        B pre-bucketed to the mesh batch granularity) -- one fused
        sharded dispatch, PG-sliced over the mesh."""
        from ceph_tpu.analysis import residency

        plane = self.plane
        arr = residency.device_put(
            stacks, plane.sharding(("pg", "shard"), None, None))
        out = self._fused_fn(self._tab_dev(tab), arr)
        host = residency.device_get(out)
        ctr = residency.counters()
        ctr.note_mesh("pg", stacks.nbytes)
        if plane.n_shard > 1:
            ctr.note_mesh("shard", stacks.nbytes // plane.n_shard)
        return host

    def run_tab(self, tab: np.ndarray, blocks: Sequence[np.ndarray],
                pgids: Sequence[int], bs_pad: int,
                slot: Optional[int] = None) -> List[np.ndarray]:
        """Apply ``tab`` to every [k, bs] block, PG-sliced.

        ``slot`` set = the PRIMARY-slot lane: the whole batch is one
        dispatch on that slot's device (a coalescer batch belongs to
        one primary OSD, whose device owns every PG it leads -- the
        per-PG mesh slicing emerges because DIFFERENT primaries' fused
        batches land on different devices and their async launches
        overlap).  ``slot=None`` spreads by per-stripe PG ownership: a
        batch covering every mesh slot rides the fused shard_map
        program, a partial one dispatches per owner slot.  Returns one
        [rows_out, bs_pad] host array per block, input order."""
        from ceph_tpu.analysis import residency

        plane = self.plane
        k = blocks[0].shape[0]
        per_slot: Dict[int, List[int]] = {}
        if slot is not None:
            per_slot[slot % plane.n_devices] = list(range(len(blocks)))
        else:
            for i, pg in enumerate(pgids):
                per_slot.setdefault(plane.owner_slot(pg), []).append(i)
        if len(per_slot) == plane.n_devices:
            stacks, where = plane._stack_pg_sliced(blocks, pgids, bs_pad)
            host = self.apply_fused(tab, stacks)
            plane.counters["mesh_fused_dispatches"] += 1
            return [host[row] for row, _bs in where]
        # partial batch: per-slot mesh-local dispatch -- the launches
        # are async, so distinct slots' kernels overlap on real devices
        ctr = residency.counters()
        outs: Dict[int, object] = {}
        total = 0
        for slot, idxs in per_slot.items():
            rows = plane._bucket_batch(len(idxs))
            arr = np.zeros((rows, k, bs_pad), dtype=np.uint8)
            for j, i in enumerate(idxs):
                b = blocks[i]
                arr[j, :, :b.shape[1]] = b
            d = residency.device_put(arr, plane.devices[slot])
            outs[slot] = self._slot_fn(self._tab_on_slot(tab, slot), d)
            total += arr.nbytes
        ctr.note_mesh("pg", total)
        plane.counters["mesh_local_dispatches"] += len(per_slot)
        results: List[Optional[np.ndarray]] = [None] * len(blocks)
        for slot, idxs in per_slot.items():
            host = residency.device_get(outs[slot])
            for j, i in enumerate(idxs):
                results[i] = host[j]
        return results  # type: ignore[return-value]

    def encode_scatter(self, stacks: np.ndarray) -> Optional[np.ndarray]:
        """In-collective parity scatter: [B, k, n] -> [B, m, n] with the
        parity computed by a shard-axis psum_scatter (each owner device
        receives exactly its slice).  None when the pool shape cannot
        ride the collective mesh."""
        codec = self.scatter_codec()
        if codec is None:
            return None
        from ceph_tpu.analysis import residency

        parity = np.asarray(codec.encode_scatter(stacks))
        ctr = residency.counters()
        ctr.note_mesh("pg", stacks.nbytes)
        ctr.note_mesh("shard", stacks.nbytes)
        return parity


class MeshDataPlane:
    """Process-wide mesh over the local devices: PG-slice ownership,
    sharded codec dispatch, and the in-collective delivery board."""

    def __init__(self, n_devices: Optional[int] = None):
        import jax
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as PSpec

        self._NamedSharding = NamedSharding
        self._PSpec = PSpec
        devs = jax.devices()
        if n_devices is None:
            try:
                from ceph_tpu.utils.config import get_config

                n_devices = int(get_config().get_val("osd_mesh_devices"))
            except Exception:  # noqa: BLE001 -- no config layer
                n_devices = 0
        n = len(devs) if not n_devices else min(int(n_devices), len(devs))
        self.devices = list(devs[:max(1, n)])
        self.n_devices = len(self.devices)
        # (pg, shard) factoring: the shard axis exists for the
        # in-collective parity scatter; pools whose k/m do not divide
        # it ride the pg axis alone (the batch shards over BOTH axes)
        n_shard = 1
        for cand in (4, 2):
            if self.n_devices % cand == 0:
                n_shard = cand
                break
        self.n_shard = n_shard
        self.n_pg = self.n_devices // n_shard
        self.mesh = Mesh(
            np.array(self.devices).reshape(self.n_pg, self.n_shard),
            axis_names=("pg", "shard"),
        )
        self._collective_mesh = None
        #: content-keyed PartitionSpec / NamedSharding caches: steady-
        #: state dispatch constructs ZERO sharding objects per op (the
        #: jax-percall-sharding-construction contract; the analogue of
        #: PR-7's accounted_device_matrix for placement objects)
        self._pspecs: Dict[tuple, object] = {}
        self._shardings: Dict[tuple, object] = {}
        self.sharding_builds = 0
        #: in-mesh OSD membership: name -> device slot (one OSD per
        #: device -- the TPU-core-per-OSD model; late binders past the
        #: device count stay out-of-mesh and keep the wire path)
        self._members: Dict[str, int] = {}
        self._codecs: Dict[tuple, _PoolCodec] = {}
        self._lock = threading.Lock()
        self.board = DeliveryBoard()
        self.counters: Dict[str, int] = {
            "mesh_encode_stripes": 0,
            "mesh_encode_dispatches": 0,
            "mesh_fused_dispatches": 0,
            "mesh_local_dispatches": 0,
            "mesh_decode_stripes": 0,
            "mesh_deliver_chunks": 0,
            "mesh_wire_bytes_avoided": 0,
            "mesh_claim_miss": 0,
        }

    # -- sharding-object cache (content-keyed, built once) -----------------

    def pspec(self, *axes):
        spec = self._pspecs.get(axes)
        if spec is None:
            spec = self._pspecs[axes] = self._PSpec(*axes)
        return spec

    def sharding(self, *axes):
        ns = self._shardings.get(axes)
        if ns is None:
            ns = self._shardings[axes] = self._NamedSharding(
                self.mesh, self.pspec(*axes))
            self.sharding_builds += 1
        return ns

    @property
    def collective_mesh(self):
        """(data, shard, sub) view of the same devices for the
        ``DistributedCodec`` scatter path (its axis names are part of
        its compiled programs)."""
        if self._collective_mesh is None:
            from ceph_tpu.parallel.distributed import make_mesh

            self._collective_mesh = make_mesh(
                n_data=self.n_pg, n_shard=self.n_shard, n_sub=1,
                devices=self.devices,
            )
        return self._collective_mesh

    # -- membership / PG-slice ownership -----------------------------------

    def bind(self, name: str) -> Optional[int]:
        """Attach an OSD to the mesh; returns its device slot, or None
        once every device hosts an OSD (the overflow stays
        out-of-mesh).  Idempotent per name."""
        with self._lock:
            slot = self._members.get(name)
            if slot is not None:
                return slot
            if len(self._members) >= self.n_devices:
                return None
            slot = len(self._members)
            self._members[name] = slot
            return slot

    def covers(self, name: str) -> bool:
        return name in self._members

    def slot_of(self, name: str) -> Optional[int]:
        return self._members.get(name)

    def owner_slot(self, pgid: int) -> int:
        """The mesh device slot owning a PG's shard slice."""
        return int(pgid) % self.n_devices

    # -- codec plumbing ----------------------------------------------------

    def can_encode(self, ec) -> bool:
        return bool(getattr(ec, "mesh_plane_capable", False))

    def _codec(self, ec) -> _PoolCodec:
        matrix = np.asarray(ec.matrix, dtype=np.uint32)
        key = (matrix.shape, matrix.tobytes(), int(ec.w))
        with self._lock:
            codec = self._codecs.get(key)
            if codec is None:
                codec = self._codecs[key] = _PoolCodec(
                    self, matrix, ec.get_data_chunk_count(),
                    ec.get_chunk_count() - ec.get_data_chunk_count(),
                    int(ec.w),
                )
            return codec

    def _scatter_on(self) -> bool:
        try:
            from ceph_tpu.utils.config import get_config

            mode = str(get_config().get_val("osd_mesh_scatter"))
        except Exception:  # noqa: BLE001
            mode = "auto"
        if mode == "on":
            return True
        if mode == "off":
            return False
        try:
            import jax

            return jax.default_backend() == "tpu"
        except Exception:  # noqa: BLE001
            return False

    def _bucket_batch(self, count_per_slot: int) -> int:
        """Rows-per-device bucket (pow2) so the jit program set stays
        bounded no matter how the coalescer's batch sizes wander."""
        return _pow2ceil(max(1, count_per_slot))

    @staticmethod
    def _bucket_bs(bs: int) -> int:
        """Stripe-width bucket: the shared rung ladder
        (``ops/bucketing.py``) extended downward with pow2 sub-rungs --
        the plane's unit is one stripe's chunk (KiBs), not the
        pipeline's fused granule (the ladder starts at 16 KiB), and
        padding a 4 KiB chunk 4x would waste sliced compute."""
        from ceph_tpu.ops import bucketing

        floor = bucketing.ladder()[0]
        if bs >= floor:
            return bucketing.bucket_bytes(bs)
        return min(floor, max(1024, _pow2ceil(bs)))

    def _stack_pg_sliced(
        self, blocks: Sequence[np.ndarray], pgids: Sequence[int],
        bs_pad: int,
    ) -> Tuple[np.ndarray, List[Tuple[int, int]]]:
        """Arrange ``blocks`` ([k, bs] u8) into the PG-sliced batch
        array [n_devices * rows, k, bs_pad]: stripe i lands in its
        owning slot's row segment, so the NamedSharding placement puts
        each stripe on the device that owns its PG.  Returns the array
        and each stripe's (global row, true width)."""
        k = blocks[0].shape[0]
        per_slot: Dict[int, List[int]] = {}
        for i, pg in enumerate(pgids):
            per_slot.setdefault(self.owner_slot(pg), []).append(i)
        rows = self._bucket_batch(
            max(len(v) for v in per_slot.values()))
        arr = np.zeros((self.n_devices * rows, k, bs_pad), dtype=np.uint8)
        where: List[Tuple[int, int]] = [(0, 0)] * len(blocks)
        for slot, idxs in per_slot.items():
            for j, i in enumerate(idxs):
                b = blocks[i]
                arr[slot * rows + j, :, :b.shape[1]] = b
                where[i] = (slot * rows + j, b.shape[1])
        return arr, where

    # -- encode (the coalescer's fused dispatch target) --------------------

    def encode_shard_major_many(
        self, ec, blocks: Sequence[np.ndarray],
        pgids: Optional[Sequence[int]] = None,
        slot: Optional[int] = None,
    ) -> List[Dict[int, np.ndarray]]:
        """ONE PG-sliced SPMD dispatch per (bucketed width) group over
        the whole coalesced batch: [k, bs] shard-major blocks in, full
        chunk maps out -- bit-exact with the single-device path and the
        jerasure oracle (gated in tests/test_mesh_plane.py)."""
        codec = self._codec(ec)
        k, m = codec.k, codec.m
        # trace attribution: the coalescer's fan-in span is task-current
        # during dispatch -- mark which lane the shared stage took so a
        # slow op's timeline says "mesh SPMD" vs "single-device"
        from ceph_tpu.utils import trace as _trace

        _trace.tag("lane", "mesh_spmd" if slot is None
                   else f"mesh_primary_slot_{slot}")
        _trace.tag("mesh_devices", self.n_devices)
        if pgids is None:
            pgids = list(range(len(blocks)))
        out: List[Optional[Dict[int, np.ndarray]]] = [None] * len(blocks)
        groups: Dict[int, List[int]] = {}
        for i, b in enumerate(blocks):
            if b.shape[1] == 0:
                out[i] = {ec.chunk_index(j): np.zeros(0, np.uint8)
                          for j in range(k + m)}
                continue
            groups.setdefault(self._bucket_bs(b.shape[1]), []).append(i)
        scatter = self._scatter_on()
        for bs_pad, idxs in groups.items():
            blocks_l = [np.asarray(blocks[i], dtype=np.uint8)
                        for i in idxs]
            pgids_l = [pgids[i] for i in idxs]
            rows_l = None
            if scatter:
                stacks, where = self._stack_pg_sliced(
                    blocks_l, pgids_l, bs_pad)
                parity = codec.encode_scatter(stacks)
                if parity is not None:
                    rows_l = [parity[row] for row, _bs in where]
            if rows_l is None:
                rows_l = codec.run_tab(
                    codec._enc_tab, blocks_l, pgids_l, bs_pad,
                    slot=slot)
            for i, pr in zip(idxs, rows_l):
                b = blocks[i]
                bs = b.shape[1]
                enc = {ec.chunk_index(j): b[j] for j in range(k)}
                for j in range(m):
                    enc[ec.chunk_index(k + j)] = np.ascontiguousarray(
                        pr[j, :bs])
                out[i] = enc
            self.counters["mesh_encode_dispatches"] += 1
            self.counters["mesh_encode_stripes"] += len(idxs)
        return out  # type: ignore[return-value]

    # -- decode (degraded reads through the same sliced plane) -------------

    def decode_maps(
        self, ec, maps: Sequence[Dict[int, np.ndarray]],
        slot: Optional[int] = None,
    ) -> List[Dict[int, np.ndarray]]:
        """Reconstruct every missing chunk of every map; signature
        groups share one composed row matrix and one sliced dispatch
        per width group (the decode twin of the encode path)."""
        from ceph_tpu.ops.pipeline import matrix_reconstruct_rows
        from ceph_tpu.ops.xla_gf import gf8_row_tables

        codec = self._codec(ec)
        k, m = codec.k, codec.m
        km = k + m
        results: List[Optional[Dict[int, np.ndarray]]] = [None] * len(maps)
        groups: Dict[tuple, List[int]] = {}
        for i, cm in enumerate(maps):
            groups.setdefault(tuple(sorted(cm.keys())), []).append(i)
        for sig, idxs in groups.items():
            erased = [c for c in range(km) if c not in sig]
            if not erased:
                for i in idxs:
                    results[i] = {c: np.asarray(a, dtype=np.uint8)
                                  for c, a in maps[i].items()}
                continue
            if len(sig) < k:
                raise ValueError("not enough chunks to decode")
            sel, rows = matrix_reconstruct_rows(
                codec.matrix, k, m, codec.w, list(sig), erased)
            tab = gf8_row_tables(rows)
            by_size: Dict[int, List[int]] = {}
            for i in idxs:
                bs = len(next(iter(maps[i].values())))
                by_size.setdefault(bs, []).append(i)
            for bs, sized in by_size.items():
                bs_pad = self._bucket_bs(bs)
                rec_l = codec.run_tab(
                    tab,
                    [np.stack([np.asarray(maps[i][c], dtype=np.uint8)
                               for c in sel]) for i in sized],
                    list(range(len(sized))), bs_pad, slot=slot)
                for i, rec in zip(sized, rec_l):
                    full = {c: np.asarray(a, dtype=np.uint8)
                            for c, a in maps[i].items()}
                    for j, e in enumerate(erased):
                        full[e] = np.ascontiguousarray(rec[j, :bs])
                    results[i] = full
                self.counters["mesh_decode_stripes"] += len(sized)
        return results  # type: ignore[return-value]

    def decode_concat_many(self, sinfo, ec, maps,
                           slot: Optional[int] = None) -> List[bytes]:
        """``ecutil.decode_concat_many`` with the reconstruction routed
        through the sliced plane (the read-path coalescer's dispatch)."""
        from ceph_tpu.osd import ecutil

        from ceph_tpu.utils import trace as _trace

        _trace.tag("lane", "mesh_spmd" if slot is None
                   else f"mesh_primary_slot_{slot}")
        results: List[bytes] = [b""] * len(maps)
        need = [i for i, cm in enumerate(maps)
                if cm and len(next(iter(cm.values()))) > 0]
        if not need:
            return results
        full = self.decode_maps(ec, [maps[i] for i in need], slot=slot)
        for i, out in zip(need, full):
            results[i] = ecutil._reassemble(sinfo, ec, out)
        return results

    # -- in-collective delivery (the wire split's board half) --------------

    def detach_sub_write(self, sub) -> int:
        """Replace a sub-write transaction's chunk payloads with board
        references (the mesh-delivery frame: the bytes ride the device
        plane, the messenger frames only the envelope).  Returns the
        payload bytes taken off the wire."""
        txn = getattr(sub, "transaction", None)
        if txn is None:
            return 0
        moved = 0
        for op in txn.ops:
            if op.op == "write" and len(op.data) >= MIN_DETACH_BYTES:
                key, nbytes, crc = self.board.deposit(op.data)
                op.op = "write_ref"
                op.data = b""
                op.attr_value = (key, nbytes, crc)
                moved += nbytes
        if moved:
            self.counters["mesh_deliver_chunks"] += 1
            self.counters["mesh_wire_bytes_avoided"] += moved
        return moved

    def resolve_transaction(self, txn) -> bool:
        """Claim every board reference back into payload bytes before
        the transaction applies (crc-checked, like the wire frame the
        bytes skipped).  False = a reference was evicted/foreign; the
        caller refuses the sub-write and recovery repairs the shard."""
        for op in txn.ops:
            if op.op != "write_ref":
                continue
            key, nbytes, crc = op.attr_value
            data = self.board.claim(key)
            if data is None or len(data) != nbytes or crc32c(data) != crc:
                self.counters["mesh_claim_miss"] += 1
                return False
            op.op = "write"
            op.data = data
            op.attr_value = None
        return True

    def status(self) -> dict:
        return {
            "n_devices": self.n_devices,
            "n_pg": self.n_pg,
            "n_shard": self.n_shard,
            "members": dict(self._members),
            "sharding_builds": self.sharding_builds,
            "board": self.board.stats(),
            "counters": dict(self.counters),
        }


_plane: Optional[MeshDataPlane] = None
_plane_lock = threading.Lock()


def configure(n_devices: Optional[int] = None) -> MeshDataPlane:
    """(Re)build the process plane over ``n_devices`` (None/0 = every
    local device) -- the bench sweep's knob.  Drops prior membership
    and board state (a mesh reshape is a process event, like an osdmap
    epoch)."""
    global _plane
    with _plane_lock:
        _plane = MeshDataPlane(n_devices)
        return _plane


def reset() -> None:
    global _plane
    with _plane_lock:
        _plane = None


def current_plane() -> Optional[MeshDataPlane]:
    """The process plane iff ``osd_mesh_data_plane`` is on and a jax
    backend exists; None otherwise (callers fall back to the
    single-device / full-wire path)."""
    try:
        from ceph_tpu.utils.config import get_config

        if not bool(get_config().get_val("osd_mesh_data_plane")):
            return None
    except Exception:  # noqa: BLE001 -- no config layer: stay off
        return None
    global _plane
    plane = _plane
    if plane is not None:
        return plane
    with _plane_lock:
        if _plane is None:
            try:
                _plane = MeshDataPlane()
            except Exception:  # noqa: BLE001 -- no jax backend
                return None
        return _plane
