"""Async stripe-batching device pipeline for the TPU codec plugin.

This is SURVEY.md section 7 step 5's "stripe-batching shim": the seam between
the reference's synchronous per-call codec contract
(/root/reference/src/erasure-code/ErasureCodeInterface.h:365-413 encode/decode
return completed buffers) and an accelerator that wants large, overlapped,
asynchronously-completed transfers.  The reference benchmark loop
(/root/reference/src/test/erasure-code/ceph_erasure_code_benchmark.cc:179-185)
calls encode() once per iteration; driving a device at that surface requires:

* **Persistent device state**: the coding matrix is uploaded once per codec
  instance and reused across every call (the ISA-L analogue: ec_init_tables
  once, ec_encode_data many -- src/erasure-code/isa/ErasureCodeIsa.cc:83-130).
* **Granule fusing**: stripes are accumulated and fused along the matmul N
  axis into fixed-shape granules, so one H2D + one dispatch + one D2H covers
  many stripes and XLA compiles a handful of programs total (a small ladder
  of granule widths, each compiled once).
* **Bounded in-flight depth**: dispatches are asynchronous (JAX async
  dispatch + copy_to_host_async); up to `depth` granules stream through the
  device while the caller assembles or consumes others, overlapping host
  prep, H2D, MXU compute, and D2H.
* **Content-addressed H2D cache**: re-encoding an unchanged buffer (exactly
  what the reference benchmark does every iteration -- the payload is
  string(size, 'X'), ceph_erasure_code_benchmark.cc:173) skips the re-upload
  the way a CPU codec's unchanged buffer stays resident in LLC.  Keyed by a
  full crc32 of the granule bytes, never by object identity alone; disable
  with CEPH_TPU_NO_H2D_CACHE=1.  Compute and parity D2H still happen every
  call -- only the *input upload* of byte-identical content is elided.
  Retained device bytes are charged to the shared HBM ledger
  (ceph_tpu/tier/device_tier.py DeviceByteAccount) and the cache evicts
  LRU-first to its osd_tier_h2d_cache_bytes sub-allocation of the
  osd_tier_hbm_bytes budget -- the cache-tier store yields to this
  working set, so both can never jointly exceed the device budget.

Decode reconstruction is fused to ONE device matmul per erasure signature:
every erased chunk (data or parity) is expressed as a GF-linear combination
of the k selected survivors, composed on host (tiny k x k inversion + row
matmul), and the combined rows are cached per signature like the reference
ISA plugin's decode-table LRU (ErasureCodeIsaTableCache.h:48).
"""

from __future__ import annotations

import hashlib
import os
import threading
import warnings
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu.analysis import residency
from ceph_tpu.matrices.bitmatrix import invert_bitmatrix, matrix_to_bitmatrix
from ceph_tpu.ops import bucketing
from ceph_tpu.ops.gf import gf

# The granule rung ladder moved to ceph_tpu/ops/bucketing.py (shared
# with the ecutil shard-major helpers and the plugin's odd-shape lanes);
# a dispatch picks the smallest fitting rung so padding waste is bounded
# by ~2x and steady state compiles nothing.  Stripes larger than the top
# rung are split into column segments (parity is columnwise, so the
# split is exact).
_DEFAULT_DEPTH = 3


# Donation is advisory: XLA backends without aliasing support for a
# layout (notably XLA:CPU) decline it and fall back to exactly the
# undonated semantics, warning once per compiled program.  The fallback
# is the designed cpu-fallback behavior here, so the warning is noise.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def _pipeline_tuning() -> Tuple[int, bool]:
    """(overlap slots, donate) from config; safe defaults for codec-only
    tools running before any Config exists."""
    try:
        from ceph_tpu.utils.config import get_config

        cfg = get_config()
        return (int(cfg.get_val("osd_ec_overlap_depth")),
                bool(cfg.get_val("osd_ec_donate")))
    except Exception:  # noqa: BLE001 -- no config layer
        return 2, True


_stats_lock = threading.Lock()
_granules_dispatched = 0


def granules_dispatched() -> int:
    """Process-wide count of fused granule dispatches -- the residency
    ledger's denominator: h2d_ops_delta / granules_delta is the
    "<= 1 H2D per granule" driver-grade number bench gates on."""
    with _stats_lock:
        return _granules_dispatched


def _note_granule() -> None:
    global _granules_dispatched
    with _stats_lock:
        _granules_dispatched += 1


def _backend_is_tpu() -> bool:
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _h2d_cache_enabled() -> bool:
    return not os.environ.get("CEPH_TPU_NO_H2D_CACHE")


def _release_h2d_entries(cache: "OrderedDict") -> None:
    """Return a stream's cached upload bytes to the shared HBM ledger
    and drop the device references.  Runs on explicit stream retirement
    (the decode-stream LRU dropping a signature) and as a GC finalizer
    backstop -- a collected stream must not leave its bytes charged
    forever.  Takes the cache dict, not the stream, so the finalizer
    holds no reference that would keep the stream alive."""
    if not cache:
        return
    from ceph_tpu.tier.device_tier import device_byte_account

    acct = device_byte_account()
    for _d, nbytes in cache.values():
        acct.release("h2d", nbytes)
    cache.clear()


#: content-keyed device uploads of codec matrices, charged to the "h2d"
#: sub-allocation of the shared HBM ledger.  The engine-level encode
#: paths (ops/xla_gf.py matrix/packet encode, parallel/distributed.py's
#: mesh codec) used to re-ship their coding matrix on EVERY call --
#: the jax-loop-invariant-transfer class tpusan now flags -- because
#: they had no per-instance stream to hang the upload on.  This seam
#: gives them one: same bytes -> same device array, LRU-evicted (and
#: ledger-settled) under budget pressure like the stripe cache.
_MATRIX_CACHE: "OrderedDict[Tuple, Tuple[object, int]]" = OrderedDict()
_MATRIX_LOCK = threading.Lock()


def accounted_device_matrix(arr: np.ndarray, sharding=None):
    """Device-resident copy of ``arr``, cached by content and accounted
    to the DeviceByteAccount ledger (budget: osd_tier_h2d_cache_bytes,
    capped by osd_tier_hbm_bytes).  Falls back to the host array when
    no jax backend is importable (callers degrade like the tier).

    ``sharding`` (a ``jax.sharding.Sharding``, e.g. the mesh plane's
    cached replicated ``NamedSharding``) places the upload across a
    device mesh; it joins the content key so the same bytes on two
    different placements are two cache entries, and steady state never
    re-places (or re-ships) either."""
    a = np.ascontiguousarray(arr)
    key = (a.shape, str(a.dtype),
           hashlib.blake2b(a, digest_size=16).digest(),
           None if sharding is None else repr(sharding))
    with _MATRIX_LOCK:
        hit = _MATRIX_CACHE.get(key)
        if hit is not None:
            _MATRIX_CACHE.move_to_end(key)
            return hit[0]
    d = residency.device_put(a) if sharding is None else \
        residency.device_put(a, sharding)
    from ceph_tpu.tier.device_tier import (DeviceByteAccount,
                                           device_byte_account)

    acct = device_byte_account()
    budget = DeviceByteAccount.h2d_budget()
    with _MATRIX_LOCK:
        _MATRIX_CACHE[key] = (d, a.nbytes)
        acct.charge("h2d", a.nbytes)
        while _MATRIX_CACHE and acct.used("h2d") > budget:
            _k, (_old, nb) = _MATRIX_CACHE.popitem(last=False)
            acct.release("h2d", nb)
    return d


class DeviceStream:
    """One uploaded GF(2) matrix + the jitted program(s) that apply it.

    kind="matrix": B is a jerasure-layout bitmatrix [R*w, k*w] applied to
    w-bit words riding byte lanes (R output chunks from k input chunks).
    kind="packet": B is a packetized bitmatrix [R, C] applied to packet rows
    (cauchy/liberation family).
    """

    def __init__(self, kind: str, B: np.ndarray, k: int, rows_out: int,
                 w: int, packetsize: int = 0,
                 gf_matrix: Optional[np.ndarray] = None):
        import jax
        import jax.numpy as jnp

        self.kind = kind
        self.k = k
        self.rows_out = rows_out
        self.w = w
        self.packetsize = packetsize
        self._tpu = _backend_is_tpu()
        self._lock = threading.Lock()
        #: content key -> (device array, nbytes); bytes charged to the
        #: shared ledger, released on eviction / retirement / GC
        self._h2d_cache: OrderedDict[Tuple, Tuple] = OrderedDict()
        import weakref

        weakref.finalize(self, _release_h2d_entries, self._h2d_cache)

        if kind == "matrix":
            if self._tpu and w == 8:
                from ceph_tpu.ops.pallas_gf import prep_matrix_w8

                self._B = jnp.asarray(prep_matrix_w8(B, k))
                self._mode = "pallas8"
            elif self._tpu and w == 16:
                from ceph_tpu.ops.pallas_gf import prep_matrix_w16

                self._B = jnp.asarray(prep_matrix_w16(B, k))
                self._mode = "pallas16"
            elif w == 8 and gf_matrix is not None:
                # off-TPU w=8 lane: GF(2^8) row-times-value lookup
                # tables ([R, k, 256], 2 KiB/entry) beat the words
                # kernel's 8x bit-plane inflation ~3.5x on a host core;
                # same bytes, same [k, n] -> [R, n] contract
                from ceph_tpu.ops.xla_gf import gf8_row_tables

                self._B = jnp.asarray(gf8_row_tables(gf_matrix))
                self._mode = "xla_bytes"
            else:
                self._B = jnp.asarray(B)
                self._mode = "xla_words"
        else:
            if self._tpu:
                self._B = jnp.asarray(B.astype(np.float32))
                self._mode = "pallas_packet"
            else:
                self._B = jnp.asarray(B)
                self._mode = "xla_packet"
        # force the upload now so it never lands inside a timed region
        jax.block_until_ready(self._B)
        residency.note_h2d(int(getattr(self._B, "nbytes", 0) or 0))

    # -- host-side layout ---------------------------------------------------

    def cols_of(self, bs: int) -> int:
        """Device columns contributed by one stripe of chunk size bs."""
        if self.kind == "matrix":
            if self._mode in ("pallas8", "pallas16"):
                return bs // 4  # int32 lanes
            return bs // (self.w // 8)  # w-bit words
        # packet rows: [k*w, bs/w] bytes -> int32 lanes on TPU
        if self._mode == "pallas_packet":
            return bs // (self.w * 4)
        return bs // self.w

    def _row_dtype(self):
        if self._mode in ("pallas8", "pallas16", "pallas_packet"):
            return np.int32
        if self._mode == "xla_words":
            return {8: np.uint8, 16: np.uint16, 32: np.uint32}[self.w]
        return np.uint8

    def rows_in(self) -> int:
        return self.k if self.kind == "matrix" else self.k * self.w

    def pack_into(self, out: np.ndarray, col0: int, data: np.ndarray) -> None:
        """Place one stripe's [k, bs] u8 chunk block at column offset col0
        of the granule assembly buffer (backend units)."""
        bs = data.shape[1]
        ncols = self.cols_of(bs)
        if self.kind == "matrix":
            view = np.ascontiguousarray(data).view(self._row_dtype())
        else:
            from ceph_tpu.ops.xla_gf import _to_packet_rows

            rows = _to_packet_rows(np.ascontiguousarray(data), self.w,
                                   self.packetsize)
            view = rows.view(self._row_dtype())
        out[:, col0:col0 + ncols] = view

    def unpack(self, out_host: np.ndarray, col0: int, bs: int) -> np.ndarray:
        """Extract one stripe's [rows_out, bs] u8 parity block."""
        ncols = self.cols_of(bs)
        block = np.ascontiguousarray(out_host[:, col0:col0 + ncols])
        if self.kind == "matrix":
            return block.view(np.uint8).reshape(self.rows_out, bs)
        from ceph_tpu.ops.xla_gf import _from_packet_rows

        rows = block.view(np.uint8).reshape(self.rows_out * self.w, bs // self.w)
        return _from_packet_rows(rows, self.w, self.packetsize)

    # -- device dispatch ----------------------------------------------------

    def seg_align_bytes(self) -> int:
        """Stripe column-segment boundaries must fall on whole device
        columns (matrix codes) or whole packet groups (packet codes)."""
        if self.kind == "matrix":
            return 4
        return self.w * self.packetsize * (4 if self._mode == "pallas_packet" else 1)

    def upload(self, packed: np.ndarray, *, cacheable: bool = True):
        """H2D slot of the two-slot dispatch pipeline: ship the packed
        granule, optionally through the content-addressed upload cache.
        Returns ``(device_array, from_cache)``.

        ``cacheable=False`` is the donation mode: the granule will be
        handed to XLA by :meth:`compute`, so retaining (or even content-
        hashing) it is wasted work -- donation and content-addressed
        retention are mutually exclusive by design (``osd_ec_donate``).

        The probe->upload stretch is a declared device-resident region:
        the H2D of ``packed`` is the sanctioned explicit upload edge,
        but nothing in here may pull a value BACK to host (that is
        :meth:`EncodePipeline._land`'s one designed D2H).  Statically
        checked by ``jax-d2h-in-resident-section``, dynamically by the
        tier-1 transfer guard.
        """
        key = None
        if cacheable and _h2d_cache_enabled():
            # Collision-resistant content key: this cache sits on the
            # durability path (ECBackend writes route through it), so a
            # 32-bit checksum is not acceptable — blake2b-128 is.
            key = (packed.shape,
                   hashlib.blake2b(packed, digest_size=16).digest())
        # cephlint: device-resident-section encode-dispatch
        with residency.resident_section("encode-dispatch"):
            with self._lock:
                hit = self._h2d_cache.get(key) if key is not None else None
                if hit is not None:
                    self._h2d_cache.move_to_end(key)
            if hit is not None:
                return hit[0], True
            d = residency.device_put(packed)
            if key is not None:
                # retention is byte-budgeted against the shared HBM
                # ledger: LRU entries fall out once the cache's
                # sub-allocation (osd_tier_h2d_cache_bytes, itself
                # capped by osd_tier_hbm_bytes) is exceeded across
                # all streams of this process
                from ceph_tpu.tier.device_tier import (
                    DeviceByteAccount, device_byte_account)

                acct = device_byte_account()
                budget = DeviceByteAccount.h2d_budget()
                with self._lock:
                    self._h2d_cache[key] = (d, packed.nbytes)
                    acct.charge("h2d", packed.nbytes)
                    while self._h2d_cache and \
                            acct.used("h2d") > budget:
                        _k, (_old, nb) = self._h2d_cache.popitem(
                            last=False)
                        acct.release("h2d", nb)
            return d, False
        # cephlint: end-device-resident-section

    def compute(self, d, *, donate: bool = False):
        """Kernel slot: apply the resident GF matrix to uploaded granule
        ``d`` (async dispatch; nothing blocks until landing).

        ``donate=True`` routes through the ``donate_argnums`` twin: the
        granule's device buffer belongs to XLA after this call and the
        caller must drop every reference (the rebind idiom
        ``jax-donated-after-use`` blesses).  Never donate a cached
        upload -- the cache entry would alias freed memory.
        """
        n4 = d.shape[1]
        # cephlint: device-resident-section encode-compute
        with residency.resident_section("encode-compute"):
            if self._mode == "pallas8":
                from ceph_tpu.ops.pallas_gf import (
                    _matrix_encode_call, _matrix_encode_call_donated)

                kern = _matrix_encode_call_donated if donate \
                    else _matrix_encode_call
                return kern(self._B, d, self.k, self.rows_out,
                            min(16384, n4))
            if self._mode == "pallas16":
                from ceph_tpu.ops.pallas_gf import (
                    _matrix_encode_w16_call, _matrix_encode_w16_call_donated)

                kern = _matrix_encode_w16_call_donated if donate \
                    else _matrix_encode_w16_call
                return kern(self._B, d, self.k, self.rows_out,
                            min(4096, n4))
            if self._mode == "pallas_packet":
                from ceph_tpu.ops.pallas_gf import (
                    _packet_encode_call, _packet_encode_call_donated)

                kern = _packet_encode_call_donated if donate \
                    else _packet_encode_call
                return kern(self._B, d, self._B.shape[0], min(2048, n4))
            if self._mode == "xla_bytes":
                from ceph_tpu.ops.xla_gf import (
                    _encode_bytes_kernel, _encode_bytes_kernel_donated)

                kern = _encode_bytes_kernel_donated if donate \
                    else _encode_bytes_kernel
                return kern(self._B, d)
            if self._mode == "xla_words":
                from ceph_tpu.ops.xla_gf import (
                    _encode_words_kernel, _encode_words_kernel_donated)

                kern = _encode_words_kernel_donated if donate \
                    else _encode_words_kernel
                return kern(self._B, d, self.w)
            from ceph_tpu.ops.xla_gf import (
                _encode_packets_kernel, _encode_packets_kernel_donated)

            kern = _encode_packets_kernel_donated if donate \
                else _encode_packets_kernel
            return kern(self._B, d)
        # cephlint: end-device-resident-section

    def dispatch(self, packed: np.ndarray):
        """One-shot compat: upload + compute in lockstep (the pipelined
        path stages the two slots separately for H2D/matmul overlap)."""
        d, _cached = self.upload(packed)
        return self.compute(d)

    def device_block(self, d_in, out, col0: int, blen: int):
        """Promote-from-encode composition: the ``[k+m, blen]`` uint8
        device block for the stripe at granule column ``col0`` -- data
        rows sliced from the packed input, parity rows from the kernel
        output, concatenated ON DEVICE.  No D2H and no re-upload: this
        is the block the cache tier keeps instead of round-tripping the
        host copy through ``put``.  None when the layout's device bytes
        are not plain shard bytes (packet codes scramble bytes into
        packet rows) or when the input was donated."""
        if self.kind != "matrix" or d_in is None or out is None:
            return None
        try:
            import jax
            import jax.numpy as jnp
        except Exception:  # noqa: BLE001 -- no backend: host put path
            return None
        ncols = self.cols_of(blen)
        block = jnp.concatenate(
            [d_in[:, col0:col0 + ncols], out[:, col0:col0 + ncols]],
            axis=0)
        if block.dtype != jnp.uint8:
            # int32-lane (pallas) / w16/w32 word layouts: bitcast the
            # lanes back to little-endian bytes, still on device
            block = jax.lax.bitcast_convert_type(
                block, jnp.uint8).reshape(block.shape[0], -1)
        return block

    def release_h2d(self) -> None:
        """Retire this stream's upload cache (ledger-settling)."""
        with self._lock:
            _release_h2d_entries(self._h2d_cache)

    @staticmethod
    def start_d2h(out) -> None:
        try:
            out.copy_to_host_async()
        except Exception:
            pass


class _Granule:
    __slots__ = ("out", "entries", "cols", "d_in")

    def __init__(self, out, entries, cols, d_in=None):
        self.out = out  # device array, in flight
        self.entries = entries  # [(ticket, granule_col0, stripe_b0, seg_bytes)]
        self.cols = cols
        self.d_in = d_in  # packed input, retained only for keep_device


class EncodePipeline:
    """Accumulation queue -> fused granule dispatch -> async completion.

    submit() buffers a stripe; granules dispatch when full (or on flush).
    Stripes larger than the top granule rung are split into column segments
    (parity is columnwise, so the split is exact) and re-assembled on
    completion.  result(ticket) blocks only until that stripe's last
    granule lands; up to `depth` granules are in flight at once,
    overlapping H2D / MXU compute / D2H.  Thread-safe; unclaimed results
    are held until result() or discard() — callers that abandon a ticket
    must discard it.

    The dispatch itself is a two-slot pipeline (``osd_ec_overlap_depth``
    slots): a granule's packed H2D is issued at dispatch time but its GF
    matmul is deferred until the NEXT granule's upload is in flight, so
    upload(N+1) rides under compute(N); ``jax.block_until_ready``
    equivalents are deferred all the way to :meth:`_land`.  With
    ``donate=True`` (``osd_ec_donate``) fresh granule uploads are handed
    to XLA by the kernel (no double-held HBM, no content hash); cached
    uploads and ``keep_device`` granules are never donated.
    """

    def __init__(self, stream: DeviceStream, depth: int = _DEFAULT_DEPTH,
                 max_granule: Optional[int] = None,
                 overlap: Optional[int] = None,
                 donate: Optional[bool] = None):
        self.stream = stream
        self.depth = depth
        if max_granule is None:
            max_granule = bucketing.ladder()[-1]
        align = stream.seg_align_bytes()
        self._max_seg_bytes = max(align, max_granule - max_granule % align)
        self._max_cols = stream.cols_of(self._max_seg_bytes)
        if overlap is None or donate is None:
            cfg_overlap, cfg_donate = _pipeline_tuning()
            overlap = cfg_overlap if overlap is None else overlap
            donate = cfg_donate if donate is None else donate
        self.overlap = max(1, int(overlap))
        self.donate = bool(donate)
        self._lock = threading.RLock()
        self._pending: List[Tuple[int, np.ndarray, int, int]] = []
        self._pending_cols = 0
        #: uploaded granules whose compute slot has not been issued yet
        self._staged: deque = deque()
        self._inflight: deque[_Granule] = deque()
        self._parts: Dict[int, Dict[int, np.ndarray]] = {}
        self._need: Dict[int, Tuple[int, int]] = {}  # ticket -> (bs, nsegs)
        self._done: Dict[int, np.ndarray] = {}
        self._keep: set = set()  # tickets wanting a resident device block
        self._dev_parts: Dict[int, Dict[int, object]] = {}
        self._dev_done: Dict[int, object] = {}
        self._next_ticket = 0

    # granule col ladder (ops/bucketing.py): one XLA program per rung
    def _rung_cols(self, need_cols: int) -> int:
        c = bucketing.bucket_cols(need_cols, self.stream.cols_of)
        return self._max_cols if c is None else min(c, self._max_cols)

    def submit(self, data: np.ndarray, keep_device: bool = False) -> int:
        """data: [k, bs] uint8 (the k prepared data chunks of one stripe).

        ``keep_device=True`` additionally composes the stripe's
        [k+m, bs] block on device at landing time (promote-from-encode;
        claim with :meth:`device_result` after :meth:`result`).  Such
        granules are exempt from donation."""
        with self._lock:
            t = self._next_ticket
            self._next_ticket += 1
            bs = data.shape[1]
            segs = []
            b0 = 0
            while b0 < bs:
                take = min(self._max_seg_bytes, bs - b0)
                segs.append((b0, take))
                b0 += take
            self._need[t] = (bs, len(segs))
            self._parts[t] = {}
            if keep_device:
                self._keep.add(t)
                self._dev_parts[t] = {}
            for b0, blen in segs:
                seg_cols = self.stream.cols_of(blen)
                if self._pending and self._pending_cols + seg_cols > self._max_cols:
                    self._dispatch_pending()
                self._pending.append((t, data, b0, blen))
                self._pending_cols += seg_cols
                if self._pending_cols >= self._max_cols:
                    self._dispatch_pending()
            return t

    def flush(self) -> None:
        with self._lock:
            if self._pending:
                self._dispatch_pending()
            while self._staged:
                self._issue_compute()

    def _dispatch_pending(self) -> None:
        # caller holds self._lock.  This is the coalescer's
        # flush->encode cut: every client op batched this tick lands
        # here as one fused granule.  From pack to staged-upload append
        # the granule must stay on its way INTO the device -- the one
        # designed D2H is _land(), outside the declared region below.
        stream = self.stream
        entries = []
        col0 = 0
        for t, data, b0, blen in self._pending:
            entries.append((t, col0, b0, blen))
            col0 += stream.cols_of(blen)
        cols = self._rung_cols(col0)
        keep = any(t in self._keep for t, _c0, _b0, _bl in entries)
        # cephlint: device-resident-section granule-flush-encode
        with residency.resident_section("granule-flush-encode"):
            buf = np.zeros((stream.rows_in(), cols),
                           dtype=stream._row_dtype())
            for (t, c0, b0, blen), (_t, data, _b0, _bl) in zip(
                    entries, self._pending):
                stream.pack_into(buf, c0, data[:, b0:b0 + blen])
            # H2D slot: issue the upload now; the GF matmul slot runs
            # when the next granule's upload is in flight (or at
            # flush/claim).  Donation granules skip the content cache.
            cacheable = not self.donate or keep
            d, cached = stream.upload(buf, cacheable=cacheable)
            self._staged.append((d, cached, keep, entries, cols))
            self._pending.clear()
            self._pending_cols = 0
        # cephlint: end-device-resident-section
        while len(self._staged) >= self.overlap:
            self._issue_compute()
        while len(self._inflight) > self.depth:
            self._land(self._inflight.popleft())

    def _issue_compute(self) -> None:
        # caller holds self._lock: compute slot of the two-slot pipeline
        d, cached, keep, entries, cols = self._staged.popleft()
        donate = self.donate and not cached and not keep
        out = self.stream.compute(d, donate=donate)
        g = _Granule(out, entries, cols, d if keep else None)
        d = None  # donated (or handed to the granule): dead past here
        DeviceStream.start_d2h(out)
        _note_granule()
        self._inflight.append(g)

    def _land(self, g: _Granule) -> None:
        # caller holds self._lock
        host = residency.device_get(g.out)  # blocks until D2H completes
        for t, c0, b0, blen in g.entries:
            if t not in self._need:
                continue  # discarded
            parts = self._parts[t]
            parts[b0] = self.stream.unpack(host, c0, blen)
            if t in self._keep:
                self._dev_parts[t][b0] = self.stream.device_block(
                    g.d_in, g.out, c0, blen)
            bs, nsegs = self._need[t]
            if len(parts) == nsegs:
                if nsegs == 1:
                    self._done[t] = parts[0]
                else:
                    whole = np.empty((self.stream.rows_out, bs), np.uint8)
                    for pb0, block in parts.items():
                        whole[:, pb0:pb0 + block.shape[1]] = block
                    self._done[t] = whole
                if t in self._keep:
                    self._dev_done[t] = self._compose_device(t, nsegs)
                del self._parts[t]
                del self._need[t]

    def _compose_device(self, ticket: int, nsegs: int):
        """Join a keep_device ticket's per-segment device blocks along
        the byte axis (still on device); None when any segment's layout
        could not be composed."""
        dsegs = self._dev_parts.pop(ticket, {})
        if len(dsegs) != nsegs or any(b is None for b in dsegs.values()):
            return None
        if nsegs == 1:
            return next(iter(dsegs.values()))
        import jax.numpy as jnp

        return jnp.concatenate(
            [dsegs[b0] for b0 in sorted(dsegs)], axis=1)

    def result(self, ticket: int) -> np.ndarray:
        """Parity/reconstruction rows for the given stripe: [rows_out, bs]."""
        with self._lock:
            if ticket not in self._done:
                self.flush()
            while ticket not in self._done and self._inflight:
                self._land(self._inflight.popleft())
            return self._done.pop(ticket)

    def device_result(self, ticket: int):
        """Still-resident [k+m, bs] uint8 device block for a
        ``keep_device`` ticket (promote-from-encode), or None when the
        stream's layout could not compose one.  Claim after
        :meth:`result`; single-shot."""
        with self._lock:
            self._keep.discard(ticket)
            return self._dev_done.pop(ticket, None)

    def discard(self, ticket: int) -> None:
        """Abandon a ticket: its result will not be retained."""
        with self._lock:
            self._done.pop(ticket, None)
            self._parts.pop(ticket, None)
            self._need.pop(ticket, None)
            self._keep.discard(ticket)
            self._dev_parts.pop(ticket, None)
            self._dev_done.pop(ticket, None)

    def drain(self) -> None:
        with self._lock:
            self.flush()
            while self._inflight:
                self._land(self._inflight.popleft())

    def encode_many(self, stripes: Sequence[np.ndarray]) -> List[np.ndarray]:
        """Pipelined convenience: [k,bs] blocks in -> [rows_out,bs] out."""
        tickets = [self.submit(s) for s in stripes]
        self.flush()
        return [self.result(t) for t in tickets]


# ---------------------------------------------------------------------------
# reconstruction-row composition (host, tiny): every erasure from k survivors
# ---------------------------------------------------------------------------


def matrix_reconstruct_rows(
    matrix: np.ndarray, k: int, m: int, w: int,
    available: Sequence[int], erased: Sequence[int],
) -> Tuple[List[int], np.ndarray]:
    """GF(2^w) rows expressing every erased chunk (data AND parity) as a
    combination of the k selected survivors.  Mirrors the two-stage logic of
    ops/xla_gf.matrix_decode but composes it into one matmul."""
    F = gf(w)
    sel = sorted(available)[:k]
    A = np.zeros((k, k), dtype=np.uint32)
    for r, cid in enumerate(sel):
        if cid < k:
            A[r, cid] = 1
        else:
            A[r, :] = matrix[cid - k, :]
    inv = F.mat_invert(A)  # data_chunks = inv @ survivors
    rows = np.zeros((len(erased), k), dtype=np.uint32)
    for i, e in enumerate(erased):
        if e < k:
            rows[i, :] = inv[e, :]
        else:
            rows[i, :] = F.mat_mul(matrix[e - k: e - k + 1, :], inv)[0]
    return sel, rows


def bitmatrix_reconstruct_rows(
    bitmatrix: np.ndarray, k: int, m: int, w: int,
    available: Sequence[int], erased: Sequence[int],
) -> Tuple[List[int], np.ndarray]:
    """GF(2) analogue of matrix_reconstruct_rows for packetized codes."""
    sel = sorted(available)[:k]
    A = np.zeros((k * w, k * w), dtype=np.uint8)
    for r, cid in enumerate(sel):
        if cid < k:
            A[r * w:(r + 1) * w, cid * w:(cid + 1) * w] = np.eye(w, dtype=np.uint8)
        else:
            A[r * w:(r + 1) * w, :] = bitmatrix[(cid - k) * w:(cid - k + 1) * w, :]
    inv = invert_bitmatrix(A)
    rows = np.zeros((len(erased) * w, k * w), dtype=np.uint8)
    for i, e in enumerate(erased):
        if e < k:
            rows[i * w:(i + 1) * w, :] = inv[e * w:(e + 1) * w, :]
        else:
            rows[i * w:(i + 1) * w, :] = (
                bitmatrix[(e - k) * w:(e - k + 1) * w, :].astype(np.uint32)
                @ inv.astype(np.uint32)
            ) % 2
    return sel, rows.astype(np.uint8)


# ---------------------------------------------------------------------------
# per-codec device state: encode stream + signature-keyed decode stream LRU
# ---------------------------------------------------------------------------


class DeviceCodec:
    """Persistent device pipelines for one codec instance.

    Built from the technique's matrix/bitmatrix; holds the encode stream and
    an LRU of reconstruction streams keyed by (available, erased) signature
    (the ISA decode-table-cache role, ErasureCodeIsaTableCache.h:48).
    """

    DECODE_LRU = 64

    def __init__(self, *, matrix: Optional[np.ndarray] = None,
                 bitmatrix: Optional[np.ndarray] = None,
                 k: int, m: int, w: int, packetsize: int = 0):
        self.k, self.m, self.w = k, m, w
        self.packetsize = packetsize
        self.matrix = matrix
        if matrix is not None:
            self._enc_B = matrix_to_bitmatrix(np.asarray(matrix, np.uint32), w)
            self.kind = "matrix"
        else:
            self._enc_B = np.asarray(bitmatrix, np.uint8)
            self.kind = "packet"
        self.bitmatrix = bitmatrix
        self._encode_stream: Optional[DeviceStream] = None
        self._decode_streams: OrderedDict[Tuple, Tuple[List[int], DeviceStream]] = (
            OrderedDict()
        )
        self._lock = threading.Lock()

    def encode_stream(self) -> DeviceStream:
        with self._lock:
            if self._encode_stream is None:
                self._encode_stream = DeviceStream(
                    self.kind, self._enc_B, self.k, self.m, self.w,
                    self.packetsize, gf_matrix=self.matrix,
                )
            return self._encode_stream

    def decode_stream(
        self, available: Sequence[int], erased: Sequence[int]
    ) -> Tuple[List[int], DeviceStream]:
        sig = (tuple(sorted(available)), tuple(sorted(erased)))
        with self._lock:
            hit = self._decode_streams.get(sig)
            if hit is not None:
                self._decode_streams.move_to_end(sig)
                return hit
        if self.kind == "matrix":
            sel, rows = matrix_reconstruct_rows(
                self.matrix, self.k, self.m, self.w, available, erased
            )
            B = matrix_to_bitmatrix(rows, self.w)
            stream = DeviceStream("matrix", B, self.k, len(erased), self.w,
                                  gf_matrix=rows)
        else:
            sel, rows = bitmatrix_reconstruct_rows(
                self._enc_B, self.k, self.m, self.w, available, erased
            )
            stream = DeviceStream("packet", rows, self.k, len(erased), self.w,
                                  self.packetsize)
        with self._lock:
            self._decode_streams[sig] = (sel, stream)
            while len(self._decode_streams) > self.DECODE_LRU:
                # retire the dropped signature's stream NOW: its cached
                # uploads must return to the HBM ledger deterministically,
                # not whenever GC gets around to the finalizer
                _sig, (_sel, old) = self._decode_streams.popitem(last=False)
                old.release_h2d()
        return sel, stream

    # -- one-shot conveniences (the sync plugin contract) -------------------

    def encode(self, data: np.ndarray) -> np.ndarray:
        """[k, bs] u8 -> [m, bs] u8, single fused dispatch.

        One-shot sync contract: donation is off so the content-addressed
        upload cache keeps eliding repeat-content H2D (tools and engine
        callers re-encode identical buffers).  The persistent write-lane
        pipeline (always-fresh granules) is where ``osd_ec_donate``
        applies."""
        pipe = EncodePipeline(self.encode_stream(), depth=0, donate=False)
        t = pipe.submit(data)
        return pipe.result(t)

    def decode(self, have: Dict[int, np.ndarray], blocksize: int) -> Dict[int, np.ndarray]:
        """Reconstruct every missing chunk in one fused dispatch."""
        available = sorted(have.keys())
        erased = [i for i in range(self.k + self.m) if i not in have]
        out = {i: np.asarray(have[i], dtype=np.uint8) for i in available}
        if not erased:
            return out
        if len(available) < self.k:
            raise ValueError("not enough chunks to decode")
        sel, stream = self.decode_stream(available, erased)
        survivors = np.stack([out[c] for c in sel])
        pipe = EncodePipeline(stream, depth=0, donate=False)
        rec = pipe.result(pipe.submit(survivors))
        for i, e in enumerate(erased):
            out[e] = rec[i]
        return out
