"""CPU reference codec engine: matrix and bitmatrix (packetized) encode/decode.

Numpy reimplementation of the jerasure compute semantics the reference plugins
drive (reference call sites: src/erasure-code/jerasure/ErasureCodeJerasure.cc:
151-165 jerasure_matrix_encode/decode, :255-270 jerasure_schedule_encode /
jerasure_schedule_decode_lazy).  This is the bit-exactness oracle for the TPU
engine and the fallback when no device is attached.

Semantics notes:
* matrix codes (w in {8,16,32}): a chunk is a dense little-endian array of
  w-bit words; coding[i] = XOR_j (matrix[i,j] * data[j]) elementwise over
  words.
* bitmatrix codes: a chunk is S super-packets, each w packet-rows of
  `packetsize` bytes; coding packet-row (i,l) = XOR of data packet-rows (j,x)
  selected by bitmatrix row i*w+l.  Chunk size must be a multiple of
  w*packetsize (the reference guarantees this via get_alignment, see
  ErasureCodeJerasure.cc:272-286).
* decode recovers erased data chunks by inverting the surviving submatrix and
  then re-encodes erased coding chunks; recovered bytes are the unique
  solution, hence bit-identical to any other correct evaluation order.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops.gf import gf
from ceph_tpu.matrices.bitmatrix import survivor_decode_bitmatrix


def _as_words(chunk: np.ndarray, w: int) -> np.ndarray:
    dtype = {8: np.uint8, 16: np.uint16, 32: np.uint32}[w]
    return chunk.view(dtype)


def matrix_encode(matrix: np.ndarray, data: np.ndarray, w: int) -> np.ndarray:
    """data: [k, size] uint8 -> coding [m, size] uint8."""
    F = gf(w)
    m, k = matrix.shape
    assert data.shape[0] == k
    size = data.shape[1]
    assert size % (w // 8) == 0, "chunk size must be a multiple of w/8"
    words = _as_words(data, w)  # [k, size/(w/8)]
    out = np.zeros((m, words.shape[1]), dtype=words.dtype)
    for i in range(m):
        acc = out[i]
        for j in range(k):
            c = int(matrix[i, j])
            if c:
                acc ^= F.mul_region(c, words[j])
    return out.view(np.uint8)


def matrix_decode(
    matrix: np.ndarray,
    chunks: dict[int, np.ndarray],
    k: int,
    m: int,
    w: int,
    size: int,
) -> dict[int, np.ndarray]:
    """Recover all erased chunks given surviving `chunks` {id: [size] uint8}.

    Returns a dict holding every chunk 0..k+m-1 (survivors pass through).
    """
    F = gf(w)
    available = sorted(chunks.keys())
    erased = [i for i in range(k + m) if i not in chunks]
    if not erased:
        return dict(chunks)
    if len(available) < k:
        raise ValueError("not enough chunks to decode")
    out = {i: np.asarray(chunks[i], dtype=np.uint8) for i in available}

    erased_data = [e for e in erased if e < k]
    if erased_data:
        # rows of the generator matrix for the first k surviving chunks
        sel = available[:k]
        A = np.zeros((k, k), dtype=np.uint32)
        for r, cid in enumerate(sel):
            if cid < k:
                A[r, cid] = 1
            else:
                A[r, :] = matrix[cid - k, :]
        inv = F.mat_invert(A)
        words = np.stack([_as_words(out[cid], w) for cid in sel])
        for e in erased_data:
            acc = np.zeros(words.shape[1], dtype=words.dtype)
            for r in range(k):
                c = int(inv[e, r])
                if c:
                    acc ^= F.mul_region(c, words[r])
            out[e] = acc.view(np.uint8)

    data = np.stack([out[j] for j in range(k)])
    for e in erased:
        if e >= k:
            out[e] = matrix_encode(matrix[e - k : e - k + 1, :], data, w)[0]
    return out


# ---- bitmatrix (packetized) codes ----------------------------------------


def _to_packet_rows(data: np.ndarray, w: int, packetsize: int) -> np.ndarray:
    """[k, size] bytes -> [k*w, S, packetsize] packet rows."""
    k, size = data.shape
    assert size % (w * packetsize) == 0, (
        f"chunk size {size} must be a multiple of w*packetsize={w * packetsize}"
    )
    s = size // (w * packetsize)
    return (
        data.reshape(k, s, w, packetsize)
        .transpose(0, 2, 1, 3)
        .reshape(k * w, s, packetsize)
    )


def _from_packet_rows(rows: np.ndarray, w: int, packetsize: int) -> np.ndarray:
    """[n*w, S, packetsize] -> [n, size] bytes."""
    nw, s, p = rows.shape
    n = nw // w
    return (
        rows.reshape(n, w, s, p).transpose(0, 2, 1, 3).reshape(n, s * w * p)
    )


def bitmatrix_encode(
    bitmatrix: np.ndarray, data: np.ndarray, w: int, packetsize: int
) -> np.ndarray:
    """bitmatrix: [m*w, k*w]; data: [k, size] -> coding [m, size]."""
    mw = bitmatrix.shape[0]
    rows = _to_packet_rows(data, w, packetsize)  # [k*w, S, P]
    out = np.zeros((mw,) + rows.shape[1:], dtype=np.uint8)
    for r in range(mw):
        idx = np.nonzero(bitmatrix[r])[0]
        if len(idx):
            out[r] = np.bitwise_xor.reduce(rows[idx], axis=0)
    return _from_packet_rows(out, w, packetsize)


def bitmatrix_decode(
    bitmatrix: np.ndarray,
    chunks: dict[int, np.ndarray],
    k: int,
    m: int,
    w: int,
    size: int,
    packetsize: int,
) -> dict[int, np.ndarray]:
    """Recover all erased chunks for a bitmatrix code."""
    available = sorted(chunks.keys())
    erased = [i for i in range(k + m) if i not in chunks]
    if not erased:
        return dict(chunks)
    if len(available) < k:
        raise ValueError("not enough chunks to decode")
    out = {i: np.asarray(chunks[i], dtype=np.uint8) for i in available}

    erased_data = [e for e in erased if e < k]
    if erased_data:
        sel = available[:k]
        D = survivor_decode_bitmatrix(bitmatrix, k, w, sel, erased_data)
        srows = np.concatenate(
            [_to_packet_rows(out[cid][None, :], w, packetsize) for cid in sel]
        )  # [k*w, S, P]
        for j, e in enumerate(erased_data):
            rec = np.zeros((w,) + srows.shape[1:], dtype=np.uint8)
            for l in range(w):
                idx = np.nonzero(D[j * w + l])[0]
                if len(idx):
                    rec[l] = np.bitwise_xor.reduce(srows[idx], axis=0)
            out[e] = _from_packet_rows(rec, w, packetsize)[0]

    data = np.stack([out[j] for j in range(k)])
    for e in erased:
        if e >= k:
            rows = bitmatrix[(e - k) * w : (e - k + 1) * w, :]
            out[e] = bitmatrix_encode(rows, data, w, packetsize)[0]
    return out
