"""cpu_engine-compatible adapter over the native C++ kernels.

Selected with profile key ``backend=native``; the AVX2 kernels handle the
bulk region math (the role of jerasure/isa-l SIMD in the reference), host
matrix prep/inversion stays in numpy/gf.  w=8 only; other widths delegate
to the numpy engine.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.matrices.bitmatrix import invert_bitmatrix
from ceph_tpu.native import gf_native
from ceph_tpu.ops import cpu_engine
from ceph_tpu.ops.gf import gf


def matrix_encode(matrix: np.ndarray, data: np.ndarray, w: int) -> np.ndarray:
    if w != 8:
        return cpu_engine.matrix_encode(matrix, data, w)
    return gf_native.matrix_encode(matrix, data)


def matrix_decode(matrix, chunks, k, m, w, size):
    if w != 8:
        return cpu_engine.matrix_decode(matrix, chunks, k, m, w, size)
    F = gf(8)
    available = sorted(chunks.keys())
    erased = [i for i in range(k + m) if i not in chunks]
    if not erased:
        return dict(chunks)
    if len(available) < k:
        raise ValueError("not enough chunks to decode")
    out = {i: np.asarray(chunks[i], dtype=np.uint8) for i in available}
    erased_data = [e for e in erased if e < k]
    if erased_data:
        sel = available[:k]
        A = np.zeros((k, k), dtype=np.uint32)
        for r, cid in enumerate(sel):
            if cid < k:
                A[r, cid] = 1
            else:
                A[r, :] = matrix[cid - k, :]
        inv = F.mat_invert(A)
        survivors = np.stack([out[cid] for cid in sel])
        rec = gf_native.matrix_encode(inv[erased_data, :], survivors)
        for idx, e in enumerate(erased_data):
            out[e] = rec[idx]
    erased_coding = [e for e in erased if e >= k]
    if erased_coding:
        data = np.stack([out[j] for j in range(k)])
        rec = gf_native.matrix_encode(
            matrix[[e - k for e in erased_coding], :], data
        )
        for idx, e in enumerate(erased_coding):
            out[e] = rec[idx]
    return out


def bitmatrix_encode(
    bitmatrix: np.ndarray, data: np.ndarray, w: int, packetsize: int
) -> np.ndarray:
    rows = cpu_engine._to_packet_rows(
        np.ascontiguousarray(data), w, packetsize
    ).reshape(data.shape[0] * w, -1)
    out = gf_native.bitmatrix_packet_encode(bitmatrix, rows)
    s = data.shape[1] // (w * packetsize)
    return cpu_engine._from_packet_rows(
        out.reshape(out.shape[0], s, packetsize), w, packetsize
    )


def bitmatrix_decode(bitmatrix, chunks, k, m, w, size, packetsize):
    available = sorted(chunks.keys())
    erased = [i for i in range(k + m) if i not in chunks]
    if not erased:
        return dict(chunks)
    if len(available) < k:
        raise ValueError("not enough chunks to decode")
    out = {i: np.asarray(chunks[i], dtype=np.uint8) for i in available}
    erased_data = [e for e in erased if e < k]
    if erased_data:
        sel = available[:k]
        A = np.zeros((k * w, k * w), dtype=np.uint8)
        for r, cid in enumerate(sel):
            if cid < k:
                A[r * w : (r + 1) * w, cid * w : (cid + 1) * w] = np.eye(
                    w, dtype=np.uint8
                )
            else:
                A[r * w : (r + 1) * w, :] = bitmatrix[
                    (cid - k) * w : (cid - k + 1) * w, :
                ]
        inv = invert_bitmatrix(A)
        rec_rows = np.concatenate(
            [inv[e * w : (e + 1) * w, :] for e in erased_data]
        )
        survivors = np.stack([out[cid] for cid in sel])
        srows = cpu_engine._to_packet_rows(survivors, w, packetsize).reshape(
            k * w, -1
        )
        rec = gf_native.bitmatrix_packet_encode(rec_rows, srows)
        s = size // (w * packetsize)
        rec = cpu_engine._from_packet_rows(
            rec.reshape(rec.shape[0], s, packetsize), w, packetsize
        )
        for idx, e in enumerate(erased_data):
            out[e] = rec[idx]
    erased_coding = [e for e in erased if e >= k]
    if erased_coding:
        data = np.stack([out[j] for j in range(k)])
        rows = np.concatenate(
            [bitmatrix[(e - k) * w : (e - k + 1) * w, :] for e in erased_coding]
        )
        rec = bitmatrix_encode(rows, data, w, packetsize)
        for idx, e in enumerate(erased_coding):
            out[e] = rec[idx]
    return out
