"""Fused Pallas TPU kernels for the GF(2) codec engine.

Design (arrived at empirically on a v5e; see git history for the variants):

* The naive XLA path materializes the 8x bit-plane unpack in HBM; fusing it
  into a kernel is necessary but not sufficient -- elementwise VPU work and
  dtype relayouts dominate next.
* Production kernel = **packed-lane** form: the host reinterprets the byte
  stream as int32 (4 bytes per lane; free view, no device relayout).  The
  kernel extracts 16 shifted/masked plane-rows from the packed lanes
  ((x >> s) & 0x00010001 covers byte positions 0&2 at bits 0/16;
  (x >> (8+s)) covers 1&3), runs two f32 MXU dots with precision=HIGHEST
  (values {0,1,65536,65537}; sums <= 64 per 8-bit field stay exact below
  2^24), merges accumulators with z = accL + (accH << 8) -- fields don't
  collide because 64 < 256 -- and masks z & 0x01010101 to read four parity
  bits per lane.  Everything stays in (8,128)-tiled i32/f32 layouts: no
  int8/bf16 relayouts, int32 in, int32 out.

API mirrors the XLA engine: same jerasure bitmatrix in, same bytes out
(validated bit-exact against ceph_tpu/ops/cpu_engine.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


# ---------------------------------------------------------------------------
# matrix codes over GF(2^8) -- packed-lane kernel
# ---------------------------------------------------------------------------


def prep_matrix_w8(bitmatrix: np.ndarray, k: int) -> np.ndarray:
    """Host prep: reorder bitmatrix columns to shift-major packed-lane order.

    Kernel operand rows are ordered (s, j) for s in 0..7 (bit plane) and j in
    0..k-1 (chunk); coefficient = bitmatrix[:, j*8 + s].
    """
    R = bitmatrix.shape[0]
    out = np.zeros((R, 8 * k), dtype=np.float32)
    for s in range(8):
        for j in range(k):
            out[:, s * k + j] = bitmatrix[:, j * 8 + s]
    return out


def _matrix_kernel(b_ref, x_ref, o_ref, *, k: int, m: int):
    x = x_ref[:]  # [k, T] int32: 4 packed bytes per lane
    mask = jnp.int32(0x00010001)
    lo = jnp.concatenate(
        [((x >> s) & mask).astype(jnp.float32) for s in range(8)], axis=0
    )  # [8k, T] byte positions 0 & 2
    hi = jnp.concatenate(
        [((x >> (8 + s)) & mask).astype(jnp.float32) for s in range(8)], axis=0
    )  # byte positions 1 & 3
    dn = (((1,), (0,)), ((), ()))
    accL = jax.lax.dot_general(
        b_ref[:], lo, dn,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    accH = jax.lax.dot_general(
        b_ref[:], hi, dn,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    z = accL + (accH << 8)  # four sums per lane at byte spacing (each <= 64)
    pb = z & jnp.int32(0x01010101)  # four parity bits per lane
    t = pb.shape[-1]
    ob = pb.reshape(m, 8, t)
    packed = ob[:, 0, :]
    for l in range(1, 8):
        packed = packed | (ob[:, l, :] << l)
    o_ref[:] = packed


def _matrix_encode_fn(Bp, d32, k: int, m: int, tile: int):
    n4 = d32.shape[1]
    return pl.pallas_call(
        functools.partial(_matrix_kernel, k=k, m=m),
        out_shape=jax.ShapeDtypeStruct((m, n4), jnp.int32),
        grid=(_cdiv(n4, tile),),
        in_specs=[
            pl.BlockSpec((m * 8, k * 8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
    )(Bp, d32)


#: jitted twins: the ``_donated`` form hands the packed data operand's
#: HBM buffer to XLA (jit-level donation composes with pallas_call; the
#: runtime frees/reuses the granule instead of double-holding it).  The
#: donated operand is dead after the call -- pipeline rebinds it.
_matrix_encode_call = jax.jit(
    _matrix_encode_fn, static_argnames=("k", "m", "tile"))
_matrix_encode_call_donated = jax.jit(
    _matrix_encode_fn, static_argnames=("k", "m", "tile"),
    donate_argnums=(1,))


def matrix_encode_w8(
    bitmatrix: np.ndarray | jax.Array,
    data: np.ndarray | jax.Array,
    k: int,
    m: int,
    tile: int = 16384,
) -> np.ndarray:
    """bitmatrix [m*8, k*8] (jerasure layout) x data [k, N] uint8 -> [m, N].

    N must be a multiple of 4 (always true for SIMD_ALIGN'd chunks).
    """
    if isinstance(bitmatrix, np.ndarray):
        Bp = jnp.asarray(prep_matrix_w8(bitmatrix, k))
    else:
        Bp = bitmatrix
    if isinstance(data, np.ndarray):
        d32 = jnp.asarray(np.ascontiguousarray(data).view(np.int32))
    else:
        d32 = data
    n4 = d32.shape[1]
    tile = min(tile, max(_cdiv(n4, 128) * 128, 128))
    out32 = _matrix_encode_call(Bp, d32, k, m, tile)
    return np.ascontiguousarray(jax.device_get(out32)).view(np.uint8)


# ---------------------------------------------------------------------------
# w=16 matrix codes: two 16-bit words per int32 lane, same field scheme
# ---------------------------------------------------------------------------


def prep_matrix_w16(bitmatrix: np.ndarray, k: int) -> np.ndarray:
    """Columns to shift-major order for w=16: row (s, j) has coefficient
    bitmatrix[:, j*16 + s] for s in 0..15."""
    R = bitmatrix.shape[0]
    out = np.zeros((R, 16 * k), dtype=np.float32)
    for s in range(16):
        for j in range(k):
            out[:, s * k + j] = bitmatrix[:, j * 16 + s]
    return out


def _matrix_kernel_w16(b_ref, x_ref, o_ref, *, k: int, m: int):
    x = x_ref[:]  # [k, T] int32: 2 packed LE uint16 words per lane
    mask = jnp.int32(0x00000001)
    # lo word (bits 0-15): shifts s; hi word (bits 16-31): shifts 16+s --
    # one 1-bit field each; packed pairwise via <<16 after the dots
    lo = jnp.concatenate(
        [((x >> s) & mask).astype(jnp.float32) for s in range(16)], axis=0
    )  # [16k, T] word position 0
    hi = jnp.concatenate(
        [((x >> (16 + s)) & mask).astype(jnp.float32) for s in range(16)],
        axis=0,
    )  # word position 1
    dn = (((1,), (0,)), ((), ()))
    accL = jax.lax.dot_general(
        b_ref[:], lo, dn,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    accH = jax.lax.dot_general(
        b_ref[:], hi, dn,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    # sums <= k*16 <= 512 < 2^16: fields don't collide
    z = accL + (accH << 16)
    pb = z & jnp.int32(0x00010001)  # one parity bit per word per lane
    t = pb.shape[-1]
    ob = pb.reshape(m, 16, t)
    packed = ob[:, 0, :]
    for l in range(1, 16):
        packed = packed | (ob[:, l, :] << l)
    o_ref[:] = packed


def _matrix_encode_w16_fn(Bp, d32, k: int, m: int, tile: int):
    n4 = d32.shape[1]
    return pl.pallas_call(
        functools.partial(_matrix_kernel_w16, k=k, m=m),
        out_shape=jax.ShapeDtypeStruct((m, n4), jnp.int32),
        grid=(_cdiv(n4, tile),),
        in_specs=[
            pl.BlockSpec((m * 16, k * 16), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
    )(Bp, d32)


_matrix_encode_w16_call = jax.jit(
    _matrix_encode_w16_fn, static_argnames=("k", "m", "tile"))
_matrix_encode_w16_call_donated = jax.jit(
    _matrix_encode_w16_fn, static_argnames=("k", "m", "tile"),
    donate_argnums=(1,))


def matrix_encode_w16(
    bitmatrix: np.ndarray | jax.Array,
    data: np.ndarray | jax.Array,
    k: int,
    m: int,
    tile: int = 4096,
) -> np.ndarray:
    """bitmatrix [m*16, k*16] x data [k, N] uint8 (LE uint16 words) -> [m, N]."""
    if isinstance(bitmatrix, np.ndarray):
        Bp = jnp.asarray(prep_matrix_w16(bitmatrix, k))
    else:
        Bp = bitmatrix
    if isinstance(data, np.ndarray):
        d32 = jnp.asarray(np.ascontiguousarray(data).view(np.int32))
    else:
        d32 = data
    n4 = d32.shape[1]
    tile = min(tile, max(_cdiv(n4, 128) * 128, 128))
    out32 = _matrix_encode_w16_call(Bp, d32, k, m, tile)
    return np.ascontiguousarray(jax.device_get(out32)).view(np.uint8)


# ---------------------------------------------------------------------------
# packetized bitmatrix codes (cauchy / liberation family)
# ---------------------------------------------------------------------------
#
# Packet rows are XOR-combined bytes; the same packed-lane trick applies
# directly (the contraction runs over packet rows, byte positions ride the
# lanes), with B used as-is (no column reorder: row c of the operand is
# packet row c).


def _packet_kernel(b_ref, x_ref, o_ref, *, r: int):
    x = x_ref[:]  # [C, T] int32 packed bytes
    mask = jnp.int32(0x00010001)
    dn = (((1,), (0,)), ((), ()))
    out = None
    # two dots per 8-bit half: positions 0&2 via shift s, 1&3 via 8+s --
    # but here the contraction dim is packet rows, so each bit plane of the
    # packed lanes is its own GF(2) system: 8 planes x 2 halves collapse to
    # 2 dots exactly like the matrix kernel, except B has no plane structure
    # (XOR weights are per-row), so plane extraction folds into the z-merge.
    lo = [((x >> s) & mask).astype(jnp.float32) for s in range(8)]
    hi = [((x >> (8 + s)) & mask).astype(jnp.float32) for s in range(8)]
    zs = []
    for s in range(8):
        aL = jax.lax.dot_general(
            b_ref[:], lo[s], dn,
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
        aH = jax.lax.dot_general(
            b_ref[:], hi[s], dn,
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
        z = (aL + (aH << 8)) & jnp.int32(0x01010101)
        zs.append(z << s)
    out = zs[0]
    for z in zs[1:]:
        out = out | z
    o_ref[:] = out


def _packet_encode_fn(B, rows32, r: int, tile: int):
    n4 = rows32.shape[1]
    c = rows32.shape[0]
    return pl.pallas_call(
        functools.partial(_packet_kernel, r=r),
        out_shape=jax.ShapeDtypeStruct((r, n4), jnp.int32),
        grid=(_cdiv(n4, tile),),
        in_specs=[
            pl.BlockSpec((r, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((c, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
    )(B, rows32)


_packet_encode_call = jax.jit(
    _packet_encode_fn, static_argnames=("r", "tile"))
_packet_encode_call_donated = jax.jit(
    _packet_encode_fn, static_argnames=("r", "tile"),
    donate_argnums=(1,))


def packet_encode(
    bitmatrix: np.ndarray | jax.Array,
    rows: np.ndarray | jax.Array,
    tile: int = 2048,
) -> np.ndarray:
    """bitmatrix [R, C] x packet rows [C, Nb] uint8 -> [R, Nb] bytes."""
    if isinstance(bitmatrix, np.ndarray):
        B = jnp.asarray(bitmatrix.astype(np.float32))
        r = bitmatrix.shape[0]
    else:
        B = bitmatrix
        r = B.shape[0]
    if isinstance(rows, np.ndarray):
        rows32 = jnp.asarray(np.ascontiguousarray(rows).view(np.int32))
    else:
        rows32 = rows
    n4 = rows32.shape[1]
    tile = min(tile, max(_cdiv(n4, 128) * 128, 128))
    out32 = _packet_encode_call(B, rows32, r, tile)
    return np.ascontiguousarray(jax.device_get(out32)).view(np.uint8)
