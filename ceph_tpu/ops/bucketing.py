"""Shared batch-shape bucketing: the granule rung ladder.

One XLA program per (matrix, shape) pair is the deal the persistent
encode pipeline makes with the compiler; feeding it raw, workload-driven
shapes breaks that deal one retrace at a time (the
``jax-recompile-hazard`` class).  This module is the single source of
truth for the sanctioned shape set: a small ladder of power-of-two byte
rungs.  Every consumer pads its batch UP to the smallest fitting rung --
padding waste is bounded by ~2x, GF parity is column-independent so
zero-padding is bit-exact and trimmed on the way out -- and steady state
therefore runs at **zero retraces** (the bench residency stage gates on
exactly that number).

Consumers:

* ``ops/pipeline.py`` -- granule dispatch widths (this ladder replaces
  the old private ``_LADDER_BYTES`` / ``EncodePipeline._rung_cols``);
* ``osd/ecutil.py`` -- the shard-major helpers pad per-block for codecs
  that opt in (``ec.shape_bucketing``) but fall outside the batched
  pipeline;
* ``plugins/tpu.py`` -- odd blocksizes (``_pipeline_ok`` false) are
  padded up to an aligned rung so they ride the bucketed pipeline
  instead of retracing the raw-shape engine kernels.

The ladder is configurable (``osd_ec_shape_rungs``: comma/space
separated byte counts) so tests can exercise tiny rungs; the parsed
form is memoized per raw string.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

#: default rung ladder: bytes per fused chunk-row, 16 KiB .. 16 MiB.
#: Each rung is one XLA compilation per matrix shape; small sync writes
#: (4 KiB EC stripes) land on the 16 KiB rung instead of being inflated
#: to a fixed granule, and anything past the top rung is split into
#: column segments by the pipeline (parity is columnwise, so exact).
DEFAULT_RUNGS: Tuple[int, ...] = (
    1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22, 1 << 24,
)

_parse_lock = threading.Lock()
_parsed: dict = {}


def ladder() -> Tuple[int, ...]:
    """The configured rung ladder (``osd_ec_shape_rungs``), ascending;
    :data:`DEFAULT_RUNGS` when unset/unparseable.  Config access is
    guarded so codec-only tools with no Config still bucket."""
    try:
        from ceph_tpu.utils.config import get_config

        raw = str(get_config().get_val("osd_ec_shape_rungs")).strip()
    except Exception:  # noqa: BLE001 -- no config layer: default ladder
        raw = ""
    if not raw:
        return DEFAULT_RUNGS
    with _parse_lock:
        rungs = _parsed.get(raw)
        if rungs is None:
            try:
                rungs = tuple(sorted({
                    int(tok) for tok in raw.replace(",", " ").split()
                    if int(tok) > 0
                }))
            except ValueError:
                rungs = ()
            rungs = _parsed[raw] = rungs or DEFAULT_RUNGS
    return rungs


def rung_for(nbytes: int, rungs: Optional[Tuple[int, ...]] = None
             ) -> Optional[int]:
    """Smallest rung >= ``nbytes``; None when past the top rung (the
    caller splits into top-rung column segments)."""
    for b in rungs if rungs is not None else ladder():
        if nbytes <= b:
            return b
    return None


def bucket_bytes(nbytes: int, align: int = 1,
                 rungs: Optional[Tuple[int, ...]] = None) -> int:
    """Padded byte count for a ``nbytes``-wide block: the smallest rung
    that fits, rounded up to ``align`` (codec packet/lane granularity).
    Past the top rung, the next ``align``-ed multiple of the top rung --
    still a bounded shape set, one program per multiple."""
    rungs = rungs if rungs is not None else ladder()
    target = rung_for(nbytes, rungs)
    if target is None:
        top = rungs[-1]
        target = ((nbytes + top - 1) // top) * top
    return target + (-target) % max(1, align)


def bucket_cols(need_cols: int, cols_of: Callable[[int], int],
                rungs: Optional[Tuple[int, ...]] = None) -> Optional[int]:
    """Granule width in device columns: the smallest rung (translated
    through the stream's ``cols_of`` byte->column algebra) that fits
    ``need_cols``; None past the top rung (caller caps at its max)."""
    for b in rungs if rungs is not None else ladder():
        c = cols_of(b)
        if need_cols <= c:
            return c
    return None
