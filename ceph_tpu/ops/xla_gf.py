"""TPU-native GF(2^w) codec engine: bit-sliced GF(2) matmuls on the MXU.

The design insight: every jerasure/ISA-style erasure code -- matrix codes
over GF(2^w) words *and* packetized bitmatrix codes -- is a linear map over
GF(2).  Multiplication by a constant field element is a w x w 0/1 matrix
(ceph_tpu/matrices/bitmatrix.py), so the whole codec collapses to

    parity_bits = (B @ data_bits) mod 2

with B the (m*w) x (k*w) bitmatrix.  On TPU we evaluate that as a dense
bfloat16 matmul on the MXU (0/1 operands; exact in f32 accumulation up to
2^24 terms, k*w <= 1024 here) followed by a cheap mod-2 -- instead of the
reference's per-word SIMD table lookups (jerasure galois_w08_region_multiply)
or XOR schedules (jerasure_schedule_encode).  GF(2^8) has no MXU-native
multiply, but GF(2) does: it is AND/XOR, i.e. multiply/add-mod-2.

API mirrors ceph_tpu/ops/cpu_engine.py exactly (matrix_encode/matrix_decode/
bitmatrix_encode/bitmatrix_decode) and is bit-exact against it; the plugins
dispatch on profile key backend=cpu|tpu.

Decode inverts the tiny surviving submatrix on host (numpy GF) and reuses the
same device matmul for reconstruction -- matching how the reference splits
host matrix prep from bulk compute (src/erasure-code/isa/ErasureCodeIsa.cc:
226-303 builds decode tables on host, ec_encode_data does the bulk work).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.matrices.bitmatrix import matrix_to_bitmatrix
from ceph_tpu.ops.gf import gf

_WORD_DTYPE = {8: np.uint8, 16: np.uint16, 32: np.uint32}


# ---------------------------------------------------------------------------
# core jitted kernels
# ---------------------------------------------------------------------------


def _encode_words(B: jax.Array, words: jax.Array, w: int) -> jax.Array:
    """[R, k*w] bitmatrix x [k, n] w-bit words -> [R//w, n] words.

    Unpack word bit-planes -> MXU matmul -> mod 2 -> repack.  All three
    stages are elementwise except the dot; XLA fuses the unpack into the
    dot's operand read on TPU.
    """
    k, n = words.shape
    shifts = jnp.arange(w, dtype=words.dtype)
    bits = ((words[:, None, :] >> shifts[None, :, None]) & 1).astype(
        jnp.bfloat16
    )  # [k, w, n]
    bits = bits.reshape(k * w, n)
    acc = jax.lax.dot(
        B.astype(jnp.bfloat16), bits, preferred_element_type=jnp.float32
    )  # [R, n]
    obits = acc.astype(jnp.int32) & 1
    m = obits.shape[0] // w
    obits = obits.reshape(m, w, n).astype(jnp.uint32)
    packed = jnp.sum(
        obits << jnp.arange(w, dtype=jnp.uint32)[None, :, None], axis=1
    )
    return packed.astype(words.dtype)


#: the jitted programs: one traced per (matrix shape, rung) pair.  The
#: ``_donated`` twins additionally hand the data operand's buffer to XLA
#: (``donate_argnums``): the packed granule stops double-holding HBM the
#: moment the kernel takes it.  Callers MUST treat the donated operand
#: as dead after the call (the ``jax-donated-after-use`` contract; the
#: pipeline rebinds it to None at the call site).
_encode_words_kernel = jax.jit(_encode_words, static_argnames=("w",))
_encode_words_kernel_donated = jax.jit(
    _encode_words, static_argnames=("w",), donate_argnums=(1,))


def gf8_row_tables(matrix: np.ndarray) -> np.ndarray:
    """[R, k] GF(2^8) coding matrix -> [R, k, 256] uint8 row-times-value
    lookup tables (``tab[r, c, v] == matrix[r, c] * v`` in GF(2^8))."""
    from ceph_tpu.ops.gf import gf

    m = np.asarray(matrix, dtype=np.uint32) & 0xFF
    return np.asarray(gf(8).mul_table, dtype=np.uint8)[m]


def _encode_bytes(tab: jax.Array, data: jax.Array) -> jax.Array:
    """[R, k, 256] GF(2^8) row tables x [k, n] bytes -> [R, n] bytes.

    CPU-fallback lane for w=8 matrix codes: on a host core the words
    kernel's 8x bit-plane inflation loses badly to one L1-resident
    table gather per (row, chunk) pair (~3.5x at 16 KiB granules); the
    MXU prefers the opposite trade, so the pallas/words modes keep the
    TPU path and this lane is only selected off-TPU.
    """
    R, k = tab.shape[0], tab.shape[1]
    g = tab[jnp.arange(R, dtype=jnp.int32)[:, None, None],
            jnp.arange(k, dtype=jnp.int32)[None, :, None],
            data[None, :, :]]  # [R, k, n] gathered products
    out = g[:, 0, :]
    for c in range(1, k):
        out = out ^ g[:, c, :]
    return out


_encode_bytes_kernel = jax.jit(_encode_bytes)
_encode_bytes_kernel_donated = jax.jit(_encode_bytes, donate_argnums=(1,))


def _encode_packet_bits(B: jax.Array, rows: jax.Array) -> jax.Array:
    """[R, C] bitmatrix x [C, nbytes] packet rows -> [R, nbytes] bytes.

    Bytes are XOR-combined, which is 8 independent GF(2) systems (one per
    bit-plane): unpack bytes -> matmul -> mod 2 -> repack.
    """
    c, n = rows.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((rows[:, :, None] >> shifts[None, None, :]) & 1).astype(
        jnp.bfloat16
    )  # [C, n, 8]
    bits = bits.reshape(c, n * 8)
    acc = jax.lax.dot(
        B.astype(jnp.bfloat16), bits, preferred_element_type=jnp.float32
    )
    obits = (acc.astype(jnp.int32) & 1).astype(jnp.uint8)
    r = obits.shape[0]
    obits = obits.reshape(r, n, 8)
    packed = jnp.sum(
        obits << shifts[None, None, :], axis=2
    )
    return packed.astype(jnp.uint8)


_encode_packets_kernel = jax.jit(_encode_packet_bits)
_encode_packets_kernel_donated = jax.jit(
    _encode_packet_bits, donate_argnums=(1,))


# ---------------------------------------------------------------------------
# matrix codes (w-bit word semantics, same bytes as cpu_engine.matrix_encode)
# ---------------------------------------------------------------------------


_bitmatrix_cache: dict = {}


def _pallas_ok() -> bool:
    """Fused Pallas kernels require a real TPU backend."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _bitmatrix_of(matrix: np.ndarray, w: int) -> np.ndarray:
    key = (matrix.tobytes(), matrix.shape, w)
    cached = _bitmatrix_cache.get(key)
    if cached is None:
        cached = matrix_to_bitmatrix(matrix, w)
        _bitmatrix_cache[key] = cached
    return cached


def matrix_encode(matrix: np.ndarray, data: np.ndarray, w: int) -> np.ndarray:
    """data: [k, size] uint8 -> coding [m, size] uint8 (device compute)."""
    m, k = matrix.shape
    size = data.shape[1]
    assert size % (w // 8) == 0
    B = _bitmatrix_of(np.asarray(matrix, dtype=np.uint32), w)
    if w == 8 and size % 4 == 0 and _pallas_ok():
        from ceph_tpu.ops import pallas_gf

        return pallas_gf.matrix_encode_w8(B, np.ascontiguousarray(data), k, m)
    if w == 16 and size % 4 == 0 and _pallas_ok():
        from ceph_tpu.ops import pallas_gf

        return pallas_gf.matrix_encode_w16(B, np.ascontiguousarray(data), k, m)
    words = np.ascontiguousarray(data).view(_WORD_DTYPE[w])
    # the coding bitmatrix is call-invariant: route it through the
    # accounted upload cache instead of re-shipping it per call (the
    # jax-loop-invariant-transfer class -- callers loop this function
    # once per stripe/object)
    from ceph_tpu.analysis import residency
    from ceph_tpu.ops.pipeline import accounted_device_matrix

    Bd = accounted_device_matrix(B)
    dw = jnp.asarray(words)
    residency.note_h2d(words.nbytes)
    out = _encode_words_kernel(Bd, dw, w)
    return residency.device_get(out).view(np.uint8)


def matrix_decode(
    matrix: np.ndarray,
    chunks: dict,
    k: int,
    m: int,
    w: int,
    size: int,
) -> dict:
    """Recover erased chunks; host inverts the k x k system, device matmuls."""
    F = gf(w)
    available = sorted(chunks.keys())
    erased = [i for i in range(k + m) if i not in chunks]
    if not erased:
        return dict(chunks)
    if len(available) < k:
        raise ValueError("not enough chunks to decode")
    out = {i: np.asarray(chunks[i], dtype=np.uint8) for i in available}

    erased_data = [e for e in erased if e < k]
    if erased_data:
        sel = available[:k]
        A = np.zeros((k, k), dtype=np.uint32)
        for r, cid in enumerate(sel):
            if cid < k:
                A[r, cid] = 1
            else:
                A[r, :] = matrix[cid - k, :]
        inv = F.mat_invert(A)
        rec_rows = inv[erased_data, :]  # [e, k]
        survivors = np.stack([out[cid] for cid in sel])
        rec = matrix_encode(rec_rows, survivors, w)
        for idx, e in enumerate(erased_data):
            out[e] = rec[idx]

    erased_coding = [e for e in erased if e >= k]
    if erased_coding:
        data = np.stack([out[j] for j in range(k)])
        rows = matrix[[e - k for e in erased_coding], :]
        rec = matrix_encode(rows, data, w)
        for idx, e in enumerate(erased_coding):
            out[e] = rec[idx]
    return out


# ---------------------------------------------------------------------------
# bitmatrix (packetized) codes
# ---------------------------------------------------------------------------


def _to_packet_rows(data: np.ndarray, w: int, packetsize: int) -> np.ndarray:
    k, size = data.shape
    assert size % (w * packetsize) == 0
    s = size // (w * packetsize)
    return (
        data.reshape(k, s, w, packetsize)
        .transpose(0, 2, 1, 3)
        .reshape(k * w, s * packetsize)
    )


def _from_packet_rows(rows: np.ndarray, w: int, packetsize: int) -> np.ndarray:
    nw, n = rows.shape
    m = nw // w
    s = n // packetsize
    return (
        rows.reshape(m, w, s, packetsize)
        .transpose(0, 2, 1, 3)
        .reshape(m, s * w * packetsize)
    )


def _encode_packets(B: np.ndarray, rows: np.ndarray) -> np.ndarray:
    if rows.shape[1] % 4 == 0 and _pallas_ok():
        from ceph_tpu.ops import pallas_gf

        return pallas_gf.packet_encode(B, rows)
    from ceph_tpu.analysis import residency
    from ceph_tpu.ops.pipeline import accounted_device_matrix

    Bd = accounted_device_matrix(B)
    dr = jnp.asarray(rows)
    residency.note_h2d(rows.nbytes)
    out = _encode_packets_kernel(Bd, dr)
    return residency.device_get(out)


def bitmatrix_encode(
    bitmatrix: np.ndarray, data: np.ndarray, w: int, packetsize: int
) -> np.ndarray:
    rows = _to_packet_rows(np.ascontiguousarray(data), w, packetsize)
    return _from_packet_rows(_encode_packets(bitmatrix, rows), w, packetsize)


def bitmatrix_decode(
    bitmatrix: np.ndarray,
    chunks: dict,
    k: int,
    m: int,
    w: int,
    size: int,
    packetsize: int,
) -> dict:
    from ceph_tpu.matrices.bitmatrix import survivor_decode_bitmatrix

    available = sorted(chunks.keys())
    erased = [i for i in range(k + m) if i not in chunks]
    if not erased:
        return dict(chunks)
    if len(available) < k:
        raise ValueError("not enough chunks to decode")
    out = {i: np.asarray(chunks[i], dtype=np.uint8) for i in available}

    erased_data = [e for e in erased if e < k]
    if erased_data:
        sel = available[:k]
        rec_rows = survivor_decode_bitmatrix(bitmatrix, k, w, sel,
                                             erased_data)
        survivors = np.stack([out[cid] for cid in sel])
        srows = _to_packet_rows(survivors, w, packetsize)
        rec = _from_packet_rows(
            _encode_packets(rec_rows.astype(np.uint8), srows), w, packetsize
        )
        for idx, e in enumerate(erased_data):
            out[e] = rec[idx]

    erased_coding = [e for e in erased if e >= k]
    if erased_coding:
        data = np.stack([out[j] for j in range(k)])
        rows = np.concatenate(
            [bitmatrix[(e - k) * w : (e - k + 1) * w, :] for e in erased_coding]
        )
        rec = bitmatrix_encode(rows, data, w, packetsize)
        for idx, e in enumerate(erased_coding):
            out[e] = rec[idx]
    return out
