"""Object user-version class (reference: src/cls/version/cls_version.cc --
RGW uses it for conditional bucket-index updates)."""

from __future__ import annotations

from ceph_tpu.cls import register
from ceph_tpu.utils.encoding import Decoder, Encoder

_KEY = "user_version"


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b):
    return Decoder(b).value() if b else None


@register("version", "set")
async def set_version(ctx, inp: bytes):
    req = _dec(inp) or {}
    await ctx.omap_set({_KEY: _enc(int(req["ver"]))})
    return 0, b""


@register("version", "inc")
async def inc_version(ctx, inp: bytes):
    for _ in range(16):
        cur_raw = (await ctx.omap_get([_KEY])).get(_KEY)
        cur = _dec(cur_raw) or 0
        ok, _ = await ctx.omap_cas(_KEY, cur_raw, _enc(cur + 1))
        if ok:
            return 0, _enc(cur + 1)
    return -11, b""


@register("version", "get")
async def get_version(ctx, inp: bytes):
    cur_raw = (await ctx.omap_get([_KEY])).get(_KEY)
    return 0, _enc(_dec(cur_raw) or 0)


@register("version", "check")
async def check_version(ctx, inp: bytes):
    """-ECANCELED unless the stored version matches (conditional-op guard)."""
    req = _dec(inp) or {}
    cur_raw = (await ctx.omap_get([_KEY])).get(_KEY)
    if (_dec(cur_raw) or 0) != int(req["ver"]):
        return -125, b""  # -ECANCELED
    return 0, b""
