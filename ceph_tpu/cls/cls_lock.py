"""Advisory object locks (reference: src/cls/lock/cls_lock.cc).

Lock state lives in omap under ``lock.<name>``; exclusive acquisition is
an atomic compare-and-swap on the primary-shard OSD, so two racing
clients cannot both hold an exclusive lock.  Shared locks append the
locker under the same key (CAS on the serialized holder list).

Methods: ``lock`` (type exclusive|shared), ``unlock``, ``break_lock``,
``get_info``.  Payloads are encoding-framework tagged dicts.
"""

from __future__ import annotations

from ceph_tpu.cls import register
from ceph_tpu.utils.encoding import Decoder, Encoder


def _dec(inp: bytes) -> dict:
    return Decoder(inp).value() if inp else {}


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _key(name: str) -> str:
    return f"lock.{name}"


@register("lock", "lock")
async def lock(ctx, inp: bytes):
    req = _dec(inp)
    name = req["name"]
    locker = req["locker"]          # e.g. "client.4213" or a cookie
    ltype = req.get("type", "exclusive")
    for _ in range(16):  # CAS retry loop against racing lockers
        cur_raw = (await ctx.omap_get([_key(name)])).get(_key(name))
        cur = Decoder(cur_raw).value() if cur_raw else None
        if cur is None:
            new = {"type": ltype, "lockers": [locker]}
        elif cur["type"] == "shared" and ltype == "shared":
            if locker in cur["lockers"]:
                return 0, b""  # idempotent re-lock
            new = {"type": "shared", "lockers": cur["lockers"] + [locker]}
        elif cur["lockers"] == [locker] and cur["type"] == ltype:
            return 0, b""      # we already hold it
        else:
            return -16, b""    # -EBUSY
        ok, _ = await ctx.omap_cas(_key(name), cur_raw, _enc(new))
        if ok:
            return 0, b""
    return -11, b""  # -EAGAIN: CAS kept losing


@register("lock", "unlock")
async def unlock(ctx, inp: bytes):
    req = _dec(inp)
    name, locker = req["name"], req["locker"]
    for _ in range(16):
        cur_raw = (await ctx.omap_get([_key(name)])).get(_key(name))
        if cur_raw is None:
            return -2, b""  # -ENOENT
        cur = Decoder(cur_raw).value()
        if locker not in cur["lockers"]:
            return -2, b""
        rest = [x for x in cur["lockers"] if x != locker]
        new_raw = None if not rest else _enc(dict(cur, lockers=rest))
        ok, _ = await ctx.omap_cas(_key(name), cur_raw, new_raw)
        if ok:
            return 0, b""
    return -11, b""


@register("lock", "break_lock")
async def break_lock(ctx, inp: bytes):
    """Forcibly remove another client's lock (operator action)."""
    req = _dec(inp)
    cur_raw = (await ctx.omap_get([_key(req["name"])])).get(_key(req["name"]))
    if cur_raw is None:
        return -2, b""
    ok, _ = await ctx.omap_cas(_key(req["name"]), cur_raw, None)
    return (0 if ok else -11), b""


@register("lock", "get_info")
async def get_info(ctx, inp: bytes):
    req = _dec(inp)
    cur_raw = (await ctx.omap_get([_key(req["name"])])).get(_key(req["name"]))
    if cur_raw is None:
        return 0, _enc({"lockers": [], "type": None})
    cur = Decoder(cur_raw).value()
    return 0, _enc(cur)
