"""RBD image-header class (reference: src/cls/rbd/cls_rbd.cc).

The librbd layer keeps each image's metadata in the omap of a header
object (``rbd_header.<id>``): size, order (object-size shift), snapshot
table, and settable key/value metadata.  These methods manage that state;
the data path (striping image extents over data objects) lives in
``ceph_tpu.rbd``.
"""

from __future__ import annotations

from ceph_tpu.cls import register
from ceph_tpu.utils.encoding import Decoder, Encoder


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b):
    return Decoder(b).value() if b else None


@register("rbd", "create")
async def create(ctx, inp: bytes):
    req = _dec(inp)
    # CAS from absent: racing creates get exactly one winner (a plain
    # get-then-set would let both succeed with interleaved headers)
    ok, _ = await ctx.omap_cas("size", None, _enc(int(req["size"])))
    if not ok:
        return -17, b""  # -EEXIST
    await ctx.omap_set({
        "order": _enc(int(req.get("order", 22))),  # 4 MiB objects
        # seq lives INSIDE the snaps blob: snapshot id allocation and the
        # table update are one CAS, so racing snap_adds cannot reuse ids
        "snaps": _enc({"seq": 0, "by_name": {}}),
        "features": _enc(sorted(req.get("features", []))),
    })
    return 0, b""


@register("rbd", "get_metadata")
async def get_metadata(ctx, inp: bytes):
    omap = await ctx.omap_get(
        ["size", "order", "snaps", "parent", "features"])
    if "size" not in omap:
        return -2, b""
    snaps = _dec(omap.get("snaps")) or {"seq": 0, "by_name": {}}
    return 0, _enc({
        "size": _dec(omap["size"]),
        "order": _dec(omap["order"]),
        "snap_seq": snaps["seq"],
        "snaps": snaps["by_name"],
        "parent": _dec(omap.get("parent")),
        "features": _dec(omap.get("features")) or [],
    })


@register("rbd", "set_size")
async def set_size(ctx, inp: bytes):
    req = _dec(inp)
    if (await ctx.omap_get(["size"])).get("size") is None:
        return -2, b""
    await ctx.omap_set({"size": _enc(int(req["size"]))})
    return 0, b""


@register("rbd", "snap_add")
async def snap_add(ctx, inp: bytes):
    req = _dec(inp)
    name = req["name"]
    for _ in range(16):
        omap = await ctx.omap_get(["snaps", "size"])
        if "size" not in omap:
            return -2, b""
        cur_raw = omap.get("snaps")
        snaps = _dec(cur_raw) or {"seq": 0, "by_name": {}}
        if name in snaps["by_name"]:
            return -17, b""
        seq = snaps["seq"] + 1
        new = {
            "seq": seq,
            "by_name": dict(
                snaps["by_name"],
                **{name: {"id": seq, "size": _dec(omap["size"])}},
            ),
        }
        ok, _ = await ctx.omap_cas("snaps", cur_raw, _enc(new))
        if ok:
            return 0, _enc(seq)
    return -11, b""


@register("rbd", "snap_remove")
async def snap_remove(ctx, inp: bytes):
    req = _dec(inp)
    for _ in range(16):
        cur_raw = (await ctx.omap_get(["snaps"])).get("snaps")
        snaps = _dec(cur_raw) or {"seq": 0, "by_name": {}}
        if req["name"] not in snaps["by_name"]:
            return -2, b""
        by_name = dict(snaps["by_name"])
        del by_name[req["name"]]
        ok, _ = await ctx.omap_cas(
            "snaps", cur_raw, _enc({"seq": snaps["seq"], "by_name": by_name})
        )
        if ok:
            return 0, b""
    return -11, b""


@register("rbd", "set_features")
async def set_features(ctx, inp: bytes):
    """Enable/disable named features (reference cls_rbd set_features:
    librbd dynamic feature toggling, e.g. journaling on/off)."""
    req = _dec(inp)
    for _ in range(16):
        omap = await ctx.omap_get(["features", "size"])
        if "size" not in omap:
            return -2, b""
        cur_raw = omap.get("features")
        feats = set(_dec(cur_raw) or [])
        feats |= set(req.get("enable", []))
        feats -= set(req.get("disable", []))
        # CAS like every RMW in this class: cls methods interleave at
        # awaits, and a lost feature bit silently bypasses journaling
        ok, _ = await ctx.omap_cas("features", cur_raw, _enc(sorted(feats)))
        if ok:
            return 0, b""
    return -11, b""


@register("rbd", "metadata_set")
async def metadata_set(ctx, inp: bytes):
    req = _dec(inp)
    await ctx.omap_set({f"meta.{k}": v for k, v in req.items()})
    return 0, b""


@register("rbd", "metadata_get")
async def metadata_get(ctx, inp: bytes):
    req = _dec(inp)
    omap = await ctx.omap_get([f"meta.{req['key']}"])
    v = omap.get(f"meta.{req['key']}")
    if v is None:
        return -2, b""
    return 0, v


# -- snapshot protection + layering parent/child registry -------------------
# (reference cls_rbd: snapshot_protect/unprotect, set_parent/remove_parent,
# add_child/remove_child/get_children -- the metadata half of librbd
# clone layering; the COW read/copy-up data path lives in ceph_tpu.rbd)


@register("rbd", "snap_protect")
async def snap_protect(ctx, inp: bytes):
    req = _dec(inp)
    for _ in range(16):
        cur_raw = (await ctx.omap_get(["snaps"])).get("snaps")
        snaps = _dec(cur_raw) or {"seq": 0, "by_name": {}}
        ent = snaps["by_name"].get(req["name"])
        if ent is None:
            return -2, b""
        by_name = dict(snaps["by_name"])
        by_name[req["name"]] = dict(ent, protected=True)
        ok, _ = await ctx.omap_cas(
            "snaps", cur_raw, _enc({"seq": snaps["seq"], "by_name": by_name})
        )
        if ok:
            return 0, b""
    return -11, b""


@register("rbd", "snap_unprotect")
async def snap_unprotect(ctx, inp: bytes):
    req = _dec(inp)
    for _ in range(16):
        cur_raw = (await ctx.omap_get(["snaps"])).get("snaps")
        snaps = _dec(cur_raw) or {"seq": 0, "by_name": {}}
        ent = snaps["by_name"].get(req["name"])
        if ent is None:
            return -2, b""
        kids = _dec((await ctx.omap_get(
            [f"children.{ent['id']}"])).get(f"children.{ent['id']}")) or []
        if kids:
            return -16, b""  # -EBUSY: clones still reference the snap
        by_name = dict(snaps["by_name"])
        by_name[req["name"]] = {k: v for k, v in ent.items()
                                if k != "protected"}
        # CAS: a concurrent clone re-registering a child bumps nothing in
        # "snaps", but a concurrent snap_add must not be clobbered, and
        # the add_child CAS below makes the child-list check repeatable
        ok, _ = await ctx.omap_cas("snaps", cur_raw, _enc(
            {"seq": snaps["seq"], "by_name": by_name}))
        if ok:
            return 0, b""
    return -11, b""


@register("rbd", "add_child")
async def add_child(ctx, inp: bytes):
    req = _dec(inp)
    key = f"children.{req['snap_id']}"
    for _ in range(16):
        cur = (await ctx.omap_get([key])).get(key)
        kids = _dec(cur) or []
        if req["child"] not in kids:
            kids.append(req["child"])
        ok, _ = await ctx.omap_cas(key, cur, _enc(sorted(kids)))
        if ok:
            return 0, b""
    return -11, b""


@register("rbd", "remove_child")
async def remove_child(ctx, inp: bytes):
    req = _dec(inp)
    key = f"children.{req['snap_id']}"
    for _ in range(16):
        cur = (await ctx.omap_get([key])).get(key)
        kids = _dec(cur) or []
        if req["child"] in kids:
            kids.remove(req["child"])
        ok, _ = await ctx.omap_cas(key, cur, _enc(kids))
        if ok:
            return 0, b""
    return -11, b""


@register("rbd", "get_children")
async def get_children(ctx, inp: bytes):
    req = _dec(inp)
    key = f"children.{req['snap_id']}"
    kids = _dec((await ctx.omap_get([key])).get(key)) or []
    return 0, _enc(kids)


@register("rbd", "set_parent")
async def set_parent(ctx, inp: bytes):
    req = _dec(inp)
    await ctx.omap_set({"parent": _enc({
        "image": req["image"], "snap_id": int(req["snap_id"]),
        "snap_name": req.get("snap_name", ""),
        "overlap": int(req["overlap"]),
    })})
    return 0, b""


@register("rbd", "get_parent")
async def get_parent(ctx, inp: bytes):
    p = _dec((await ctx.omap_get(["parent"])).get("parent"))
    if p is None:
        return -2, b""
    return 0, _enc(p)


@register("rbd", "remove_parent")
async def remove_parent(ctx, inp: bytes):
    await ctx.omap_rm(["parent"])
    return 0, b""
