"""Server-side object classes (reference: src/cls + src/osd/ClassHandler).

The reference dlopens ``libcls_*.so`` plugins into the OSD; RADOS clients
invoke their methods with ``exec(oid, cls, method, input)`` and methods
mutate the object atomically on the primary.  Here the registry lives in
the primary EC engine (which is where our primary logic runs); methods
get a context exposing the object surface (read/stat/omap/xattr) and the
``omap_cas`` primitive served by the primary-shard OSD for atomic
read-modify-write.

Registering a class:

    @register("lock", "lock")
    async def lock(ctx, inp): ...

Method input/output are bytes (the reference's bufferlist in/out); the
encoding framework's tagged values are the usual payload format.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

#: (cls, method) -> coroutine fn(ctx, input bytes) -> (int ret, bytes out)
_METHODS: Dict[Tuple[str, str], Callable] = {}


def register(cls: str, method: str):
    def deco(fn):
        _METHODS[(cls, method)] = fn
        return fn
    return deco


def list_methods():
    return sorted(_METHODS)


class ClsContext:
    """What a method may touch -- the cls_cxx_* surface."""

    def __init__(self, backend, oid: str):
        self.backend = backend
        self.oid = oid

    async def read(self) -> bytes:
        return await self.backend.read(self.oid)

    async def stat(self) -> int:
        size, _ = await self.backend._stat(self.oid)
        return size

    async def write_full(self, data: bytes) -> None:
        await self.backend.write(self.oid, data)

    async def omap_get(self, keys=None):
        return await self.backend.omap_get(self.oid, keys)

    async def omap_set(self, kvs) -> None:
        await self.backend.omap_set(self.oid, kvs)

    async def omap_rm(self, keys) -> None:
        await self.backend.omap_rm(self.oid, keys)

    async def omap_cas(self, key, expect, new):
        return await self.backend.omap_cas(self.oid, key, expect, new)


async def call_method(backend, oid: str, cls: str, method: str,
                      inp: bytes) -> Tuple[int, bytes]:
    fn = _METHODS.get((cls, method))
    if fn is None:
        return -8, b""  # -ENOEXEC: unknown class/method (reference rc)
    ctx = ClsContext(backend, oid)
    return await fn(ctx, inp)


# importing the package loads the in-tree classes (the reference preloads
# via osd_class_load_list)
from ceph_tpu.cls import cls_lock, cls_rbd, cls_version  # noqa: E402,F401
