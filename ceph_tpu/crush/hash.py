"""Robert Jenkins 32-bit mix hash, as used by CRUSH.

Reference: src/crush/hash.c (crush_hash32_rjenkins1 .. _5).  The mix is the
public-domain Jenkins "evahash" 96-bit mix; the seed constant and the
argument schedule match the reference so that placements computed by this
framework are stable in the same way the reference's are.

All entry points accept plain ints or numpy uint32 arrays (any one argument
may be an array; scalars broadcast), enabling vectorized straw2 draws over a
whole bucket in one shot.
"""

from __future__ import annotations

from typing import Union

import numpy as np

_SEED = np.uint32(1315423911)
_M32 = 0xFFFFFFFF

ArrayOrInt = Union[int, np.ndarray]


def _mix(a, b, c):
    """One Jenkins 96-bit mix round over uint32 lanes (vectorized)."""
    a = (a - b) & _M32
    a = (a - c) & _M32
    a = a ^ (c >> 13)
    b = (b - c) & _M32
    b = (b - a) & _M32
    b = (b ^ (a << 8)) & _M32
    c = (c - a) & _M32
    c = (c - b) & _M32
    c = c ^ (b >> 13)
    a = (a - b) & _M32
    a = (a - c) & _M32
    a = a ^ (c >> 12)
    b = (b - c) & _M32
    b = (b - a) & _M32
    b = (b ^ (a << 16)) & _M32
    c = (c - a) & _M32
    c = (c - b) & _M32
    c = c ^ (b >> 5)
    a = (a - b) & _M32
    a = (a - c) & _M32
    a = a ^ (c >> 3)
    b = (b - c) & _M32
    b = (b - a) & _M32
    b = (b ^ (a << 10)) & _M32
    c = (c - a) & _M32
    c = (c - b) & _M32
    c = c ^ (b >> 15)
    return a, b, c


def _u32(v: ArrayOrInt):
    if isinstance(v, np.ndarray):
        return v.astype(np.uint64) & _M32
    return int(v) & _M32


def crush_hash32(a: ArrayOrInt) -> ArrayOrInt:
    a = _u32(a)
    h = int(_SEED) ^ a
    b = a
    x, y = 231232, 1232
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def crush_hash32_2(a: ArrayOrInt, b: ArrayOrInt) -> ArrayOrInt:
    a, b = _u32(a), _u32(b)
    h = int(_SEED) ^ a ^ b
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def crush_hash32_4(a: ArrayOrInt, b: ArrayOrInt, c: ArrayOrInt,
                   d: ArrayOrInt) -> ArrayOrInt:
    """4-argument schedule (hash.c crush_hash32_rjenkins1_4); used by
    the tree bucket's per-node draws."""
    a, b, c, d = _u32(a), _u32(b), _u32(c), _u32(d)
    h = int(_SEED) ^ a ^ b ^ c ^ d
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def crush_hash32_3(a: ArrayOrInt, b: ArrayOrInt, c: ArrayOrInt) -> ArrayOrInt:
    a, b, c = _u32(a), _u32(b), _u32(c)
    h = int(_SEED) ^ a ^ b ^ c
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h
