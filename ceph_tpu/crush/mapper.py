"""CRUSH rule execution: straw2 draws, firstn/indep descent, retries.

Reference: src/crush/mapper.c — bucket_straw2_choose (:361),
crush_choose_firstn (:470), crush_choose_indep (:720), crush_do_rule (:860),
is_out (:441).  The straw2 exponential draw replaces the reference's
fixed-point log lookup table (crush_ln_table.h) with a precomputed
2^44*log2(u+1) table built at import — same fixed-point scale, same
[0,0xffff] -> [-2^48,0] mapping, built from the formula rather than the
shipped table (semantic parity; see docs/crush.md for the derivation).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ceph_tpu.crush.hash import (crush_hash32_2, crush_hash32_3,
                                 crush_hash32_4)
from ceph_tpu.crush.map import (
    BUCKET_LIST,
    BUCKET_STRAW,
    BUCKET_STRAW2,
    BUCKET_TREE,
    BUCKET_UNIFORM,
    ITEM_NONE,
    ITEM_UNDEF,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_SET_CHOOSE_TRIES,
    RULE_SET_CHOOSELEAF_TRIES,
    RULE_TAKE,
    Bucket,
    CrushMap,
)

_S64_MIN = -(2**63)

# ln table: u in [0,0xffff] -> 2^44*log2(u+1) - 2^48  (<= 0).
# The reference's crush_ln computes the same quantity via a 256-entry
# reciprocal+log lookup (mapper.c:248-292); we build the full table directly.
_LN = (np.floor((2.0**44) * np.log2(np.arange(1, 0x10001, dtype=np.float64)))
       .astype(np.int64) - (1 << 48))


@dataclass
class Tunables:
    """Default values = the reference's "jewel" optimal profile
    (reference: src/crush/CrushWrapper.h set_tunables_jewel)."""

    choose_total_tries: int = 50
    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1


def _straw2_choose(bucket: Bucket, x: int, r: int) -> int:
    """Max exponential draw wins; weight-0 items can never win unless all
    are weight 0 (then index 0 wins, as the reference's i==0 seed does)."""
    items = bucket.items_array()
    weights = bucket.weights_array()
    u = np.asarray(
        crush_hash32_3(x, (items & 0xFFFFFFFF).astype(np.uint64), r)
    ).astype(np.int64) & 0xFFFF
    ln = _LN[u]
    draws = np.full(len(items), _S64_MIN, dtype=np.int64)
    nz = weights > 0
    # C div64_s64 truncates toward zero; ln <= 0, so negate-floordiv-negate.
    draws[nz] = -((-ln[nz]) // weights[nz])
    return int(items[int(np.argmax(draws))])


def _perm_choose(bucket: Bucket, x: int, r: int) -> int:
    """Pseudorandom-permutation choose for uniform buckets: a deterministic
    Fisher-Yates shuffle seeded by (x, bucket.id), position r mod size
    (reference: mapper.c bucket_perm_choose builds work->perm lazily)."""
    n = bucket.size
    perm = list(range(n))
    for i in range(n - 1):
        j = i + int(crush_hash32_3(x, bucket.id & 0xFFFFFFFF, i)) % (n - i)
        perm[i], perm[j] = perm[j], perm[i]
    return bucket.items[perm[r % n]]


def _list_choose(bucket: Bucket, x: int, r: int) -> int:
    """Walk from most-recently-added; draw w*2^16-scaled hash vs cumulative
    weight (reference: mapper.c bucket_list_choose)."""
    cum = 0
    for i in range(bucket.size - 1, -1, -1):
        cum += bucket.weights[i]
    running = cum
    for i in range(bucket.size - 1, 0, -1):
        w = int(crush_hash32_3(x, bucket.items[i] & 0xFFFFFFFF, r)) & 0xFFFF
        if w * running < bucket.weights[i] << 16:
            return bucket.items[i]
        running -= bucket.weights[i]
    return bucket.items[0]


def _tree_choose(bucket: Bucket, x: int, r: int) -> int:
    """Binary-tree descent (reference: mapper.c bucket_tree_choose
    :195-222): at each interior node draw a point in [0, node weight)
    via hash32_4(x, node, r, bucket id) and descend left when it falls
    under the left subtree's weight; items sit at odd node labels."""
    nw = bucket.tree_node_weights()
    n = len(nw) >> 1  # root
    if int(nw[n]) == 0:
        # all-zero weights: every draw is 0 and the descent would walk
        # into the right padding (IndexError); mirror straw2's
        # all-zero tiebreak and answer item 0
        return bucket.items[0]
    while not (n & 1):
        w = int(nw[n])
        t = (int(crush_hash32_4(
            x, n, r, bucket.id & 0xFFFFFFFF)) * w) >> 32
        h = 0
        m = n
        while (m & 1) == 0:
            h += 1
            m >>= 1
        left = n - (1 << (h - 1))
        n = left if t < int(nw[left]) else n + (1 << (h - 1))
    return bucket.items[n >> 1]


def _straw_choose(bucket: Bucket, x: int, r: int) -> int:
    """Legacy straw1 draw (reference: mapper.c bucket_straw_choose
    :227-248): (hash & 0xffff) * precomputed straw length, max wins."""
    straws = bucket.straws()
    items = bucket.items_array()
    draws = (np.asarray(crush_hash32_3(
        x, (items & 0xFFFFFFFF).astype(np.uint64), r)
    ).astype(np.int64) & 0xFFFF) * straws
    return int(items[int(np.argmax(draws))])


def _bucket_choose(bucket: Bucket, x: int, r: int) -> int:
    if bucket.alg == BUCKET_STRAW2:
        return _straw2_choose(bucket, x, r)
    if bucket.alg == BUCKET_UNIFORM:
        return _perm_choose(bucket, x, r)
    if bucket.alg == BUCKET_LIST:
        return _list_choose(bucket, x, r)
    if bucket.alg == BUCKET_TREE:
        return _tree_choose(bucket, x, r)
    if bucket.alg == BUCKET_STRAW:
        return _straw_choose(bucket, x, r)
    raise ValueError(f"unknown bucket alg {bucket.alg}")


def _is_out(
    device_weights: Optional[Sequence[int]], item: int, x: int
) -> bool:
    """Probabilistic reweight/out test (reference: mapper.c:441 is_out)."""
    if device_weights is None:
        return False
    if item >= len(device_weights):
        return True
    w = device_weights[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (int(crush_hash32_2(x, item)) & 0xFFFF) >= w


def _item_type(m: CrushMap, item: int) -> int:
    return m.buckets[item].type if item < 0 else 0


def _choose_firstn(
    m: CrushMap,
    bucket: Bucket,
    device_weights: Optional[Sequence[int]],
    x: int,
    numrep: int,
    type: int,
    out: List[int],
    outpos: int,
    out_size: int,
    tries: int,
    recurse_tries: int,
    local_retries: int,
    local_fallback_retries: int,
    recurse_to_leaf: bool,
    vary_r: int,
    stable: int,
    out2: Optional[List[int]],
    parent_r: int,
) -> int:
    """Returns new outpos.  Mirrors mapper.c crush_choose_firstn's
    reject/collide/retry ladder exactly."""
    count = out_size
    item = 0
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_b = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                collide = False
                reject = False
                r = rep + parent_r + ftotal
                if in_b.size == 0:
                    reject = True
                else:
                    if (
                        local_fallback_retries > 0
                        and flocal >= (in_b.size >> 1)
                        and flocal > local_fallback_retries
                    ):
                        item = _perm_choose(in_b, x, r)
                    else:
                        item = _bucket_choose(in_b, x, r)
                    if item >= m.max_device:
                        skip_rep = True
                        break
                    itemtype = _item_type(m, item)
                    if itemtype != type:
                        if item >= 0 or item not in m.buckets:
                            skip_rep = True
                            break
                        in_b = m.buckets[item]
                        retry_bucket = True
                        continue
                    for i in range(outpos):
                        if out[i] == item:
                            collide = True
                            break
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            if (
                                _choose_firstn(
                                    m,
                                    m.buckets[item],
                                    device_weights,
                                    x,
                                    1 if stable else outpos + 1,
                                    0,
                                    out2,
                                    outpos,
                                    count,
                                    recurse_tries,
                                    0,
                                    local_retries,
                                    local_fallback_retries,
                                    False,
                                    vary_r,
                                    stable,
                                    None,
                                    sub_r,
                                )
                                <= outpos
                            ):
                                reject = True  # didn't get a leaf
                        else:
                            out2[outpos] = item
                    if not reject and not collide and itemtype == 0:
                        reject = _is_out(device_weights, item, x)

                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (
                        local_fallback_retries > 0
                        and flocal <= in_b.size + local_fallback_retries
                    ):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True
        if skip_rep:
            rep += 1
            continue
        out[outpos] = item
        outpos += 1
        count -= 1
        rep += 1
    return outpos


def _choose_indep(
    m: CrushMap,
    bucket: Bucket,
    device_weights: Optional[Sequence[int]],
    x: int,
    left: int,
    numrep: int,
    type: int,
    out: List[int],
    outpos: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
    out2: Optional[List[int]],
    parent_r: int,
) -> None:
    """Positional selection with CRUSH_ITEM_NONE holes
    (reference: mapper.c crush_choose_indep)."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = ITEM_UNDEF
        if out2 is not None:
            out2[rep] = ITEM_UNDEF

    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != ITEM_UNDEF:
                continue
            in_b = bucket
            while True:
                r = rep + parent_r
                if in_b.alg == BUCKET_UNIFORM and in_b.size % numrep == 0:
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_b.size == 0:
                    # reference breaks without placing (mapper.c "empty
                    # bucket"): a later ftotal pass may pick a different
                    # subtree; cleanup converts leftover UNDEF to NONE.
                    break
                item = _bucket_choose(in_b, x, r)
                if item >= m.max_device:
                    out[rep] = ITEM_NONE
                    if out2 is not None:
                        out2[rep] = ITEM_NONE
                    left -= 1
                    break
                itemtype = _item_type(m, item)
                if itemtype != type:
                    if item >= 0 or item not in m.buckets:
                        out[rep] = ITEM_NONE
                        if out2 is not None:
                            out2[rep] = ITEM_NONE
                        left -= 1
                        break
                    in_b = m.buckets[item]
                    continue
                collide = False
                for i in range(outpos, endpos):
                    if out[i] == item:
                        collide = True
                        break
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        _choose_indep(
                            m,
                            m.buckets[item],
                            device_weights,
                            x,
                            1,
                            numrep,
                            0,
                            out2,
                            rep,
                            recurse_tries,
                            0,
                            False,
                            None,
                            r,
                        )
                        if out2[rep] == ITEM_NONE:
                            break
                    else:
                        out2[rep] = item
                if itemtype == 0 and _is_out(device_weights, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1

    for rep in range(outpos, endpos):
        if out[rep] == ITEM_UNDEF:
            out[rep] = ITEM_NONE
        if out2 is not None and out2[rep] == ITEM_UNDEF:
            out2[rep] = ITEM_NONE


def do_rule(
    m: CrushMap,
    ruleno: int,
    x: int,
    result_max: int,
    device_weights: Optional[Sequence[int]] = None,
    tunables: Optional[Tunables] = None,
) -> List[int]:
    """Execute a rule for input x; returns up to result_max device ids
    (ITEM_NONE marks an unmappable indep position).
    Reference: mapper.c crush_do_rule."""
    t = tunables or Tunables()
    if ruleno >= len(m.rules):
        return []
    rule = m.rules[ruleno]

    choose_tries = t.choose_total_tries + 1  # off-by-one compat (mapper.c:884)
    choose_leaf_tries = 0
    result: List[int] = []
    w: List[int] = []
    for step in rule.steps:
        if step.op == RULE_TAKE:
            tgt = step.arg1
            if (0 <= tgt < m.max_device) or tgt in m.buckets:
                w = [tgt]
        elif step.op == RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                choose_tries = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0:
                choose_leaf_tries = step.arg1
        elif step.op in (
            RULE_CHOOSE_FIRSTN,
            RULE_CHOOSE_INDEP,
            RULE_CHOOSELEAF_FIRSTN,
            RULE_CHOOSELEAF_INDEP,
        ):
            if not w:
                continue
            firstn = step.op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN)
            recurse_to_leaf = step.op in (
                RULE_CHOOSELEAF_FIRSTN,
                RULE_CHOOSELEAF_INDEP,
            )
            o: List[int] = [ITEM_NONE] * result_max
            c: List[int] = [ITEM_NONE] * result_max
            osize = 0
            for wi in w:
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                if wi >= 0 or wi not in m.buckets:
                    continue  # probably ITEM_NONE
                bucket = m.buckets[wi]
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    osize = _choose_firstn(
                        m,
                        bucket,
                        device_weights,
                        x,
                        numrep,
                        step.arg2,
                        o,
                        osize,
                        result_max - osize,
                        choose_tries,
                        recurse_tries,
                        t.choose_local_tries,
                        t.choose_local_fallback_tries,
                        recurse_to_leaf,
                        t.chooseleaf_vary_r,
                        t.chooseleaf_stable,
                        c,
                        0,
                    )
                else:
                    out_size = min(numrep, result_max - osize)
                    _choose_indep(
                        m,
                        bucket,
                        device_weights,
                        x,
                        out_size,
                        numrep,
                        step.arg2,
                        o,
                        osize,
                        choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf,
                        c,
                        0,
                    )
                    osize += out_size
            if recurse_to_leaf:
                o = c[:]  # final leaf values become the working set
            w = o[:osize]
        elif step.op == RULE_EMIT:
            result.extend(w[: result_max - len(result)])
            w = []
    return result
