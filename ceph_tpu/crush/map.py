"""CRUSH map model: devices, buckets, rules.

Reference: src/crush/crush.h (struct crush_map / crush_bucket / crush_rule),
src/crush/builder.c (map construction), src/crush/CrushWrapper.h (named
types/items).  Weights are 16.16 fixed point exactly as the reference's
(0x10000 == weight 1.0); bucket ids are negative, device ids >= 0.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# bucket algorithms (reference: crush.h:140-190)
BUCKET_UNIFORM = 1
BUCKET_LIST = 2
BUCKET_TREE = 3
BUCKET_STRAW = 4  # legacy straw1 (hammer straw_calc_version=1 straws)
BUCKET_STRAW2 = 5

# rule step ops (reference: crush.h CRUSH_RULE_*)
RULE_TAKE = 1
RULE_CHOOSE_FIRSTN = 2
RULE_CHOOSE_INDEP = 3
RULE_EMIT = 4
RULE_CHOOSELEAF_FIRSTN = 6
RULE_CHOOSELEAF_INDEP = 7
RULE_SET_CHOOSE_TRIES = 8
RULE_SET_CHOOSELEAF_TRIES = 9

ITEM_NONE = 0x7FFFFFFF  # reference: crush.h CRUSH_ITEM_NONE
ITEM_UNDEF = 0x7FFFFFFE

_STEP_NAMES = {
    RULE_TAKE: "take",
    RULE_CHOOSE_FIRSTN: "choose firstn",
    RULE_CHOOSE_INDEP: "choose indep",
    RULE_EMIT: "emit",
    RULE_CHOOSELEAF_FIRSTN: "chooseleaf firstn",
    RULE_CHOOSELEAF_INDEP: "chooseleaf indep",
    RULE_SET_CHOOSE_TRIES: "set_choose_tries",
    RULE_SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries",
}


@dataclass
class Bucket:
    """One interior node of the hierarchy.

    ``weights`` are per-item 16.16 fixed point; the bucket's own weight is
    their sum (straw2 draws only consult per-item weights).
    """

    id: int  # negative
    type: int  # 0 is reserved for devices
    alg: int = BUCKET_STRAW2
    items: List[int] = field(default_factory=list)
    weights: List[int] = field(default_factory=list)
    name: str = ""

    def __post_init__(self) -> None:
        if self.id >= 0:
            raise ValueError("bucket ids must be negative")
        if len(self.items) != len(self.weights):
            raise ValueError("items/weights length mismatch")

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.weights)

    def add_item(self, item: int, weight: int) -> None:
        self.items.append(item)
        self.weights.append(weight)
        # derived-array caches (tree node weights, straw lengths)
        self._tree_cache = None
        self._straw_cache = None

    def items_array(self) -> np.ndarray:
        return np.asarray(self.items, dtype=np.int64)

    def weights_array(self) -> np.ndarray:
        return np.asarray(self.weights, dtype=np.int64)

    # -- tree bucket (reference builder.c crush_make_tree_bucket) ----------

    def tree_node_weights(self) -> np.ndarray:
        """node_weights over the 2^depth binary-tree labels (items at
        odd nodes via crush_calc_tree_node(i) = ((i+1)<<1)-1; each
        ancestor holds its subtree's weight sum).  Cached: do_rule
        draws per replica per retry, and the reference computes this
        once at map build (builder.c crush_make_tree_bucket)."""
        cached = getattr(self, "_tree_cache", None)
        if cached is not None:
            return cached
        size = self.size
        if size == 0:
            return np.zeros(0, dtype=np.int64)
        depth = 1
        t = size - 1
        while t:
            t >>= 1
            depth += 1
        nw = np.zeros(1 << depth, dtype=np.int64)
        for i, w in enumerate(self.weights):
            node = ((i + 1) << 1) - 1
            nw[node] = w
            for _ in range(1, depth):
                node = _tree_parent(node)
                nw[node] += w
        self._tree_cache = nw
        return nw

    # -- legacy straw1 (builder.c crush_calc_straw, calc version 1) --------

    def straws(self) -> np.ndarray:
        """Per-item straw lengths (16.16) for the legacy straw bucket,
        per the hammer straw_calc_version=1 recipe: ascending-weight
        walk, each weight step scales the remaining straws by
        (1/pbelow)^(1/numleft).  Cached like the tree node weights."""
        import math

        cached = getattr(self, "_straw_cache", None)
        if cached is not None:
            return cached
        size = self.size
        straws = np.zeros(size, dtype=np.int64)
        order = sorted(range(size), key=lambda i: self.weights[i])
        numleft = size
        straw = 1.0
        wbelow = 0.0
        lastw = 0.0
        i = 0
        while i < size:
            if self.weights[order[i]] == 0:
                straws[order[i]] = 0
                i += 1
                numleft -= 1
                continue
            straws[order[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            wbelow += (float(self.weights[order[i - 1]]) - lastw) * numleft
            numleft -= 1
            wnext = numleft * (self.weights[order[i]]
                               - self.weights[order[i - 1]])
            pbelow = wbelow / (wbelow + wnext) if (wbelow + wnext) else 1.0
            if pbelow > 0:
                straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(self.weights[order[i - 1]])
        self._straw_cache = straws
        return straws


def _tree_parent(n: int) -> int:
    """Parent in the tree bucket's node labelling (builder.c parent())."""
    h = 0
    t = n
    while (t & 1) == 0:
        h += 1
        t >>= 1
    if n & (1 << (h + 1)):  # on the right of its parent
        return n - (1 << h)
    return n + (1 << h)


@dataclass
class Step:
    op: int
    arg1: int = 0
    arg2: int = 0

    def __str__(self) -> str:
        return f"{_STEP_NAMES.get(self.op, self.op)} {self.arg1} {self.arg2}"


@dataclass
class Rule:
    steps: List[Step]
    name: str = ""
    # reference rules carry min_size/max_size; unused by do_rule itself.


class CrushMap:
    """The placement map: devices + bucket hierarchy + rules.

    ``max_device`` bounds device ids (reference: crush_map.max_devices);
    out-ness is controlled by the per-device ``device_weights`` vector the
    caller passes to :func:`ceph_tpu.crush.mapper.do_rule` (reference passes
    the osdmap's weights the same way, OSDMap.cc crush->do_rule call sites).
    """

    def __init__(self) -> None:
        self.buckets: Dict[int, Bucket] = {}
        self.rules: List[Rule] = []
        self.max_device = 0
        self.type_names: Dict[int, str] = {0: "osd"}
        self._next_id = -1

    # -- construction ------------------------------------------------------

    def new_bucket(
        self,
        type: int,
        alg: int = BUCKET_STRAW2,
        name: str = "",
        id: Optional[int] = None,
    ) -> Bucket:
        if id is None:
            id = self._next_id
        b = Bucket(id=id, type=type, alg=alg, name=name)
        if id in self.buckets:
            raise ValueError(f"duplicate bucket id {id}")
        self.buckets[id] = b
        self._next_id = min(self.buckets) - 1
        return b

    def note_device(self, dev: int) -> None:
        self.max_device = max(self.max_device, dev + 1)

    def add_rule(self, rule: Rule) -> int:
        self.rules.append(rule)
        return len(self.rules) - 1

    def bucket_by_name(self, name: str) -> Bucket:
        for b in self.buckets.values():
            if b.name == name:
                return b
        raise KeyError(name)

    # -- introspection (CrushWrapper-lite) ---------------------------------

    def dump(self) -> dict:
        return {
            "max_device": self.max_device,
            "buckets": [
                {
                    "id": b.id,
                    "name": b.name,
                    "type": b.type,
                    "alg": {BUCKET_UNIFORM: "uniform", BUCKET_LIST: "list",
                            BUCKET_TREE: "tree", BUCKET_STRAW: "straw",
                            BUCKET_STRAW2: "straw2"}.get(b.alg, b.alg),
                    "items": [
                        {"id": i, "weight": w / 0x10000}
                        for i, w in zip(b.items, b.weights)
                    ],
                }
                for b in sorted(self.buckets.values(), key=lambda b: -b.id)
            ],
            "rules": [
                {"rule_id": i, "name": r.name, "steps": [str(s) for s in r.steps]}
                for i, r in enumerate(self.rules)
            ],
        }


def weight_fp(w: float) -> int:
    """Float weight -> 16.16 fixed point."""
    return int(round(w * 0x10000))


def build_flat_map(
    n_osds: int, weights: Optional[Sequence[float]] = None
) -> Tuple[CrushMap, int]:
    """One straw2 root holding all OSDs. Returns (map, root_id)."""
    m = CrushMap()
    root = m.new_bucket(type=1, name="root")
    m.type_names[1] = "root"
    for i in range(n_osds):
        w = weight_fp(weights[i]) if weights is not None else 0x10000
        root.add_item(i, w)
        m.note_device(i)
    return m, root.id


def build_hierarchy(
    hosts: Sequence[Sequence[int]],
    weights: Optional[Dict[int, float]] = None,
) -> Tuple[CrushMap, int]:
    """root -> host buckets -> osds (the canonical 2-level tree).

    ``hosts`` is a list of osd-id lists, one per host.  Returns
    (map, root_id); host buckets get type 2 ("host"), root type 3 ("root").
    """
    m = CrushMap()
    m.type_names.update({2: "host", 3: "root"})
    root = m.new_bucket(type=3, name="root", id=-1)
    next_id = -2
    for hi, osds in enumerate(hosts):
        hb = m.new_bucket(type=2, name=f"host{hi}", id=next_id)
        next_id -= 1
        for o in osds:
            w = weight_fp(weights.get(o, 1.0)) if weights else 0x10000
            hb.add_item(o, w)
            m.note_device(o)
        root.add_item(hb.id, hb.weight)
    return m, root.id


def replicated_rule(root_id: int, leaf_type: int = 0) -> Rule:
    """firstn rule: N distinct leaves (reference: default replicated_rule)."""
    steps = [Step(RULE_TAKE, root_id)]
    if leaf_type:
        steps.append(Step(RULE_CHOOSELEAF_FIRSTN, 0, leaf_type))
    else:
        steps.append(Step(RULE_CHOOSE_FIRSTN, 0, 0))
    steps.append(Step(RULE_EMIT))
    return Rule(steps, name="replicated")


def erasure_rule(
    root_id: int, failure_domain_type: int = 0, tries: int = 100
) -> Rule:
    """indep rule with positional holes, as ErasureCode::create_rule builds
    (reference: src/erasure-code/ErasureCode.cc:54-73 — set_chooseleaf_tries 5,
    take root, chooseleaf indep 0 type <domain>, emit; "indep" mode keeps
    surviving shards at their positions when one is unmappable)."""
    steps = [
        Step(RULE_SET_CHOOSELEAF_TRIES, 5),
        Step(RULE_SET_CHOOSE_TRIES, tries),
        Step(RULE_TAKE, root_id),
    ]
    if failure_domain_type:
        steps.append(Step(RULE_CHOOSELEAF_INDEP, 0, failure_domain_type))
    else:
        steps.append(Step(RULE_CHOOSE_INDEP, 0, 0))
    steps.append(Step(RULE_EMIT))
    return Rule(steps, name="erasure")
