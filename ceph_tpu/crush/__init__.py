"""CRUSH placement (TPU-native framework port of the reference's C core).

Reference: src/crush/mapper.c (crush_do_rule, crush_choose_firstn/indep,
bucket_straw2_choose), src/crush/hash.c (rjenkins1), src/crush/crush.h
(bucket algorithms).  Reimplemented from the published CRUSH algorithm
(Weil et al., SC'06) and the straw2 exponential-draw derivation; the
fixed-point log table of the reference is replaced by direct 2^44*log2
fixed-point arithmetic (semantic, not bit, parity — see docs/crush.md).
"""

from ceph_tpu.crush.hash import crush_hash32, crush_hash32_2, crush_hash32_3
from ceph_tpu.crush.map import (
    Bucket,
    CrushMap,
    Rule,
    Step,
    build_flat_map,
    build_hierarchy,
)
from ceph_tpu.crush.mapper import Tunables, do_rule

__all__ = [
    "Bucket",
    "CrushMap",
    "Rule",
    "Step",
    "Tunables",
    "build_flat_map",
    "build_hierarchy",
    "crush_hash32",
    "crush_hash32_2",
    "crush_hash32_3",
    "do_rule",
]
