"""Multi-active MDS cluster: subtree partitioning + balancer.

Reference: a multi-MDS CephFS partitions the directory tree over active
ranks by SUBTREE AUTHORITY (src/mds/MDCache.cc subtree map, exports via
src/mds/Migrator.cc) and rebalances hot subtrees between ranks with the
MDBalancer (src/mds/MDBalancer.cc mds_load / try_rebalance).

This subset keeps the same authority model over the shared metadata
pool: every rank is a full ``MDS`` with its OWN journal and ino table
(``mds<rank>_*``), mutations on a path are serialized by the rank that
owns its subtree, and the subtree map itself is a replicated omap object
so a restarted coordinator (or a standby taking over a rank) sees the
same partition.  Cross-subtree renames journal the unlink in the source
rank and the link in the destination rank under both ranks' locks in
rank order (the reference's two-phase Migrator rename, reduced: our
dentries live in shared RADOS omaps, so no inode data moves).

The balancer is the MDBalancer reduced to its decision rule: per-subtree
request counters; when the busiest rank carries more than
``rebalance_factor`` times the load of the idlest, its hottest
non-root subtree is exported to the idlest rank.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ceph_tpu.mds.mds import MDS, _dec, _enc

SUBTREE_MAP_OID = "mds_subtree_map"


class MultiMDS:
    """N active MDS ranks over one metadata pool."""

    def __init__(self, backend, n_ranks: int = 2,
                 rebalance_factor: float = 2.0):
        assert n_ranks >= 1
        self.backend = backend
        self.ranks: List[MDS] = [MDS(backend, rank=r)
                                 for r in range(n_ranks)]
        #: subtree authority: top-level path prefix -> rank ("" = root,
        #: always rank 0 -- the reference pins root to rank 0 too)
        self.subtrees: Dict[str, int] = {"": 0}
        #: per-subtree request counters (MDBalancer mds_load input)
        self.load: Dict[str, int] = {}
        self.rebalance_factor = rebalance_factor

    async def start(self) -> None:
        # rank 0 creates the root; later ranks only replay their journal
        for mds in self.ranks:
            await mds.start()
        try:
            raw = await self.backend.omap_get(SUBTREE_MAP_OID)
        except (FileNotFoundError, IOError):
            raw = {}
        for prefix, rank_b in raw.items():
            rank = int(_dec(rank_b))
            if rank < len(self.ranks):
                self.subtrees["" if prefix == "/" else prefix] = rank

    # -- subtree authority (MDCache subtree map role) ----------------------

    @staticmethod
    def _top(path: str) -> str:
        parts = [p for p in path.split("/") if p and p != "."]
        return parts[0] if parts else ""

    def rank_of(self, path: str) -> int:
        """The rank with authority over ``path``'s subtree."""
        return self.subtrees.get(self._top(path), self.subtrees[""])

    def _route(self, path: str) -> MDS:
        top = self._top(path)
        self.load[top] = self.load.get(top, 0) + 1
        mds = self.ranks[self.rank_of(path)]
        mds.op_count += 1
        return mds

    async def export_subtree(self, path: str, rank: int) -> None:
        """Move a top-level subtree's authority to ``rank`` (the
        Migrator export, reduced to an authority handoff: dentries live
        in shared RADOS omaps, so no data migrates)."""
        if not 0 <= rank < len(self.ranks):
            raise ValueError(f"no rank {rank}")
        top = self._top(path)
        if not top:
            raise ValueError("root stays on rank 0")
        # serialize against in-flight ops of the CURRENT authority: an
        # export mid-mutation would let two ranks mutate one subtree
        old = self.ranks[self.rank_of(path)]
        async with old._mutate_lock:
            self.subtrees[top] = rank
            await self.backend.omap_set(
                SUBTREE_MAP_OID, {top: _enc(rank)})

    async def balance(self) -> Optional[str]:
        """One MDBalancer pass: if the busiest rank carries >
        rebalance_factor x the idlest's load, export its hottest
        subtree there.  Returns the exported subtree or None."""
        per_rank: Dict[int, int] = {r: 0 for r in range(len(self.ranks))}
        for top, n in self.load.items():
            per_rank[self.subtrees.get(top, 0)] += n
        busiest = max(per_rank, key=per_rank.get)
        idlest = min(per_rank, key=per_rank.get)
        if busiest == idlest or per_rank[busiest] <= \
                self.rebalance_factor * max(1, per_rank[idlest]):
            return None
        candidates = [
            (n, top) for top, n in self.load.items()
            if top and self.subtrees.get(top, 0) == busiest
        ]
        if not candidates:
            return None
        _n, top = max(candidates)
        await self.export_subtree(top, idlest)
        self.load[top] = 0  # exported load starts fresh on the new rank
        return top

    # -- the FS surface, routed by subtree authority -----------------------

    async def mkdir(self, path: str) -> int:
        return await self._route(path).mkdir(path)

    async def create(self, path: str, **kw) -> dict:
        return await self._route(path).create(path, **kw)

    async def readdir(self, path: str):
        return await self._route(path).readdir(path)

    async def stat(self, path: str) -> dict:
        return await self._route(path).stat(path)

    async def set_size(self, path: str, size: int) -> None:
        await self._route(path).set_size(path, size)

    async def unlink(self, path: str) -> dict:
        return await self._route(path).unlink(path)

    async def rmdir(self, path: str) -> dict:
        return await self._route(path).rmdir(path)

    async def symlink(self, path: str, target: str) -> None:
        await self._route(path).symlink(path, target)

    async def readlink(self, path: str) -> str:
        return await self._route(path).readlink(path)

    async def setxattr(self, path: str, name: str, value: bytes) -> None:
        await self._route(path).setxattr(path, name, value)

    async def getxattrs(self, path: str):
        return await self._route(path).getxattrs(path)

    async def resolve_full(self, path: str, **kw):
        return await self._route(path).resolve_full(path, **kw)

    async def rename(self, src: str, dst: str) -> None:
        """Same-subtree renames run on the owning rank; cross-subtree
        renames take both ranks' mutation locks in rank order and
        journal the unlink on the source rank, the link on the
        destination rank (the Migrator rename, reduced -- see module
        docstring)."""
        a, b = self.rank_of(src), self.rank_of(dst)
        if a == b:
            await self._route(src).rename(src, dst)
            return
        from ceph_tpu.mds.mds import FSError

        src_mds, dst_mds = self.ranks[a], self.ranks[b]
        first, second = sorted((src_mds, dst_mds), key=lambda m: m.rank)
        async with first._mutate_lock:
            async with second._mutate_lock:
                src_dir, src_name, dent = await src_mds.resolve_full(
                    src, follow=False)
                if dent is None:
                    raise FSError(
                        2, f"no such file or directory: {src!r}")
                dst_dir, dst_name, existing = await dst_mds.resolve_full(
                    dst, follow=False)
                if existing is not None:
                    raise FSError(17, f"exists: {dst!r}")
                # destination link journals on the DESTINATION rank,
                # then the source unlink on the SOURCE rank -- the same
                # link-before-unlink crash ordering as a local rename
                await dst_mds._journal_and_apply({
                    "op": "link", "dir": dst_dir, "name": dst_name,
                    "dentry": dent,
                })
                await src_mds._journal_and_apply({
                    "op": "unlink", "dir": src_dir, "name": src_name,
                })
