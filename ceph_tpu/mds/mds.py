"""MDS rank 0: the metadata server (reference src/mds).

State layout in the metadata pool (all through an Objecter, so the
namespace inherits EC durability, recovery and scrub):

* ``mds0_inotable``       omap {"next": int}        InoTable role
* ``mds0_journal``        omap {seq16: event}       MDLog/LogEvent role
*                         omap {"_committed": seq}  expire pointer
* ``<ino-hex>.dir``       omap {name: dentry}       CDir role

A dentry embeds its inode (CephFS primary-dentry embedding):
``{"ino", "type": "f"|"d", "size", "mtime", "layout": [su, sc, osz]}``.

Every mutation is journaled before application and applied with
idempotent operations, so replay after a crash (or by a standby taking
over) converges -- the up:replay state.  A single MDS serializes
mutations behind one asyncio lock (the reference serializes through the
MDCache locker at rank granularity).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.utils.encoding import Decoder, Encoder

ROOT_INO = 1
#: rank-0 names kept for compatibility; instances use their
#: own self.journal_oid / self.inotable_oid (per-rank MDLog)
INOTABLE = "mds0_inotable"
JOURNAL = "mds0_journal"
COMMITTED_KEY = "_committed"
DEFAULT_LAYOUT = (1 << 20, 1, 1 << 20)  # (stripe_unit, count, object_size)


def dir_oid(ino: int) -> str:
    return f"{ino:x}.dir"


def data_oid(ino: int, objno: int) -> str:
    return f"{ino:x}.{objno:08x}"


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b: bytes):
    return Decoder(b).value()


class FSError(OSError):
    pass


class MDS:
    """One metadata-server rank over a RADOS backend (an Objecter).

    Rank 0 is the historical single-MDS shape; a multi-active cluster
    (``ceph_tpu.mds.multimds.MultiMDS``) runs several ranks, each with
    its own journal and ino table (``mds<rank>_journal`` /
    ``mds<rank>_inotable`` -- the reference's per-rank MDLog and
    InoTable, src/mds/MDLog.cc, src/mds/InoTable.cc), serialized
    independently, with the namespace partitioned by subtree."""

    def __init__(self, backend, rank: int = 0):
        self.backend = backend
        self.rank = rank
        self.journal_oid = f"mds{rank}_journal"
        self.inotable_oid = f"mds{rank}_inotable"
        self._mutate_lock = asyncio.Lock()
        self._journal_seq = 0
        self.replayed = 0  # events replayed at the last start()
        self.op_count = 0  # balancer load metric (MDBalancer mds_load)

    # -- boot / journal replay (up:replay -> up:active) --------------------

    async def start(self) -> None:
        """Create the root on a fresh filesystem; replay the journal
        tail left by a crashed predecessor; trim it."""
        omap = await self.backend.omap_get(self.journal_oid)
        committed = int(
            _dec(omap[COMMITTED_KEY]) if COMMITTED_KEY in omap else 0
        )
        events = sorted(
            (int(k), _dec(v)) for k, v in omap.items()
            if k != COMMITTED_KEY
        )
        self.replayed = 0
        # new seqs must stay above the committed pointer even when the
        # journal is empty, else a fresh MDS reuses low seqs and its own
        # crash-recovery filter would skip them (review finding)
        self._journal_seq = max(self._journal_seq, committed)
        for seq, ev in events:
            self._journal_seq = max(self._journal_seq, seq)
            if seq > committed:
                await self._apply(ev)
                self.replayed += 1
        if events:
            await self._trim(max(s for s, _ in events))
        root = await self.backend.omap_get(dir_oid(ROOT_INO))
        if "." not in root:
            await self.backend.omap_set(dir_oid(ROOT_INO), {
                ".": _enc(self._mkdentry(ROOT_INO, "d")),
            })

    # -- inode allocation (InoTable) ---------------------------------------

    async def _alloc_ino(self) -> int:
        while True:
            cur = await self.backend.omap_get(self.inotable_oid, ["next"])
            have = int(_dec(cur["next"])) if "next" in cur else ROOT_INO + 1
            ok, _ = await self.backend.omap_cas(
                self.inotable_oid, "next",
                cur.get("next"), _enc(have + 1),
            )
            if ok:
                return have

    # -- journal -----------------------------------------------------------

    async def _journal_and_apply(self, ev: dict) -> None:
        """MDLog contract: the event is durable in the journal BEFORE the
        directory objects change; apply is idempotent for replay."""
        self._journal_seq += 1
        seq = self._journal_seq
        await self.backend.omap_set(self.journal_oid,
                                    {f"{seq:016d}": _enc(ev)})
        await self._apply(ev)
        await self._trim(seq, keys=[f"{seq:016d}"])

    async def _trim(self, upto: int, keys=None) -> None:
        """Advance the committed pointer and drop applied events (MDLog
        trim/expire).  The hot path passes the exact keys it just
        journaled; replay passes None and pays one full scan."""
        if keys is None:
            omap = await self.backend.omap_get(self.journal_oid)
            keys = [k for k in omap
                    if k != COMMITTED_KEY and int(k) <= upto]
        await self.backend.omap_set(self.journal_oid,
                                    {COMMITTED_KEY: _enc(upto)})
        if keys:
            await self.backend.omap_rm(self.journal_oid, keys)

    async def _apply(self, ev: dict) -> None:
        op = ev["op"]
        if op == "link":  # create dentry (mkdir/create/rename-target)
            await self.backend.omap_set(
                dir_oid(ev["dir"]), {ev["name"]: _enc(ev["dentry"])}
            )
            if ev["dentry"]["type"] == "d":
                await self.backend.omap_set(dir_oid(ev["dentry"]["ino"]), {
                    ".": _enc(self._mkdentry(ev["dentry"]["ino"], "d")),
                })
        elif op == "unlink":
            await self.backend.omap_rm(dir_oid(ev["dir"]), [ev["name"]])
        elif op == "setattr":
            cur = await self.backend.omap_get(dir_oid(ev["dir"]),
                                              [ev["name"]])
            if ev["name"] in cur:
                d = _dec(cur[ev["name"]])
                d.update(ev["attrs"])
                await self.backend.omap_set(
                    dir_oid(ev["dir"]), {ev["name"]: _enc(d)}
                )
        elif op == "xattr":
            # user xattrs ride in the dentry next to the embedded inode
            # (the reference's CInode xattr map); idempotent merge/erase
            cur = await self.backend.omap_get(dir_oid(ev["dir"]),
                                              [ev["name"]])
            if ev["name"] in cur:
                d = _dec(cur[ev["name"]])
                xattrs = d.get("xattrs", {})
                xattrs.update(ev.get("set", {}))
                for k in ev.get("rm", []):
                    xattrs.pop(k, None)
                d["xattrs"] = xattrs
                await self.backend.omap_set(
                    dir_oid(ev["dir"]), {ev["name"]: _enc(d)}
                )
        else:
            raise ValueError(f"unknown journal op {op!r}")

    # -- path resolution (MDCache::path_traverse) --------------------------

    @staticmethod
    def _split(path: str) -> List[str]:
        return [p for p in path.split("/") if p and p != "."]

    def _mkdentry(self, ino: int, typ: str, size: int = 0,
                  layout=DEFAULT_LAYOUT) -> dict:
        return {"ino": ino, "type": typ, "size": size,
                "mtime": int(time.time()), "layout": list(layout)}

    async def resolve(self, path: str, follow: bool = True,
                      _depth: int = 0) -> Tuple[int, Optional[dict]]:
        """-> (parent dir ino, dentry|None for the final component)."""
        parent, _name, dentry = await self.resolve_full(
            path, follow=follow, _depth=_depth)
        return parent, dentry

    async def resolve_full(self, path: str, follow: bool = True,
                           _depth: int = 0,
                           _chain: Optional[List[int]] = None
                           ) -> Tuple[int, str, Optional[dict]]:
        """-> (parent dir ino, RESOLVED final name, dentry|None); the
        root resolves to (ROOT_INO, ".", its self dentry).  Symlinks in
        the MIDDLE of a path are always followed; a final-component
        symlink only when ``follow`` (lstat vs stat).  Mutators MUST
        journal under the resolved name: after following a final
        symlink the real dentry lives in the TARGET's directory under
        the TARGET's name, and journaling the original link name would
        silently no-op on replay."""
        if _depth > 8:
            raise FSError(40, f"too many symlinks resolving {path!r}")
        parts = self._split(path)
        if _chain is not None and ROOT_INO not in _chain:
            _chain.append(ROOT_INO)  # collects every traversed dir ino
        if not parts:
            root = await self.backend.omap_get(dir_oid(ROOT_INO), ["."])
            return ROOT_INO, ".", _dec(root["."])
        cur = ROOT_INO
        for i, name in enumerate(parts):
            ent = await self.backend.omap_get(dir_oid(cur), [name])
            if name not in ent:
                if i == len(parts) - 1:
                    return cur, name, None
                raise FSError(2, f"no such directory: {name!r} in {path!r}")
            dentry = _dec(ent[name])
            last = i == len(parts) - 1
            if dentry["type"] == "l" and (follow or not last):
                rest = "/".join(parts[i + 1:])
                target = dentry["target"]
                newpath = target + ("/" + rest if rest else "")
                return await self.resolve_full(newpath, follow=follow,
                                               _depth=_depth + 1,
                                               _chain=_chain)
            if last:
                return cur, name, dentry
            if dentry["type"] != "d":
                raise FSError(20, f"not a directory: {name!r}")
            cur = dentry["ino"]
            if _chain is not None:
                _chain.append(cur)
        raise AssertionError("unreachable")

    async def _resolve_dir(self, path: str) -> int:
        _, dentry = await self.resolve(path)
        if dentry is None:
            raise FSError(2, f"no such file or directory: {path!r}")
        if dentry["type"] != "d":
            raise FSError(20, f"not a directory: {path!r}")
        return dentry["ino"]

    # -- metadata ops (Server::handle_client_request dispatch) -------------

    async def mkdir(self, path: str) -> int:
        async with self._mutate_lock:
            parent, name, existing = await self.resolve_full(path)
            if existing is not None:
                raise FSError(17, f"exists: {path!r}")
            ino = await self._alloc_ino()
            dentry = self._mkdentry(ino, "d")
            await self._journal_and_apply(
                {"op": "link", "dir": parent, "name": name,
                 "dentry": dentry}
            )
            return ino

    async def create(self, path: str, layout=DEFAULT_LAYOUT) -> dict:
        async with self._mutate_lock:
            parent, name, existing = await self.resolve_full(path)
            if existing is not None:
                if existing["type"] == "d":
                    raise FSError(21, f"is a directory: {path!r}")
                return existing  # open-existing semantics
            if not name or name == ".":
                raise FSError(22, "empty file name")
            ino = await self._alloc_ino()
            dentry = self._mkdentry(ino, "f", layout=layout)
            await self._journal_and_apply(
                {"op": "link", "dir": parent, "name": name,
                 "dentry": dentry}
            )
            return dentry

    async def readdir(self, path: str) -> Dict[str, dict]:
        ino = await self._resolve_dir(path)
        omap = await self.backend.omap_get(dir_oid(ino))
        return {
            name: _dec(raw) for name, raw in omap.items() if name != "."
        }

    async def stat(self, path: str) -> dict:
        _, dentry = await self.resolve(path)
        if dentry is None:
            raise FSError(2, f"no such file or directory: {path!r}")
        return dentry

    async def set_size(self, path: str, size: int) -> None:
        async with self._mutate_lock:
            parent, name, dentry = await self.resolve_full(path)
            if dentry is None:
                raise FSError(2, f"no such file: {path!r}")
            await self._journal_and_apply({
                "op": "setattr", "dir": parent, "name": name,
                "attrs": {"size": size, "mtime": int(time.time())},
            })

    async def unlink(self, path: str) -> dict:
        """Remove a FILE (or symlink) dentry; returns it (caller purges
        data objects -- the reference strays/purge queue role lives
        client-side here).  Never follows a final symlink: unlink
        removes the link, not its target."""
        async with self._mutate_lock:
            parent, name, dentry = await self.resolve_full(
                path, follow=False)
            if dentry is None:
                raise FSError(2, f"no such file: {path!r}")
            if dentry["type"] == "d":
                raise FSError(21, f"is a directory: {path!r}")
            await self._journal_and_apply(
                {"op": "unlink", "dir": parent, "name": name}
            )
            if dentry["type"] == "f":
                await self._purge_flock(dentry["ino"])
            return dentry

    async def rmdir(self, path: str) -> dict:
        """Remove an empty directory; returns its dentry.  Never
        follows a final symlink: POSIX rmdir on a symlink is ENOTDIR,
        not a deletion of the target directory."""
        async with self._mutate_lock:
            parent, name, dentry = await self.resolve_full(
                path, follow=False)
            if dentry is None:
                raise FSError(2, f"no such directory: {path!r}")
            if dentry["type"] != "d":
                raise FSError(20, f"not a directory: {path!r}")
            entries = await self.backend.omap_get(dir_oid(dentry["ino"]))
            if set(entries) - {"."}:
                raise FSError(39, f"directory not empty: {path!r}")
            await self._journal_and_apply(
                {"op": "unlink", "dir": parent, "name": name}
            )
            await self._purge_flock(dentry["ino"])
            return dentry

    async def symlink(self, path: str, target: str) -> None:
        """Create a symbolic link (Server::handle_client_symlink).
        Targets are absolute paths within this filesystem."""
        async with self._mutate_lock:
            parent, name, existing = await self.resolve_full(
                path, follow=False)
            if existing is not None:
                raise FSError(17, f"exists: {path!r}")
            ino = await self._alloc_ino()
            dentry = self._mkdentry(ino, "l")
            dentry["target"] = target
            await self._journal_and_apply(
                {"op": "link", "dir": parent, "name": name,
                 "dentry": dentry}
            )

    async def readlink(self, path: str) -> str:
        _, dentry = await self.resolve(path, follow=False)
        if dentry is None:
            raise FSError(2, f"no such file or directory: {path!r}")
        if dentry["type"] != "l":
            raise FSError(22, f"not a symlink: {path!r}")
        return dentry["target"]

    # -- user xattrs (CInode xattr map; Server::handle_set/removexattr) ----

    async def setxattr(self, path: str, name: str, value: bytes) -> None:
        async with self._mutate_lock:
            parent, rname, dentry = await self.resolve_full(path)
            if dentry is None:
                raise FSError(2, f"no such file or directory: {path!r}")
            await self._journal_and_apply({
                "op": "xattr", "dir": parent, "name": rname,
                "set": {name: bytes(value)},
            })

    async def removexattr(self, path: str, name: str) -> None:
        async with self._mutate_lock:
            parent, rname, dentry = await self.resolve_full(path)
            if dentry is None:
                raise FSError(2, f"no such file or directory: {path!r}")
            if name not in dentry.get("xattrs", {}):
                raise FSError(61, f"no xattr {name!r} on {path!r}")
            await self._journal_and_apply({
                "op": "xattr", "dir": parent, "name": rname,
                "rm": [name],
            })

    async def getxattrs(self, path: str) -> Dict[str, bytes]:
        _, dentry = await self.resolve(path)
        if dentry is None:
            raise FSError(2, f"no such file or directory: {path!r}")
        return dict(dentry.get("xattrs", {}))

    # -- advisory file locks (reference src/mds/flock.cc, setfilelock) -----

    def _flock_oid(self, ino: int) -> str:
        return f"{ino:x}.flock"

    async def _purge_flock(self, ino: int) -> None:
        """Drop an inode's lock object with it (runs under the mutate
        lock, so a racing flock cannot recreate it after the purge)."""
        try:
            await self.backend.omap_clear(self._flock_oid(ino))
            await self.backend.remove_object(self._flock_oid(ino))
        except (FileNotFoundError, IOError):
            pass  # never locked

    async def flock(self, path: str, owner: str,
                    exclusive: bool = True) -> None:
        """Acquire an advisory lock; -EAGAIN (BlockingIOError) on
        conflict -- shared locks coexist, exclusive conflicts with
        everything (the ceph_flock semantics, non-blocking form).
        Serialized under the mutate lock so a lock can never be taken
        on (or recreated for) an inode mid-unlink."""
        async with self._mutate_lock:
            await self._flock_locked(path, owner, exclusive)

    async def _flock_locked(self, path: str, owner: str,
                            exclusive: bool) -> None:
        _, dentry = await self.resolve(path)
        if dentry is None:
            raise FSError(2, f"no such file: {path!r}")
        oid = self._flock_oid(dentry["ino"])
        for _ in range(16):
            cur = await self.backend.omap_get(oid)
            raw = cur.get("holders")
            holders = _dec(raw) if raw else {}
            mode = "x" if exclusive else "s"
            others = {o: m for o, m in holders.items() if o != owner}
            if mode == "x" and others:
                raise BlockingIOError(
                    11, f"{path!r} locked by {sorted(others)}")
            if mode == "s" and any(m == "x" for m in others.values()):
                raise BlockingIOError(
                    11, f"{path!r} exclusively locked")
            holders[owner] = mode
            ok, _ = await self.backend.omap_cas(
                oid, "holders", raw, _enc(holders))
            if ok:
                return
        raise FSError(11, f"flock contended on {path!r}")

    async def funlock(self, path: str, owner: str) -> None:
        async with self._mutate_lock:
            await self._funlock_locked(path, owner)

    async def _funlock_locked(self, path: str, owner: str) -> None:
        _, dentry = await self.resolve(path)
        if dentry is None:
            raise FSError(2, f"no such file: {path!r}")
        oid = self._flock_oid(dentry["ino"])
        for _ in range(16):
            cur = await self.backend.omap_get(oid)
            raw = cur.get("holders")
            holders = _dec(raw) if raw else {}
            if owner not in holders:
                return
            del holders[owner]
            ok, _ = await self.backend.omap_cas(
                oid, "holders", raw, _enc(holders))
            if ok:
                return
        raise FSError(11, f"funlock contended on {path!r}")

    async def rename(self, src: str, dst: str) -> None:
        """Journaled as link(dst)+unlink(src): replay-idempotent and in
        that order, so a crash between them leaves a hard-link-like
        state, never a lost file (the reference journals both halves in
        one EUpdate)."""
        async with self._mutate_lock:
            sparent, sname, sdentry = await self.resolve_full(
                src, follow=False)
            if sdentry is None:
                raise FSError(2, f"no such file or directory: {src!r}")
            dparent, dname, ddentry = await self.resolve_full(
                dst, follow=False)
            if ddentry is not None:
                raise FSError(17, f"exists: {dst!r}")
            if sdentry["type"] == "d":
                # moving a directory under itself would orphan the
                # whole subtree behind an unreachable cycle (POSIX
                # EINVAL).  Checked on the RESOLVED ancestor-inode
                # chain of dst (symlink-proof, O(path depth)) -- a
                # textual prefix test would be defeated by an alias,
                # and a subtree scan would pay one read per
                # descendant directory.
                chain: List[int] = []
                await self.resolve_full(dst, follow=False, _chain=chain)
                if dparent == sdentry["ino"] or sdentry["ino"] in chain:
                    raise FSError(22, f"cannot move {src!r} into itself")
            await self._journal_and_apply({
                "op": "link", "dir": dparent,
                "name": dname, "dentry": sdentry,
            })
            await self._journal_and_apply({
                "op": "unlink", "dir": sparent, "name": sname,
            })

