"""MDS rank 0: the metadata server (reference src/mds).

State layout in the metadata pool (all through an Objecter, so the
namespace inherits EC durability, recovery and scrub):

* ``mds0_inotable``       omap {"next": int}        InoTable role
* ``mds0_journal``        omap {seq16: event}       MDLog/LogEvent role
*                         omap {"_committed": seq}  expire pointer
* ``<ino-hex>.dir``       omap {name: dentry}       CDir role

A dentry embeds its inode (CephFS primary-dentry embedding):
``{"ino", "type": "f"|"d", "size", "mtime", "layout": [su, sc, osz]}``.

Every mutation is journaled before application and applied with
idempotent operations, so replay after a crash (or by a standby taking
over) converges -- the up:replay state.  A single MDS serializes
mutations behind one asyncio lock (the reference serializes through the
MDCache locker at rank granularity).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.utils.encoding import Decoder, Encoder

ROOT_INO = 1
INOTABLE = "mds0_inotable"
JOURNAL = "mds0_journal"
COMMITTED_KEY = "_committed"
DEFAULT_LAYOUT = (1 << 20, 1, 1 << 20)  # (stripe_unit, count, object_size)


def dir_oid(ino: int) -> str:
    return f"{ino:x}.dir"


def data_oid(ino: int, objno: int) -> str:
    return f"{ino:x}.{objno:08x}"


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b: bytes):
    return Decoder(b).value()


class FSError(OSError):
    pass


class MDS:
    """Rank-0 metadata server over a RADOS backend (an Objecter)."""

    def __init__(self, backend):
        self.backend = backend
        self._mutate_lock = asyncio.Lock()
        self._journal_seq = 0
        self.replayed = 0  # events replayed at the last start()

    # -- boot / journal replay (up:replay -> up:active) --------------------

    async def start(self) -> None:
        """Create the root on a fresh filesystem; replay the journal
        tail left by a crashed predecessor; trim it."""
        omap = await self.backend.omap_get(JOURNAL)
        committed = int(
            _dec(omap[COMMITTED_KEY]) if COMMITTED_KEY in omap else 0
        )
        events = sorted(
            (int(k), _dec(v)) for k, v in omap.items()
            if k != COMMITTED_KEY
        )
        self.replayed = 0
        # new seqs must stay above the committed pointer even when the
        # journal is empty, else a fresh MDS reuses low seqs and its own
        # crash-recovery filter would skip them (review finding)
        self._journal_seq = max(self._journal_seq, committed)
        for seq, ev in events:
            self._journal_seq = max(self._journal_seq, seq)
            if seq > committed:
                await self._apply(ev)
                self.replayed += 1
        if events:
            await self._trim(max(s for s, _ in events))
        root = await self.backend.omap_get(dir_oid(ROOT_INO))
        if "." not in root:
            await self.backend.omap_set(dir_oid(ROOT_INO), {
                ".": _enc(self._mkdentry(ROOT_INO, "d")),
            })

    # -- inode allocation (InoTable) ---------------------------------------

    async def _alloc_ino(self) -> int:
        while True:
            cur = await self.backend.omap_get(INOTABLE, ["next"])
            have = int(_dec(cur["next"])) if "next" in cur else ROOT_INO + 1
            ok, _ = await self.backend.omap_cas(
                INOTABLE, "next",
                cur.get("next"), _enc(have + 1),
            )
            if ok:
                return have

    # -- journal -----------------------------------------------------------

    async def _journal_and_apply(self, ev: dict) -> None:
        """MDLog contract: the event is durable in the journal BEFORE the
        directory objects change; apply is idempotent for replay."""
        self._journal_seq += 1
        seq = self._journal_seq
        await self.backend.omap_set(JOURNAL, {f"{seq:016d}": _enc(ev)})
        await self._apply(ev)
        await self._trim(seq, keys=[f"{seq:016d}"])

    async def _trim(self, upto: int, keys=None) -> None:
        """Advance the committed pointer and drop applied events (MDLog
        trim/expire).  The hot path passes the exact keys it just
        journaled; replay passes None and pays one full scan."""
        if keys is None:
            omap = await self.backend.omap_get(JOURNAL)
            keys = [k for k in omap
                    if k != COMMITTED_KEY and int(k) <= upto]
        await self.backend.omap_set(JOURNAL, {COMMITTED_KEY: _enc(upto)})
        if keys:
            await self.backend.omap_rm(JOURNAL, keys)

    async def _apply(self, ev: dict) -> None:
        op = ev["op"]
        if op == "link":  # create dentry (mkdir/create/rename-target)
            await self.backend.omap_set(
                dir_oid(ev["dir"]), {ev["name"]: _enc(ev["dentry"])}
            )
            if ev["dentry"]["type"] == "d":
                await self.backend.omap_set(dir_oid(ev["dentry"]["ino"]), {
                    ".": _enc(self._mkdentry(ev["dentry"]["ino"], "d")),
                })
        elif op == "unlink":
            await self.backend.omap_rm(dir_oid(ev["dir"]), [ev["name"]])
        elif op == "setattr":
            cur = await self.backend.omap_get(dir_oid(ev["dir"]),
                                              [ev["name"]])
            if ev["name"] in cur:
                d = _dec(cur[ev["name"]])
                d.update(ev["attrs"])
                await self.backend.omap_set(
                    dir_oid(ev["dir"]), {ev["name"]: _enc(d)}
                )
        else:
            raise ValueError(f"unknown journal op {op!r}")

    # -- path resolution (MDCache::path_traverse) --------------------------

    @staticmethod
    def _split(path: str) -> List[str]:
        return [p for p in path.split("/") if p and p != "."]

    def _mkdentry(self, ino: int, typ: str, size: int = 0,
                  layout=DEFAULT_LAYOUT) -> dict:
        return {"ino": ino, "type": typ, "size": size,
                "mtime": int(time.time()), "layout": list(layout)}

    async def resolve(self, path: str) -> Tuple[int, Optional[dict]]:
        """-> (parent dir ino, dentry|None for the final component);
        the root resolves to (ROOT_INO, its self dentry)."""
        parts = self._split(path)
        if not parts:
            root = await self.backend.omap_get(dir_oid(ROOT_INO), ["."])
            return ROOT_INO, _dec(root["."])
        cur = ROOT_INO
        for i, name in enumerate(parts):
            ent = await self.backend.omap_get(dir_oid(cur), [name])
            if name not in ent:
                if i == len(parts) - 1:
                    return cur, None
                raise FSError(2, f"no such directory: {name!r} in {path!r}")
            dentry = _dec(ent[name])
            if i == len(parts) - 1:
                return cur, dentry
            if dentry["type"] != "d":
                raise FSError(20, f"not a directory: {name!r}")
            cur = dentry["ino"]
        raise AssertionError("unreachable")

    async def _resolve_dir(self, path: str) -> int:
        _, dentry = await self.resolve(path)
        if dentry is None:
            raise FSError(2, f"no such file or directory: {path!r}")
        if dentry["type"] != "d":
            raise FSError(20, f"not a directory: {path!r}")
        return dentry["ino"]

    # -- metadata ops (Server::handle_client_request dispatch) -------------

    async def mkdir(self, path: str) -> int:
        async with self._mutate_lock:
            parent, existing = await self.resolve(path)
            if existing is not None:
                raise FSError(17, f"exists: {path!r}")
            name = self._split(path)[-1]
            ino = await self._alloc_ino()
            dentry = self._mkdentry(ino, "d")
            await self._journal_and_apply(
                {"op": "link", "dir": parent, "name": name,
                 "dentry": dentry}
            )
            return ino

    async def create(self, path: str, layout=DEFAULT_LAYOUT) -> dict:
        async with self._mutate_lock:
            parent, existing = await self.resolve(path)
            if existing is not None:
                if existing["type"] == "d":
                    raise FSError(21, f"is a directory: {path!r}")
                return existing  # open-existing semantics
            name = self._split(path)[-1]
            if not name:
                raise FSError(22, "empty file name")
            ino = await self._alloc_ino()
            dentry = self._mkdentry(ino, "f", layout=layout)
            await self._journal_and_apply(
                {"op": "link", "dir": parent, "name": name,
                 "dentry": dentry}
            )
            return dentry

    async def readdir(self, path: str) -> Dict[str, dict]:
        ino = await self._resolve_dir(path)
        omap = await self.backend.omap_get(dir_oid(ino))
        return {
            name: _dec(raw) for name, raw in omap.items() if name != "."
        }

    async def stat(self, path: str) -> dict:
        _, dentry = await self.resolve(path)
        if dentry is None:
            raise FSError(2, f"no such file or directory: {path!r}")
        return dentry

    async def set_size(self, path: str, size: int) -> None:
        async with self._mutate_lock:
            parent, dentry = await self.resolve(path)
            if dentry is None:
                raise FSError(2, f"no such file: {path!r}")
            name = self._split(path)[-1]
            await self._journal_and_apply({
                "op": "setattr", "dir": parent, "name": name,
                "attrs": {"size": size, "mtime": int(time.time())},
            })

    async def unlink(self, path: str) -> dict:
        """Remove a FILE dentry; returns it (caller purges data objects
        -- the reference strays/purge queue role lives client-side
        here)."""
        async with self._mutate_lock:
            parent, dentry = await self.resolve(path)
            if dentry is None:
                raise FSError(2, f"no such file: {path!r}")
            if dentry["type"] == "d":
                raise FSError(21, f"is a directory: {path!r}")
            name = self._split(path)[-1]
            await self._journal_and_apply(
                {"op": "unlink", "dir": parent, "name": name}
            )
            return dentry

    async def rmdir(self, path: str) -> None:
        async with self._mutate_lock:
            parent, dentry = await self.resolve(path)
            if dentry is None or dentry["type"] != "d":
                raise FSError(2, f"no such directory: {path!r}")
            entries = await self.backend.omap_get(dir_oid(dentry["ino"]))
            if set(entries) - {"."}:
                raise FSError(39, f"directory not empty: {path!r}")
            name = self._split(path)[-1]
            await self._journal_and_apply(
                {"op": "unlink", "dir": parent, "name": name}
            )

    async def rename(self, src: str, dst: str) -> None:
        """Journaled as link(dst)+unlink(src): replay-idempotent and in
        that order, so a crash between them leaves a hard-link-like
        state, never a lost file (the reference journals both halves in
        one EUpdate)."""
        async with self._mutate_lock:
            sparts = self._split(src)
            dparts = self._split(dst)
            if dparts[:len(sparts)] == sparts:
                # moving a directory under itself would orphan the whole
                # subtree behind an unreachable cycle (POSIX EINVAL)
                raise FSError(22, f"cannot move {src!r} into itself")
            sparent, sdentry = await self.resolve(src)
            if sdentry is None:
                raise FSError(2, f"no such file or directory: {src!r}")
            dparent, ddentry = await self.resolve(dst)
            if ddentry is not None:
                raise FSError(17, f"exists: {dst!r}")
            await self._journal_and_apply({
                "op": "link", "dir": dparent,
                "name": self._split(dst)[-1], "dentry": sdentry,
            })
            await self._journal_and_apply({
                "op": "unlink", "dir": sparent,
                "name": self._split(src)[-1],
            })
