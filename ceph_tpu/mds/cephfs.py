"""CephFS client (the libcephfs / src/client role).

Metadata operations go to the MDS; file DATA goes straight to RADOS,
striped over ``<ino>.<objno>`` objects by the shared Striper with the
file's ``file_layout_t`` -- exactly the reference's split (the client
never proxies data through the MDS).  File sizes flush back to the MDS
as a journaled setattr (the size-cap writeback role).
"""

from __future__ import annotations

from typing import Dict, List

from ceph_tpu.mds.mds import MDS, FSError, data_oid
from ceph_tpu.osdc.striper import FileLayout, Striper


class CephFS:
    def __init__(self, backend, mds: MDS = None):
        self.backend = backend
        self.mds = mds if mds is not None else MDS(backend)

    @classmethod
    async def mount(cls, backend) -> "CephFS":
        fs = cls(backend)
        await fs.mds.start()
        return fs

    # -- namespace ---------------------------------------------------------

    async def mkdir(self, path: str) -> None:
        await self.mds.mkdir(path)

    async def mkdirs(self, path: str) -> None:
        parts = [p for p in path.split("/") if p]
        cur = ""
        for p in parts:
            cur += "/" + p
            try:
                await self.mds.mkdir(cur)
            except FSError as e:
                if e.errno != 17:
                    raise
    async def readdir(self, path: str) -> List[str]:
        return sorted(await self.mds.readdir(path))

    async def stat(self, path: str) -> dict:
        return await self.mds.stat(path)

    async def lstat(self, path: str) -> dict:
        """stat that does NOT follow a final symlink."""
        _, dentry = await self.mds.resolve(path, follow=False)
        if dentry is None:
            raise FSError(2, f"no such file or directory: {path!r}")
        return dentry

    async def symlink(self, path: str, target: str) -> None:
        await self.mds.symlink(path, target)

    async def readlink(self, path: str) -> str:
        return await self.mds.readlink(path)

    # -- user xattrs -------------------------------------------------------

    async def setxattr(self, path: str, name: str, value: bytes) -> None:
        await self.mds.setxattr(path, name, value)

    async def getxattr(self, path: str, name: str) -> bytes:
        xattrs = await self.mds.getxattrs(path)
        if name not in xattrs:
            raise FSError(61, f"no xattr {name!r} on {path!r}")
        return xattrs[name]

    async def listxattr(self, path: str) -> List[str]:
        return sorted(await self.mds.getxattrs(path))

    async def removexattr(self, path: str, name: str) -> None:
        await self.mds.removexattr(path, name)

    # -- advisory locks ----------------------------------------------------

    async def flock(self, path: str, owner: str,
                    exclusive: bool = True) -> None:
        await self.mds.flock(path, owner, exclusive=exclusive)

    async def funlock(self, path: str, owner: str) -> None:
        await self.mds.funlock(path, owner)

    async def rename(self, src: str, dst: str) -> None:
        await self.mds.rename(src, dst)

    async def rmdir(self, path: str) -> None:
        await self.mds.rmdir(path)

    async def unlink(self, path: str) -> None:
        """Remove the file and purge its data objects (the purge-queue
        role, client-side; the MDS purges flock state under its mutate
        lock so a racing flock cannot recreate it)."""
        dentry = await self.mds.unlink(path)
        if dentry["type"] == "l":
            return  # a symlink has no data objects
        layout = FileLayout(*self._layout_tuple(dentry))
        striper = Striper(layout)
        for objno in range(striper.object_count(dentry["size"])):
            try:
                await self.backend.remove_object(
                    data_oid(dentry["ino"], objno)
                )
            except IOError:
                pass  # sparse file: object never written

    # -- file I/O (straight to RADOS, MDS only for size) -------------------

    @staticmethod
    def _layout_tuple(dentry) -> tuple:
        su, sc, osz = dentry["layout"]
        return osz, su, sc  # FileLayout(object_size, stripe_unit, count)

    async def write_file(self, path: str, data: bytes,
                         offset: int = 0) -> None:
        dentry = await self.mds.create(path)
        striper = Striper(FileLayout(*self._layout_tuple(dentry)))
        # extents come out in logical order (Striper::file_to_extents)
        pos = 0
        for objno, obj_off, length in striper.map_extent(offset, len(data)):
            piece = data[pos:pos + length]
            pos += length
            await self.backend.write_range(
                data_oid(dentry["ino"], objno), obj_off, piece
            )
        new_size = max(dentry["size"], offset + len(data))
        if new_size != dentry["size"]:
            await self.mds.set_size(path, new_size)

    async def read_file(self, path: str, offset: int = 0,
                        length: int = -1) -> bytes:
        dentry = await self.mds.stat(path)
        if dentry["type"] != "f":
            raise FSError(21, f"is a directory: {path!r}")
        size = dentry["size"]
        if length < 0:
            length = max(0, size - offset)
        end = min(offset + length, size)
        if end <= offset:
            return b""
        striper = Striper(FileLayout(*self._layout_tuple(dentry)))
        out = bytearray(end - offset)
        pos = 0
        for objno, obj_off, ln in striper.map_extent(offset, end - offset):
            try:
                piece = await self.backend.read_range(
                    data_oid(dentry["ino"], objno), obj_off, ln
                )
            except IOError:
                piece = b""  # sparse hole: zeros
            out[pos:pos + len(piece)] = piece
            pos += ln
        return bytes(out)

    async def truncate(self, path: str, size: int) -> None:
        dentry = await self.mds.stat(path)
        old = dentry["size"]
        await self.mds.set_size(path, size)
        if size < old:
            striper = Striper(FileLayout(*self._layout_tuple(dentry)))
            first_dead = striper.object_count(size)
            for objno in range(first_dead, striper.object_count(old)):
                try:
                    await self.backend.remove_object(
                        data_oid(dentry["ino"], objno)
                    )
                except IOError:
                    pass
            # POSIX: bytes exposed by a later re-grow must read as zeros,
            # so the surviving boundary object's stale tail is zeroed
            for objno, obj_off, ln in striper.map_extent(size, old - size):
                if objno >= first_dead:
                    continue  # removed above
                try:
                    await self.backend.write_range(
                        data_oid(dentry["ino"], objno), obj_off, bytes(ln)
                    )
                except IOError:
                    pass  # sparse: nothing stored there anyway
