"""CephFS: metadata server + POSIX-style client over RADOS.

Reference: src/mds (MDCache / MDLog / LogEvent journaling, 76.9k LoC) +
src/client (libcephfs, 24.1k LoC), reduced to the architecture:

* **Namespace in RADOS** -- each directory is one RADOS object whose
  omap maps entry name -> encoded dentry with the inode EMBEDDED
  (CephFS's primary-dentry inode embedding); the inode-number table is
  an omap counter allocated through the CAS primitive
  (src/mds/InoTable.h).
* **Journaled mutations** (MDLog/LogEvent): every metadata mutation is
  appended to the MDS journal object BEFORE it is applied to the
  directory objects; a restarted or standby MDS replays the journal
  tail (idempotent events) and trims it -- the up:replay ->
  up:active takeover flow (src/mds/MDLog.cc).
* **File data striped over objects** via the shared Striper
  (src/osdc/Striper.cc, file_layout_t): data object "<ino>.<objno>",
  I/O through the same EC/replicated pool machinery as everything else.

``MDS`` is one rank; ``MultiMDS`` runs several active ranks with the
namespace partitioned by subtree and an MDBalancer-style rebalancer
(src/mds/MDBalancer.cc); ``CephFS`` is the libcephfs-role client
(metadata calls to the MDS, data I/O straight to RADOS -- the
reference's split between MDS requests and OSD file I/O).
"""

from ceph_tpu.mds.mds import MDS
from ceph_tpu.mds.cephfs import CephFS
from ceph_tpu.mds.multimds import MultiMDS

__all__ = ["MDS", "CephFS", "MultiMDS"]
