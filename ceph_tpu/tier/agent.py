"""Tier agent: the hitset-driven promote / flush / evict loop.

Reference: the cache-tier agent (src/osd/PrimaryLogPG.cc agent_work +
TierAgentState) that walks objects ranking hotness from hit sets and
promotes/flushes/evicts against the cache pool's targets.  Here the
agent is one async tick riding the OSD's background tick loop (a peer
of ``scrub_tick`` in ``osd/shard.py``), and the cache device is the
accelerator's own memory:

* **flush**: dirty entries left behind by a failed/abandoned
  write-through fan-out are dropped (the shards hold the authoritative
  bytes; see ``DeviceTierStore.flush_dirty``);
* **promote**: objects this OSD is PRIMARY for whose hit-set
  temperature clears ``osd_tier_promote_temp`` and which are not yet
  resident get their full shard set gathered (consistent-cut read, the
  codec reconstructing any missing position) and shipped in ONE batched
  device transfer (``put_many``), at most
  ``osd_tier_promote_max_per_tick`` objects per tick;
* **evict**: the store is trimmed back under ``osd_tier_hbm_bytes``
  coldest-first (temperature, then LRU).

Only pools whose cache mode is ``writeback`` or ``readproxy`` take
part; the mode flows from the mon (`osd tier cache-mode`) via the
osdmap, or from ``ECCluster.set_tier_mode`` in-process.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ceph_tpu.osd import ecutil


class TierAgent:
    """One OSD's promote/flush/evict agent over its hosted pools."""

    def __init__(self, shard):
        self.shard = shard

    # -- candidate selection -----------------------------------------------

    def _is_primary(self, backend, oid: str) -> bool:
        acting = backend.acting_set(oid)
        for s in range(backend.km):
            if backend._shard_up(acting, s):
                return f"osd.{acting[s]}" == self.shard.name
        return False

    def _promotion_candidates(self, active, limit: int,
                              thresh: float) -> List[tuple]:
        """(pool, backend, oid) triples worth promoting this tick:
        locally-held base objects this OSD leads, hot by hit-set
        temperature, not yet resident.  Reuses the scrub cursor's cached
        base listing so a big store is not re-scanned per tick."""
        shard = self.shard
        tier = shard.tier
        bases = shard._scrub_base_list()
        tags = getattr(shard, "_scrub_pool_tags", {})
        out: List[tuple] = []
        for base in bases:
            if len(out) >= limit:
                break
            if "~" in base:
                continue  # clones are cold history; heads only
            tag = tags.get(base)
            for pool, backend in active.items():
                if not backend._pool_match(tag):
                    continue
                if tier.contains(pool, base):
                    break
                if shard.hitsets.temperature(base) < thresh:
                    break
                if not self._is_primary(backend, base):
                    break
                out.append((pool, backend, base))
                break
        return out

    # -- promotion gather --------------------------------------------------

    async def _gather_block(self, backend, oid: str) -> Optional[Tuple]:
        """(shard-major host block [km, shard_len], version, logical
        size) for one object, or None when it cannot be assembled right
        now.  Reads a consistent cut of every up shard (scrub op class:
        background priority) and reconstructs missing positions through
        the codec, so the resident block always holds ALL km shards --
        a later degraded acting set never forces a decode on the hit
        path."""
        km = backend.km
        acting = backend.acting_set(oid)
        up = [s for s in range(km) if backend._shard_up(acting, s)]
        if len(up) < backend.k:
            return None
        chunks, logical_size, _attrs, version = \
            await backend._gather_consistent(
                oid, up, acting, op_class="scrub", up_shards=up
            )
        if len(chunks) < backend.k or logical_size is None or \
                tuple(version) == (0, ""):
            return None
        shard_len = len(next(iter(chunks.values())))
        if shard_len == 0:
            return None  # zero-byte object: nothing to keep resident
        missing = [s for s in range(km) if s not in chunks]
        if missing:
            rebuilt = ecutil.decode_shards(backend.ec, chunks, missing)
            for s in missing:
                chunks[s] = rebuilt[s]
        block = np.stack(
            [np.asarray(chunks[s], dtype=np.uint8) for s in range(km)]
        )
        return block, tuple(version), logical_size

    # -- the tick ----------------------------------------------------------

    async def tick(self) -> dict:
        """One agent round; returns {"promoted", "flushed",
        "evicted_bytes"} for the caller's accounting."""
        from ceph_tpu.utils.config import get_config

        shard = self.shard
        stats = {"promoted": 0, "flushed": 0, "evicted_bytes": 0}
        active = {
            name: b for name, b in shard.pools.items()
            if getattr(b, "tier_mode", "none") != "none"
            and getattr(b, "ec", None) is not None
        }
        if not active:
            return stats
        cfg = get_config()
        thresh = float(cfg.get_val("osd_tier_promote_temp"))
        limit = int(cfg.get_val("osd_tier_promote_max_per_tick"))

        stats["flushed"] = shard.tier.flush_dirty()

        # the consistent-cut gathers below span awaits; a sub-write
        # applying inside that window invalidates BEFORE the block is
        # resident (a no-op) and put_many would insert the stale cut.
        # The watch collects every invalidated oid for the window so
        # the insert step can drop them (asyncsan rmw-across-await at
        # the tier layer; a false drop just defers one tick).
        watch = shard.tier.watch_invalidations()
        try:
            items = []
            for pool, backend, oid in self._promotion_candidates(
                active, limit, thresh
            ):
                got = await self._gather_block(backend, oid)
                if got is None or oid in watch:
                    continue
                block, version, logical_size = got
                items.append((pool, oid, block, version, logical_size))
            if items:
                # filter + insert must be ONE yield-free step or the
                # window the watch closes reopens between them
                # cephlint: atomic-section tier-promote-cut
                fresh = [it for it in items if it[1] not in watch]
                if fresh:
                    stats["promoted"] = shard.tier.put_many(fresh)
                # cephlint: end-atomic-section
        finally:
            shard.tier.unwatch(watch)

        stats["evicted_bytes"] = shard.tier.evict_to_budget()
        return stats
