"""Device-resident cache tier: HBM as the cache device.

Reference: Ceph's cache-tiering subsystem (src/osd/TierAgentState.h,
src/osd/PrimaryLogPG.cc agent_work, src/mon/OSDMonitor.cc `osd tier`
commands) re-targeted at a TPU-native deployment: instead of an SSD
cache pool overlaying an HDD base pool, hot objects' ENCODED shards
stay resident in device memory and reads decode without the H2D ingest
step.  ``device_tier`` holds the byte-budgeted store and the
process-wide HBM ledger; ``agent`` is the promote/flush/evict loop.
"""

from ceph_tpu.tier.device_tier import (  # noqa: F401
    DeviceByteAccount,
    DeviceTierStore,
    TierEntry,
    device_byte_account,
)

#: pool cache modes honored by the data path + agent (the pg_pool_t
#: cache_mode subset that makes sense with device residency: writeback
#: keeps write-through copies resident, readproxy promotes on read
#: temperature only, none disables the tier for the pool)
CACHE_MODES = ("writeback", "readproxy", "none")
